"""Tensor (hidden-unit) parallelism: the LSTM stack sharded over a ``tp`` axis.

The fourth and last classic mesh axis, completing the framework's
parallel story: **dp** shards the batch (`data_parallel.py`), **sp** the
window (`sequence.py`), **seed** the ensemble members (`multi_seed.py`)
— **tp** shards the *model width*.  Each of the T devices on the ``tp``
axis owns H/T of every LSTM layer's hidden units: its slice of the gate
columns of ``kernel`` (F, 4H), ``recurrent_kernel`` (H, 4H) and ``bias``
(4H,), and the matching (B, Hl) slice of the (h, c) state.  Per
timestep a device computes

    z_loc = xz_loc[t] + all_gather(h_loc) @ R[:, gates, own units]

— the full-H contraction against its own 4·Hl gate columns — and
updates its (h, c) slice elementwise.  The single ``all_gather`` of the
(B, Hl) hidden slices is the only per-step communication; between
layers the full hidden sequence is reassembled ONCE by the same
masked-psum idiom as :func:`~hfrep_tpu.parallel.sequence.sp_generate`
(typed tp-*invariant* — an all_gather's varying output type would leak
spurious tp-variance into every downstream loss; see that docstring).

When tp pays: the per-device recurrent matmul is 8·B·H·Hl flops against
~4·B·(H−Hl) gathered bytes, i.e. ~2·Hl flops/byte — at the production
width (H=100) the gather dominates and tp=1 is the right call, but in
the wide-model regime this framework measured in round 4 (H ≥ 384,
where the fused kernels hit their 16 MB VMEM ceiling and f32/H=512
OOM'd before the width-aware dispatch) tp divides both the recurrent
FLOPs and the resident gate matrices by T.  tp is to *width* what sp is
to *window length*: a capacity axis, proven trajectory-exact here and
advisory until the model outgrows one chip.

Parameters and optimizer state stay REPLICATED over ``tp`` (the
framework-wide invariant `shard_map(check_vma=True)` proves at trace
time): each device *slices* its gate columns inside the region, and the
transpose of that invariant→varying slice is automatically a psum, so
`jax.grad` hands every device the full, already-reduced parameter
gradient — no collective code in the step, same machinery as the dp
gradient story (`train/steps.py::_psum_if`, here with nothing left to
normalize because no axis shards the batch).

Reference anchor: the models being widened are the flagship stack
``GAN/MTSS_WGAN_GP.py:221-252`` (two LSTM(100) layers); the reference
has no tensor parallelism to port (SURVEY §5.8 — single device
throughout).

Backend note: the tp recurrence runs the XLA scan only.  The pallas
kernels (`ops/pallas_lstm.py`) are whole-H single-device programs whose
speed comes from keeping the gate matrices VMEM-resident across the
whole traversal; a per-timestep cross-chip all_gather in the middle of
the kernel body is exactly what they cannot express.  At tp-worthy
widths the per-device matmuls are large enough that the XLA scan is
MXU-bound anyway (the kernels' edge is latency at small H, RESULTS.md).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from hfrep_tpu.parallel._compat import axis_size, shard_map
from hfrep_tpu.ops.layers import ACTIVATIONS
from hfrep_tpu.utils.vma import match_vma


def _resolve_tp_axis(mesh: Mesh, axis_name: Optional[str]) -> str:
    """The tp axis: the axis literally named ``"tp"``, else whatever the
    caller names explicitly.  A bare single-axis mesh named e.g.
    ``('dp',)`` is refused rather than silently width-sharded — handing
    the wrong mesh to a tp builder is a mix-up, not a request
    (consistent with the trainer's name-based dispatch,
    ``train/trainer.py:48-51``)."""
    if axis_name is not None:
        if axis_name not in mesh.axis_names:
            raise ValueError(
                f"axis {axis_name!r} not in mesh axes {mesh.axis_names}")
        return axis_name
    if "tp" in mesh.axis_names:
        return "tp"
    raise ValueError(
        f"mesh {mesh.axis_names} has no 'tp' axis; pass axis_name "
        f"explicitly to shard hidden units over a differently-named axis")


def _check_width(h: int, n_dev: int) -> int:
    if h % n_dev:
        raise ValueError(
            f"hidden width {h} not divisible by tp={n_dev} devices")
    return h // n_dev


def _slice_gate_params(params: dict, t_idx, hl: int) -> dict:
    """This tp rank's Hl unit columns of a Keras LSTM param dict, in the
    flat gate-blocked layout ({kernel: (Fin, 4·Hl), recurrent_kernel:
    (H, 4·Hl), bias: (4·Hl,)}).

    Gate blocks stay Keras-ordered [i|f|c|o] within the sliced 4·Hl —
    slicing each block's own-unit columns commutes with every
    contraction.  axis_index-dependent slices type the results
    tp-varying, which is what makes AD psum the parameter cotangents
    back to the replicated trees at the boundary.  Shared by the tp
    layer forward here and the sp pipeline's tp-sliced chunks
    (:mod:`hfrep_tpu.parallel.sequence`), so the two layouts cannot
    drift."""
    f_in = params["kernel"].shape[0]
    h = params["recurrent_kernel"].shape[0]
    k = lax.dynamic_slice_in_dim(
        params["kernel"].reshape(f_in, 4, h), t_idx * hl, hl, axis=2)
    r = lax.dynamic_slice_in_dim(
        params["recurrent_kernel"].reshape(h, 4, h), t_idx * hl, hl, axis=2)
    bb = lax.dynamic_slice_in_dim(
        params["bias"].reshape(4, h), t_idx * hl, hl, axis=1)
    return {"kernel": k.reshape(f_in, 4 * hl),
            "recurrent_kernel": r.reshape(h, 4 * hl),
            "bias": bb.reshape(4 * hl)}


def tp_chunk_scan(xz_chunk: jnp.ndarray, carry, r_loc: jnp.ndarray,
                  act, rec_act, tp_axis: str):
    """Scan a (W, B, 4·Hl) pre-projected gate-slice chunk from the given
    (B, Hl) carry slices — the tp recurrence kernel shared by the plain
    tp layer and the sp pipeline's tp-sliced chunks.

    Each timestep all_gathers the T hidden slices into the full (B, H)
    state in unit order (device t owns columns [t·Hl, (t+1)·Hl) — tiled
    concat order matches :func:`_slice_gate_params`'s column slicing;
    the ONLY per-step tp communication) and contracts it against the
    local (H, 4·Hl) recurrent columns; gate math updates the owned
    slice elementwise, arithmetic identical to the single-device cell
    (`ops/lstm.py::lstm_cell_step`) on those units."""

    def cell(c, xz_t):
        h_loc, c_loc = c
        h_full = lax.all_gather(h_loc, tp_axis, axis=1, tiled=True)
        z = xz_t + h_full @ r_loc
        zi, zf, zc, zo = jnp.split(z, 4, axis=-1)
        i = rec_act(zi)
        fgt = rec_act(zf)
        cc = fgt * c_loc + i * act(zc)
        o = rec_act(zo)
        h_t = o * act(cc)
        return (h_t, cc), h_t

    return lax.scan(cell, carry, xz_chunk)


def _tp_lstm_local(params: dict, x: jnp.ndarray, axis_name: str, *,
                   activation: Optional[str],
                   recurrent_activation: str = "sigmoid") -> jnp.ndarray:
    """One Keras-semantics LSTM layer, hidden units sharded over
    ``axis_name``; runs inside a shard_map region.

    ``x`` is the full (B, W, Fin) input (tp-invariant — either the raw
    noise/window or a previous layer's reassembled sequence); returns
    this device's LOCAL (B, W, Hl) hidden-sequence slice (tp-varying).
    The input projection for the whole window is hoisted out of the
    recurrence as one MXU matmul, same as the single-device path; the
    recurrence is :func:`tp_chunk_scan` from the zero carry.
    """
    h = params["recurrent_kernel"].shape[0]
    hl = _check_width(h, axis_size(axis_name))
    act = ACTIVATIONS[activation]
    rec_act = ACTIVATIONS[recurrent_activation]

    b, w, f = x.shape
    loc = _slice_gate_params(params, lax.axis_index(axis_name), hl)
    # Hoisted input projection for all timesteps: (B·W, Fin) @ (Fin, 4·Hl).
    xz = (x.reshape(b * w, f) @ loc["kernel"] + loc["bias"]).reshape(b, w, 4 * hl)
    xz = jnp.swapaxes(xz, 0, 1)                       # time-major (W, B, 4·Hl)

    # Carry slices vary over every axis the projected input does (tp
    # always; dp too under the composed dp×tp step).
    init = match_vma((jnp.zeros((b, hl), xz.dtype),
                      jnp.zeros((b, hl), xz.dtype)), xz)
    _, hs = tp_chunk_scan(xz, init, loc["recurrent_kernel"], act, rec_act,
                          axis_name)                  # (W, B, Hl)
    return jnp.swapaxes(hs, 0, 1)                     # (B, W, Hl)


def _tp_assemble(y_loc: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Local (B, W, Hl) unit slices → full (B, W, H), typed tp-INVARIANT.

    Masked psum, not all_gather, for the same two reasons as
    :func:`~hfrep_tpu.parallel.sequence.sp_generate`: a gather's output
    is typed varying even though the values agree (poisoning every
    downstream loss type), and the psum's invariant output is what lets
    AD see that the next layer's slice needs its transpose-psum."""
    n_dev = axis_size(axis_name)
    hl = y_loc.shape[-1]
    buf = jnp.zeros(y_loc.shape[:-1] + (hl * n_dev,), y_loc.dtype)
    buf = lax.dynamic_update_slice_in_dim(
        match_vma(buf, y_loc), y_loc, lax.axis_index(axis_name) * hl,
        axis=y_loc.ndim - 1)
    return lax.psum(buf, axis_name)


def _tp_generate_local(g_params: dict, z: jnp.ndarray, axis_name: str,
                       slope: float, activation: str,
                       ln_eps: float) -> jnp.ndarray:
    """The full MTSS generator (LSTM → LN → LSTM → LeakyReLU → LN →
    Dense) with both recurrences unit-sharded; body of
    :func:`tp_generate` and of the tp train steps' g_apply."""
    from hfrep_tpu.parallel.sequence import _sp_ln, _sp_head_impl

    h0 = _tp_assemble(
        _tp_lstm_local(g_params["KerasLSTM_0"], z, axis_name,
                       activation=activation), axis_name)
    h0 = _sp_ln(g_params["KerasLayerNorm_0"], h0, ln_eps)
    h1 = _tp_assemble(
        _tp_lstm_local(g_params["KerasLSTM_1"], h0, axis_name,
                       activation=activation), axis_name)
    # LeakyReLU → LN → Dense tail: the same head impl the sp pipeline
    # runs (per-timestep ops on a tp-invariant sequence; un-jitted —
    # inner jits trip the manual-mesh consistency check, see _sp_ln).
    return _sp_head_impl(g_params, h1, slope, ln_eps)


def _tp_critic_local(d_params: dict, x: jnp.ndarray,
                     axis_name: str) -> jnp.ndarray:
    """The flagship critic (LSTM → LSTM → Flatten → Dense(1)) with both
    recurrences unit-sharded: (B, W, F) → (B, 1) tp-invariant scores.

    The flattened (W·H → 1) head needs no reassembly of the second
    layer: each device dots its (B, W, Hl) slice with its own
    (W, Hl)-rows of the Dense kernel (flatten order is w-major, so the
    unit slice of each timestep's block) and one psum over ``tp``
    completes the contraction — the tp twin of
    :func:`~hfrep_tpu.parallel.sequence.sp_critic`'s window-sliced head.
    """
    h0 = _tp_assemble(
        _tp_lstm_local(d_params["KerasLSTM_0"], x, axis_name,
                       activation="tanh"), axis_name)
    h1_loc = _tp_lstm_local(d_params["KerasLSTM_1"], h0, axis_name,
                            activation="tanh")

    dense = d_params["KerasDense_0"]["Dense_0"]
    bb, w, hl = h1_loc.shape
    h = hl * axis_size(axis_name)
    k_loc = lax.dynamic_slice_in_dim(
        dense["kernel"].reshape(w, h, -1),
        lax.axis_index(axis_name) * hl, hl, axis=1)       # (W, Hl, 1)
    part = h1_loc.reshape(bb, w * hl) @ k_loc.reshape(w * hl, -1)
    scores = lax.psum(part, axis_name)
    if "bias" in dense:
        scores = scores + dense["bias"]
    return scores


def tp_generate(g_params: dict, z: jnp.ndarray, mesh: Mesh, *,
                axis_name: Optional[str] = None, slope: float = 0.2,
                activation: str = "sigmoid", ln_eps: float = 1e-3,
                manual: bool = False) -> jnp.ndarray:
    """MTSS generator forward with hidden units sharded over the tp axis
    — output matches the single-device ``generator.apply`` to f32
    round-off (tests/test_tensor_parallel.py).

    ``g_params`` is the LSTMGenerator tree (``KerasLSTM_0/1``,
    ``KerasLayerNorm_0/1``, ``KerasDense_0``), replicated; ``z`` is the
    full (B, W, F) noise.  ``manual=True`` runs inside an enclosing
    shard_map region (the tp train steps)."""
    axis_name = _resolve_tp_axis(mesh, axis_name)
    if manual:
        return _tp_generate_local(g_params, z, axis_name, slope,
                                  activation, ln_eps)
    return shard_map(
        lambda p, zz: _tp_generate_local(p, zz, axis_name, slope,
                                         activation, ln_eps),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=True)(g_params, z)


def tp_critic(d_params: dict, x: jnp.ndarray, mesh: Mesh, *,
              axis_name: Optional[str] = None,
              manual: bool = False) -> jnp.ndarray:
    """Flagship critic forward with hidden units sharded over the tp
    axis — (B, W, F) → (B, 1) scores matching the single-device
    ``critic.apply`` to f32 round-off.  Differentiable end to end
    (slice/psum transposes), including the gradient penalty's
    second-order path — what tp WGAN-GP *training* needs."""
    axis_name = _resolve_tp_axis(mesh, axis_name)
    if manual:
        return _tp_critic_local(d_params, x, axis_name)
    return shard_map(
        lambda p, xx: _tp_critic_local(p, xx, axis_name),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=True)(d_params, x)


def validate_tp_pair(pair, n_tp: int) -> None:
    """The tp modules mirror the flagship LSTMGenerator/LSTMFlatCritic
    param trees (same precondition family as
    :func:`~hfrep_tpu.parallel.sequence.validate_sp_pair`) and need the
    hidden width to split evenly across the tp devices."""
    if pair.family != "mtss_wgan_gp":
        raise ValueError(f"tensor-parallel step supports the "
                         f"mtss_wgan_gp family, got {pair.family!r}")
    _check_width(pair.generator.hidden, n_tp)
    # the critic's width is sliced by the same Hl arithmetic — validate it
    # here too so a mismatched pair fails at build, not at trace
    _check_width(pair.discriminator.hidden, n_tp)


def _validate_tp_backend(tcfg) -> None:
    """Same backend policy as the sp path's dtype gate: an EXPLICIT
    pallas request must refuse (the per-step cross-chip all_gather is
    what the fused kernels cannot express — module docstring), never
    silently run the scan; ``'auto'`` quietly takes the scan (on a tp
    mesh that IS the best available backend); invalid values get
    `resolve_lstm_backend`'s usual ValueError."""
    from hfrep_tpu.train.steps import resolve_lstm_backend

    if tcfg.lstm_backend == "pallas":
        raise NotImplementedError(
            "tensor-parallel training runs the XLA scan recurrence: the "
            "pallas kernels keep gate matrices VMEM-resident across the "
            "whole traversal and cannot express the per-timestep "
            "cross-chip all_gather; use lstm_backend='auto' or 'xla'")
    resolve_lstm_backend(tcfg.lstm_backend)


def _tp_apply_fns(pair, axis_name: str) -> Tuple:
    slope = pair.generator.slope
    g_apply = lambda p, z: _tp_generate_local(p, z, axis_name, slope,
                                              "sigmoid", 1e-3)
    d_apply = lambda p, x: _tp_critic_local(p, x, axis_name)
    return g_apply, d_apply


def _wrap_replicated(inner, mesh: Mesh, jit: bool):
    """shard_map a fully-replicated step over the 1-D tp mesh: state,
    key, metrics all P() — every device runs the identical epoch with
    tp-sharded internals, and ``check_vma=True`` proves the outputs are
    invariant (the psum'd activations/scores make every loss, gradient
    and update provably identical across the axis)."""
    fn = shard_map(inner, mesh=mesh, in_specs=(P(), P()),
                   out_specs=(P(), P()), check_vma=True)
    return jax.jit(fn, donate_argnums=(0,)) if jit else fn


def make_tp_train_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                       axis_name: Optional[str] = None, jit: bool = True):
    """Tensor-parallel MTSS-WGAN-GP training: one epoch (n_critic GP
    critic updates + generator update) with every forward's hidden
    units sharded over the tp axis, trajectory-exact vs the plain step.

    All step semantics (sampling streams, critic loop, optimizer
    updates) are shared verbatim with the single-device step via
    ``make_train_step(apply_fns=...)`` — the same reuse contract as the
    sp and dp×sp steps, so the three parallel modes cannot drift
    arithmetically.  No gradient normalization is needed: nothing
    shards the batch, and the slice-transpose psums already hand every
    device the full parameter gradients (module docstring)."""
    from hfrep_tpu.obs import instrument_launch
    from hfrep_tpu.train.steps import make_train_step

    axis_name = _resolve_tp_axis(mesh, axis_name)
    validate_tp_pair(pair, mesh.shape[axis_name])
    _validate_tp_backend(tcfg)
    inner = make_train_step(pair, tcfg, dataset,
                            apply_fns=_tp_apply_fns(pair, axis_name))
    return instrument_launch(_wrap_replicated(inner, mesh, jit),
                             "tp_train_step", mesh=mesh, tcfg=tcfg, jit=jit)


def make_tp_multi_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                       axis_name: Optional[str] = None, jit: bool = True):
    """``tcfg.steps_per_call`` tp epochs scanned into ONE compiled
    program — the dispatch-amortized launch shape (same argument as
    :func:`~hfrep_tpu.train.steps.make_multi_step`)."""
    from hfrep_tpu.obs import instrument_launch
    from hfrep_tpu.train.steps import make_multi_step, make_train_step

    axis_name = _resolve_tp_axis(mesh, axis_name)
    validate_tp_pair(pair, mesh.shape[axis_name])
    _validate_tp_backend(tcfg)
    step = make_train_step(pair, tcfg, dataset,
                           apply_fns=_tp_apply_fns(pair, axis_name))
    inner = make_multi_step(pair, tcfg, dataset, jit=False, step=step)
    return instrument_launch(_wrap_replicated(inner, mesh, jit),
                             "tp_multi_step", mesh=mesh, tcfg=tcfg, jit=jit)


def _split_dp_tp(mesh: Mesh) -> Tuple[str, str]:
    if tuple(mesh.axis_names) != ("dp", "tp"):
        raise ValueError(
            f"dp×tp composition wants a ('dp', 'tp') mesh, got {mesh.axis_names}")
    return "dp", "tp"


def _make_dp_tp_inner(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh,
                      controlled_sampling: bool):
    """Per-device epoch step for the composed dp×tp mesh: batch sharded
    over ``dp`` (gradients dp-normalized by the existing `_psum_if` vma
    machinery), hidden units sharded over ``tp`` — the width twin of
    :mod:`hfrep_tpu.parallel.dp_sp`."""
    from hfrep_tpu.train.steps import make_train_step

    dp_axis, tp_axis = _split_dp_tp(mesh)
    validate_tp_pair(pair, mesh.shape[tp_axis])
    _validate_tp_backend(tcfg)
    n_dp = mesh.shape[dp_axis]
    if tcfg.batch_size % n_dp:
        raise ValueError(
            f"global batch {tcfg.batch_size} not divisible by dp={n_dp}")
    local_tcfg = dataclasses.replace(tcfg,
                                     batch_size=tcfg.batch_size // n_dp)
    return make_train_step(
        pair, local_tcfg, dataset, axis_name=dp_axis,
        sample_batch=tcfg.batch_size if controlled_sampling else None,
        apply_fns=_tp_apply_fns(pair, tp_axis))


def make_dp_tp_train_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                          controlled_sampling: bool = False,
                          jit: bool = True):
    """One dp×tp epoch on a 2-D ``('dp', 'tp')`` mesh: batch sharded
    over dp, hidden units sharded over tp, state replicated over both
    (proven by check_vma).  ``controlled_sampling=True`` follows the
    single-device sample stream at the same global batch — the
    trajectory-test mode (tests/test_tensor_parallel.py)."""
    from hfrep_tpu.obs import instrument_launch
    from hfrep_tpu.parallel.data_parallel import wrap_batch_parallel

    inner = _make_dp_tp_inner(pair, tcfg, dataset, mesh, controlled_sampling)
    return instrument_launch(
        wrap_batch_parallel(inner, mesh, "dp", controlled_sampling, jit),
        "dp_tp_train_step", mesh=mesh, tcfg=tcfg, jit=jit)


def make_dp_tp_multi_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                          controlled_sampling: bool = False,
                          jit: bool = True):
    """``tcfg.steps_per_call`` dp×tp epochs scanned into ONE compiled
    program — the launch shape for real runs (the trainer dispatches
    this from its ordinary block loop)."""
    from hfrep_tpu.obs import instrument_launch
    from hfrep_tpu.parallel.data_parallel import wrap_batch_parallel
    from hfrep_tpu.train.steps import make_multi_step

    step = _make_dp_tp_inner(pair, tcfg, dataset, mesh, controlled_sampling)
    inner = make_multi_step(pair, tcfg, dataset, jit=False, step=step)
    return instrument_launch(
        wrap_batch_parallel(inner, mesh, "dp", controlled_sampling, jit),
        "dp_tp_multi_step", mesh=mesh, tcfg=tcfg, jit=jit)
