"""Sequence (window-axis) parallelism — GSPMD edition.

The 850-line manual pipeline (superstep schedule, ppermute carry
handoffs, masked-psum reassembly, vma casts — all dead on runtimes
without ``jax.shard_map``) is replaced by the unified mesh launch: the
window axis of the sampled batch is sharding-constrained over ``sp``
and GSPMD partitions the per-timestep math, inserting the collectives
the old code hand-wrote (:mod:`hfrep_tpu.parallel.rules`).  On a
1-device ``('sp',)`` mesh the program is the literal single-device
program, so the old "sp tax" (134 vs 167 steps/s at prod shape,
RESULTS.md) disappears by construction.

What intentionally remains here:

* the plain param-level LSTM-stack forwards (:func:`sp_generate` /
  :func:`sp_critic` / :func:`sp_lstm`) — the single source of the
  flagship arithmetic shared with :mod:`hfrep_tpu.parallel.tensor` and
  :mod:`hfrep_tpu.parallel.layer_pipeline` (``_local_chunk_scan`` /
  ``_sp_ln`` / ``_sp_head_impl`` live here for that reason);
* :func:`sp_microbatch_plan` — the analytic microbatch model the chip
  studies anchored (advisory; the GSPMD path has no M knob).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hfrep_tpu.ops.layers import ACTIVATIONS
from hfrep_tpu.ops.lstm import lstm_cell_step


def _local_chunk_scan(xz_chunk: jnp.ndarray,
                      carry: Tuple[jnp.ndarray, jnp.ndarray],
                      recurrent: jnp.ndarray, act, rec_act):
    """Scan one (W, B, 4H) pre-projected sequence from the given carry,
    using the same fused cell as the single-device :class:`KerasLSTM` —
    shared by the layer pipeline's stage scans and the plain forwards
    below, so no path can drift arithmetically."""

    def cell(c, xz_t):
        return lstm_cell_step(c, xz_t, recurrent=recurrent, act=act,
                              rec_act=rec_act)

    return lax.scan(cell, carry, xz_chunk)


def _sp_ln(p: dict, v: jnp.ndarray, eps: float) -> jnp.ndarray:
    """LayerNorm via the same :class:`~hfrep_tpu.ops.layers.KerasLayerNorm`
    module the single-device generator runs.  Deliberately NOT jitted:
    it also executes inside the layer pipeline's shard_map body (a
    Manual-mesh context where an inner jit trips the mesh-consistency
    check); as plain traced ops it inlines everywhere."""
    from hfrep_tpu.ops.layers import KerasLayerNorm

    return KerasLayerNorm(epsilon=eps).apply({"params": p}, v)


def _sp_head_impl(g_params: dict, v: jnp.ndarray, slope: float,
                  eps: float) -> jnp.ndarray:
    """LeakyReLU → LN → Dense tail of the generator — per-timestep ops,
    identical on a full sequence or a pipeline stage's chunk."""
    from hfrep_tpu.ops.layers import KerasDense, KerasLayerNorm, leaky_relu

    v = leaky_relu(v, slope)
    v = KerasLayerNorm(epsilon=eps).apply(
        {"params": g_params["KerasLayerNorm_1"]}, v)
    features = g_params["KerasDense_0"]["Dense_0"]["kernel"].shape[1]
    return KerasDense(features).apply({"params": g_params["KerasDense_0"]}, v)


def _jit_replicated_out(fn, mesh: Mesh):
    """jit with (state, metrics) pinned REPLICATED over the mesh — the
    layer pipeline's launch wrapper (and historically every manual
    path's).  Now a one-liner over :func:`~hfrep_tpu.parallel.rules.
    mesh_launch`."""
    from hfrep_tpu.parallel.rules import mesh_launch

    return mesh_launch(fn, mesh, in_specs=(P(), P()), out_specs=(P(), P()),
                       donate_argnums=(0,))


# --------------------------------------------------- plain stack forwards
def _lstm_layer(params: dict, x: jnp.ndarray, activation: str,
                recurrent_activation: str = "sigmoid",
                backend: Optional[str] = None) -> jnp.ndarray:
    """One Keras-semantics LSTM layer on (B, W, Fin) → (B, W, H): the
    input projection hoisted as one MXU matmul, the recurrence the
    shared fused cell — the arithmetic every parallel mode launches.

    An explicit ``backend`` (the chip tools' ``backend="pallas"``)
    routes through :class:`~hfrep_tpu.ops.lstm.KerasLSTM`'s dispatch so
    the pallas-vs-xla oracles really compare the kernels; the default
    keeps the hand-hoisted scan (jaxpr-pinned by the identity tests)."""
    if backend not in (None, "xla"):
        from hfrep_tpu.ops.lstm import KerasLSTM
        return KerasLSTM(features=int(params["recurrent_kernel"].shape[0]),
                         activation=activation,
                         recurrent_activation=recurrent_activation).apply(
            {"params": params}, x, backend=backend)
    k, r, b = params["kernel"], params["recurrent_kernel"], params["bias"]
    bsz, w, f = x.shape
    xz = (x.reshape(bsz * w, f) @ k + b).reshape(bsz, w, -1)
    xz = jnp.swapaxes(xz, 0, 1)                       # time-major
    h = r.shape[0]
    init = (jnp.zeros((bsz, h), xz.dtype), jnp.zeros((bsz, h), xz.dtype))
    _, hs = _local_chunk_scan(xz, init, r, ACTIVATIONS[activation],
                              ACTIVATIONS[recurrent_activation])
    return jnp.swapaxes(hs, 0, 1)                     # (B, W, H)


def generator_forward(g_params: dict, z: jnp.ndarray, *,
                      slope: float = 0.2, activation: str = "sigmoid",
                      ln_eps: float = 1e-3,
                      backend: Optional[str] = None) -> jnp.ndarray:
    """The full MTSS generator (LSTM → LN → LSTM → LeakyReLU → LN →
    Dense) from a raw param tree — matches ``generator.apply`` to f32
    round-off (the layout-agnostic body :func:`sp_generate` and
    :func:`~hfrep_tpu.parallel.tensor.tp_generate` launch)."""
    x = _lstm_layer(g_params["KerasLSTM_0"], z, activation, backend=backend)
    x = _sp_ln(g_params["KerasLayerNorm_0"], x, ln_eps)
    x = _lstm_layer(g_params["KerasLSTM_1"], x, activation, backend=backend)
    return _sp_head_impl(g_params, x, slope, ln_eps)


def critic_forward(d_params: dict, x: jnp.ndarray,
                   backend: Optional[str] = None) -> jnp.ndarray:
    """The flagship critic (LSTM → LSTM → Flatten → Dense(1)) from a raw
    param tree: (B, W, F) → (B, 1) scores."""
    h = _lstm_layer(d_params["KerasLSTM_0"], x, "tanh", backend=backend)
    h = _lstm_layer(d_params["KerasLSTM_1"], h, "tanh", backend=backend)
    dense = d_params["KerasDense_0"]["Dense_0"]
    s = h.reshape(h.shape[0], -1) @ dense["kernel"]
    return s + dense["bias"] if "bias" in dense else s


# ----------------------------------------------------- sp public surface
def _window_spec(mesh: Mesh, axis_name: Optional[str]) -> str:
    if axis_name is None:
        axis_name = "sp" if "sp" in mesh.axis_names else mesh.axis_names[0]
    if axis_name not in mesh.axis_names:
        raise ValueError(f"axis {axis_name!r} not in mesh {mesh.axis_names}")
    return axis_name


def _check_backend(mesh: Mesh, backend: Optional[str]) -> Optional[str]:
    """An explicit non-xla ``backend`` (the chip tools' pallas oracles
    run on 1-device meshes) must not be silently ignored — and GSPMD
    cannot partition an opaque pallas call over a >1-device mesh, so
    refuse loudly there instead of tracing something wrong."""
    if backend in (None, "xla"):
        return backend
    if mesh.devices.size > 1:
        raise ValueError(
            f"backend={backend!r} (a pallas kernel path) cannot be "
            f"GSPMD-partitioned over the {mesh.devices.size}-device mesh; "
            "multi-device sp launches use the xla scan (backend=None)")
    return backend


def sp_generate(g_params: dict, z: jnp.ndarray, mesh: Mesh, *,
                axis_name: Optional[str] = None, slope: float = 0.2,
                activation: str = "sigmoid", ln_eps: float = 1e-3,
                backend: Optional[str] = None,
                microbatches=None, manual=None, tp_axis=None,
                remat=None, check_vma=None) -> jnp.ndarray:
    """Window-sharded generator synthesis: the plain forward launched
    with ``z`` (and the output) sharded (B, W@sp, F) over the mesh.
    Long-window memory still divides across devices — the layout is
    GSPMD's, not a hand schedule.  ``backend="pallas"`` runs the fused
    kernels (1-device meshes — the chip oracles); the NAMED knobs of
    the retired manual pipeline (microbatches/manual/tp_axis/remat/
    check_vma) are accepted and ignored — anything else is a TypeError,
    so a typo'd live kwarg fails instead of silently defaulting."""
    del microbatches, manual, tp_axis, remat, check_vma
    from hfrep_tpu.parallel.rules import mesh_launch

    backend = _check_backend(mesh, backend)
    axis = _window_spec(mesh, axis_name)
    spec = P(None, axis, None)
    z = jax.device_put(z, NamedSharding(mesh, spec))
    fn = mesh_launch(
        lambda p, zz: generator_forward(p, zz, slope=slope,
                                        activation=activation, ln_eps=ln_eps,
                                        backend=backend),
        mesh, in_specs=(P(), spec), out_specs=spec)
    return fn(g_params, z)


def sp_critic(d_params: dict, x: jnp.ndarray, mesh: Mesh, *,
              axis_name: Optional[str] = None,
              backend: Optional[str] = None,
              microbatches=None, manual=None, tp_axis=None,
              remat=None, check_vma=None) -> jnp.ndarray:
    """Window-sharded critic scores: (B, W@sp, F) → replicated (B, 1).
    Retired-knob handling as :func:`sp_generate`."""
    del microbatches, manual, tp_axis, remat, check_vma
    from hfrep_tpu.parallel.rules import mesh_launch

    backend = _check_backend(mesh, backend)
    axis = _window_spec(mesh, axis_name)
    spec = P(None, axis, None)
    x = jax.device_put(x, NamedSharding(mesh, spec))
    fn = mesh_launch(lambda p, xx: critic_forward(p, xx, backend=backend),
                     mesh, in_specs=(P(), spec), out_specs=P())
    return fn(d_params, x)


def sp_lstm(kernel: jnp.ndarray, recurrent: jnp.ndarray, bias: jnp.ndarray,
            x: jnp.ndarray, mesh: Mesh, *, axis_name: Optional[str] = None,
            activation: str = "tanh",
            recurrent_activation: str = "sigmoid",
            backend: Optional[str] = None,
            microbatches=None, manual=None, tp_axis=None,
            remat=None, check_vma=None, chunk=None) -> jnp.ndarray:
    """One LSTM layer over (B, W@sp, F) → (B, W@sp, H).  Retired-knob
    handling as :func:`sp_generate` (``chunk`` was the manual
    pipeline's per-device time-block width)."""
    del microbatches, manual, tp_axis, remat, check_vma, chunk
    from hfrep_tpu.parallel.rules import mesh_launch

    backend = _check_backend(mesh, backend)
    axis = _window_spec(mesh, axis_name)
    spec = P(None, axis, None)
    params = {"kernel": kernel, "recurrent_kernel": recurrent, "bias": bias}
    fn = mesh_launch(
        lambda p, xx: _lstm_layer(p, xx, activation, recurrent_activation,
                                  backend=backend),
        mesh, in_specs=(P(), spec), out_specs=spec)
    return fn(params, jax.device_put(x, NamedSharding(mesh, spec)))


def sp_lstm_sharded_input(params: dict, x: jnp.ndarray, mesh: Mesh,
                          **kw) -> jnp.ndarray:
    """Convenience wrapper taking a KerasLSTM param dict and placing
    ``x`` window-sharded before the launch."""
    return sp_lstm(params["kernel"], params["recurrent_kernel"],
                   params["bias"], x, mesh, **kw)


def make_sp_train_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                       axis_name: Optional[str] = None,
                       microbatches: Optional[int] = None, jit: bool = True):
    """Window-sharded MTSS-WGAN-GP training — the unified mesh launch on
    an ``('sp',)`` mesh.  ``microbatches`` is accepted for source
    compatibility and ignored (no pipeline schedule exists to tune)."""
    del axis_name, microbatches
    from hfrep_tpu.parallel.rules import make_gan_train_step
    return make_gan_train_step(pair, tcfg, dataset, mesh, jit=jit)


def make_sp_multi_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                       axis_name: Optional[str] = None,
                       microbatches: Optional[int] = None, jit: bool = True):
    """``tcfg.steps_per_call`` window-sharded epochs as ONE program."""
    del axis_name, microbatches
    from hfrep_tpu.parallel.rules import make_gan_multi_step
    return make_gan_multi_step(pair, tcfg, dataset, mesh, jit=jit)


def sp_microbatch_plan(batch: int, n_dev: int, window: int = 168,
                       hidden: int = 100,
                       step_latency_s: float = 2e-6,
                       mxu_flops: float = 1e14) -> dict:
    """Analytic model of the retired pipeline's microbatch trade — kept
    because its conclusions (latency-bound at shipped shapes, the
    crossover at Bm* ≈ 1500 rows for Hp=128) remain the published
    explanation of WHY the manual sp pipeline never beat the plain step
    at these shapes, and ``tools/bench_sp_microbatch.py`` still anchors
    the chip-measured t_step it rests on (RESULTS.md round 4)."""
    from hfrep_tpu.ops.pallas_lstm import LANE

    hp = ((hidden + LANE - 1) // LANE) * LANE
    plans = []
    for m in range(1, batch + 1):
        if batch % m:
            continue
        bm = batch // m
        t_step = max(step_latency_s, 8.0 * bm * hp * hp / mxu_flops)
        t_single = window * max(step_latency_s,
                                8.0 * batch * hp * hp / mxu_flops)
        rel = (m + n_dev - 1) * (window / n_dev) * t_step / t_single
        plans.append({"microbatches": m, "rows": bm,
                      "supersteps": m + n_dev - 1,
                      "relative_time": rel})
    best = min(plans, key=lambda p: p["relative_time"])
    return {"plans": plans, "recommended": best["microbatches"]}
