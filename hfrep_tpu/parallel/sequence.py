"""Sequence (context) parallelism: pipelined LSTM over a window-sharded mesh.

The reference processes windows of 48-168 months sequentially on one
device (SURVEY §5.7 — no sequence parallelism exists to port).  For
long-window synthesis (W ≫ 168) a recurrent model cannot use ring
attention's trick of reordering blockwise softmax — the carry is a hard
sequential dependency.  The idiomatic TPU answer is *pipeline parallelism
over the time axis*:

* the window axis W is sharded into contiguous chunks, one per device on
  the ``sp`` mesh axis (device k owns timesteps [k·W/D, (k+1)·W/D));
* the batch is split into M microbatches; device k runs its chunk of
  microbatch m at pipeline superstep s = k + m, so after the k-step
  fill the pipe all D devices compute concurrently;
* the (h, c) carry crosses device boundaries via `lax.ppermute` over
  ICI — the only communication, 2·Bm·H floats per superstep.

Per-chunk compute follows :class:`hfrep_tpu.ops.lstm.KerasLSTM`: the
input projection for the whole local chunk is one big MXU matmul hoisted
out of the recurrence; only the (Bm, H) @ (H, 4H) recurrent matmul runs
per timestep.

Exactness: the pipeline computes the identical recurrence (same order,
same arithmetic) as the single-device scan — verified to float32
round-off in tests/test_sequence.py on an 8-device CPU mesh.

Backends: ``backend="xla"`` scans the fused cell; ``backend="pallas"``
dispatches each device's chunk to the carry-injection pallas kernels
(:func:`hfrep_tpu.ops.pallas_lstm.lstm_seq_carry` — nonzero (h0, c0) in,
final carry out, twice-differentiable).  The pallas path compiles only
on real TPU (interpret-mode pallas cannot propagate vma under
``shard_map(check_vma=True)``); on TPU the default ``lstm_backend='auto'``
resolves to it; in the full sp training composition the kernels are
3.8× the scan backend and bring the window-sharded step to ~80% of the
plain single-device step's speed (7.5 vs 6.0 ms/epoch at prod shape on
one chip; RESULTS.md "Sequence-parallel pallas chunks" — note the two
measurement traps documented there).  The kernels are oracle-tested against the scan twin on a
single chip (tests/test_pallas_lstm.py carry tests,
tools/chip_check_carry.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hfrep_tpu.parallel._compat import shard_map
from hfrep_tpu.ops.layers import ACTIVATIONS
from hfrep_tpu.ops.lstm import lstm_cell_step
from hfrep_tpu.utils.vma import match_vma


def _local_chunk_scan(xz_chunk: jnp.ndarray, carry: Tuple[jnp.ndarray, jnp.ndarray],
                      recurrent: jnp.ndarray, act, rec_act):
    """Scan one (Wl, Bm, 4H) pre-projected chunk from the given carry,
    using the same fused cell as the single-device :class:`KerasLSTM`."""

    def cell(c, xz_t):
        return lstm_cell_step(c, xz_t, recurrent=recurrent, act=act, rec_act=rec_act)

    return lax.scan(cell, carry, xz_chunk)


#: Time-block length for rematerialized chunk scans: sized so one
#: block's transient recompute residuals (~16 × (REMAT_BLOCK, Bm, 4Hp)
#: buffers in the GP second-order pass — the chip OOM dump's census) stay
#: ~100 MB while the stored per-block carries remain negligible.
REMAT_BLOCK = 512


def _local_chunk_scan_remat(y_chunk, kernel, bias, carry, recurrent,
                            act, rec_act, block: Optional[int] = None):
    """:func:`_local_chunk_scan` with remat over the TIME axis — and the
    input projection pulled INSIDE each block: the chunk scans in
    ``block``-timestep slices, each slice's ``y @ kernel + bias``
    projection AND recurrence wrapped together in one `jax.checkpoint`,
    so the stored residual per block is the raw (block, Bm, F/H) input —
    not the 4H-wide gate buffer (the difference is what XLA's memory
    report showed: a hoisted projection kept an 11.5 GiB gate tensor
    alive as a checkpoint input at W=37 632).  The backward (and the GP
    second-order backward-of-backward) recomputes one block at a time:
    O(Wl/block · Bm·H) carries + one transient block of residuals,
    instead of O(Wl · Bm·4Hp · ~16).  This is what lets remat move the
    memory wall even at sp=1, where superstep checkpointing alone
    degenerates (one superstep = the whole window — measured: W=37 632
    still wants 55 GiB without time blocking, 40 GiB with blocking but a
    hoisted projection, see RESULTS.md).  Identical recurrence,
    identical order — trajectory pinned in tests/test_sequence.py."""
    if block is None:
        block = REMAT_BLOCK          # late-bound so tests can shrink it
    gates = kernel.shape[1]

    def proj_scan(c, y_b):
        rows = y_b.shape[0] * y_b.shape[1]
        xz_b = (y_b.reshape(rows, y_b.shape[-1]) @ kernel
                + bias).reshape(*y_b.shape[:-1], gates)
        return _local_chunk_scan(xz_b, c, recurrent, act, rec_act)

    wl = y_chunk.shape[0]
    if wl <= block:
        return proj_scan(carry, y_chunk)
    nb = wl // block
    main = y_chunk[:nb * block].reshape(nb, block, *y_chunk.shape[1:])
    carry, hs = lax.scan(jax.checkpoint(proj_scan), carry, main)
    h_seq = hs.reshape(nb * block, *y_chunk.shape[1:-1], hs.shape[-1])
    if wl % block:
        carry, h_tail = proj_scan(carry, y_chunk[nb * block:])
        h_seq = jnp.concatenate([h_seq, h_tail], axis=0)
    return carry, h_seq


def _local_chunk_scan_tp(xz_chunk: jnp.ndarray,
                         carry: Tuple[jnp.ndarray, jnp.ndarray],
                         r_loc: jnp.ndarray, act, rec_act, tp_axis: str):
    """The tp twin of :func:`_local_chunk_scan`: the chunk's gates and
    (h, c) carry are this device's Hl = H/T unit slices, and the
    recurrence is the SAME shared cell the plain tp layer scans
    (:func:`hfrep_tpu.parallel.tensor.tp_chunk_scan` — per-step hidden
    all_gather against the local gate columns), so the sp-pipelined and
    standalone tp paths cannot drift arithmetically."""
    from hfrep_tpu.parallel.tensor import tp_chunk_scan

    return tp_chunk_scan(xz_chunk, carry, r_loc, act, rec_act, tp_axis)


def _resolve_axis(mesh: Mesh, axis_name: Optional[str]) -> str:
    """Default the sharded-window axis: the mesh's only axis for a 1-D
    mesh (dp- or sp-named — callers need not thread axis names), or an
    axis literally named ``"sp"`` on a multi-axis mesh."""
    if axis_name is not None:
        return axis_name
    if len(mesh.axis_names) == 1:
        return mesh.axis_names[0]
    if "sp" in mesh.axis_names:
        return "sp"
    raise ValueError(
        f"pass axis_name explicitly for multi-axis mesh {mesh.axis_names}")


def _sp_pipeline(layers, x: jnp.ndarray, mesh: Mesh, *,
                 axis_name: Optional[str] = None,
                 microbatches: Optional[int] = None,
                 activation: str = "tanh",
                 recurrent_activation: str = "sigmoid",
                 backend: str = "xla",
                 inters=None,
                 manual: bool = False,
                 tp_axis: Optional[str] = None,
                 remat: bool = False) -> jnp.ndarray:
    """N stacked LSTMs through ONE window-sharded pipeline pass.

    ``layers`` is a list of KerasLSTM param dicts ({kernel,
    recurrent_kernel, bias}); ``inters[i]`` is an optional *per-timestep*
    transform applied between layer i and i+1 (e.g. the generator's
    LayerNorm), given as a ``(fn, params)`` pair — ``fn(params, y)`` with
    ``params`` threaded through `shard_map` as a real operand (closure
    capture of arrays inside the manual-mesh body trips jax's
    mesh-consistency check when the pipeline is scanned over epochs).
    Per-timestep means position-independent, so applying it chunk-wise
    inside the pipeline computes exactly what applying it to the full
    sequence would.  Each superstep runs this device's chunk
    through every layer back-to-back (layer i+1's chunk input is layer
    i's chunk output, same device, same superstep) and hands ALL layers'
    (h, c) carries forward together — one pipeline fill/drain and one
    shard_map region for the whole stack, where per-layer passes pay
    both per layer.

    ``manual=True`` runs the pipeline *inside an enclosing*
    ``shard_map`` region (the dp×sp composed step,
    :mod:`hfrep_tpu.parallel.dp_sp`): ``x`` is then this device's
    full-window batch shard (replicated over the sp axis), the body
    slices its own window chunk by ``lax.axis_index(axis_name)``, and
    the return value is the LOCAL (B, W/D, H) chunk — the caller owns
    reassembly (masked psum for the generator, sliced-head psum for the
    critic; never all_gather — see :func:`sp_generate`).  The vma casts adapt automatically: loop carries are
    matched against the pre-projected chunk's actual variance
    (``match_vma``), which is {sp} standalone and {dp, sp} composed.

    ``tp_axis`` (manual mode only) additionally shards every layer's
    HIDDEN UNITS over that mesh axis, the
    :mod:`hfrep_tpu.parallel.tensor` layout composed into the pipeline:
    each device's chunk scan carries its (Bm, H/T) unit slices (carry
    handoffs ppermute the slices over ``axis_name`` — the T unit
    pipelines run the same schedule in lockstep), every timestep
    all_gathers the slices over ``tp_axis``
    (:func:`_local_chunk_scan_tp`), inter-layer transforms see the full
    width via a masked-psum reassembly per chunk, and the returned
    chunk is full-H, typed tp-*invariant* — so the sp callers
    (:func:`sp_generate` / :func:`sp_critic`) work unchanged on top.
    XLA-scan backend only (a per-step cross-chip gather is what the
    fused kernels cannot express).
    """
    axis_name = _resolve_axis(mesh, axis_name)
    n_dev = mesh.shape[axis_name]
    b, w, f = x.shape
    h_dims = [l["recurrent_kernel"].shape[0] for l in layers]
    n_tp = mesh.shape[tp_axis] if tp_axis is not None else 1
    if remat and tp_axis is not None:
        raise NotImplementedError(
            "sp_remat supports the sp and dp×sp meshes only: under tp the "
            "chunk scan all_gathers the hidden slices per timestep "
            "(_local_chunk_scan_tp) and is not time-blocked, so remat "
            "would silently keep the hoisted gate buffer it exists to "
            "eliminate — refuse instead of degrading")
    if tp_axis is not None:
        if not manual:
            raise ValueError("tp_axis requires manual mode (an enclosing "
                             "shard_map over the ('…', 'sp', 'tp') mesh)")
        if backend == "pallas":
            raise NotImplementedError(
                "the pipelined chunks run the XLA scan under tp_axis: the "
                "pallas kernels cannot express the per-timestep cross-chip "
                "all_gather of the hidden slices")
        from hfrep_tpu.parallel.tensor import _check_width
        for h in h_dims:
            _check_width(h, n_tp)
    m = n_dev if microbatches is None else microbatches
    if m < 1:
        raise ValueError(f"microbatches must be >= 1, got {m}")
    if b % m:
        raise ValueError(f"batch {b} not divisible by microbatches {m}")
    if w % n_dev:
        raise ValueError(f"window {w} not divisible by sp devices {n_dev}")
    bm = b // m
    n_lay = len(layers)
    inters = list(inters) if inters is not None else [None] * n_lay
    inter_fns = [i[0] if i is not None else None for i in inters]
    inter_params = [i[1] if i is not None else () for i in inters]
    act, rec_act = ACTIVATIONS[activation], ACTIVATIONS[recurrent_activation]

    use_kernel = backend == "pallas"
    if use_kernel:
        from hfrep_tpu.ops.pallas_lstm import (LANE, _supported,
                                               kernel_eligible,
                                               lstm_seq_carry,
                                               pad_keras_params)
        _supported(activation, recurrent_activation)
        if jax.default_backend() != "tpu":
            raise NotImplementedError(
                "sp_lstm(backend='pallas') needs a real TPU: interpret-mode "
                "pallas cannot propagate vma under shard_map(check_vma)")
        if x.dtype != jnp.float32:
            # a pallas backend request with an unsupported dtype must raise,
            # not silently run scan chunks; only the width gate below falls
            # back quietly.  (The framework's sp/dp×sp steps can't get here
            # — validate_sp_pair pins f32 before the backend resolves.)
            raise NotImplementedError("sp_lstm pallas backend runs f32")
        if not kernel_eligible("pallas", x.dtype, hidden=max(h_dims)):
            # measured VMEM ceiling (ops/pallas_lstm.py): oversized widths
            # take the scan chunks instead of OOMing in the carry adjoint
            use_kernel = False
    if use_kernel:
        hp = [((h + LANE - 1) // LANE) * LANE for h in h_dims]
        lay = []
        for l, h, hpi in zip(layers, h_dims, hp):
            k_p, r_p, b_p = pad_keras_params(l, h, hpi)
            lay.append({"kernel": k_p, "recurrent_kernel": r_p, "bias": b_p})
        act_name = activation if activation else "linear"
    else:
        hp = h_dims
        lay = list(layers)
    # Per-device gate/carry widths: the tp-sliced Hl when the hidden
    # units are sharded, the (possibly lane-padded) full width otherwise.
    wid = [h // n_tp for h in h_dims] if tp_axis is not None else hp

    fwd = [(k, k + 1) for k in range(n_dev - 1)]        # no wraparound: dev0 keeps zeros

    def per_device(lay, inter_params, x_local):
        # x_local: (B, Wl, F) — this device's time chunk for every row.
        wl = x_local.shape[1]
        k_idx = lax.axis_index(axis_name)
        if tp_axis is not None:
            # Composed width sharding: slice this tp rank's gate columns
            # out of every layer — the same shared layout helper the
            # plain tp path uses (parallel/tensor.py).
            from hfrep_tpu.parallel.tensor import _slice_gate_params

            t_tp = lax.axis_index(tp_axis)
            lay = [_slice_gate_params(l, t_tp, hl)
                   for l, hl in zip(lay, wid)]
        # Hoisted layer-0 input projection: one MXU matmul for the whole
        # chunk (padded-gate layout when the pallas kernels run it).
        # Deeper layers' projections run per superstep — their inputs
        # only exist once the previous layer's chunk has run.
        # EXCEPT under remat: the hoisted 4H-wide gate buffer would live
        # the whole backward as a checkpoint input (11.5 GiB at
        # W=37 632); the remat path feeds RAW features through and
        # projects inside each checkpointed time block
        # (_local_chunk_scan_remat).
        no_hoist = remat and not use_kernel and tp_axis is None
        if no_hoist:
            xz = jnp.swapaxes(x_local, 0, 1)            # (Wl, B, F) raw
            xz_mb = xz.reshape(wl, m, bm, f)
        else:
            g0 = 4 * wid[0]
            xz = (x_local.reshape(b * wl, f) @ lay[0]["kernel"]
                  + lay[0]["bias"]).reshape(b, wl, g0)
            xz = jnp.swapaxes(xz, 0, 1)                 # (Wl, B, 4Hp0)
            xz_mb = xz.reshape(wl, m, bm, g0)           # microbatch split

        # Cast the loop state to the variance the loop body will produce:
        # the pre-projected chunk carries the true vma ({sp} standalone,
        # {dp, sp} under the composed dp×sp step, plus {tp} when the
        # units are sharded), so matching against it keeps the scan's
        # carry-in/carry-out types equal in every mode.
        carry_reg = tuple(
            (match_vma(jnp.zeros((bm, hpi), xz.dtype), xz),
             match_vma(jnp.zeros((bm, hpi), xz.dtype), xz)) for hpi in wid)

        # Kernel mode: the pallas custom_vjp emits *varying* cotangents
        # (hand-computed per-device, never auto-psum'd), so a replicated
        # rec would give the AD-generated reverse scan a drec accumulator
        # whose carry-in (invariant zeros) mismatches its carry-out under
        # check_vma.  Casting rec to varying keeps the whole cotangent
        # chain varying; the pcast's own transpose then psums it back to
        # the replicated param exactly once at the boundary.
        recs = [(match_vma(l["recurrent_kernel"], xz) if use_kernel
                 else l["recurrent_kernel"]) for l in lay]

        def run_chunk(i, xz_s, h0, c0):
            """((h_fin, c_fin), h_seq) for one chunk: (Wl, Bm, 4Hp_i)
            pre-projected gates, or the RAW (Wl, Bm, F/H) layer input in
            remat mode (projection happens inside the time blocks)."""
            if use_kernel:
                h_seq, c_f = lstm_seq_carry(xz_s, recs[i], h0, c0, act_name)
                return (h_seq[-1], c_f), h_seq
            if tp_axis is not None:
                return _local_chunk_scan_tp(xz_s, (h0, c0), recs[i],
                                            act, rec_act, tp_axis)
            if remat:
                # time-blocked remat inside the chunk: without it the
                # superstep-level checkpoint still recomputes (and thus
                # transiently stores) the WHOLE chunk's residuals in each
                # backward — degenerate at sp=1 where Wl = W.
                return _local_chunk_scan_remat(
                    xz_s, lay[i]["kernel"], lay[i]["bias"], (h0, c0),
                    recs[i], act, rec_act)
            return _local_chunk_scan(xz_s, (h0, c0), recs[i], act, rec_act)

        # Scan-then-gather: every superstep emits its chunk's last-layer
        # hidden sequence; afterwards this device keeps exactly its m
        # active supersteps (s = k_idx + mb).  No output masking is
        # needed — device k is active precisely for s ∈ [k, k+m-1], so
        # (a) every gathered output comes from an active compute, and
        # (b) a carry consumed by an active step was always produced by
        # an active step at s-1 (k active at s ⟺ k-1 active at s-1);
        # inactive chunks produce bounded garbage that nothing selects.
        # This replaces the earlier fori_loop that scatter-updated a
        # (Wl, M, Bm, H) buffer under a `where` every superstep — two
        # full-buffer copies per superstep that AD then re-materialized.
        def superstep(carry, s):
            mb = s - k_idx                              # microbatch this device runs now
            active = jnp.logical_and(mb >= 0, mb < m)
            mb_c = jnp.clip(mb, 0, m - 1)
            seq = lax.dynamic_index_in_dim(xz_mb, mb_c, axis=1, keepdims=False)
            new_carry = []
            for i in range(n_lay):
                if i > 0:
                    # previous layer's real lanes → inter-layer transform
                    # → this layer's input projection (one (Wl·Bm)-row
                    # MXU matmul per chunk).  Under tp the chunk holds
                    # only this rank's unit slices: reassemble the full
                    # width by masked psum so the transform (LayerNorm
                    # normalizes over ALL H units) and the projection's
                    # H-contraction see the true sequence.
                    if tp_axis is not None:
                        from hfrep_tpu.parallel.tensor import _tp_assemble
                        y = _tp_assemble(seq, tp_axis)
                    else:
                        y = seq[..., :h_dims[i - 1]]
                    if inter_fns[i - 1] is not None:
                        y = inter_fns[i - 1](inter_params[i - 1], y)
                    if no_hoist:
                        seq = y          # raw input; blocks project it
                    else:
                        gi = 4 * wid[i]
                        seq = (y.reshape(wl * bm, h_dims[i - 1])
                               @ lay[i]["kernel"]
                               + lay[i]["bias"]).reshape(wl, bm, gi)
                h_in, c_in = carry[i]
                # Device 0 always starts microbatches from the zero carry.
                h0 = jnp.where(k_idx == 0, 0.0, 1.0) * h_in
                c0 = jnp.where(k_idx == 0, 0.0, 1.0) * c_in
                (h_f, c_f), seq = run_chunk(i, seq, h0, c0)
                # Inactive fill/drain chunks never feed a *selected*
                # output, but their carries must still be zeroed at the
                # handoff: with a non-saturating activation an unselected
                # garbage chain could otherwise compound across
                # supersteps to inf, and 0-cotangent × inf residuals
                # would NaN the real gradients.
                h_f = jnp.where(active, h_f, 0.0)
                c_f = jnp.where(active, c_f, 0.0)
                # Hand the finished carry to the next pipeline stage
                # (padding lanes ride along in kernel mode; their
                # outgoing recurrent weights are zero, so they never
                # touch real lanes).
                new_carry.append((lax.ppermute(h_f, axis_name, perm=fwd),
                                  lax.ppermute(c_f, axis_name, perm=fwd)))
            return tuple(new_carry), seq

        # remat: store only the superstep carries + emitted chunks and
        # re-run each body (projection, chunk scan, ppermute) inside the
        # backward — the scan-level residuals drop from ~16 (Wl, Bm, 4Hp)
        # buffers per GP-grad layer (the chip OOM dump's census) to the
        # carry chain, the same strategy the pallas kernels' adjoints use
        # natively.  The recomputed ppermutes re-run as collectives in
        # the backward; gradient values are unchanged (pinned vs the
        # plain step in tests/test_sequence.py).
        body = jax.checkpoint(superstep) if remat else superstep
        _, ys = lax.scan(body, carry_reg,
                         jnp.arange(m + n_dev - 1))     # (S, Wl, Bm, Hp[-1])
        out = ys[k_idx + jnp.arange(m)]                 # (M, Wl, Bm, Hp[-1])
        # (M, Wl, Bm, Hp) → (Wl, M, Bm, Hp) → (B, Wl, H)
        out = jnp.swapaxes(out, 0, 1).reshape(wl, b, wid[-1])
        out = jnp.swapaxes(out, 0, 1)
        if tp_axis is not None:
            # Full-H, typed tp-invariant — the sp callers' reassembly
            # and head logic work unchanged on top.
            from hfrep_tpu.parallel.tensor import _tp_assemble
            return _tp_assemble(out, tp_axis)
        return out[..., :h_dims[-1]]

    if manual:
        # Already inside a shard_map region: slice this device's window
        # chunk and run the body directly; the caller reassembles.
        wl = w // n_dev
        k_sp = lax.axis_index(axis_name)
        x_loc = lax.dynamic_slice_in_dim(x, k_sp * wl, wl, axis=1)
        return per_device(lay, inter_params, x_loc)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P(), P(None, axis_name, None)),
        out_specs=P(None, axis_name, None))
    return mapped(lay, inter_params, x)


def sp_lstm(kernel: jnp.ndarray, recurrent: jnp.ndarray, bias: jnp.ndarray,
            x: jnp.ndarray, mesh: Mesh, *, axis_name: Optional[str] = None,
            microbatches: Optional[int] = None,
            activation: str = "tanh",
            recurrent_activation: str = "sigmoid",
            backend: str = "xla") -> jnp.ndarray:
    """LSTM over (B, W, F) with W sharded across ``axis_name`` (defaults
    to the mesh's only axis).

    Returns the full hidden sequence (B, W, H), sharded over W the same
    way.  ``microbatches`` defaults to the number of ``sp`` devices
    (square pipeline — fill/drain overhead D/(M+D-1)).  Activation
    defaults mirror :class:`hfrep_tpu.ops.lstm.KerasLSTM` (tanh candidate
    transform, sigmoid gates); the reference's generators override the
    candidate transform with sigmoid (``GAN/MTSS_WGAN_GP.py:224-226``).

    ``backend="pallas"`` runs each chunk through the carry-injection
    pallas kernels (TPU-only; see module docstring).
    """
    return _sp_pipeline(
        [{"kernel": kernel, "recurrent_kernel": recurrent, "bias": bias}],
        x, mesh, axis_name=axis_name, microbatches=microbatches,
        activation=activation, recurrent_activation=recurrent_activation,
        backend=backend)


def sp_lstm2(p0: dict, p1: dict, x: jnp.ndarray, mesh: Mesh, *,
             inter=None, axis_name: Optional[str] = None,
             microbatches: Optional[int] = None,
             activation: str = "tanh",
             recurrent_activation: str = "sigmoid",
             backend: str = "xla",
             manual: bool = False,
             tp_axis: Optional[str] = None,
             remat: bool = False) -> jnp.ndarray:
    """Two stacked LSTMs fused into ONE pipeline pass (optionally with a
    per-timestep ``inter = (fn, params)`` transform between them, applied
    as ``fn(params, y)``) — the sp analogue of the single-device fused
    stack kernels (`ops/pallas_lstm_stack.py`): one fill/drain and one
    shard_map region instead of two of each.  ``manual=True`` runs
    inside an enclosing shard_map and returns the local window chunk
    (see :func:`_sp_pipeline`); ``tp_axis`` additionally shards the
    hidden units of both layers over that axis (manual mode only)."""
    return _sp_pipeline([p0, p1], x, mesh, inters=[inter, None],
                        axis_name=axis_name, microbatches=microbatches,
                        activation=activation,
                        recurrent_activation=recurrent_activation,
                        backend=backend, manual=manual, tp_axis=tp_axis,
                        remat=remat)


def sp_microbatch_plan(batch: int, n_dev: int, window: int = 168,
                       hidden: int = 100,
                       step_latency_s: float = 2e-6,
                       mxu_flops: float = 1e14) -> dict:
    """Analytic model of the microbatch count's two competing effects —
    the M-vs-Bm trade the round-3 numbers (measured at D=1, where no
    pipeline exists) do not constrain.

    Critical path: S = M + D − 1 supersteps of W/D recurrence timesteps,
    each costing ``t_step(Bm) = max(t_lat, 8·Bm·Hp² / mxu_flops)`` with
    Bm = B/M rows.  Relative to the single-device scan (W steps at B
    rows):

    * **latency-bound** (t_lat dominates — true for every shape this
      framework ships: at Hp=128, Bm=32 the matmul is ~21 ns against
      ~2 µs of per-step latency): time ∝ S·W/D, so SMALL M wins — M=1
      is latency-*parity* with the single device while cutting per-device
      window state D×.  In this regime sequence parallelism is a memory/
      capacity play, not a throughput play, and the pipeline 'utilization'
      M/(M+D−1) is the wrong metric to optimize.
    * **work-bound** (huge Bm·Hp²): time ∝ S·(W/D)·Bm ∝ (M+D−1)/M, so
      LARGE M wins, approaching D× speedup — the classical pipeline
      regime.  The crossover Bm* = t_lat·mxu_flops/(8·Hp²) sits at
      ~1500 rows for Hp=128: far above any realistic batch here, which
      is why the recommendation is latency-regime M unless hidden is
      scaled into the thousands.

    Returns per-M predictions (supersteps, Bm, predicted time relative
    to the single-device scan) and the recommended M.  The model's core
    assumption — t_step flat in Bm at these shapes — is validated on
    chip by ``tools/bench_sp_microbatch.py`` (RESULTS.md round 4).
    The pipeline's DEFAULT stays M = D (every published number used it);
    this planner is advisory for pod runs.
    """
    from hfrep_tpu.ops.pallas_lstm import LANE

    hp = ((hidden + LANE - 1) // LANE) * LANE
    plans = []
    for m in range(1, batch + 1):
        if batch % m:
            continue
        bm = batch // m
        t_step = max(step_latency_s, 8.0 * bm * hp * hp / mxu_flops)
        t_single = window * max(step_latency_s, 8.0 * batch * hp * hp / mxu_flops)
        rel = (m + n_dev - 1) * (window / n_dev) * t_step / t_single
        plans.append({"microbatches": m, "rows": bm,
                      "supersteps": m + n_dev - 1,
                      "relative_time": rel})
    best = min(plans, key=lambda p: p["relative_time"])
    return {"plans": plans, "recommended": best["microbatches"]}


def validate_sp_pair(pair) -> None:
    """The sp modules mirror the flagship LSTMGenerator/LSTMFlatCritic
    param trees and run f32 — shared precondition of the standalone sp
    step and the composed dp×sp step (:mod:`hfrep_tpu.parallel.dp_sp`)."""
    if pair.family != "mtss_wgan_gp":
        raise ValueError(f"sequence-parallel step supports the "
                         f"mtss_wgan_gp family, got {pair.family!r}")
    if (pair.generator.dtype or jnp.float32) != jnp.float32:
        raise NotImplementedError(
            "sequence-parallel step runs f32; configure dtype=float32")


def make_sp_train_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                       axis_name: Optional[str] = None,
                       microbatches: Optional[int] = None, jit: bool = True):
    """Sequence-parallel MTSS-WGAN-GP training: the full epoch (n_critic
    GP critic updates + generator update) with the window axis sharded.

    Long-window training, not just synthesis: every generator/critic
    forward — including the gradient penalty's input-grad and the
    second-order path through it — runs the pipelined window-sharded
    recurrences (:func:`sp_generate` / :func:`sp_critic`); AD transposes
    the ppermute carry handoffs and the psum'd critic head
    automatically.  All other step semantics (sampling streams, critic
    loop, optimizer updates) are shared verbatim with the single-device
    step via ``make_train_step(apply_fns=...)``, so a moderate-W sp run
    is trajectory-comparable to the plain step (tests/test_sequence.py).

    Requires the flagship ``mtss_wgan_gp`` family (the sp modules mirror
    its LSTMGenerator / LSTMFlatCritic trees).
    """
    from hfrep_tpu.train.steps import make_train_step

    axis_name = _resolve_axis(mesh, axis_name)
    validate_sp_pair(pair)
    if microbatches is None:
        # config-driven M (TrainConfig.sp_microbatches; the measured
        # recommendation at shipped shapes is M=1 — sp_microbatch_plan);
        # an explicit kwarg wins.
        microbatches = tcfg.sp_microbatches
    # Mirror the dp×sp builder's build-time checks (dp_sp.py:87-103) so a
    # bad M refuses here rather than on the first call inside _sp_pipeline.
    n_sp = mesh.shape[axis_name]
    m_eff = _effective_sp_microbatches(mesh, axis_name, tcfg, microbatches)
    if m_eff < 1:
        raise ValueError(f"sp_microbatches must be >= 1, got {m_eff}")
    if tcfg.batch_size % m_eff:
        raise ValueError(
            f"batch {tcfg.batch_size} not divisible by sp_microbatches="
            f"{m_eff}" + ("" if microbatches is not None else
                          " (the pipeline's default M = sp devices)"))
    if dataset.shape[1] % n_sp:
        raise ValueError(
            f"window {dataset.shape[1]} not divisible by sp={n_sp} devices")
    slope = pair.generator.slope

    # Same resolution/validation as the plain step: 'auto' → pallas on a
    # real TPU, xla elsewhere; anything else raises.
    from hfrep_tpu.train.steps import resolve_lstm_backend
    backend = resolve_lstm_backend(tcfg.lstm_backend)
    # TrainConfig.sp_remat: superstep rematerialization for long-window
    # runs near the HBM wall (config.py; only meaningful on the scan
    # backend — the pallas kernels' adjoints already recompute).
    remat = tcfg.sp_remat
    g_apply = lambda p, z: sp_generate(p, z, mesh, axis_name=axis_name,
                                       activation="sigmoid", slope=slope,
                                       microbatches=microbatches,
                                       backend=backend, remat=remat)
    d_apply = lambda p, x: sp_critic(p, x, mesh, axis_name=axis_name,
                                     microbatches=microbatches,
                                     backend=backend, remat=remat)
    step = make_train_step(pair, tcfg, dataset, apply_fns=(g_apply, d_apply))
    if not jit:
        return step
    from hfrep_tpu.obs import instrument_launch
    # sp_microbatches passed explicitly: the telemetry must report the
    # effective M (kwarg > config > one-per-device), not whatever
    # tcfg.sp_microbatches happens to hold — a microbatch sweep's points
    # would otherwise all log the same value.
    return instrument_launch(_jit_replicated_out(step, mesh),
                             "sp_train_step", mesh=mesh, tcfg=tcfg, sp=True,
                             sp_microbatches=m_eff)


def make_sp_multi_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                       axis_name: Optional[str] = None,
                       microbatches: Optional[int] = None, jit: bool = True):
    """``fn(state, key) -> (state, stacked_metrics)``:
    ``tcfg.steps_per_call`` sequence-parallel epochs scanned into ONE
    compiled program — the sp twin of
    :func:`hfrep_tpu.train.steps.make_multi_step` and the launch shape
    real sp training should use.  Measured on chip (RESULTS.md): a
    single-epoch dispatch pays ~1 s of fixed per-dispatch overhead
    through the tunneled runtime, so one-epoch-at-a-time timing
    overstates the sp program's cost by ~6×; 50-epoch blocks amortize it
    exactly as the plain trainer's ``steps_per_call`` does."""
    from hfrep_tpu.train.steps import make_multi_step

    step = make_sp_train_step(pair, tcfg, dataset, mesh,
                              axis_name=axis_name,
                              microbatches=microbatches, jit=False)
    multi = make_multi_step(pair, tcfg, dataset, jit=False, step=step)
    if not jit:
        return multi
    # telemetry hook — the shared build-time contract (obs disabled ⇒
    # the raw jitted step back, zero wrapper frames)
    from hfrep_tpu.obs import instrument_launch
    m_eff = _effective_sp_microbatches(
        mesh, _resolve_axis(mesh, axis_name), tcfg, microbatches)
    return instrument_launch(_jit_replicated_out(multi, mesh),
                             "sp_multi_step", mesh=mesh, tcfg=tcfg, sp=True,
                             sp_microbatches=m_eff)


def _effective_sp_microbatches(mesh: Mesh, axis_name: str, tcfg,
                               microbatches: Optional[int]) -> int:
    """The M the sp pipeline actually runs: explicit kwarg beats
    ``TrainConfig.sp_microbatches`` beats one microbatch per sp device.
    Both sp builders and their telemetry attrs resolve through here so
    a sweep's ``parallel_build`` events report the swept value."""
    if microbatches is None:
        microbatches = tcfg.sp_microbatches
    return mesh.shape[axis_name] if microbatches is None else microbatches


def _jit_replicated_out(fn, mesh: Mesh):
    """jit with the (state, metrics) outputs pinned REPLICATED over the
    mesh.  The sp step's state is logically replicated (every update is
    computed from window-summed gradients), but an unconstrained jit
    lets GSPMD pick output layouts, and with window-sharded
    intermediates it may leave param leaves sharded — harmless on one
    host, but on a multi-host mesh the trainer's checkpoint
    `device_get` then faces non-addressable arrays.  Pinning P() makes
    the replication a compiled fact.  Inputs are pinned identically so
    the donated state's layout always matches the output it aliases."""
    rep = NamedSharding(mesh, P())
    return jax.jit(fn, donate_argnums=(0,),
                   in_shardings=(rep, rep), out_shardings=(rep, rep))


def sp_lstm_sharded_input(params: dict, x: jnp.ndarray, mesh: Mesh,
                          **kw) -> jnp.ndarray:
    """Convenience wrapper taking a KerasLSTM param dict
    ({kernel, recurrent_kernel, bias}) and placing ``x`` window-sharded
    on the mesh before the pipelined scan."""
    axis = kw.get("axis_name", "sp")
    sharding = NamedSharding(mesh, P(None, axis, None))
    x = jax.device_put(x, sharding)
    return sp_lstm(params["kernel"], params["recurrent_kernel"], params["bias"],
                   x, mesh, **kw)


def _sp_ln(p: dict, v: jnp.ndarray, eps: float) -> jnp.ndarray:
    """LayerNorm between the pipelined recurrences — the same
    :class:`~hfrep_tpu.ops.layers.KerasLayerNorm` module the
    single-device generator runs, so the two paths cannot drift.
    Deliberately NOT jitted: it executes inside the fused pipeline's
    `shard_map` body (a Manual-mesh context), where an inner jit's
    sharding plumbing raises a mesh-consistency error under `lax.scan`
    tracing; as plain traced ops it inlines and partitions per-timestep
    with zero communication."""
    from hfrep_tpu.ops.layers import KerasLayerNorm

    return KerasLayerNorm(epsilon=eps).apply({"params": p}, v)


def _sp_head_impl(g_params: dict, v: jnp.ndarray, slope: float, eps: float) -> jnp.ndarray:
    """LeakyReLU → LN → Dense tail of the generator — every op is
    per-timestep, so it applies identically to a full sequence (GSPMD
    path) or to one device's window chunk (manual dp×sp path, where an
    inner jit would trip the manual-mesh consistency check — see
    `_sp_ln`)."""
    from hfrep_tpu.ops.layers import KerasDense, KerasLayerNorm, leaky_relu

    v = leaky_relu(v, slope)
    v = KerasLayerNorm(epsilon=eps).apply(
        {"params": g_params["KerasLayerNorm_1"]}, v)
    features = g_params["KerasDense_0"]["Dense_0"]["kernel"].shape[1]
    return KerasDense(features).apply({"params": g_params["KerasDense_0"]}, v)


_sp_head = jax.jit(_sp_head_impl, static_argnames=("slope", "eps"))


def sp_critic(d_params: dict, x: jnp.ndarray, mesh: Mesh, *,
              axis_name: Optional[str] = None,
              microbatches: Optional[int] = None,
              backend: str = "xla",
              manual: bool = False,
              tp_axis: Optional[str] = None,
              remat: bool = False) -> jnp.ndarray:
    """The MTSS-WGAN-GP critic (LSTM → LSTM → Flatten → Dense(1),
    :class:`hfrep_tpu.models.discriminators.LSTMFlatCritic`) with the
    window axis sharded — (B, W, F) → (B, 1) scores.

    Both recurrences run in ONE fused pipeline pass (:func:`sp_lstm2` —
    layer 1's chunk consumes layer 0's chunk in the same superstep, both
    carry pairs ppermute together); the flattened (W·H → 1) head is a
    window-sharded contraction: each device dots its local (B, Wl, H)
    chunk with its Wl·H slice of the Dense kernel and a single `psum`
    over ``axis_name`` completes the reduction — the only collective
    beyond the carry handoffs.  Differentiable end to end
    (ppermute/psum transposes), which is what sequence-parallel WGAN-GP
    *training* needs; exactness and gradient tests in
    tests/test_sequence.py.

    ``manual=True`` (the dp×sp composed step): ``x`` is the device's
    full-window batch shard inside an enclosing shard_map; the pipeline
    returns the local chunk and the head dots it with this device's
    W/D-slice of the flatten-Dense kernel before the same psum.
    ``tp_axis`` additionally shards the recurrences' hidden units over
    that axis (the pipeline's chunks come back full-H tp-invariant, so
    the head below is unchanged — dp×sp×tp composition,
    :mod:`hfrep_tpu.parallel.dp_sp_tp`).
    """
    axis_name = _resolve_axis(mesh, axis_name)
    # both recurrences in ONE fused pipeline pass (see sp_lstm2)
    h2 = sp_lstm2(d_params["KerasLSTM_0"], d_params["KerasLSTM_1"], x, mesh,
                  axis_name=axis_name, microbatches=microbatches,
                  backend=backend, manual=manual, tp_axis=tp_axis,
                  remat=remat)

    dense = d_params["KerasDense_0"]["Dense_0"]
    w = x.shape[1]
    h = h2.shape[-1]
    kernel_w = dense["kernel"].reshape(w, h, -1)     # (W, H, 1): shardable by W

    def local_head(h_local, k_local):
        bb, wl, hh = h_local.shape
        part = h_local.reshape(bb, wl * hh) @ k_local.reshape(wl * hh, -1)
        return lax.psum(part, axis_name)

    if manual:
        wl = w // mesh.shape[axis_name]
        k_local = lax.dynamic_slice_in_dim(
            kernel_w, lax.axis_index(axis_name) * wl, wl, axis=0)
        scores = local_head(h2, k_local)
    else:
        scores = shard_map(
            local_head, mesh=mesh,
            in_specs=(P(None, axis_name, None), P(axis_name, None, None)),
            out_specs=P())(h2, kernel_w)
    if "bias" in dense:
        scores = scores + dense["bias"]
    return scores


def sp_generate(g_params: dict, z: jnp.ndarray, mesh: Mesh, *,
                axis_name: Optional[str] = None, slope: float = 0.2,
                activation: str = "sigmoid",
                ln_eps: float = 1e-3,
                microbatches: Optional[int] = None,
                backend: str = "xla",
                manual: bool = False,
                tp_axis: Optional[str] = None,
                remat: bool = False) -> jnp.ndarray:
    """The FULL MTSS generator (LSTM → LN → LSTM → LeakyReLU → LN →
    Dense, :class:`hfrep_tpu.models.generators.LSTMGenerator`) with the
    window axis sharded over ``axis_name`` — long-window synthesis
    (W ≫ 168) on a mesh.

    Both recurrences AND the inter-layer LayerNorm run in ONE fused
    pipeline pass (:func:`sp_lstm2`): the LN executes chunk-wise inside
    the shard_map body, with its params threaded through as a real
    operand (see `_sp_ln`'s no-inner-jit note); only the head layers
    after the second LSTM run outside under GSPMD.  The (h, c) ppermutes
    of the two LSTMs are the only ICI traffic.  ``g_params`` is the
    LSTMGenerator tree (``KerasLSTM_0/1``, ``KerasLayerNorm_0/1``,
    ``KerasDense_0``); output matches the single-device
    ``generator.apply`` to f32 round-off (tests/test_sequence.py).

    ``manual=True`` (the dp×sp composed step, inside an enclosing
    shard_map): the head runs un-jitted on the local chunk (its ops are
    all per-timestep), then the full (B, W, F) windows are reassembled
    by a masked ``psum`` — each device scatters its chunk into a zeros
    buffer at its offset and the sum concatenates the disjoint chunks.
    Deliberately NOT ``all_gather``: the vma type system types a
    gather's output *varying* over ``axis_name`` even though the values
    agree, which would (a) leak spurious sp-variance into every
    downstream loss/carry type and (b) hide from AD that the critic's
    later chunk-slice needs its transpose-psum — the masked psum's
    output is typed *invariant*, making both exact automatically (the
    gradient-penalty note in :func:`hfrep_tpu.train.steps.gradient_penalty`).
    Costs ~2× a gather's ICI bytes on a (B, W, F) buffer — noise next to
    the pipeline's compute.
    """
    axis_name = _resolve_axis(mesh, axis_name)
    if manual:
        x = sp_lstm2(g_params["KerasLSTM_0"], g_params["KerasLSTM_1"], z, mesh,
                     inter=(lambda p, v: _sp_ln(p, v, ln_eps),
                            g_params["KerasLayerNorm_0"]),
                     axis_name=axis_name, microbatches=microbatches,
                     activation=activation,
                     backend=backend, manual=True, tp_axis=tp_axis,
                     remat=remat)
        y = _sp_head_impl(g_params, x, slope, ln_eps)   # chunk-wise head
        wl = y.shape[1]
        buf = jnp.zeros((y.shape[0], wl * mesh.shape[axis_name], y.shape[2]),
                        y.dtype)
        buf = lax.dynamic_update_slice_in_dim(
            match_vma(buf, y), y, lax.axis_index(axis_name) * wl, axis=1)
        return lax.psum(buf, axis_name)
    sharding = NamedSharding(mesh, P(None, axis_name, None))
    z = jax.device_put(z, sharding)

    # both recurrences + the inter-layer LayerNorm in ONE fused pipeline
    # pass: LN is per-timestep, so applying it chunk-wise inside the
    # pipeline computes exactly the full-sequence result (see sp_lstm2)
    x = sp_lstm2(g_params["KerasLSTM_0"], g_params["KerasLSTM_1"], z, mesh,
                 inter=(lambda p, v: _sp_ln(p, v, ln_eps),
                        g_params["KerasLayerNorm_0"]),
                 axis_name=axis_name, microbatches=microbatches,
                 activation=activation, backend=backend, remat=remat)
    return _sp_head(g_params, x, slope, ln_eps)
