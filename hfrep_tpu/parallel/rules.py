"""One mesh to rule the launch paths: partition-rule-driven
``NamedSharding``/``pjit`` (ROADMAP item 1).

The round-11 state of the parallel package was seven hand-built
``shard_map`` launch paths (~2,150 LoC), several dead on the pinned
runtime (jax 0.4.37 has no ``jax.shard_map`` — the committed
``HF005_KILL_LIST.md``), each re-implementing per-device sampling, key
folding, gradient normalization and replication proofs by hand.  This
module replaces the per-path plumbing with the rule-driven GSPMD idiom
(SNIPPETS.md [2] ``match_partition_rules`` / ``make_shard_and_gather_fns``;
the approach Podracer-style fabrics, arxiv 2104.06272, and TPU GAN
training, arxiv 2111.04628, use to scale one program across chips):

* **one mesh** — :class:`MeshSpec` declares ``dp``/``sp``/``tp``/``pp``
  as axis *sizes*; :func:`build_mesh` turns it into the single
  :class:`jax.sharding.Mesh` every launch shares;
* **regex partition rules** — :func:`match_partition_rules` maps
  ``(pattern, PartitionSpec)`` rules over the '/'-joined param-pytree
  paths (scalar leaves replicated, unmatched params a hard error naming
  the offending path); axis names a mesh does not carry are stripped,
  so ONE rule set serves every mesh shape;
* **pjit** — :func:`mesh_launch` jits a *global-semantics* program with
  ``in_shardings``/``out_shardings`` derived from those rules plus
  data/batch specs.  The traced jaxpr is the single-device program —
  GSPMD partitions it — so a 1×1-mesh launch is jaxpr- AND
  trajectory-identical to the plain jit by construction, and an N-device
  launch differs only by collective reduction order (f32 round-off;
  pinned in tests/test_mesh_rules.py and the MULTICHIP dry run);
* **shard/gather fns** — :func:`make_shard_and_gather_fns` /
  :func:`shard_put` move host data (the padded (K+1)×L dataset cube, GAN
  train states) onto the mesh once, so steady dispatches copy nothing.

This runs on every JAX version (no ``shard_map`` dependency).  The
sampling semantics are the old *controlled* mode, now the only mode:
the global batch is drawn exactly as the single-device program draws it
and sharding constraints hand GSPMD the layout — dp=N follows the
single-device trajectory at the same global batch by construction,
which is what every trajectory pin in this repo asserts.  Compile-cache
policy is untouched (chaos corpus entry 004 pins the 1.0 s persistent
XLA cache threshold as load-bearing).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Canonical axis order.  ``dp`` shards batch / lane-grid rows, ``sp``
#: the window (time) axis, ``tp`` hidden units (gate columns), ``pp``
#: the stack depth (layer_pipeline.py — the one remaining manual path).
AXES = ("dp", "sp", "tp", "pp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh: axis sizes, not separate modules.

    ``MeshSpec(dp=8)`` is the 1-D data-parallel mesh; ``MeshSpec(dp=2,
    sp=4)`` the composed 2-D mesh the old ``dp_sp.py`` hand-built; all
    sizes 1 is the single-device mesh (axes collapse to ``('dp',)`` so
    there is always one named axis to spec against)."""

    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1

    def __post_init__(self):
        for name in AXES:
            if getattr(self, name) < 1:
                raise ValueError(f"mesh axis sizes must be >= 1, got "
                                 f"{name}={getattr(self, name)}")

    @property
    def size(self) -> int:
        return self.dp * self.sp * self.tp * self.pp

    @property
    def axis_names(self) -> Tuple[str, ...]:
        names = tuple(n for n in AXES if getattr(self, n) > 1)
        return names or ("dp",)

    @property
    def axis_sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, n) for n in self.axis_names)

    def describe(self) -> dict:
        """JSON-safe manifest section (the ``mesh`` config annotation
        bench/bench_pp write into run.json — under ``config``, NOT the
        top-level ``mesh`` key, so history comparability keys stay
        continuous across the shard_map→pjit migration)."""
        return {"axes": {n: int(s) for n, s in
                         zip(self.axis_names, self.axis_sizes)},
                "devices": int(self.size), "unified": True}


def build_mesh(spec: MeshSpec = MeshSpec(),
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The ONE :class:`jax.sharding.Mesh` from a declarative spec."""
    devices = list(devices) if devices is not None else jax.devices()
    if spec.size > len(devices):
        raise ValueError(f"mesh spec {spec} wants {spec.size} devices but "
                         f"only {len(devices)} are present")
    arr = np.asarray(devices[:spec.size]).reshape(spec.axis_sizes)
    return Mesh(arr, spec.axis_names)


def mesh_spec(mesh: Optional[Mesh]) -> MeshSpec:
    """The :class:`MeshSpec` a mesh realizes (unknown axis names refuse —
    the trainer's name-based dispatch contract)."""
    if mesh is None:
        return MeshSpec()
    sizes = {}
    for name in mesh.axis_names:
        if name not in AXES:
            raise ValueError(f"mesh axis {name!r} not in {AXES}")
        sizes[name] = int(mesh.shape[name])
    return MeshSpec(**sizes)


# ------------------------------------------------------------ rule matching
def named_leaves(tree):
    """``[(path, leaf)]`` with '/'-joined human-readable paths — the
    names the regex rules match (``g_params/KerasLSTM_0/kernel``,
    ``g_opt/0/mu/KerasLSTM_0/recurrent_kernel``, …)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)

    def part(entry) -> str:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                return str(getattr(entry, attr))
        return str(entry)

    return [("/".join(part(e) for e in path), leaf) for path, leaf in flat]


def normalize_spec(spec: P, mesh: Mesh) -> P:
    """Strip axis names the mesh does not carry (size-1 axes are not in
    ``mesh.axis_names``), so one rule set serves every mesh shape."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    kept = [keep(e) for e in spec]
    while kept and kept[-1] is None:    # P(None, None) is NOT P(): trim
        kept.pop()
    return P(*kept)


def match_partition_rules(rules, tree, mesh: Optional[Mesh] = None):
    """Pytree of :class:`PartitionSpec` per ``rules`` over ``tree``.

    ``rules`` is a sequence of ``(regex, PartitionSpec)`` pairs matched
    (``re.search``) against each leaf's '/'-joined path, first match
    wins.  Scalar leaves (rank 0 or a single element) are always
    replicated — optimizer step counts never deserve an axis.  A leaf no
    rule matches is a HARD error naming the offending path: silence here
    is how a new param sneaks in unsharded/unreplicated by accident
    (SNIPPETS.md [2]'s contract, kept).  With ``mesh``, axis names the
    mesh lacks are stripped from every matched spec."""
    specs = []
    for name, leaf in named_leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is not None and (len(shape) == 0 or int(np.prod(shape)) <= 1):
            specs.append(P())
            continue
        for pattern, ps in rules:
            if re.search(pattern, name) is not None:
                specs.append(normalize_spec(ps, mesh) if mesh is not None
                             else ps)
                break
        else:
            raise ValueError(
                f"partition rule not found for param: {name!r} "
                f"(shape {shape}); every leaf must match a rule — add one "
                f"or extend the catch-all")
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, specs)


def tree_shardings(mesh: Mesh, specs):
    """PartitionSpec pytree (or prefix) → NamedSharding pytree (prefix).
    ``None`` entries mean replicated."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s if s is not None else P()),
        specs, is_leaf=lambda s: s is None or isinstance(s, P))


def _check_divisible(name: str, leaf, spec: P, mesh: Mesh) -> None:
    shape = getattr(leaf, "shape", ())
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        n = int(np.prod([mesh.shape[a] for a in axes]))
        if n > 1 and shape[dim] % n:
            raise ValueError(
                f"cannot shard {name!r}: dimension {dim} (size {shape[dim]}) "
                f"is not divisible by the {'×'.join(axes)}={n} mesh extent")


def _is_spec(s) -> bool:
    return s is None or isinstance(s, P)


def broadcast_specs(tree, specs):
    """Align ``specs`` — a single :class:`PartitionSpec` or a pytree
    *prefix* of them — to ``tree``'s exact structure (``None`` →
    replicated).  PartitionSpec is a tuple subclass, so every traversal
    here must treat it as a LEAF, never a container."""
    if _is_spec(specs):
        one = specs if specs is not None else P()
        return jax.tree_util.tree_map(lambda _: one, tree)
    return jax.tree_util.tree_map(
        lambda s, sub: jax.tree_util.tree_map(
            lambda _: s if s is not None else P(), sub),
        specs, tree, is_leaf=_is_spec)


def make_shard_and_gather_fns(mesh: Mesh, specs) -> Tuple[Callable, Callable]:
    """``(shard_fn, gather_fn)`` for host↔mesh movement (SNIPPETS.md [2]).

    ``shard_fn(tree)`` device_puts every leaf under its spec'd
    NamedSharding — ONE placement, after which steady pjit dispatches
    copy nothing (an uncommitted operand would be re-laid-out every
    call).  Divisibility is checked leaf-by-leaf with the offending
    path named.  ``gather_fn(tree)`` is the inverse: fully-addressable
    host numpy copies."""

    def shard_fn(tree):
        spec_tree = broadcast_specs(tree, specs)
        flat = named_leaves(tree)
        flat_specs = jax.tree_util.tree_flatten(spec_tree,
                                                is_leaf=_is_spec)[0]
        out = []
        for (name, leaf), spec in zip(flat, flat_specs):
            _check_divisible(name, leaf, spec, mesh)
            out.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
        treedef = jax.tree_util.tree_structure(tree)
        return jax.tree_util.tree_unflatten(treedef, out)

    def gather_fn(tree):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), tree)

    return shard_fn, gather_fn


def shard_put(tree, mesh: Mesh, specs):
    """One-shot :func:`make_shard_and_gather_fns` shard: place ``tree``
    on the mesh under ``specs`` (a PartitionSpec, or a pytree of them
    matching ``tree``)."""
    shard_fn, _ = make_shard_and_gather_fns(mesh, specs)
    return shard_fn(tree)


# ----------------------------------------------------------------- launch
def mesh_launch(fn, mesh: Mesh, in_specs, out_specs,
                donate_argnums: Tuple[int, ...] = (),
                static_argnums: Tuple[int, ...] = ()):
    """jit ``fn`` across ``mesh`` with rule/spec-derived shardings.

    ``in_specs``/``out_specs`` are pytrees (or prefixes) of
    :class:`PartitionSpec` aligned with ``fn``'s args/outputs; ``None``
    means replicated.  The function itself stays a GLOBAL program —
    GSPMD inserts every collective — so this is jaxpr-identical to
    ``jax.jit(fn)`` and the 1×1-mesh executable is the single-device
    executable (the pinned identity every migrated path rests on)."""
    return jax.jit(fn,
                   in_shardings=tree_shardings(mesh, in_specs),
                   out_shardings=tree_shardings(mesh, out_specs),
                   donate_argnums=donate_argnums,
                   static_argnums=static_argnums)


def data_constraint(mesh: Optional[Mesh]) -> Optional[Callable]:
    """The batch/window layout hint for sampled tensors inside a step:
    ``hint(x, batch_axis)`` constrains ``x``'s batch axis over ``dp``
    and (rank ≥ batch_axis+2, divisible) window axis over ``sp``.

    Returns ``None`` — the LITERAL identity, no constraint ops traced —
    when the mesh has no dp/sp extent to shard over, which is what makes
    the 1×1-mesh jaxpr identical to the single-device program."""
    if mesh is None:
        return None
    n_dp = int(mesh.shape["dp"]) if "dp" in mesh.axis_names else 1
    n_sp = int(mesh.shape["sp"]) if "sp" in mesh.axis_names else 1
    if n_dp <= 1 and n_sp <= 1:
        return None

    def hint(x, batch_axis: int = 0):
        entries = [None] * x.ndim
        if n_dp > 1:
            entries[batch_axis] = "dp"
        w_axis = batch_axis + 1
        if (n_sp > 1 and x.ndim > w_axis + 1
                and x.shape[w_axis] % n_sp == 0 and x.shape[w_axis] > 1):
            entries[w_axis] = "sp"
        if all(e is None for e in entries):
            return x
        # TWO constraints, deliberately: the replicated pin first BLOCKS
        # the sharded layout from propagating backward into the
        # producer.  That producer is usually jax.random — and on this
        # runtime (threefry_partitionable=False) a PARTITIONED threefry
        # computes DIFFERENT values per shard, which silently changes
        # the sample stream and every trajectory pin with it (measured:
        # normal() under a bare dp×sp constraint drifted by O(1)).  The
        # pin makes the random values the literal single-device values;
        # the second constraint then hands GSPMD the compute layout.
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*entries)))

    return hint


# ------------------------------------------------------------- GAN rules
#: Partition rules for the GAN train state (params + optimizer state —
#: optax trees mirror param paths, so one vocabulary covers both).
#: ``tp`` shards every LSTM layer's gate columns (kernel (F, 4H) and
#: recurrent_kernel (H, 4H) on their 4H axis, bias (4H,) on its only
#: axis) — the Megatron-style layout the old ``tensor.py`` hand-sliced
#: inside shard_map, now a LAYOUT declaration GSPMD lowers to the same
#: per-step hidden-state all_gather.  Everything else (Dense heads,
#: LayerNorms, dense-family stacks) replicates.  On a mesh without a
#: ``tp`` axis the tp names strip away and the whole state replicates —
#: the dp/sp story.
GAN_PARTITION_RULES: Tuple[Tuple[str, P], ...] = (
    (r"KerasLSTM_\d+/(kernel|recurrent_kernel)$", P(None, "tp")),
    (r"KerasLSTM_\d+/bias$", P("tp")),
    (r".*", P()),
)

#: THE lane-grid layout: every carry leaf leads with the lane grid's
#: dataset axis (``multi``) or lane axis (``lanes``) — shard it over
#: ``dp``.  The AE engine's chunk programs broadcast this spec as their
#: operand/carry prefix (``replication/engine.py::_lane_specs``); the
#: rule form below is the same declaration for per-leaf resolution
#: (scalars replicate via the matcher's guard — pinned against the
#: real engine carry in tests/test_mesh_rules.py).
AE_LANE_SPEC = P("dp")
AE_LANE_RULES: Tuple[Tuple[str, P], ...] = ((r".*", AE_LANE_SPEC),)


def gan_state_specs(state, mesh: Mesh):
    """Rule-resolved PartitionSpec pytree for a :class:`GanState`."""
    return match_partition_rules(GAN_PARTITION_RULES, state, mesh)


def _validate_gan_mesh(pair, tcfg, dataset, mesh: Mesh) -> MeshSpec:
    spec = mesh_spec(mesh)      # refuses unknown axis names
    if spec.pp > 1:
        raise ValueError(
            "pp is the layer_pipeline.py axis (manual schedule); the "
            "rule-driven mesh launch shards dp/sp/tp only")
    if spec.dp > 1 and tcfg.batch_size % spec.dp:
        raise ValueError(
            f"global batch {tcfg.batch_size} not divisible by dp={spec.dp}")
    if spec.sp > 1 and dataset.shape[1] % spec.sp:
        raise ValueError(
            f"window {dataset.shape[1]} not divisible by sp={spec.sp}")
    if spec.tp > 1:
        if pair.family != "mtss_wgan_gp":
            raise ValueError(
                f"tp (hidden-unit) sharding supports the mtss_wgan_gp "
                f"family's LSTM stacks, got {pair.family!r}")
        for h in {int(pair.generator.hidden), int(pair.discriminator.hidden)}:
            if h % spec.tp:
                raise ValueError(
                    f"hidden width {h} not divisible by tp={spec.tp} devices")
    return spec


def _launch_name(mesh: Mesh, kind: str) -> str:
    """Historical launch names, preserved: ``dp_multi_step``,
    ``sp_train_step``, ``dp_sp_multi_step``, … — the obs compile-span /
    dispatch-counter vocabulary stays continuous across the migration."""
    return f"{'_'.join(mesh.axis_names)}_{kind}"


def _resolve_mesh_backend(tcfg, mesh: Mesh):
    """GSPMD cannot partition an opaque pallas call, so a >1-device
    mesh must not trace the pallas kernels the single-device TPU step
    prefers: ``lstm_backend='auto'`` (a preference, not a demand)
    resolves to the partitionable XLA scan here, while an EXPLICIT
    ``'pallas'`` refuses loudly — the contract the retired tp path
    enforced with ``_validate_tp_backend``, kept.  1-device meshes
    (the single-chip bench, the chip oracles) keep whatever resolves."""
    if mesh.devices.size <= 1:
        return tcfg
    from hfrep_tpu.train.steps import resolve_lstm_backend
    if resolve_lstm_backend(tcfg.lstm_backend) != "pallas":
        return tcfg
    if tcfg.lstm_backend == "pallas":
        raise ValueError(
            "lstm_backend='pallas' cannot be GSPMD-partitioned over a "
            f"{mesh.devices.size}-device mesh; use 'auto' (resolves to "
            "the xla scan on multi-device meshes) or 'xla'")
    return dataclasses.replace(tcfg, lstm_backend="xla")


def _gan_step(pair, tcfg, dataset, mesh: Mesh, multi: bool):
    from hfrep_tpu.train.steps import make_multi_step, make_train_step

    _validate_gan_mesh(pair, tcfg, dataset, mesh)
    tcfg = _resolve_mesh_backend(tcfg, mesh)
    step = make_train_step(pair, tcfg, dataset,
                           shard_data=data_constraint(mesh))
    if multi:
        return make_multi_step(pair, tcfg, dataset, jit=False, step=step)
    return step


def gan_launch_specs(pair, tcfg, dataset, mesh: Mesh):
    """The state layout the rule-driven launch compiles against:
    everything replicated (one ``P()`` prefix) on dp/sp meshes — the
    state IS replicated there, pinned as a compiled fact so GSPMD can
    never leave a param leaf sharded at a multi-host checkpoint
    boundary (the old ``_jit_replicated_out`` lesson).  A tp mesh
    rule-resolves the real per-leaf layout over an abstract
    ``eval_shape`` of the state (build-time-cheap, nothing
    materializes); every leaf must match a rule — the hard-error
    contract.  The trainer promotes/checkpoints multi-host state
    against these SAME specs (pjit refuses committed args whose
    layout disagrees)."""
    if "tp" not in mesh.axis_names:
        return P()
    from hfrep_tpu.train.states import init_gan_state
    state_shape = jax.eval_shape(
        lambda: init_gan_state(
            jax.random.PRNGKey(0),
            _model_cfg_of(pair, dataset), tcfg, pair))
    return gan_state_specs(state_shape, mesh)


def _gan_launch(pair, tcfg, dataset, mesh: Mesh, kind: str, fn):
    from hfrep_tpu.obs import instrument_launch

    specs = gan_launch_specs(pair, tcfg, dataset, mesh)
    launched = mesh_launch(fn, mesh,
                           in_specs=(specs, P()),
                           out_specs=(specs, P()),
                           donate_argnums=(0,))
    return instrument_launch(launched, _launch_name(mesh, kind), mesh=mesh,
                             tcfg=tcfg)


def _model_cfg_of(pair, dataset):
    """Reconstruct the ModelConfig init needs from the pair + data —
    the builders take (pair, tcfg, dataset) like every launch factory
    before them, so the config is derived, not re-threaded.  Only the
    tp path needs it, and tp validation has already pinned the family
    to the LSTM stack (hidden is a real attribute there)."""
    from hfrep_tpu.config import ModelConfig
    return ModelConfig(family=pair.family,
                       window=int(dataset.shape[1]),
                       features=int(dataset.shape[2]),
                       hidden=int(pair.generator.hidden))


def make_gan_train_step(pair, tcfg, dataset, mesh: Mesh, *, jit: bool = True):
    """ONE epoch (n_critic critic updates + generator update) launched
    across ``mesh`` — the unified replacement for the seven hand-built
    single-epoch builders.  Global-stream sampling: the dp=N run follows
    the single-device trajectory at the same global batch and key (f32
    round-off on >1 device, bit-identical on a 1×1 mesh)."""
    step = _gan_step(pair, tcfg, dataset, mesh, multi=False)
    if not jit:
        return step
    return _gan_launch(pair, tcfg, dataset, mesh, "train_step", step)


def make_gan_multi_step(pair, tcfg, dataset, mesh: Mesh, *, jit: bool = True):
    """``tcfg.steps_per_call`` epochs scanned into ONE compiled program
    across ``mesh`` — the launch shape real training dispatches
    (per-dispatch amortization unchanged from the single-device
    multi-step)."""
    fn = _gan_step(pair, tcfg, dataset, mesh, multi=True)
    if not jit:
        return fn
    return _gan_launch(pair, tcfg, dataset, mesh, "multi_step", fn)


# ---------------------------------------------------------------- helpers
def lane_mesh(n_lanes: int,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A ``('dp',)`` mesh sized to the largest divisor of ``n_lanes``
    that fits the host — the convenience the sweep/walk-forward drives
    use to shard a (K+1)- or L-row lane grid without the caller doing
    divisor arithmetic.  ``n_lanes`` prime (or 1) degrades to a 1-device
    mesh (still the unified launch path, just unsharded)."""
    devices = list(devices) if devices is not None else jax.devices()
    n = max((d for d in range(1, min(n_lanes, len(devices)) + 1)
             if n_lanes % d == 0), default=1)
    return build_mesh(MeshSpec(dp=n), devices=devices)
