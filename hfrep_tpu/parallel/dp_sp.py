"""Composed data × sequence parallelism on one 2-D ``('dp', 'sp')`` mesh.

Round-3 state of the framework had two disjoint scaling stories: batch
sharding over a 1-D dp mesh (:mod:`hfrep_tpu.parallel.data_parallel`) and
window sharding over a 1-D sp mesh (:mod:`hfrep_tpu.parallel.sequence`).
A pod training a long-window MTSS-WGAN-GP wants BOTH — the window axis
pipelined over ``sp`` to fit/parallelize the recurrence, and the batch
sharded over ``dp`` so the remaining chips contribute throughput.  This
module composes them in ONE ``shard_map`` region over the 2-D mesh:

* **dp axis** — each dp row samples its own batch shard (i.i.d. folded
  keys, or controlled global sampling for trajectory tests); gradients
  are globally batch-mean normalized by the existing
  :func:`hfrep_tpu.train.steps._psum_if` vma machinery (AD's automatic
  psum over dp for standard paths, explicit pmean for varying
  custom-vjp leaves).
* **sp axis** — every generator/critic forward inside the step (and the
  gradient penalty's second-order path) runs the pipelined
  window-sharded recurrence in *manual* mode
  (:func:`hfrep_tpu.parallel.sequence._sp_pipeline` with
  ``manual=True``): each device slices its own window chunk, carries
  hop via ``ppermute``, the critic head psums over ``sp``, and the
  generator reassembles full windows by masked psum (typed
  sp-*invariant* — an all_gather's sp-varying output would poison every
  downstream loss type; see :func:`~hfrep_tpu.parallel.sequence.sp_generate`).
* **params/optimizer state** — replicated over both axes;
  ``check_vma=True`` proves replication is preserved at trace time.

The reference anchor is the training loop being scaled,
``GAN/MTSS_WGAN_GP.py:254-292`` — single-device, window ≤168.  Here
dp×sp at the same global batch follows the plain step's trajectory to
f32 round-off (``tests/test_dp_sp.py``, controlled sampling on a 2×4
virtual mesh), so scaling out is a layout change, not a semantics
change.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp
from jax.sharding import Mesh

from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair
from hfrep_tpu.parallel.sequence import (sp_critic, sp_generate,
                                         validate_sp_pair)


def _split_axes(mesh: Mesh, tp_axis=None) -> Tuple[str, str]:
    want = ("dp", "sp", "tp") if tp_axis is not None else ("dp", "sp")
    if tuple(mesh.axis_names) != want:
        raise ValueError(
            f"dp×sp{'×tp' if tp_axis is not None else ''} composition wants "
            f"a {want} mesh, got {mesh.axis_names}")
    return "dp", "sp"


def _make_inner(pair: GanPair, tcfg: TrainConfig, dataset: jnp.ndarray,
                mesh: Mesh, controlled_sampling: bool, tp_axis=None):
    """The per-device epoch step: plain-step semantics with manual-mode
    window-sharded apply fns, dp-axis gradient normalization.  The ONE
    home of the composed-mesh inner-step contract: ``tp_axis`` extends
    it to the 3-D ``('dp', 'sp', 'tp')`` mesh
    (:mod:`hfrep_tpu.parallel.dp_sp_tp`) with the hidden units
    additionally sharded inside every pipeline chunk (XLA-scan chunks —
    see the tp backend note in :mod:`hfrep_tpu.parallel.tensor`)."""
    from hfrep_tpu.train.steps import make_train_step, resolve_lstm_backend

    dp_axis, sp_axis = _split_axes(mesh, tp_axis)
    validate_sp_pair(pair)
    if tp_axis is not None:
        from hfrep_tpu.parallel.tensor import (_check_width,
                                               _validate_tp_backend)
        if tcfg.sp_remat:
            # build-time twin of _sp_pipeline's refusal: the tp chunk
            # scan is not time-blocked, so remat would silently degrade
            raise NotImplementedError(
                "sp_remat supports the sp and dp×sp meshes only, not the "
                "3-D dp×sp×tp composition (the per-timestep hidden-slice "
                "all_gather is not time-blocked)")
        _validate_tp_backend(tcfg)
        _check_width(pair.generator.hidden, mesh.shape[tp_axis])
        backend = "xla"
    else:
        backend = resolve_lstm_backend(tcfg.lstm_backend)
    n_dp = mesh.shape[dp_axis]
    n_sp = mesh.shape[sp_axis]
    if tcfg.batch_size % n_dp:
        raise ValueError(
            f"global batch {tcfg.batch_size} not divisible by dp={n_dp}")
    local_batch = tcfg.batch_size // n_dp
    if tcfg.sp_microbatches is None:
        if local_batch % n_sp:
            raise ValueError(
                f"per-dp-row batch {local_batch} not divisible by sp={n_sp} "
                "(the pipeline's default microbatch count)")
    elif tcfg.sp_microbatches < 1:
        raise ValueError(
            f"sp_microbatches must be >= 1, got {tcfg.sp_microbatches}")
    elif local_batch % tcfg.sp_microbatches:
        raise ValueError(
            f"per-dp-row batch {local_batch} not divisible by "
            f"sp_microbatches={tcfg.sp_microbatches}")
    if dataset.shape[1] % n_sp:
        raise ValueError(
            f"window {dataset.shape[1]} not divisible by sp={n_sp}")
    slope = pair.generator.slope
    g_apply = lambda p, z: sp_generate(p, z, mesh, axis_name=sp_axis,
                                       activation="sigmoid", slope=slope,
                                       microbatches=tcfg.sp_microbatches,
                                       backend=backend, manual=True,
                                       tp_axis=tp_axis,
                                       remat=tcfg.sp_remat)
    d_apply = lambda p, x: sp_critic(p, x, mesh, axis_name=sp_axis,
                                     microbatches=tcfg.sp_microbatches,
                                     backend=backend, manual=True,
                                     tp_axis=tp_axis,
                                     remat=tcfg.sp_remat)
    local_tcfg = dataclasses.replace(tcfg, batch_size=local_batch)
    return make_train_step(
        pair, local_tcfg, dataset, axis_name=dp_axis,
        sample_batch=tcfg.batch_size if controlled_sampling else None,
        apply_fns=(g_apply, d_apply))


def _wrap(inner, mesh: Mesh, controlled_sampling: bool, jit: bool,
          tp_axis=None):
    """The shared batch-parallel shard_map wrapper along the dp axis —
    on the composed meshes, check_vma additionally proves state
    replication over sp (and tp on the 3-D mesh)."""
    from hfrep_tpu.parallel.data_parallel import wrap_batch_parallel

    dp_axis, _ = _split_axes(mesh, tp_axis)
    return wrap_batch_parallel(inner, mesh, dp_axis, controlled_sampling, jit)


def make_dp_sp_train_step(pair: GanPair, tcfg: TrainConfig,
                          dataset: jnp.ndarray, mesh: Mesh, *,
                          controlled_sampling: bool = False,
                          jit: bool = True):
    """One dp×sp epoch: ``fn(state, key) -> (state, metrics)`` with state
    replicated over the 2-D mesh and metrics pmean'd over ``dp``.

    ``controlled_sampling=True`` draws the global batch identically on
    every device and shards by dp position — the run then consumes the
    exact sample stream of a single-device run at the same global batch
    (the dp trajectory-test pattern, composed with window sharding).
    """
    inner = _make_inner(pair, tcfg, dataset, mesh, controlled_sampling)
    return _instrument(_wrap(inner, mesh, controlled_sampling, jit),
                       "dp_sp_train_step", mesh, tcfg, jit)


def _instrument(fn, name: str, mesh: Mesh, tcfg: TrainConfig, jit: bool):
    """The launch paths' telemetry hook: build-time no-op (``fn``
    returned unchanged) when obs is disabled or the caller asked for the
    raw un-jitted step (composition builds must stay wrappable).
    Delegates to the one shared contract in ``hfrep_tpu.obs``."""
    from hfrep_tpu.obs import instrument_launch
    return instrument_launch(fn, name, mesh=mesh, tcfg=tcfg, jit=jit,
                             sp=True)


def make_dp_sp_multi_step(pair: GanPair, tcfg: TrainConfig,
                          dataset: jnp.ndarray, mesh: Mesh, *,
                          controlled_sampling: bool = False,
                          jit: bool = True):
    """``tcfg.steps_per_call`` dp×sp epochs scanned into ONE compiled
    program — the launch shape for real pod training (same per-dispatch
    amortization argument as :func:`make_sp_multi_step`; the trainer
    dispatches this from its ordinary block loop)."""
    from hfrep_tpu.train.steps import make_multi_step

    step = _make_inner(pair, tcfg, dataset, mesh, controlled_sampling)
    inner = make_multi_step(pair, tcfg, dataset, jit=False, step=step)
    return _instrument(_wrap(inner, mesh, controlled_sampling, jit),
                       "dp_sp_multi_step", mesh, tcfg, jit)
