"""Composed data × sequence parallelism — thin shim over the unified
mesh launch (:mod:`hfrep_tpu.parallel.rules`).

The one ``('dp', 'sp')`` mesh now carries both axes as sharding
constraints on the sampled batch (batch over ``dp``, window over
``sp``) of the SINGLE-DEVICE program; GSPMD derives the collectives the
old manual pipeline hand-wrote (ppermute carry handoffs, masked-psum
reassembly, vma replication proofs — see the git history).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import Mesh

from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair


def make_dp_sp_train_step(pair: GanPair, tcfg: TrainConfig,
                          dataset: jnp.ndarray, mesh: Mesh, *,
                          controlled_sampling: bool = False,
                          jit: bool = True):
    del controlled_sampling         # the mesh launch's one (stronger) mode
    from hfrep_tpu.parallel.rules import make_gan_train_step
    return make_gan_train_step(pair, tcfg, dataset, mesh, jit=jit)


def make_dp_sp_multi_step(pair: GanPair, tcfg: TrainConfig,
                          dataset: jnp.ndarray, mesh: Mesh, *,
                          controlled_sampling: bool = False,
                          jit: bool = True):
    del controlled_sampling
    from hfrep_tpu.parallel.rules import make_gan_multi_step
    return make_gan_multi_step(pair, tcfg, dataset, mesh, jit=jit)
