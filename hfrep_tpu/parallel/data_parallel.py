"""Data-parallel GAN training — thin shim over the unified mesh launch.

The hand-built ``shard_map`` path (per-device folded-key sampling,
vma-typed gradient normalization, ~100 LoC) is gone: a ``('dp',)`` mesh
now launches the SINGLE-DEVICE program under ``pjit`` with the batch
sharding-constrained over ``dp`` (:mod:`hfrep_tpu.parallel.rules`), so
the dp run follows the single-device sample stream and trajectory by
construction — what the old *controlled* mode simulated by hand, now
the only mode — and it runs on every JAX version.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import Mesh

from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair


def make_dp_multi_step(pair: GanPair, tcfg: TrainConfig, dataset: jnp.ndarray,
                       mesh: Mesh, controlled_sampling: bool = False):
    """``tcfg.steps_per_call`` data-parallel epochs as ONE compiled
    program.  ``controlled_sampling`` is accepted for source
    compatibility and ignored: the mesh launch always follows the
    single-device sample stream (the stronger guarantee)."""
    del controlled_sampling
    from hfrep_tpu.parallel.rules import make_gan_multi_step
    return make_gan_multi_step(pair, tcfg, dataset, mesh)
