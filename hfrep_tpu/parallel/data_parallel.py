"""Data-parallel GAN training over a 1-D mesh via `shard_map`.

Design (SURVEY §5.8): the global batch (reference: 32,
``GAN/MTSS_WGAN_GP.py:292``) is split evenly across the ``dp`` axis; each
device samples its own batch shard and noise with a per-device folded
PRNG key, computes local gradients, and the train step `pmean`s gradients
inside — so every device applies the identical update and parameter /
optimizer state stay replicated without any explicit broadcast.  Losses
are `pmean`'d for logging.  The window dataset (≤7 MB) is replicated;
sampling indices differ per device, which is exactly the reference's
i.i.d.-batch semantics at global-batch granularity.

Single-device equivalence: with mean-of-shard losses, pmean-of-gradients
equals the global-batch gradient, so dp=N at global batch B matches dp=1
at batch B in expectation (bitwise for the loss surface; batch membership
differs because each device draws its own indices).  This is tested on an
8-way virtual CPU mesh in ``tests/test_parallel.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair
from hfrep_tpu.train.states import GanState
from hfrep_tpu.train.steps import make_multi_step


def make_dp_multi_step(pair: GanPair, tcfg: TrainConfig, dataset: jnp.ndarray, mesh: Mesh):
    """Build the jitted data-parallel multi-epoch step.

    Returns ``fn(state, key) -> (state, metrics)`` where ``state`` is
    replicated over the mesh and ``metrics`` are global (pmean'd) with one
    entry per inner epoch.
    """
    (axis_name,) = mesh.axis_names
    n_dev = mesh.devices.size
    if tcfg.batch_size % n_dev:
        raise ValueError(
            f"global batch {tcfg.batch_size} not divisible by dp={n_dev}")
    local_tcfg = dataclasses.replace(tcfg, batch_size=tcfg.batch_size // n_dev)
    inner = make_multi_step(pair, local_tcfg, dataset, axis_name=axis_name, jit=False)

    def per_device(state: GanState, key: jax.Array) -> Tuple[GanState, dict]:
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
        state, metrics = inner(state, key)
        return state, lax.pmean(metrics, axis_name)

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P()),
        # The varying-manual-axis checker would demand pcast annotations in
        # every scan carry (LSTM cells, fori_loop); replication of the
        # outputs is guaranteed dynamically by the pmean'd gradients.
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0,))
