"""Data-parallel GAN training over a 1-D mesh via `shard_map`.

Design (SURVEY §5.8): the global batch (reference: 32,
``GAN/MTSS_WGAN_GP.py:292``) is split evenly across the ``dp`` axis; each
device samples its own batch shard and noise with a per-device folded
PRNG key and computes local gradients.  Under ``check_vma=True``'s type
system the backward pass cross-device-sums those gradients automatically
(the transpose of broadcasting replicated params into varying data is a
psum), so the train step only divides by the axis size
(``steps._psum_if``) — every device then applies the identical
global-batch-mean update and parameter / optimizer state stay replicated
without any explicit broadcast, a fact the static checker *proves* at
trace time.  Losses are `pmean`'d for logging.  The window dataset
(≤7 MB) is replicated; sampling indices differ per device, which is
exactly the reference's i.i.d.-batch semantics at global-batch
granularity.

Single-device equivalence: axis-normalized gradients of mean-of-shard
losses equal the global-batch gradient, so dp=N at global batch B
matches dp=1 at batch B in expectation — and *exactly* (to f32
round-off) under ``controlled_sampling=True``, which
``tests/test_parallel.py`` uses to assert full trajectory + final-params
equivalence on an 8-way virtual CPU mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from hfrep_tpu.parallel._compat import shard_map
from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair
from hfrep_tpu.train.states import GanState
from hfrep_tpu.train.steps import make_multi_step


def wrap_batch_parallel(inner, mesh: Mesh, batch_axis: str,
                        controlled_sampling: bool, jit: bool = True):
    """shard_map a replicated-state step over ``mesh``, batch-parallel
    along ``batch_axis``: i.i.d. mode folds the key by axis position so
    each row samples independently (controlled mode leaves the shared
    key — the inner step shards by axis index instead), metrics are
    pmean'd over the axis, and ``check_vma=True`` proves parameters and
    optimizer state stay replicated.  The single home of the dp sampling
    contract — used by both the 1-D dp trainer here and the composed
    dp×sp step (:mod:`hfrep_tpu.parallel.dp_sp`)."""

    def per_device(state: GanState, key: jax.Array) -> Tuple[GanState, dict]:
        if not controlled_sampling:
            key = jax.random.fold_in(key, lax.axis_index(batch_axis))
        state, metrics = inner(state, key)
        return state, lax.pmean(metrics, batch_axis)

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P()),
        check_vma=True,
    )
    return jax.jit(fn, donate_argnums=(0,)) if jit else fn


def make_dp_multi_step(pair: GanPair, tcfg: TrainConfig, dataset: jnp.ndarray,
                       mesh: Mesh, controlled_sampling: bool = False):
    """Build the jitted data-parallel multi-epoch step.

    Returns ``fn(state, key) -> (state, metrics)`` where ``state`` is
    replicated over the mesh and ``metrics`` are global (pmean'd) with one
    entry per inner epoch.

    ``controlled_sampling=True`` draws the *global* batch identically on
    every device (shared key) and feeds each device its shard — the dp
    run then follows the exact sample stream of a single-device run at
    the same global batch, making full trajectories comparable
    (``tests/test_parallel.py``).  Default is i.i.d. per-device sampling
    (key folded by mesh position): cheaper, same semantics at
    global-batch granularity.

    Static replication safety: ``check_vma=True`` — the checker proves at
    trace time that parameters and optimizer state stay replicated across
    the mesh (pmean'd gradients ⇒ invariant updates), with loop carries
    pre-cast to their true variance (:mod:`hfrep_tpu.utils.vma`).
    """
    (axis_name,) = mesh.axis_names
    n_dev = mesh.devices.size
    if tcfg.batch_size % n_dev:
        raise ValueError(
            f"global batch {tcfg.batch_size} not divisible by dp={n_dev}")
    local_tcfg = dataclasses.replace(tcfg, batch_size=tcfg.batch_size // n_dev)
    inner = make_multi_step(
        pair, local_tcfg, dataset, axis_name=axis_name, jit=False,
        sample_batch=tcfg.batch_size if controlled_sampling else None)
    fn = wrap_batch_parallel(inner, mesh, axis_name, controlled_sampling)
    # telemetry hook — decided at build time: a no-op (fn returned
    # unchanged, zero wrapper frames) unless hfrep_tpu.obs is enabled
    from hfrep_tpu.obs import instrument_launch
    return instrument_launch(fn, "dp_multi_step", mesh=mesh, tcfg=tcfg,
                             steps_per_call=tcfg.steps_per_call)
