"""Layer (pipeline) parallelism over the 2-LSTM stack — built to measure,
measured to be dominated (RESULTS.md "Layer pipeline: the depth axis").

The reference's models are two stacked LSTMs plus a small head
(``GAN/MTSS_WGAN_GP.py:237-284``) on one GPU — no layer pipelining
exists to port.  This module is the classic GPipe-style depth split the
VERDICT r4 stretch item asks about, composed the TPU way:

* a ``('pp',)`` mesh of exactly 2 devices (the stack's depth) — stage 0
  owns layer 0's LSTM weights, stage 1 owns layer 1's (stacked leading
  axis sharded over ``pp``; the tiny non-recurrent params — LayerNorms,
  output/score Dense — ride replicated, they are <2% of the bytes);
* the batch splits into M microbatches; stage k runs microbatch m at
  superstep s = k + m, so both stages compute concurrently after a
  1-superstep fill;
* the full (Bm, W, H) hidden *sequence* of stage 0 crosses to stage 1
  via `lax.ppermute` each superstep — layer pipelining's inter-stage
  traffic is W·H floats per row where sequence parallelism's carry
  handoff is 2·H (the first structural strike against the axis);
* outputs accumulate on stage 1 and reassemble with a masked `psum`
  (typed invariant — same rationale as :func:`sp_generate`).

Exactness: stage selection is by masking inside one SPMD program (both
stages trace the same ops; each superstep runs ONE full-window
zero-carry scan with this device's stage weights), so values and
gradients — including the WGAN-GP second-order path — match the plain
modules to f32 round-off (tests/test_layer_pipeline.py), and
:func:`make_pp_train_step` is trajectory-exact vs the plain step via the
same ``make_train_step(apply_fns=...)`` contract as sp/tp.

Why it loses (the measured negative, RESULTS.md): at the shipped shapes
the per-timestep recurrent matmul is latency-floor-bound below ~32 rows
(the sp microbatch study's measured t_step), so an M-way microbatch
split does not shrink superstep time — pp time ≈ (M+1)·W·t vs the plain
step's 2·W·t: parity at M=1 *using two devices*, strictly worse for
M ≥ 2, against dp=2's ~1.9× on the same two devices.  The capacity
lever is just as empty: stages would shard ~0.4 MB of parameters while
the real HBM driver is W-proportional activations — the axis sequence
parallelism already shards (results/sp_capacity.json).  Kept as a
working, tested implementation so the negative is a measurement, not an
opinion.

Backend: XLA scan only.  An explicit ``lstm_backend='pallas'`` refuses
(the fused kernels are single-device whole-stack programs; splitting the
stack across chips is exactly what pp does, so the kernel fusion and the
pp axis are mutually exclusive by construction); ``'auto'`` quietly
takes the scan.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hfrep_tpu.parallel._compat import shard_map
from hfrep_tpu.ops.layers import ACTIVATIONS
from hfrep_tpu.utils.vma import match_vma
from hfrep_tpu.parallel.sequence import (_local_chunk_scan, _sp_head_impl,
                                         _sp_ln)

N_STAGES = 2          # the stack's depth — pp's one honest configuration


def _resolve_pp_axis(mesh: Mesh, axis_name: Optional[str]) -> str:
    """Fail fast on mesh mix-ups (the ADVICE r4 tp lesson applied from
    birth): the axis must be literally named ``'pp'`` unless the caller
    names one explicitly, and must span exactly 2 devices."""
    if axis_name is None:
        if "pp" not in mesh.axis_names:
            raise ValueError(
                f"mesh axes {mesh.axis_names} have no 'pp' axis; pass "
                f"axis_name explicitly to shard layers over another name")
        axis_name = "pp"
    if mesh.shape[axis_name] != N_STAGES:
        raise ValueError(
            f"layer pipeline needs exactly {N_STAGES} '{axis_name}' devices "
            f"(the stack depth), got {mesh.shape[axis_name]}")
    return axis_name


def _stack_stage_params(l0: dict, l1: dict, pad_to: int):
    """Stack the two layers' LSTM params on a leading stage axis, zero-
    padding layer 0's (F, 4H) kernel rows up to ``pad_to`` so both
    stages run the identical SPMD op shapes.  Zero rows never touch real
    values: the padded input lanes are zero-filled in lockstep."""
    k0, k1 = l0["kernel"], l1["kernel"]
    k0 = jnp.pad(k0, ((0, pad_to - k0.shape[0]), (0, 0)))
    k1 = jnp.pad(k1, ((0, pad_to - k1.shape[0]), (0, 0)))
    return {"kernel": jnp.stack([k0, k1]),
            "recurrent_kernel": jnp.stack([l0["recurrent_kernel"],
                                           l1["recurrent_kernel"]]),
            "bias": jnp.stack([l0["bias"], l1["bias"]])}


def _pp_pipeline(stage_params, aux_params, x: jnp.ndarray, mesh: Mesh, *,
                 axis_name: str, microbatches: Optional[int],
                 send_fn, head_fn, out_tail: Tuple[int, ...],
                 activation: str, recurrent_activation: str = "sigmoid"):
    """Run the 2-stage GPipe schedule; returns stage 1's head outputs
    reassembled to (B, *out_tail), replicated over the mesh.

    ``send_fn(aux, h_seq)`` transforms stage 0's scan output before the
    inter-stage handoff (the generator's first LayerNorm; identity for
    the critic).  ``head_fn(aux, h_seq)`` maps stage 1's scan output to
    the model output.  Both are traced on BOTH devices (SPMD) and
    masked — they are per-timestep/head ops, <2% of a superstep's FLOPs
    next to the W-step recurrence.
    """
    n_dev = mesh.shape[axis_name]
    b, w, f = x.shape
    h = stage_params["recurrent_kernel"].shape[-2]
    pad_to = stage_params["kernel"].shape[-2]
    m = microbatches if microbatches is not None else N_STAGES
    if m < 1:
        raise ValueError(f"microbatches must be >= 1, got {m}")
    if b % m:
        raise ValueError(f"batch {b} not divisible by microbatches {m}")
    bm = b // m
    act = ACTIVATIONS[activation]
    rec_act = ACTIVATIONS[recurrent_activation]

    def per_device(sp_loc, aux, x_full):
        # sp_loc: this stage's (1, ...) param slices; squeeze the stage axis.
        kern = sp_loc["kernel"][0]                  # (P, 4H)
        rec = sp_loc["recurrent_kernel"][0]         # (H, 4H)
        bias = sp_loc["bias"][0]                    # (4H,)
        k_idx = lax.axis_index(axis_name)
        is_first = k_idx == 0
        is_last = k_idx == n_dev - 1
        # Replicated input, padded to the common lane width and split
        # into microbatches: (M, Bm, W, P).
        x_pad = jnp.pad(x_full, ((0, 0), (0, 0), (0, pad_to - f)))
        x_mb = x_pad.reshape(m, bm, w, pad_to)

        def superstep(recv, s):
            mb = s - k_idx                          # this stage's microbatch id
            active = jnp.logical_and(mb >= 0, mb < m)
            x_sel = lax.dynamic_index_in_dim(x_mb, jnp.clip(mb, 0, m - 1),
                                             axis=0, keepdims=False)
            y_in = jnp.where(is_first, x_sel, recv)  # (Bm, W, P)
            # One full-window zero-carry scan with this stage's weights —
            # the projection is one (Bm·W, P) MXU matmul, the recurrence
            # the same fused cell every other path scans.
            xz = (y_in.reshape(bm * w, pad_to) @ kern + bias)
            xz = jnp.swapaxes(xz.reshape(bm, w, 4 * h), 0, 1)
            zeros = match_vma(jnp.zeros((bm, h), xz.dtype), xz)
            _, h_seq = _local_chunk_scan(xz, (zeros, zeros), rec, act, rec_act)
            h_seq = jnp.swapaxes(h_seq, 0, 1)       # (Bm, W, H)
            # Stage 0 → stage 1 handoff: the transformed full hidden
            # sequence, re-padded to the common lane width.  Masking
            # keeps fill/drain garbage out of the pipe (bounded here —
            # the activations saturate — but zeroing is free and makes
            # the schedule's data flow exact by construction).
            send = send_fn(aux, h_seq)
            send = jnp.pad(send, ((0, 0), (0, 0), (0, pad_to - h)))
            send = jnp.where(active, send, 0.0)
            recv_next = lax.ppermute(send, axis_name,
                                     perm=[(k, k + 1) for k in range(n_dev - 1)])
            out = head_fn(aux, h_seq)               # (Bm, *out_tail)
            out = jnp.where(jnp.logical_and(is_last, active), out, 0.0)
            return recv_next, out

        recv0 = match_vma(jnp.zeros((bm, w, pad_to), x_full.dtype),
                          lax.axis_index(axis_name))
        _, ys = lax.scan(superstep, recv0, jnp.arange(m + n_dev - 1))
        # Stage 1 emits microbatch mb at superstep mb + (n_dev - 1); the
        # masked psum reassembles (only the last stage contributes).
        outs = ys[(n_dev - 1) + jnp.arange(m)]      # (M, Bm, *out_tail)
        outs = lax.psum(outs, axis_name)
        return outs.reshape(b, *out_tail)

    return shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis_name), P(), P()),
        out_specs=P())(stage_params, aux_params, x)


def pp_generate(g_params: dict, z: jnp.ndarray, mesh: Mesh, *,
                axis_name: Optional[str] = None, slope: float = 0.2,
                activation: str = "sigmoid", ln_eps: float = 1e-3,
                microbatches: Optional[int] = None) -> jnp.ndarray:
    """The FULL MTSS generator with the two recurrences on different
    pipeline stages: stage 0 = LSTM₀ + LayerNorm₀, stage 1 = LSTM₁ +
    (LeakyReLU → LayerNorm₁ → Dense) — the same head helpers the sp path
    runs (:func:`hfrep_tpu.parallel.sequence._sp_head_impl`), so the two
    parallel modes share one arithmetic."""
    axis_name = _resolve_pp_axis(mesh, axis_name)
    f = z.shape[-1]
    h = g_params["KerasLSTM_0"]["recurrent_kernel"].shape[0]
    stage = _stack_stage_params(g_params["KerasLSTM_0"],
                                g_params["KerasLSTM_1"], max(f, h))
    aux = {"KerasLayerNorm_0": g_params["KerasLayerNorm_0"],
           "KerasLayerNorm_1": g_params["KerasLayerNorm_1"],
           "KerasDense_0": g_params["KerasDense_0"]}
    return _pp_pipeline(
        stage, aux, z, mesh, axis_name=axis_name, microbatches=microbatches,
        send_fn=lambda a, v: _sp_ln(a["KerasLayerNorm_0"], v, ln_eps),
        head_fn=lambda a, v: _sp_head_impl(a, v, slope, ln_eps),
        out_tail=(z.shape[1], f), activation=activation)


def pp_critic(d_params: dict, x: jnp.ndarray, mesh: Mesh, *,
              axis_name: Optional[str] = None,
              microbatches: Optional[int] = None) -> jnp.ndarray:
    """The MTSS-WGAN-GP critic depth-split: stage 0 = LSTM₀, stage 1 =
    LSTM₁ + flattened (W·H → 1) score head; (B, W, F) → (B, 1)."""
    axis_name = _resolve_pp_axis(mesh, axis_name)
    f = x.shape[-1]
    h = d_params["KerasLSTM_0"]["recurrent_kernel"].shape[0]
    stage = _stack_stage_params(d_params["KerasLSTM_0"],
                                d_params["KerasLSTM_1"], max(f, h))

    def head(aux, h_seq):
        dense = aux["Dense_0"]
        bb = h_seq.shape[0]
        s = h_seq.reshape(bb, -1) @ dense["kernel"]
        return s + dense["bias"] if "bias" in dense else s

    return _pp_pipeline(
        stage, d_params["KerasDense_0"], x, mesh, axis_name=axis_name,
        microbatches=microbatches,
        send_fn=lambda a, v: v, head_fn=head,
        out_tail=(1,), activation="tanh")


def validate_pp_pair(pair) -> None:
    """Same flagship-family precondition as the sp/tp steps: the pp
    modules mirror the LSTMGenerator / LSTMFlatCritic trees, f32."""
    if pair.family != "mtss_wgan_gp":
        raise ValueError(f"layer-pipeline step supports the mtss_wgan_gp "
                         f"family, got {pair.family!r}")
    if (pair.generator.dtype or jnp.float32) != jnp.float32:
        raise NotImplementedError(
            "layer-pipeline step runs f32; configure dtype=float32")


def _validate_pp_backend(tcfg) -> None:
    from hfrep_tpu.train.steps import resolve_lstm_backend

    if tcfg.lstm_backend == "pallas":
        raise NotImplementedError(
            "layer-pipeline recurrences run the XLA scan: the pallas "
            "kernels fuse the WHOLE 2-layer stack into one single-device "
            "program (ops/pallas_lstm_stack.py) — splitting the stack "
            "across chips is the opposite layout, so the kernel fusion "
            "and the pp axis are mutually exclusive by construction")
    resolve_lstm_backend(tcfg.lstm_backend)      # keep the usual ValueError


def make_pp_train_step(pair, tcfg, dataset: jnp.ndarray, mesh: Mesh, *,
                       axis_name: Optional[str] = None,
                       microbatches: Optional[int] = None, jit: bool = True):
    """Layer-pipelined MTSS-WGAN-GP training: the full epoch (n_critic GP
    critic updates + generator update) with the stack depth-split over
    the ``pp`` mesh axis, trajectory-exact vs the plain step.

    Step semantics (sampling streams, critic loop, optimizer updates)
    are shared verbatim with the single-device step via
    ``make_train_step(apply_fns=...)`` — the same reuse contract as the
    sp/tp/dp×sp steps, so no parallel mode can drift arithmetically.
    Exists to make the depth axis *measurable*; the measurement is a
    negative (module docstring + RESULTS.md), so nothing in the trainer
    or CLI dispatches to it — the classroom copy, kept honest.
    """
    from hfrep_tpu.parallel.sequence import _jit_replicated_out
    from hfrep_tpu.train.steps import make_train_step

    axis_name = _resolve_pp_axis(mesh, axis_name)
    validate_pp_pair(pair)
    _validate_pp_backend(tcfg)
    m_eff = N_STAGES if microbatches is None else microbatches
    if m_eff < 1:
        raise ValueError(f"microbatches must be >= 1, got {m_eff}")
    if tcfg.batch_size % m_eff:
        raise ValueError(f"batch {tcfg.batch_size} not divisible by "
                         f"microbatches={m_eff}")
    slope = pair.generator.slope
    g_apply = lambda p, z: pp_generate(p, z, mesh, axis_name=axis_name,
                                       slope=slope, microbatches=microbatches)
    d_apply = lambda p, x: pp_critic(p, x, mesh, axis_name=axis_name,
                                     microbatches=microbatches)
    step = make_train_step(pair, tcfg, dataset, apply_fns=(g_apply, d_apply))
    if not jit:
        return step
    # telemetry hook — build-time no-op unless hfrep_tpu.obs is enabled
    from hfrep_tpu.obs import instrument_launch
    return instrument_launch(_jit_replicated_out(step, mesh),
                             "pp_train_step", mesh=mesh, tcfg=tcfg,
                             microbatches=m_eff)
