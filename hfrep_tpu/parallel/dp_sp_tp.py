"""Full 3-D dp × sp × tp composition — thin shim over the unified mesh
launch: batch over ``dp``, window over ``sp`` (data constraints), gate
columns over ``tp`` (partition rules on the param pytree).  See
:mod:`hfrep_tpu.parallel.rules`.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import Mesh

from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair


def make_dp_sp_tp_train_step(pair: GanPair, tcfg: TrainConfig,
                             dataset: jnp.ndarray, mesh: Mesh, *,
                             controlled_sampling: bool = False,
                             jit: bool = True):
    del controlled_sampling         # the mesh launch's one (stronger) mode
    from hfrep_tpu.parallel.rules import make_gan_train_step
    return make_gan_train_step(pair, tcfg, dataset, mesh, jit=jit)


def make_dp_sp_tp_multi_step(pair: GanPair, tcfg: TrainConfig,
                             dataset: jnp.ndarray, mesh: Mesh, *,
                             controlled_sampling: bool = False,
                             jit: bool = True):
    del controlled_sampling
    from hfrep_tpu.parallel.rules import make_gan_multi_step
    return make_gan_multi_step(pair, tcfg, dataset, mesh, jit=jit)
