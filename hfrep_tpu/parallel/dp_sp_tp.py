"""Full 3-D composition: data × sequence × tensor parallelism on one
``('dp', 'sp', 'tp')`` mesh.

Round 4 built the pairwise compositions — dp×sp
(:mod:`hfrep_tpu.parallel.dp_sp`) and dp×tp
(:mod:`hfrep_tpu.parallel.tensor`).  This module closes the set: one
``shard_map`` region over the 3-D mesh where

* **dp** shards the batch — each dp slab samples its own rows (i.i.d.
  folded keys, or controlled global sampling for trajectory tests) and
  gradients are globally batch-mean normalized by the existing
  `_psum_if` vma machinery;
* **sp** shards the window — the pipelined chunk recurrence with
  ppermute carry handoffs (:func:`hfrep_tpu.parallel.sequence._sp_pipeline`);
* **tp** shards the hidden units *inside* each pipeline chunk — the
  chunk scans carry (Bm, H/T) unit slices and all_gather them per
  timestep (:func:`~hfrep_tpu.parallel.sequence._local_chunk_scan_tp`),
  the :mod:`hfrep_tpu.parallel.tensor` layout composed into the sp
  superstep schedule.  Carry handoffs ppermute the slices over ``sp``
  (the T unit pipelines run the same schedule in lockstep); inter-layer
  transforms and the heads see full-H tp-invariant chunks via masked
  psum, so :func:`~hfrep_tpu.parallel.sequence.sp_generate` /
  :func:`~hfrep_tpu.parallel.sequence.sp_critic` compose unchanged.

Honest costing note (ADVICE r4): in this 3-D path the inter-layer
``_tp_assemble`` masked psum runs **once per superstep per layer** —
O((M + D_sp − 1) · layers) collectives, including on inactive fill/drain
supersteps — where the plain tp path reassembles once per layer.  At the
shipped shapes (M=1, D_sp ≤ 4, 2 LSTM layers) that is ≤ 10 extra psums
of (Bm, W/D, H) chunks per epoch; on a pod, weigh it against the 2-D
meshes before picking the 3-D layout (RESULTS.md §tensor-parallel
honest-costing).

Params and optimizer state stay replicated over all three axes
(``check_vma=True`` proves it), and a controlled-sampling run at the
same global batch follows the single-device trajectory to f32 round-off
(``tests/test_dp_sp_tp.py`` on a 2×2×2 virtual mesh) — on a pod,
scaling any of batch, window length, or model width is a mesh-shape
change, not a semantics change.  The reference anchor is the loop being
scaled, ``GAN/MTSS_WGAN_GP.py:254-292`` (single device, W ≤ 168,
H = 100).  XLA-scan chunks only (see the tp backend note in
:mod:`hfrep_tpu.parallel.tensor`).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import Mesh

from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair
from hfrep_tpu.parallel.dp_sp import _instrument, _make_inner, _wrap


def make_dp_sp_tp_train_step(pair: GanPair, tcfg: TrainConfig,
                             dataset: jnp.ndarray, mesh: Mesh, *,
                             controlled_sampling: bool = False,
                             jit: bool = True):
    """One dp×sp×tp epoch: ``fn(state, key) -> (state, metrics)`` with
    state replicated over the 3-D mesh and metrics pmean'd over ``dp``.
    ``controlled_sampling=True`` consumes the exact single-device sample
    stream at the same global batch (the trajectory-test mode).

    Both the inner step and the batch-parallel wrapper are the dp×sp
    contract's ONE home (:func:`hfrep_tpu.parallel.dp_sp._make_inner` /
    ``_wrap``) with ``tp_axis`` threaded through — validation, sampling
    streams, gradient normalization, and the shard_map wrap cannot
    drift between the 2-D and 3-D meshes.
    """
    inner = _make_inner(pair, tcfg, dataset, mesh, controlled_sampling,
                        tp_axis="tp")
    return _instrument(_wrap(inner, mesh, controlled_sampling, jit,
                             tp_axis="tp"),
                       "dp_sp_tp_train_step", mesh, tcfg, jit)


def make_dp_sp_tp_multi_step(pair: GanPair, tcfg: TrainConfig,
                             dataset: jnp.ndarray, mesh: Mesh, *,
                             controlled_sampling: bool = False,
                             jit: bool = True):
    """``tcfg.steps_per_call`` dp×sp×tp epochs scanned into ONE compiled
    program — the launch shape for real pod runs (dispatched from the
    trainer's ordinary block loop)."""
    from hfrep_tpu.train.steps import make_multi_step

    step = _make_inner(pair, tcfg, dataset, mesh, controlled_sampling,
                       tp_axis="tp")
    inner = make_multi_step(pair, tcfg, dataset, jit=False, step=step)
    return _instrument(_wrap(inner, mesh, controlled_sampling, jit,
                             tp_axis="tp"),
                       "dp_sp_tp_multi_step", mesh, tcfg, jit)
