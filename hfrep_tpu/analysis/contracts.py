"""Shape contracts: ``@contract("(B,W,F)->(B,W,H)")`` + spec parsing.

One tiny grammar serves three consumers:

* the **runtime decorator** below — checks argument/return ranks and
  literal dims at call time (under ``jit`` that is trace time, so the
  check costs nothing per step and fires exactly where a bad reshape
  would otherwise surface 30 stack frames later inside XLA);
* the **static rule** JAX006 (:mod:`hfrep_tpu.analysis.rules.shape_contracts`)
  — verifies ``# shape: (...)`` comments and ``@contract`` specs against
  literal constructor shapes without running anything;
* humans — the spec doubles as the only shape doc that can't go stale.

Grammar::

    spec     := shapes "->" shapes
    shapes   := shape ("," shape)*
    shape    := "(" dim ("," dim)* ")" | "()" | "*"
    dim      := INT | NAME | "_"          # "_" matches anything

``*`` opts a whole position out (any rank — e.g. a PRNG key argument,
whose rank differs between raw uint32 and new-style typed keys).

Symbolic NAMEs bind consistently across one call: ``(T,S),(T,K)->(N,K,S)``
requires both inputs to share T and the output to repeat the K/S bound
from the inputs.  Checks are skipped for arguments without a ``.shape``
(python scalars, configs) so decorated functions stay polymorphic.
Set ``HFREP_CONTRACTS=0`` to disable runtime checking entirely.
"""

from __future__ import annotations

import functools
import os
import re
from typing import Dict, List, Sequence, Tuple, Union

Dim = Union[int, str]          # int literal, symbolic name, or "_" wildcard
ShapeSpec = Tuple[Dim, ...]

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


class ContractError(Exception):
    """A shape contract failed to parse or was violated at call time."""


def parse_shape_spec(text: str) -> Union[ShapeSpec, str]:
    """``"(B, T, F)"`` -> ``("B", "T", "F")``; ``"()"`` -> ``()``;
    ``"*"`` -> ``"*"`` (any rank: this position is unchecked)."""
    t = text.strip()
    if t == "*":
        return "*"
    if not (t.startswith("(") and t.endswith(")")):
        raise ContractError(f"shape spec must be parenthesized: {text!r}")
    inner = t[1:-1].strip()
    if not inner:
        return ()
    dims: List[Dim] = []
    for part in inner.split(","):
        part = part.strip()
        if not part:
            continue               # tolerate a trailing comma: "(B,)"
        if re.fullmatch(r"-?\d+", part):
            dims.append(int(part))
        elif _NAME_RE.match(part):
            dims.append(part)
        else:
            raise ContractError(f"bad dim {part!r} in shape spec {text!r}")
    return tuple(dims)


def _split_top_level(text: str) -> List[str]:
    """Split ``"(T,S),(T,K)"`` on commas outside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth < 0:
                raise ContractError(f"unbalanced parens in {text!r}")
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if depth != 0:
        raise ContractError(f"unbalanced parens in {text!r}")
    if cur:
        parts.append("".join(cur))
    return [p for p in (s.strip() for s in parts) if p]


def parse_contract_spec(spec: str) -> Tuple[List[ShapeSpec], List[ShapeSpec]]:
    """``"(T,S),(T,K)->(N,K,S)"`` -> ([("T","S"),("T","K")], [("N","K","S")])."""
    if "->" not in spec:
        raise ContractError(f"contract spec needs '->': {spec!r}")
    lhs, rhs = spec.split("->", 1)
    ins = [parse_shape_spec(s) for s in _split_top_level(lhs)]
    outs = [parse_shape_spec(s) for s in _split_top_level(rhs)]
    if not outs:
        raise ContractError(f"contract spec has no output shape: {spec!r}")
    return ins, outs


def _concrete_shape(x) -> Union[Tuple[int, ...], None]:
    shape = getattr(x, "shape", None)
    if shape is None:
        return None
    try:
        return tuple(int(d) for d in shape)
    except (TypeError, ValueError):
        return None                # symbolic / polymorphic dims: skip


def check_shape(spec: Union[ShapeSpec, str], shape: Sequence[int],
                env: Dict[str, int], where: str) -> None:
    """Unify one concrete shape against one spec, binding names in ``env``."""
    if spec == "*":
        return
    shape = tuple(shape)
    if len(spec) != len(shape):
        raise ContractError(
            f"{where}: rank mismatch — contract {spec} vs shape {shape}")
    for d_spec, d in zip(spec, shape):
        if d_spec == "_":
            continue
        if isinstance(d_spec, int):
            if d_spec >= 0 and d_spec != d:
                raise ContractError(
                    f"{where}: dim mismatch — contract {spec} vs shape {shape}")
        else:
            bound = env.setdefault(d_spec, d)
            if bound != d:
                raise ContractError(
                    f"{where}: symbol {d_spec!r} bound to {bound} but got "
                    f"{d} in shape {shape} (contract {spec})")


def contracts_enabled() -> bool:
    return os.environ.get("HFREP_CONTRACTS", "1") not in ("0", "false", "off")


def contract(spec: str):
    """Decorator enforcing a shape contract on positional array args and
    outputs.  Non-array positions (no ``.shape``) are skipped; specs past
    the last checked position simply don't fire, so keyword-only knobs
    and trailing config args need no spec entries."""
    ins, outs = parse_contract_spec(spec)   # parse eagerly: bad specs fail at import

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not contracts_enabled():
                return fn(*args, **kwargs)
            env: Dict[str, int] = {}
            for i, (s, a) in enumerate(zip(ins, args)):
                shape = _concrete_shape(a)
                if shape is not None:
                    check_shape(s, shape, env,
                                f"{fn.__qualname__} arg[{i}]")
            out = fn(*args, **kwargs)
            out_vals = (tuple(out) if isinstance(out, tuple) and len(outs) > 1
                        else (out,))
            for i, (s, v) in enumerate(zip(outs, out_vals)):
                shape = _concrete_shape(v)
                if shape is not None:
                    check_shape(s, shape, env,
                                f"{fn.__qualname__} out[{i}]")
            return out

        wrapper.__contract__ = spec
        return wrapper

    return deco
