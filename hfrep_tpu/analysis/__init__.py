"""hfrep_tpu.analysis — JAX-aware static lint, shape contracts &
cross-layer invariant checking.

A pure-AST analyzer (no jax import, no tracing) for the silent-failure
bug classes that TPU JAX code grows: host ops on tracers inside jitted
functions, PRNG key reuse, collective/mesh axis-name drift, donated
buffers read after donation, Python-side mutation of traced pytrees,
and shape/dtype contract violations (JAX001–006) — plus, since ISSUE
11, a two-phase whole-project pass (:mod:`hfrep_tpu.analysis.project`)
behind six cross-layer rules (HF001–006) for the string-protocol
invariants a per-file linter structurally cannot see: history-store
gauge directions, fault-site registry round-trips, atomic-publish
discipline, obs schema/doc sync, version-gated jax APIs, and
signal/lock safety.  See ``hfrep_tpu/analysis/README.md`` for the rule
catalogue and ``python -m hfrep_tpu.analysis check --help`` for the
CLI (JSON/SARIF output, ``--changed`` git scoping, fingerprint cache).

The package is import-light by design: everything here runs on a bare
CPython, so the checker can gate CI before any accelerator runtime is
even installed.
"""

from __future__ import annotations

from hfrep_tpu.analysis.engine import (  # noqa: F401
    AnalysisError,
    FileContext,
    Finding,
    analyze_paths,
    analyze_source,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from hfrep_tpu.analysis.contracts import (  # noqa: F401
    ContractError,
    contract,
    parse_contract_spec,
    parse_shape_spec,
)
from hfrep_tpu.analysis.rules import ALL_RULES, RULES_BY_ID  # noqa: F401
