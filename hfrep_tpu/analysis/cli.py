"""CLI: ``python -m hfrep_tpu.analysis check hfrep_tpu/ tools/ tests/``.

Exit codes: 0 — clean (every finding fixed, suppressed, or baselined);
1 — non-baselined findings; 2 — usage or analyzer error.  ``--format
json`` emits a machine-readable report for CI annotation; ``--format
sarif`` emits SARIF 2.1.0 for code-scanning UIs; ``--changed`` scopes
the *reported* files to the git working-set diff (the project pre-pass
still covers the whole tree, so cross-layer facts stay whole-project);
``--write-baseline`` snapshots the current findings so existing debt can
be burned down incrementally without blocking the gate.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from collections import Counter
from pathlib import Path
from typing import List, Optional, Set

from hfrep_tpu.analysis.engine import (
    AnalysisError, Finding, REPO_ROOT, analyze_paths, apply_baseline,
    load_baseline, write_baseline,
)
from hfrep_tpu.analysis.rules import (ALL_RULES, PROGRAM_RULES,
                                      PROGRAM_RULES_BY_ID, RULES_BY_ID)

#: the repo's checked-in debt ledger, used when ``--baseline`` is absent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
#: the program audit's ledger — separate file so `check` runs never see
#: JPX fingerprints as stale (and vice versa)
DEFAULT_AUDIT_BASELINE = Path(__file__).resolve().parent / "audit_baseline.json"
#: committed 0-findings SARIF snapshot `audit --diff` (and obs explain's
#: regressed-boundary pointer) compare against
DEFAULT_AUDIT_SNAPSHOT = Path(__file__).resolve().parent / "audit_snapshot.sarif"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m hfrep_tpu.analysis",
        description="JAX-aware static lint & shape-contract checker")
    sub = p.add_subparsers(dest="command", required=True)

    check = sub.add_parser("check", help="analyze files/directories")
    check.add_argument("paths", nargs="+", help=".py files or directories")
    check.add_argument("--format", choices=("human", "json", "sarif"),
                       default="human")
    check.add_argument("--select", default=None,
                       help="comma-separated rule ids (default: all)")
    check.add_argument("--baseline", default=None,
                       help=f"baseline file (default: {DEFAULT_BASELINE})")
    check.add_argument("--no-baseline", action="store_true",
                       help="ignore any baseline file")
    check.add_argument("--write-baseline", action="store_true",
                       help="snapshot current findings into the baseline "
                            "file and exit 0")
    check.add_argument("--known-axes", default=None,
                       help="comma-separated mesh axis names to trust in "
                            "addition to the declared ones (JAX003)")
    check.add_argument("--changed", action="store_true",
                       help="report findings only for files changed vs git "
                            "HEAD (+ untracked); the project pre-pass still "
                            "covers every path given, and project-level "
                            "findings always report")
    check.add_argument("--no-cache", action="store_true",
                       help="ignore and don't write the per-file "
                            "fingerprint cache")
    check.add_argument("--cache", default=None,
                       help="fingerprint cache file (default: "
                            "<repo>/.analysis-cache.json)")

    audit = sub.add_parser(
        "audit",
        help="trace + audit every registered compile boundary (JPX rules)")
    audit.add_argument("--format", choices=("human", "json", "sarif"),
                       default="human")
    audit.add_argument("--select", default=None,
                       help="comma-separated JPX rule ids (default: all)")
    audit.add_argument("--baseline", default=None,
                       help=f"baseline file (default: {DEFAULT_AUDIT_BASELINE})")
    audit.add_argument("--no-baseline", action="store_true")
    audit.add_argument("--write-baseline", action="store_true",
                       help="snapshot current audit findings and exit 0")
    audit.add_argument("--changed", action="store_true",
                       help="audit only boundaries whose defining modules "
                            "changed vs git HEAD (+ untracked)")
    audit.add_argument("--no-cache", action="store_true",
                       help="ignore and don't write the per-boundary cache")
    audit.add_argument("--cache", default=None,
                       help="audit cache file (default: "
                            "<repo>/.analysis-programs-cache.json)")
    audit.add_argument("--diff", default=None, metavar="BASE_SARIF",
                       help="also render findings added/removed vs a "
                            "committed SARIF snapshot")
    audit.add_argument("--list", action="store_true",
                       help="list registered boundaries without tracing")

    sub.add_parser("rules", help="list rule ids and descriptions")
    return p


def _select_rules(spec: Optional[str]):
    if spec is None:
        return list(ALL_RULES)
    rules = []
    for rid in (s.strip().upper() for s in spec.split(",") if s.strip()):
        if rid not in RULES_BY_ID:
            raise AnalysisError(
                f"unknown rule id {rid!r}; known: "
                f"{', '.join(sorted(RULES_BY_ID))}")
        rules.append(RULES_BY_ID[rid])
    return rules


def _report_human(new: List[Finding], baselined: List[Finding],
                  stale: Counter, out) -> None:
    for f in new:
        print(f.render(), file=out)
    counts = Counter(f.rule for f in new)
    if new:
        per_rule = ", ".join(f"{r}×{n}" for r, n in sorted(counts.items()))
        print(f"\n{len(new)} finding(s) [{per_rule}]"
              f" ({len(baselined)} baselined)", file=out)
    else:
        print(f"clean: 0 findings ({len(baselined)} baselined)", file=out)
    if stale:
        print(f"note: {sum(stale.values())} stale baseline entr"
              f"{'y' if sum(stale.values()) == 1 else 'ies'} (fixed or "
              f"moved — prune with --write-baseline):", file=out)
        for fp in sorted(stale):
            print(f"  {fp}", file=out)


def changed_files() -> Set[str]:
    """Repo-relative posix paths of .py files changed vs HEAD (staged,
    unstaged and untracked) — the ``--changed`` scope.  Raises
    :class:`AnalysisError` outside a git checkout: an empty scope would
    read as "clean", which is worse than an error."""
    out: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=REPO_ROOT, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise AnalysisError(f"--changed needs git: {e}")
        if proc.returncode != 0:
            raise AnalysisError(
                f"--changed: {' '.join(cmd)} failed: {proc.stderr.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip().endswith(".py"))
    return out


def _report_sarif(new: List[Finding], baselined: List[Finding],
                  stale: Counter, out, rule_set=None,
                  result_props: Optional[dict] = None) -> None:
    """SARIF 2.1.0 — one run, one result per non-baselined finding, so
    code-scanning UIs (and ``sarif``-aware CI annotators) ingest the
    gate without a custom adapter.  ``rule_set`` defaults to the AST
    rules; the audit passes the JPX rules plus ``result_props`` (a
    fingerprint → properties map carrying the ``boundary`` join key
    ``obs explain`` reads)."""
    rules = {}
    for r in (ALL_RULES if rule_set is None else rule_set):
        rules[r.id] = {
            "id": r.id,
            "name": r.name,
            "shortDescription": {"text": r.description or r.name},
        }
    result_props = result_props or {}
    results = []
    for f in new:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "partialFingerprints": {"hfrepFingerprint/v1": f.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": f.col + 1,
                               "snippet": {"text": f.snippet}},
                },
            }],
        }
        if f.fingerprint in result_props:
            result["properties"] = result_props[f.fingerprint]
        results.append(result)
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "hfrep-analysis",
                # no informationUri: SARIF 2.1.0 wants an absolute URI
                # and the docs live in-repo (hfrep_tpu/analysis/README.md)
                "rules": sorted(rules.values(), key=lambda r: r["id"]),
            }},
            "originalUriBaseIds": {
                "SRCROOT": {"uri": REPO_ROOT.as_uri() + "/"}},
            "results": results,
            "properties": {"baselined": len(baselined),
                           "staleBaseline": sorted(stale.elements())},
        }],
    }
    json.dump(doc, out, indent=2)
    out.write("\n")


def _report_json(new: List[Finding], baselined: List[Finding],
                 stale: Counter, out) -> None:
    payload = {
        "version": 1,
        "findings": [f.to_dict() for f in new],
        "counts": dict(Counter(f.rule for f in new)),
        "baselined": len(baselined),
        "stale_baseline": sorted(stale.elements()),
    }
    json.dump(payload, out, indent=2)
    out.write("\n")


def _select_program_rules(spec: Optional[str]):
    if spec is None:
        return list(PROGRAM_RULES)
    rules = []
    for rid in (s.strip().upper() for s in spec.split(",") if s.strip()):
        if rid not in PROGRAM_RULES_BY_ID:
            raise AnalysisError(
                f"unknown program rule id {rid!r}; known: "
                f"{', '.join(sorted(PROGRAM_RULES_BY_ID))}")
        rules.append(PROGRAM_RULES_BY_ID[rid])
    return rules


def _load_sarif_fingerprints(path) -> Counter:
    """Fingerprint multiset of a SARIF snapshot (the ``--diff`` base)."""
    p = Path(path)
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        raise AnalysisError(f"cannot read SARIF snapshot {p}: {e}")
    fps: Counter = Counter()
    for run in data.get("runs", []) if isinstance(data, dict) else []:
        for res in run.get("results", []):
            fp = (res.get("partialFingerprints") or {}).get(
                "hfrepFingerprint/v1")
            if fp:
                fps[fp] += 1
    return fps


def _main_audit(args) -> int:
    from hfrep_tpu.analysis import programs

    if args.list:
        for b in programs.PROGRAM_BOUNDARIES:
            print(f"{b.label:32s} {b.kind:10s} donate={b.donate!r:6s} "
                  f"policy={b.policy:5s} site={b.site}")
        return 0

    try:
        rules = _select_program_rules(args.select)
        if args.select and args.write_baseline:
            raise AnalysisError(
                "--write-baseline requires a full-rule audit; drop --select")
        if args.changed and args.write_baseline:
            raise AnalysisError(
                "--write-baseline needs the full finding set; drop --changed")
        restrict = changed_files() if args.changed else None
        res = programs.audit_boundaries(
            rules=rules, cache_path=args.cache,
            use_cache=not args.no_cache, restrict_to=restrict)

        baseline_path = (Path(args.baseline) if args.baseline
                         else DEFAULT_AUDIT_BASELINE)
        if args.write_baseline:
            n = write_baseline(res.findings, baseline_path)
            print(f"wrote {n} audit baseline entr{'y' if n == 1 else 'ies'} "
                  f"to {baseline_path}")
            return 0

        baseline = Counter()
        if not args.no_baseline and baseline_path.exists():
            baseline = load_baseline(baseline_path)
            if args.select:
                selected = {r.id for r in rules}
                baseline = Counter({
                    fp: n for fp, n in baseline.items()
                    if fp.split("::", 1)[0] in selected})
        new, matched, stale = apply_baseline(res.findings, baseline)
        if args.changed:
            stale = Counter()

        diff = None
        if args.diff:
            base_fps = _load_sarif_fingerprints(args.diff)
            cur = Counter(f.fingerprint for f in new)
            diff = {"added": sorted((cur - base_fps).elements()),
                    "removed": sorted((base_fps - cur).elements())}
    except AnalysisError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    out = sys.stdout
    if args.format == "json":
        payload = {
            "version": 1,
            "findings": [f.to_dict() for f in new],
            "counts": dict(Counter(f.rule for f in new)),
            "baselined": len(matched),
            "stale_baseline": sorted(stale.elements()),
            "traced": len(res.traced),
            "boundaries": res.traced,
            "skipped": res.skipped,
        }
        if diff is not None:
            payload["diff"] = diff
        json.dump(payload, out, indent=2)
        out.write("\n")
    elif args.format == "sarif":
        props = {fp: {"boundary": b}
                 for fp, b in res.boundary_of.items()}
        _report_sarif(new, matched, stale, out, rule_set=rules,
                      result_props=props)
    else:
        _report_human(new, matched, stale, out)
        print(f"audited {len(res.traced)} boundar"
              f"{'y' if len(res.traced) == 1 else 'ies'}"
              f" ({len(res.skipped)} skipped)", file=out)
        for label, why in sorted(res.skipped.items()):
            print(f"  skip {label}: {why}", file=out)
        if diff is not None:
            for fp in diff["added"]:
                print(f"  diff +{fp}", file=out)
            for fp in diff["removed"]:
                print(f"  diff -{fp}", file=out)
            if not diff["added"] and not diff["removed"]:
                print("  diff: no change vs snapshot", file=out)
    return 1 if new else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.command == "rules":
        for r in (*ALL_RULES, *PROGRAM_RULES):
            print(f"{r.id}  {r.name:22s} {r.description}")
        return 0

    if args.command == "audit":
        return _main_audit(args)

    try:
        rules = _select_rules(args.select)
        if args.select and args.write_baseline:
            # a partial-rule snapshot would silently drop every other
            # rule's entries (and their justifications) from the ledger
            raise AnalysisError(
                "--write-baseline requires a full-rule run; drop --select")
        axes = (set(s.strip() for s in args.known_axes.split(",") if s.strip())
                if args.known_axes else None)
        restrict = changed_files() if args.changed else None
        if args.changed and args.write_baseline:
            raise AnalysisError(
                "--write-baseline needs the full finding set; drop --changed")
        findings = analyze_paths(
            args.paths, rules=rules, known_axes=axes,
            cache_path=args.cache, use_cache=not args.no_cache,
            restrict_to=restrict)

        baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
        if args.write_baseline:
            # carry forward justifications for entries that still match
            old = {}
            if baseline_path.exists():
                try:
                    data = json.loads(
                        baseline_path.read_text(encoding="utf-8"))
                except (OSError, json.JSONDecodeError) as e:
                    raise AnalysisError(
                        f"cannot re-read baseline {baseline_path}: {e}")
                for e in data.get("entries", []):
                    if isinstance(e, dict) and "fingerprint" in e:
                        old.setdefault(e["fingerprint"], e.get("justification"))
            n = write_baseline(findings, baseline_path,
                               justifications={k: v for k, v in old.items()
                                               if v})
            print(f"wrote {n} baseline entr{'y' if n == 1 else 'ies'} to "
                  f"{baseline_path}")
            return 0

        baseline = Counter()
        if not args.no_baseline and baseline_path.exists():
            baseline = load_baseline(baseline_path)
            if args.select:
                # only the selected rules ran: other rules' entries are
                # not stale, they just weren't checked this run
                selected = {r.id for r in rules}
                baseline = Counter({
                    fp: n for fp, n in baseline.items()
                    if fp.split("::", 1)[0] in selected})
        new, matched, stale = apply_baseline(findings, baseline)
        if args.changed:
            # a diff-scoped run never saw the unchanged files' findings,
            # so their baseline entries are not stale, just unchecked
            stale = Counter()
    except AnalysisError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    report = {"json": _report_json, "sarif": _report_sarif,
              "human": _report_human}[args.format]
    report(new, matched, stale, sys.stdout)
    return 1 if new else 0


if __name__ == "__main__":          # pragma: no cover
    sys.exit(main())
