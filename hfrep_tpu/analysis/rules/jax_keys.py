"""JAX002 — ``jax.random`` key reuse.

Failure mode: passing one PRNG key to two primitives makes their outputs
perfectly correlated — samples that should be independent share a seed.
Nothing crashes; the GAN just trains on statistically broken noise (the
classic variant: reusing the init key as the first epoch key, which
pins epoch 0's batch selection to the parameter init).

Model: a per-scope linear scan over statements.  Any name passed as the
first argument to a consuming ``jax.random`` primitive (samplers *and*
``split`` — using a key after splitting it is the textbook bug) is
tracked; a second consumption without an intervening rebind is a
finding.  ``fold_in`` is a *derivation* (it mixes extra data in) and
does not consume.  Control flow:

* ``if``/``else`` branches fork the state and are merged afterwards, so
  a key consumed once per exclusive branch is not flagged;
* a consumption inside a ``for``/``while``/comprehension of a key that
  is never reassigned in that loop body is flagged even on first use —
  every iteration would draw the same randomness (the sanctioned
  patterns rebind per iteration, ``key, sub = split(key)``, or derive
  per iteration, ``fold_in(key, i)``).

Dotted targets (``self.key``) are tracked like plain names so the
trainer's ``self.key, sub = jax.random.split(self.key)`` idiom checks
out.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import (
    Rule, dotted_name, from_imports, import_aliases, scope_body, walk_scopes,
)

#: jax.random callables whose first argument is a key they consume.
_CONSUMERS = {
    "split", "normal", "uniform", "randint", "permutation", "bernoulli",
    "categorical", "choice", "gumbel", "truncated_normal", "beta", "gamma",
    "dirichlet", "exponential", "laplace", "logistic", "multivariate_normal",
    "poisson", "rademacher", "t", "shuffle", "orthogonal", "ball", "cauchy",
    "maxwell", "bits", "binomial", "loggamma", "pareto", "rayleigh",
    "triangular", "weibull_min",
}
#: derive-don't-consume: safe to call repeatedly on the same key with
#: different data.
_DERIVERS = {"fold_in", "key_data", "wrap_key_data", "key_impl", "clone"}


class _KeyState:
    """consumed: name -> line of first consumption; assigned_depth: name ->
    loop depth of the most recent (re)bind."""

    def __init__(self) -> None:
        self.consumed: Dict[str, int] = {}
        self.assigned_depth: Dict[str, int] = {}
        self.loop_rebound: Set[str] = set()

    def fork(self) -> "_KeyState":
        s = _KeyState()
        s.consumed = dict(self.consumed)
        s.assigned_depth = dict(self.assigned_depth)
        s.loop_rebound = set(self.loop_rebound)
        return s

    def merge(self, *branches: "_KeyState") -> None:
        """Join control-flow branches.  Each branch *started* as a fork of
        this state, so the union of the branches' consumed maps is the
        post-join truth: a key rebound on every path appears in no
        branch and is correctly cleared; a key still stale on any one
        path survives (earliest consumption line wins)."""
        merged: Dict[str, int] = {}
        for b in branches:
            for k, line in b.consumed.items():
                merged.setdefault(k, line)
        self.consumed = merged
        for b in branches:
            self.assigned_depth.update(b.assigned_depth)


class KeyReuseRule(Rule):
    id = "JAX002"
    name = "prng-key-reuse"
    description = ("a jax.random key consumed twice (or consumed inside a "
                   "loop) without split/fold_in")

    def check(self, ctx: FileContext) -> List[Finding]:
        self._random_names = self._resolve_random_names(ctx.tree)
        findings: List[Finding] = []
        for scope in walk_scopes(ctx.tree):
            findings.extend(self._check_scope(ctx, scope))
        return findings

    # ------------------------------------------------------------ naming
    def _resolve_random_names(self, tree: ast.AST) -> Dict[str, str]:
        """local callable name -> jax.random fn name, for every way the
        module can be spelled (jax.random.X, jr.X, random.X, bare X)."""
        names: Dict[str, str] = {}
        prefixes = import_aliases(tree, "jax.random") | {"jax.random"}
        for alias in import_aliases(tree, "jax"):
            prefixes.add(f"{alias}.random")
        for local, orig in from_imports(tree, "jax.random").items():
            names[local] = orig
        self._random_prefixes = prefixes
        return names

    def _random_fn(self, call: ast.Call) -> Optional[str]:
        fname = dotted_name(call.func)
        if fname is None:
            return None
        if fname in self._random_names:         # bare from-import
            return self._random_names[fname]
        head, _, tail = fname.rpartition(".")
        if head in self._random_prefixes:
            return tail
        return None

    # ------------------------------------------------------------- scan
    def _check_scope(self, ctx: FileContext, scope: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        state = _KeyState()
        self._visit_block(ctx, scope_body(scope), state, 0, findings)
        return findings

    def _visit_block(self, ctx, stmts, state: _KeyState, depth: int,
                     findings: List[Finding]) -> None:
        for stmt in stmts:
            self._visit_stmt(ctx, stmt, state, depth, findings)

    def _visit_stmt(self, ctx, stmt: ast.stmt, state: _KeyState, depth: int,
                    findings: List[Finding]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                  # nested scopes are walked separately
        if isinstance(stmt, ast.If):
            self._visit_exprs(ctx, stmt.test, state, depth, findings)
            body_s, else_s = state.fork(), state.fork()
            self._visit_block(ctx, stmt.body, body_s, depth, findings)
            self._visit_block(ctx, stmt.orelse, else_s, depth, findings)
            state.merge(body_s, else_s)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._visit_exprs(ctx, stmt.iter, state, depth, findings)
                self._bind_target(stmt.target, state, depth + 1)
            else:
                self._visit_exprs(ctx, stmt.test, state, depth, findings)
            loop_state = state.fork()
            loop_state.loop_rebound |= self._assigned_names(stmt.body)
            self._visit_block(ctx, stmt.body, loop_state, depth + 1, findings)
            self._visit_block(ctx, stmt.orelse, loop_state, depth, findings)
            state.merge(loop_state)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._visit_exprs(ctx, item.context_expr, state, depth, findings)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars, state, depth)
            self._visit_block(ctx, stmt.body, state, depth, findings)
            return
        if isinstance(stmt, ast.Try):
            self._visit_block(ctx, stmt.body, state, depth, findings)
            for h in stmt.handlers:
                self._visit_block(ctx, h.body, state, depth, findings)
            self._visit_block(ctx, stmt.orelse, state, depth, findings)
            self._visit_block(ctx, stmt.finalbody, state, depth, findings)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._visit_exprs(ctx, stmt.value, state, depth, findings)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                self._bind_target(t, state, depth)
            return
        # generic statement: just walk its expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._visit_exprs(ctx, child, state, depth, findings)

    def _assigned_names(self, stmts) -> Set[str]:
        out: Set[str] = set()
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                        getattr(node, "ctx", None), ast.Store):
                    d = dotted_name(node)
                    if d:
                        out.add(d)
        return out

    def _bind_target(self, target: ast.AST, state: _KeyState, depth: int) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, state, depth)
            return
        if isinstance(target, ast.Starred):
            self._bind_target(target.value, state, depth)
            return
        d = dotted_name(target)
        if d:
            state.consumed.pop(d, None)
            state.assigned_depth[d] = depth

    # ------------------------------------------------------- expressions
    def _visit_exprs(self, ctx, expr: ast.AST, state: _KeyState, depth: int,
                     findings: List[Finding]) -> None:
        """Single-visit recursive walk; entering a comprehension bumps the
        loop depth (its body repeats per item), entering a lambda stops
        (lambdas are separate scopes, walked on their own)."""
        if isinstance(expr, ast.Lambda):
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for i, gen in enumerate(expr.generators):
                # the first iterable evaluates once, in the enclosing scope
                self._visit_exprs(ctx, gen.iter, state,
                                  depth if i == 0 else depth + 1, findings)
                # the target rebinds per item — `[normal(k) for k in
                # split(key, n)]` consumes a FRESH k each iteration
                self._bind_target(gen.target, state, depth + 1)
                for cond in gen.ifs:
                    self._visit_exprs(ctx, cond, state, depth + 1, findings)
            elts = ([expr.key, expr.value] if isinstance(expr, ast.DictComp)
                    else [expr.elt])
            for e in elts:
                self._visit_exprs(ctx, e, state, depth + 1, findings)
            return
        if isinstance(expr, ast.Call):
            self._handle_call(ctx, expr, state, depth, findings)
        for child in ast.iter_child_nodes(expr):
            self._visit_exprs(ctx, child, state, depth, findings)

    def _handle_call(self, ctx, call: ast.Call, state: _KeyState, depth: int,
                     findings: List[Finding]) -> None:
        fn = self._random_fn(call)
        if fn is None or fn in _DERIVERS or fn not in _CONSUMERS:
            return
        key_arg = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "key":
                key_arg = kw.value
        name = dotted_name(key_arg) if key_arg is not None else None
        if name is None:
            return                  # derived expr (fold_in(...), keys[i], …)
        prev = state.consumed.get(name)
        if prev is not None:
            findings.append(ctx.finding(
                self.id, call,
                f"key {name!r} reused by jax.random.{fn} (already consumed "
                f"on line {prev}); split it first"))
        else:
            bind_depth = state.assigned_depth.get(name, 0)
            rebound = getattr(state, "loop_rebound", set())
            if depth > bind_depth and name not in rebound:
                findings.append(ctx.finding(
                    self.id, call,
                    f"key {name!r} consumed by jax.random.{fn} inside a "
                    f"loop without per-iteration split/fold_in"))
        state.consumed[name] = call.lineno
