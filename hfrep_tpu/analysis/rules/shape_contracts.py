"""JAX006 — shape/dtype contract annotations, checked where shapes are literal.

Two annotation forms, both sharing the grammar in
:mod:`hfrep_tpu.analysis.contracts`:

* trailing ``# shape: (B, W, F)`` comments on assignments — when the
  right-hand side is a literal-shape constructor (``jnp.zeros((4, 8))``,
  ``jnp.full((n, 3), v)``, ``jax.random.normal(k, (B, W, F))``,
  ``x.reshape(4, -1)``), the annotated rank and any literal dims are
  checked against the constructed shape, and repeated symbols must bind
  consistently (``# shape: (B, B)`` over ``zeros((3, 4))`` is an error);
* ``@contract("(T,S),(T,K)->(N,K,S)")`` decorators — the spec must
  parse, must not declare more inputs than the function has positional
  parameters, and a literal-constructor ``return`` is rank-checked
  against the output spec.  (Full value checking happens at trace time
  via the runtime decorator; this rule catches the annotations that
  could never fire.)

Failure mode being defended: on TPU a wrong static shape doesn't crash —
XLA happily compiles the wrong program, and the error surfaces as NaNs
or a silently transposed einsum three modules away.  Pinning intent in
a machine-checked comment keeps the doc and the code from drifting.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple, Union

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import (
    Rule, direct_nodes, dotted_name, literal_int_tuple, param_names,
)
from hfrep_tpu.analysis.contracts import (
    ContractError, parse_contract_spec, parse_shape_spec,
)

#: parens can't nest in the spec grammar, so match one balanced group —
#: trailing prose (even with its own parens) is ignored, not "unparseable"
_SHAPE_COMMENT_RE = re.compile(r"#\s*shape:\s*(?P<spec>\([^()#]*\))")

#: constructors whose literal shape argument we can read off the AST:
#: name -> positional index of the shape tuple
_SHAPE_ARG_POS = {
    "zeros": 0, "ones": 0, "empty": 0, "full": 0,
    "normal": 1, "uniform": 1, "truncated_normal": 1,
    "broadcast_to": 1, "zeros_like": None, "ones_like": None,
}


def _constructed_shape(value: ast.AST) -> Optional[Tuple[object, ...]]:
    """Literal shape of a constructor call, with ints literal, names
    symbolic and unknowns "_"; None when the expression isn't one."""
    if not isinstance(value, ast.Call):
        return None
    fname = dotted_name(value.func)
    if fname is None:
        return None
    tail = fname.split(".")[-1]
    if tail == "reshape":
        args = list(value.args)
        root = fname.split(".")[0]
        if root in ("jnp", "np", "numpy", "jax"):
            args = args[1:]         # function form: jnp.reshape(x, shape)
        # method form: x.reshape(4, -1) or x.reshape((4, -1))
        if len(args) == 1:
            tup = literal_int_tuple(args[0])
            if tup is not None:
                return tup
        dims: List[object] = []
        for a in args:
            if isinstance(a, ast.Constant) and isinstance(a.value, int):
                dims.append(a.value)
            elif isinstance(a, ast.Name):
                dims.append(a.id)
            else:
                return None
        return tuple(dims) if dims else None
    pos = _SHAPE_ARG_POS.get(tail, "missing")
    if pos == "missing" or pos is None:
        return None
    args = list(value.args)
    shape_node = args[pos] if len(args) > pos else None
    for kw in value.keywords:
        if kw.arg == "shape":
            shape_node = kw.value
    if shape_node is None:
        return None
    return literal_int_tuple(shape_node)


def _unify(spec, shape, env: Dict[str, object]) -> Optional[str]:
    """Check one spec against one AST-derived shape; returns an error
    message or None.  Symbolic AST dims ("n") and "_" match anything but
    symbolic spec letters still have to bind consistently over literal
    ints."""
    if spec == "*" or shape == "*":
        return None
    if len(spec) != len(shape):
        return (f"rank mismatch: annotation {_fmt(spec)} vs constructed "
                f"shape {_fmt(shape)}")
    for d_spec, d in zip(spec, shape):
        if d_spec == "_" or d == "_":
            continue
        if isinstance(d_spec, int):
            if isinstance(d, int) and d_spec >= 0 and d >= 0 and d_spec != d:
                return (f"dim mismatch: annotation {_fmt(spec)} vs "
                        f"constructed shape {_fmt(shape)}")
        else:                       # symbolic letter: bind consistently
            if isinstance(d, int) and d >= 0:
                bound = env.setdefault(d_spec, d)
                if bound != d:
                    return (f"symbol {d_spec!r} bound to {bound} and "
                            f"{d} in the same annotation")
    return None


def _fmt(dims) -> str:
    return "(" + ", ".join(str(d) for d in dims) + ")"


class ShapeContractRule(Rule):
    id = "JAX006"
    name = "shape-contract"
    description = ("`# shape: (...)` comments and @contract decorators "
                   "verified against literal constructor shapes")

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        specs = self._comment_specs(ctx, findings)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                # the annotation may sit on any physical line of a
                # multi-line assignment (usually the last)
                spec = next(
                    (specs[ln] for ln in range(
                        node.lineno, (node.end_lineno or node.lineno) + 1)
                     if ln in specs), None)
                if spec is None or node.value is None:
                    continue
                shape = _constructed_shape(node.value)
                if shape is None:
                    continue        # annotation is documentation only
                err = _unify(spec, shape, {})
                if err:
                    findings.append(ctx.finding(self.id, node, err))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_contract_decorators(ctx, node))
        return findings

    def _comment_specs(self, ctx: FileContext,
                       findings: List[Finding]) -> Dict[int, tuple]:
        """line -> parsed ``# shape:`` spec; bad specs become findings.
        Only real comment tokens are scanned — a ``# shape: (...)``
        example inside a docstring is prose, not a contract."""
        specs: Dict[int, tuple] = {}
        for lineno, text in ctx.comments.items():
            m = _SHAPE_COMMENT_RE.search(text)
            if not m:
                continue
            marker = ast.Expr(value=ast.Constant(value=None))
            marker.lineno, marker.col_offset = lineno, m.start()
            try:
                specs[lineno] = parse_shape_spec(m.group("spec"))
            except ContractError as e:
                findings.append(ctx.finding(
                    self.id, marker, f"unparseable shape annotation: {e}"))
        return specs

    def _check_contract_decorators(self, ctx: FileContext, fn) -> List[Finding]:
        findings: List[Finding] = []
        for dec in fn.decorator_list:
            if not (isinstance(dec, ast.Call)
                    and dotted_name(dec.func) is not None
                    and dotted_name(dec.func).split(".")[-1] == "contract"):
                continue
            if not (dec.args and isinstance(dec.args[0], ast.Constant)
                    and isinstance(dec.args[0].value, str)):
                continue            # dynamic spec: nothing to check statically
            try:
                ins, outs = parse_contract_spec(dec.args[0].value)
            except ContractError as e:
                findings.append(ctx.finding(
                    self.id, dec, f"unparseable @contract spec: {e}"))
                continue
            n_params = len(param_names(fn))
            if len(ins) > n_params:
                findings.append(ctx.finding(
                    self.id, dec,
                    f"@contract on `{fn.name}` declares {len(ins)} input "
                    f"shapes but the function has only {n_params} "
                    f"parameters"))
            # literal-return rank check against the (single) output spec;
            # only THIS function's returns — a nested helper's literal
            # return answers the helper's contract, not this one
            if len(outs) == 1 and outs[0] != "*":
                for node in direct_nodes(fn):
                    if isinstance(node, ast.Return) and node.value is not None:
                        shape = _constructed_shape(node.value)
                        if shape is not None and len(shape) != len(outs[0]):
                            findings.append(ctx.finding(
                                self.id, node,
                                f"`{fn.name}` returns a rank-{len(shape)} "
                                f"literal but @contract declares output "
                                f"{_fmt(outs[0])}"))
        return findings
