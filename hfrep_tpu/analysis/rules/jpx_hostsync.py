"""JPX003 — host transfer/sync inside a loop body.

A callback primitive under ``scan``/``while`` forces a device→host
round-trip PER ITERATION: the multi-step scan that exists to amortize
one ~4ms dispatch over 50 epochs silently degrades back to one sync per
epoch, and the dispatch-vs-compute overlap the perf microscope
measures collapses (the arxiv 2111.04628 argument, enforced at compile
time instead of discovered in a bench round).

Flagged: ``pure_callback`` / ``io_callback`` / ``debug_callback`` (and
the infeed/outfeed pair) appearing in an eqn whose enclosing scope is a
loop body.  The SAME primitives at top level are fine — a one-off
host call per program dispatch is the ordinary logging/IO posture, and
the AST rule JAX001 already polices host *Python* in jitted scopes;
this rule sees what survived tracing, where f-string debug prints and
`jax.debug.print` become real callback eqns.
"""

from __future__ import annotations

from typing import List

from hfrep_tpu.analysis.engine import Finding
from hfrep_tpu.analysis.rules.jpx_base import (ProgramContext, ProgramRule,
                                               iter_eqns)

SYNC_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call", "infeed", "outfeed",
})


class ProgramHostSyncRule(ProgramRule):
    id = "JPX003"
    name = "program-host-sync"
    description = ("host callback/transfer primitive inside a scan/while "
                   "body — one device→host sync per loop iteration "
                   "defeats the multi-step dispatch amortization")

    def check_program(self, pctx: ProgramContext) -> List[Finding]:
        if pctx.jaxpr is None:
            return []
        hits = {}
        for eqn, in_loop in iter_eqns(pctx.jaxpr):
            name = eqn.primitive.name
            if in_loop and name in SYNC_PRIMITIVES:
                hits[name] = hits.get(name, 0) + 1
        return [pctx.finding(
            self.id,
            f"{n}× `{name}` inside a loop body — a host sync per "
            "iteration; hoist it out of the scan or batch it into the "
            "stacked per-epoch outputs",
            token=name) for name, n in sorted(hits.items())]
