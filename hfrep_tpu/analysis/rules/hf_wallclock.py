"""HF009 — wall-clock monopoly: raw timestamps bypass the ledger.

The wall-clock ledger (:mod:`hfrep_tpu.obs.timeline`) can only uphold
its conservation invariant — every measured millisecond of a drive
assigned to exactly one category — if the code that *measures* wall
time routes through it.  A raw ``time.perf_counter()`` pair in a drive
or tool measures seconds the ledger never sees: the time silently
lands in ``unattributed`` (or worse, gets double-reported through a
side channel the timeline CLI cannot reconcile).

Flagged: call sites of ``time.perf_counter`` and ``time.time`` —
through any import spelling (``import time``, ``import time as t``,
``from time import perf_counter [as pc]``) — anywhere outside
``hfrep_tpu/obs/`` (the ledger's own implementation must read the
clock) and test files.  The fix is almost always mechanical:

* a bare timestamp read → :func:`hfrep_tpu.obs.timeline.clock`
* a measure-and-report pair → ``with timeline.stopwatch() as sw:``
* a measure-and-*account* pair → ``with timeline.timed(category):``

``time.monotonic`` stays legal: the serve/admission layers use it as an
injectable *scheduling* clock (deadlines, batching windows), which is
exactly the use the ledger does not want to own.  Deliberate
exceptions carry ``# noqa: HF009``.
"""

from __future__ import annotations

import ast
from typing import List

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name, from_imports, \
    import_aliases

_BANNED_ATTRS = ("perf_counter", "time")


def _is_exempt_path(path: str) -> bool:
    p = path.replace("\\", "/")
    return ("hfrep_tpu/obs/" in p or p.startswith("tests/")
            or "/tests/" in p or p.split("/")[-1].startswith("test_"))


class WallClockRule(Rule):
    id = "HF009"
    name = "wall-clock-monopoly"
    description = ("raw time.perf_counter()/time.time() outside "
                   "hfrep_tpu/obs/ — wall time the ledger cannot account")

    def check(self, ctx: FileContext) -> List[Finding]:
        if _is_exempt_path(ctx.path):
            return []
        tree = ctx.tree
        time_aliases = import_aliases(tree, "time")
        # from time import perf_counter [as pc] / time [as t]
        direct = {alias: orig for alias, orig in from_imports(tree, "time")
                  .items() if orig in _BANNED_ATTRS}
        if not time_aliases and not direct:
            return []
        banned = {f"{mod}.{attr}" for mod in time_aliases
                  for attr in _BANNED_ATTRS}
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname is None:
                continue
            hit = fname in banned or (fname in direct
                                      and "." not in fname)
            if hit:
                tail = fname.split(".")[-1] if "." in fname \
                    else direct.get(fname, fname)
                findings.append(ctx.finding(
                    "HF009", node,
                    f"raw time.{tail}() outside hfrep_tpu/obs/: wall "
                    "time measured here never reaches the ledger and "
                    "degrades to unattributed — use timeline.clock() "
                    "(bare read), timeline.stopwatch() (measure+report) "
                    "or timeline.timed(category) (measure+account)"))
        return findings
