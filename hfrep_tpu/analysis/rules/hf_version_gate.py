"""HF005 — version-gated JAX API use.

The seed tier-1 failure set (38F/5E) had ONE root cause: ``from jax
import shard_map`` at module top of four launch-path modules, on a
pinned runtime (jax 0.4.37) where the attribute does not exist — each
import killed its whole module, every module importing it, and five
entire test files at collection.  That class is statically detectable:
the absent-API registry (:data:`hfrep_tpu.analysis.project.
ABSENT_JAX_APIS`, curated against the pinned runtime and verified
against the installed jax by the test suite) names every such
attribute, and this rule flags any *unguarded* static reference.

Guarded references are the sanctioned pattern and never flagged:

* inside a ``try`` whose handlers catch ``ImportError`` /
  ``ModuleNotFoundError`` / ``AttributeError`` (or a bare/``Exception``
  handler) — the ``_compat`` gate and ``utils.vma.vma_of`` idioms;
* inside an ``if hasattr(jax, "...")`` (or equivalently-guarded)
  branch.

The findings over ``hfrep_tpu/parallel/`` are the ROADMAP item-1 kill
list — committed as ``hfrep_tpu/analysis/HF005_KILL_LIST.md``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name, import_aliases

_GUARD_EXCEPTIONS = {"ImportError", "ModuleNotFoundError",
                     "AttributeError", "Exception", "BaseException"}


def _handler_names(handler: ast.ExceptHandler) -> Set[str]:
    t = handler.type
    if t is None:
        return {"Exception"}                 # bare except
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out: Set[str] = set()
    for e in elts:
        name = dotted_name(e)
        if name:
            out.add(name.split(".")[-1])
    return out


def _is_guard_test(test: ast.AST) -> bool:
    """``hasattr(jax, "shard_map")``-shaped truth tests (possibly
    parenthesized into bool ops)."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname and fname.split(".")[-1] in ("hasattr", "getattr"):
                return True
    return False


def _guard_branches(test: ast.AST):
    """Which branches of an ``if`` a hasattr-shaped test guards:
    ``(body_guarded, orelse_guarded)``.  Polarity matters —
    ``if hasattr(...):`` blesses the body, ``if not hasattr(...):``
    blesses the *else* branch (the body is the degraded path)."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        if _is_guard_test(test.operand):
            return False, True
        return False, False
    if _is_guard_test(test):
        return True, False
    return False, False


class VersionGateRule(Rule):
    id = "HF005"
    name = "version-gated-jax-api"
    description = ("unguarded references to jax APIs absent on the "
                   "pinned runtime (the dead-module import class)")

    def check(self, ctx: FileContext) -> List[Finding]:
        project = ctx.project
        if project is None or not project.absent_jax:
            return []
        absent = project.absent_jax
        findings: List[Finding] = []

        # dotted-prefix aliases for normalization: {"jnp": "jax.numpy"}
        alias_of: Dict[str, str] = {}
        roots = {api.rsplit(".", 1)[0] for api in absent}
        for module in sorted(roots):
            for alias in import_aliases(ctx.tree, module):
                alias_of[alias] = module

        def visit(node: ast.AST, guarded: bool) -> None:
            if isinstance(node, ast.Try):
                handlers = {n for h in node.handlers
                            for n in _handler_names(h)}
                body_guarded = guarded or bool(handlers & _GUARD_EXCEPTIONS)
                for child in node.body:
                    visit(child, body_guarded)
                for h in node.handlers:
                    for child in h.body:
                        visit(child, guarded)
                for child in node.orelse + node.finalbody:
                    visit(child, guarded)
                return
            if isinstance(node, ast.If):
                body_ok, orelse_ok = _guard_branches(node.test)
                if body_ok or orelse_ok:
                    visit(node.test, guarded)
                    for child in node.body:
                        visit(child, guarded or body_ok)
                    for child in node.orelse:
                        visit(child, guarded or orelse_ok)
                    return
            self._check_node(ctx, node, guarded, alias_of, absent, findings)
            for child in ast.iter_child_nodes(node):
                visit(child, guarded)

        for top in ast.iter_child_nodes(ctx.tree):
            visit(top, False)
        return findings

    def _check_node(self, ctx, node, guarded, alias_of, absent,
                    findings) -> None:
        if guarded:
            return
        api = None
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                candidate = f"{node.module}.{a.name}"
                if candidate in absent:
                    api = candidate
                    break
        elif isinstance(node, ast.Attribute):
            name = dotted_name(node)
            if name:
                root, _, rest = name.partition(".")
                normalized = (f"{alias_of[root]}.{rest}"
                              if root in alias_of and rest else name)
                # longest-prefix match so jax.lax.axis_size resolves even
                # as part of a longer chain (jax.lax.axis_size("dp") is a
                # Call over the Attribute, handled; attribute-of-result
                # chains match on their prefix)
                for candidate in absent:
                    if normalized == candidate or \
                            normalized.startswith(candidate + "."):
                        api = candidate
                        break
        if api is None:
            return
        from hfrep_tpu.analysis.project import PINNED_JAX
        findings.append(ctx.finding(
            "HF005", node,
            f"{api} does not exist on the pinned runtime "
            f"(jax {PINNED_JAX}) and the reference is unguarded — "
            f"this code path is dead here; {absent[api]}"))
