"""JPX006 — scan-carry bloat against the boundary's declared budget.

Everything that rides a ``lax.scan`` carry is live for EVERY iteration
— XLA cannot free or overlap it — so carry bytes are the scarcest
memory in the program.  This repo's carries are deliberately sized
(params + opt state + a handful of scalars; the flight-recorder health
traces ride the stacked OUTPUTS precisely to stay out of the carry),
and each registered boundary declares a ``carry_budget_bytes`` ceiling
at its audit fixture shapes.  A grown carry — someone threading a
per-epoch metrics dict, a debug buffer, or an accidentally-carried
dataset through the loop — blows the declared budget and fails the
gate at analysis time, long before prod shapes multiply the waste by
five orders of magnitude.

The measurement walks every scan eqn in the (nested) jaxpr and sums
the carry block of its body (``in_avals[num_consts : num_consts +
num_carry]``); nested scans (vmapped lanes) each count separately, so
the budget is per-scan, set ~1.5x the audited carry at registration
time.  ``carry_budget_bytes=None`` (the default) skips the boundary.
"""

from __future__ import annotations

from typing import List

from hfrep_tpu.analysis.engine import Finding
from hfrep_tpu.analysis.rules.jpx_base import (ProgramContext, ProgramRule,
                                               aval_bytes, iter_eqns,
                                               scan_carry_avals)


class ProgramCarryRule(ProgramRule):
    id = "JPX006"
    name = "program-carry"
    description = ("a scan carry grew past the boundary's declared byte "
                   "budget — carried state is live for every iteration "
                   "and should hold params+opt state, not buffers")

    def check_program(self, pctx: ProgramContext) -> List[Finding]:
        budget = pctx.boundary.carry_budget_bytes
        if budget is None or pctx.jaxpr is None:
            return []
        findings: List[Finding] = []
        for idx, (eqn, _) in enumerate(iter_eqns(pctx.jaxpr)):
            if eqn.primitive.name != "scan":
                continue
            carry = scan_carry_avals(eqn)
            total = sum(aval_bytes(a) for a in carry)
            if total > budget:
                findings.append(pctx.finding(
                    self.id,
                    f"scan #{idx} carries {total} bytes across "
                    f"{len(carry)} leaves — over the declared budget of "
                    f"{budget} bytes at audit shapes; move non-state "
                    "through the stacked outputs or raise the declared "
                    "budget with justification",
                    token=f"scan{idx}"))
        return findings
