"""HF003 — atomic-publish discipline.

The durability model (PR 5) is only as strong as its weakest writer: one
``open(path, "w")`` straight into a checkpoint/result/artifact location
and a crash mid-write leaves a torn file that resume paths, manifests
and the committed history store happily read back.  The sanctioned
writers — ``utils.checkpoint.write_atomic`` (directories),
``utils.checkpoint.atomic_text`` (single files),
``obs.manifest._write_with_retry`` (manifests; lenient readers) —
stage into a tmp sibling and publish by rename.

Flagged: ``open(..., "w"/"wb")``, ``Path.write_text``/``write_bytes``,
``np.save``/``np.savez``/``np.savetxt`` whose destination *names an
artifact location* (a path expression mentioning ``results``,
``checkpoint(s)``/``ckpt``, ``snapshot(s)``, ``history``, ``spool``,
``manifest`` or ``artifact(s)``) — outside the sanctioned contexts:

* lexically inside one of the sanctioned writer functions themselves;
* a destination rooted at a ``tmp``/``tmp_dir``/``tmp_path`` name — the
  ``writer(tmp)`` callback convention, where ``write_atomic`` owns the
  publish (this is the pinned false-positive class: staging writes are
  the *mechanism* of atomic publication, not a violation of it).

Append-mode opens are exempt (the event stream and history store are
append-only by design, with torn-tail-tolerant readers).  Tests are
exempt.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name

ARTIFACT_TOKENS = {
    "results", "ckpt", "checkpoint", "checkpoints", "snapshot",
    "snapshots", "history", "spool", "manifest", "artifact", "artifacts",
}

#: destination roots that mark the write as staging inside an atomic
#: publish (the writer-callback convention)
STAGING_ROOTS = {"tmp", "tmp_dir", "tmp_path"}

_NP_WRITERS = {"save", "savez", "savez_compressed", "savetxt"}


def _expr_tokens(node: ast.AST) -> Set[str]:
    """Identifier-ish tokens of a path expression: names, attribute
    parts, and path segments of string literals — lowercased, split on
    separators, so ``os.path.join(ckpt_dir, name)`` yields ``ckpt``."""
    tokens: Set[str] = set()

    def add(text: str) -> None:
        for sep in ("/", "\\", "."):
            text = text.replace(sep, "_")
        for part in text.lower().split("_"):
            if part:
                tokens.add(part)

    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            add(sub.id)
        elif isinstance(sub, ast.Attribute):
            add(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            add(sub.value)
    return tokens


def _root_name(node: ast.AST) -> Optional[str]:
    """The leftmost name a destination expression hangs off
    (``(tmp / "x").write_text`` -> "tmp"; ``args.out`` -> "args")."""
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.BinOp):      # Path / "name"
            node = node.left
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _write_mode(call: ast.Call) -> Optional[str]:
    """The mode literal of an ``open`` call (positional or keyword)."""
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


class AtomicWriteRule(Rule):
    id = "HF003"
    name = "atomic-publish-discipline"
    description = ("direct writes into checkpoint/result/artifact "
                   "locations must go through write_atomic/atomic_text/"
                   "_write_with_retry")

    def check(self, ctx: FileContext) -> List[Finding]:
        from hfrep_tpu.analysis.project import _is_test_path
        from hfrep_tpu.analysis.rules.base import import_aliases

        project = ctx.project
        if project is None or not project.atomic_writers:
            return []
        if _is_test_path(ctx.relpath):
            return []
        findings: List[Finding] = []
        sanctioned_fns = project.atomic_writers
        # only real numpy writers count as np.save-class writes — a
        # dotted ``ckpt.save(...)`` is the atomic checkpoint writer
        # itself, not a raw array dump (pinned false-positive class)
        self._np_aliases = import_aliases(ctx.tree, "numpy")

        def scan(scope: ast.AST, inside_sanctioned: bool) -> None:
            for node in ast.iter_child_nodes(scope):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan(node, inside_sanctioned
                         or node.name in sanctioned_fns)
                    continue
                if isinstance(node, ast.Call):
                    self._check_call(ctx, node, inside_sanctioned, findings)
                scan(node, inside_sanctioned)

        scan(ctx.tree, False)
        return findings

    def _check_call(self, ctx: FileContext, call: ast.Call,
                    inside_sanctioned: bool,
                    findings: List[Finding]) -> None:
        if inside_sanctioned:
            return
        dest: Optional[ast.AST] = None
        what = None
        fname = dotted_name(call.func)
        if fname and fname.split(".")[0] in ("open",) and call.args:
            mode = _write_mode(call)
            if not mode or not any(c in mode for c in "wx"):
                return
            dest, what = call.args[0], f"open(..., {mode!r})"
        elif isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("write_text", "write_bytes"):
            dest, what = call.func.value, f".{call.func.attr}()"
        elif fname and "." in fname \
                and fname.split(".")[-1] in _NP_WRITERS \
                and fname.rsplit(".", 1)[0] in getattr(self, "_np_aliases", ()) \
                and call.args:
            dest, what = call.args[0], fname.split(".", 1)[-1] + "()"
        if dest is None:
            return
        root = _root_name(dest)
        if root in STAGING_ROOTS:
            return
        tokens = _expr_tokens(dest)
        hit = tokens & ARTIFACT_TOKENS
        if not hit:
            return
        findings.append(ctx.finding(
            "HF003", call,
            f"direct {what} into an artifact location "
            f"({'/'.join(sorted(hit))}): a crash mid-write leaves a torn "
            "file readers trust — publish through write_atomic/"
            "atomic_text/_write_with_retry instead"))
