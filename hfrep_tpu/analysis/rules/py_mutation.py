"""JAX005 — mutable default arguments & in-place mutation of arg pytrees.

Two related impurity classes, one rule:

* **Mutable defaults** (``def f(x, acc=[])``): the default is evaluated
  once at import; state leaks across calls.  In a JAX codebase this is
  doubly poisonous because a cached default list/dict can end up baked
  into a traced closure on first call and silently shared by every
  later trace.  Checked on *every* function.

* **In-place mutation of parameters** (``params['w'] = …``,
  ``batch.update(…)``): a jitted function must be pure — mutation
  happens once at trace time, the compiled program replays the traced
  *values*, and the Python-side object silently diverges from what the
  program computes on every later call.  Checked only on functions that
  are actually jit/pmap/shard_map-compiled (host-side accumulators and
  pallas kernel ``ref[...] =`` stores are sanctioned idioms, not bugs);
  ``self``/``cls`` are exempt, as are names rebound before the mutation
  (``x = dict(x)``).
"""

from __future__ import annotations

import ast
from typing import List, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import (
    Rule, direct_nodes, jitted_defs, tracer_scopes,
)

_MUTABLE_DISPLAYS = (ast.List, ast.Dict, ast.Set)
_MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "defaultdict",
                  "OrderedDict", "Counter", "deque"}
_MUTATORS = {"append", "extend", "insert", "update", "setdefault", "pop",
             "popitem", "clear", "remove", "sort", "reverse", "add",
             "discard", "appendleft", "extendleft"}


class MutationRule(Rule):
    id = "JAX005"
    name = "arg-mutation"
    description = ("mutable default arguments (any function) and in-place "
                   "mutation of arguments inside jitted functions")

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_defaults(ctx, node))
        seen: Set[int] = set()
        for fn in jitted_defs(ctx.tree):
            for scope, tracers in tracer_scopes(fn):
                if id(scope) in seen:
                    continue
                seen.add(id(scope))
                findings.extend(self._check_mutations(
                    ctx, scope, getattr(fn, "name", "<fn>"), tracers))
        return findings

    # ---------------------------------------------------------- defaults
    def _check_defaults(self, ctx: FileContext, fn) -> List[Finding]:
        findings: List[Finding] = []
        a = fn.args
        for default in [*a.defaults, *[d for d in a.kw_defaults if d]]:
            bad = isinstance(default, _MUTABLE_DISPLAYS)
            if (not bad and isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in _MUTABLE_CALLS):
                bad = True
            if bad:
                findings.append(ctx.finding(
                    self.id, default,
                    f"mutable default argument in `{fn.name}`; default "
                    f"to None and construct inside the body"))
        return findings

    # --------------------------------------------------------- mutations
    def _check_mutations(self, ctx: FileContext, scope: ast.AST,
                         jit_name: str, tracers: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        if not tracers:
            return findings
        rebound: Set[str] = set()

        def param_root(node: ast.AST) -> str:
            """name of the tracer a subscript/attribute chain hangs off,
            or '' when the root is not an un-rebound tracer param."""
            while isinstance(node, (ast.Subscript, ast.Attribute)):
                node = node.value
            if (isinstance(node, ast.Name) and node.id in tracers
                    and node.id not in rebound):
                return node.id
            return ""

        for node in direct_nodes(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        rebound.add(t.id)       # x = dict(x): later edits fine
                    else:
                        root = param_root(t)
                        if root:
                            findings.append(ctx.finding(
                                self.id, t,
                                f"in-place mutation of argument {root!r} "
                                f"inside jitted `{jit_name}`; rebuild the "
                                f"pytree instead (replace/tree_map)"))
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    rebound.add(node.target.id)  # x += 1 rebinds the name
                else:
                    root = param_root(node.target)
                    if root:
                        findings.append(ctx.finding(
                            self.id, node.target,
                            f"in-place mutation of argument {root!r} "
                            f"inside jitted `{jit_name}`; rebuild the "
                            f"pytree instead"))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    root = param_root(t)
                    if root and not isinstance(t, ast.Name):
                        findings.append(ctx.finding(
                            self.id, t,
                            f"`del` into argument {root!r} inside jitted "
                            f"`{jit_name}`; rebuild the pytree instead"))
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                        and isinstance(f.value, ast.Name)
                        and f.value.id in tracers
                        and f.value.id not in rebound):
                    findings.append(ctx.finding(
                        self.id, node,
                        f"in-place `{f.attr}` on argument {f.value.id!r} "
                        f"inside jitted `{jit_name}`; copy or rebuild "
                        f"instead"))
        return findings
