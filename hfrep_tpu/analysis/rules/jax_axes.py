"""JAX003 — collective axis names must match declared mesh axes.

Failure mode: ``lax.psum(x, 'db')`` against a mesh declared ``('dp',)``
is a *runtime* NameError on TPU — but only on the code path that
actually executes the collective, which for rarely-taken branches
(recovery paths, eval-only reductions) can be weeks after the typo
landed.  Cross-checking every literal axis string against the axes the
project declares (``parallel/mesh.py`` Mesh constructions, ``MeshConfig``
defaults, ``axis_name=``/``axis_names=`` keywords and parameter
defaults) turns that into a static error.

The engine's project pre-pass (:func:`hfrep_tpu.analysis.engine.analyze_paths`)
unions :func:`collect_declared_axes` over every analyzed file into
``ctx.known_axes``; single-file runs can inject the set explicitly.
When no axes are known at all the rule stays silent rather than flag
every collective in a fresh checkout.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name

#: collectives whose 2nd positional / ``axis_name=`` argument names a mesh axis
_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute", "pshuffle",
    "all_to_all", "axis_index", "axis_size", "psum_scatter", "pbroadcast",
}
_AXIS_PARAM_NAMES = {"axis_name", "axis_names", "batch_axis", "sp_axis",
                     "tp_axis", "pp_axis", "dp_axis", "mesh_axis"}


def _axis_strings(node: ast.AST) -> Set[str]:
    """String constants in a literal (string or tuple/list of strings)."""
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def collect_declared_axes(tree: ast.AST) -> Set[str]:
    """Axis names this file *declares* (as opposed to *uses*)."""
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            tail = fname.split(".")[-1] if fname else ""
            if tail == "Mesh":
                # Mesh(devices, ('dp', 'sp')) — names are the 2nd arg
                for arg in node.args[1:2]:
                    axes |= _axis_strings(arg)
            # only mesh/SPMD *constructors* declare axes through call
            # keywords; an `axis_name='db'` kwarg on an ordinary helper
            # call is a use — counting it would let a typo self-whitelist
            # project-wide
            if tail in ("shard_map", "pmap", "xmap") or tail.startswith(
                    ("make_mesh", "Mesh")):
                for kw in node.keywords:
                    if kw.arg in _AXIS_PARAM_NAMES:
                        axes |= _axis_strings(kw.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = [*a.posonlyargs, *a.args]
            defaults = list(a.defaults)
            # defaults align right: pad on the left
            defaults = [None] * (len(params) - len(defaults)) + defaults
            for p, d in zip(params, defaults):
                if d is not None and p.arg in _AXIS_PARAM_NAMES:
                    axes |= _axis_strings(d)
            for p, d in zip(a.kwonlyargs, a.kw_defaults):
                if d is not None and p.arg in _AXIS_PARAM_NAMES:
                    axes |= _axis_strings(d)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in _AXIS_PARAM_NAMES:
                    axes |= _axis_strings(node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if (isinstance(node.target, ast.Name)
                    and node.target.id in _AXIS_PARAM_NAMES):
                axes |= _axis_strings(node.value)
    return axes


class AxisConsistencyRule(Rule):
    id = "JAX003"
    name = "axis-name-consistency"
    description = ("literal axis names at psum/pmean/all_gather/… call "
                   "sites must be declared mesh axes")

    def check(self, ctx: FileContext) -> List[Finding]:
        known = set(ctx.known_axes) | collect_declared_axes(ctx.tree)
        if not known:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            tail = fname.split(".")[-1] if fname else ""
            if tail not in _COLLECTIVES:
                continue
            axis_arg = self._axis_argument(node, tail)
            if axis_arg is None:
                continue
            for axis in sorted(_axis_strings(axis_arg)):
                if axis not in known:
                    findings.append(ctx.finding(
                        self.id, node,
                        f"collective `{tail}` over undeclared axis "
                        f"{axis!r}; declared axes: "
                        f"{', '.join(sorted(known))}"))
        return findings

    def _axis_argument(self, call: ast.Call, tail: str) -> Optional[ast.AST]:
        # NOT `axis=`: on all_gather/all_to_all that kwarg is the
        # concatenation *dimension*, never the mesh axis
        for kw in call.keywords:
            if kw.arg == "axis_name":
                return kw.value
        # positional: axis_index/axis_size take it 1st, the rest 2nd
        pos = 0 if tail in ("axis_index", "axis_size") else 1
        if len(call.args) > pos:
            return call.args[pos]
        return None
