"""JPX002 — precision-policy conformance of a traced program.

A boundary registered with ``policy="bf16"`` promises its matmuls run
at the accelerator's matrix-unit rate.  The classic leak: one code path
builds the model WITHOUT threading the compute dtype (a constructor
default, a serving head built from a different factory than training),
and the bf16 configuration silently traces full-f32 dots — correct
numerics, 8x the MXU cost, and nothing crashes so nobody notices.

The check counts f32-input ``dot_general``/``conv_general_dilated``
eqns in the traced jaxpr (recursing through scan/cond/pjit bodies).
fp32 *accumulation* is deliberate policy here — losses, optimizer
updates and reductions lift to f32 (``core/precision.py``) — but those
are adds/mults, not dots; the rare legitimate f32 dot under a bf16
policy (e.g. an fp32 OLS solve stage fused into the same program) is
declared per boundary via ``f32_dot_allow``.  Boundaries with
``policy="fp32"`` (the default) are exempt: all-f32 programs are the
contract there.

When a jaxpr is unavailable but HLO text is, the same census runs over
``stablehlo.dot_general``/``stablehlo.convolution`` lines with f32
operand tensor types — the fixture tests pin both paths.
"""

from __future__ import annotations

import re
from typing import List

from hfrep_tpu.analysis.engine import Finding
from hfrep_tpu.analysis.rules.jpx_base import (ProgramContext, ProgramRule,
                                               eqn_in_avals, iter_eqns)

DOT_PRIMITIVES = frozenset({"dot_general", "conv_general_dilated"})

#: one StableHLO dot/conv op line; operand types trail in the signature
_HLO_DOT_RE = re.compile(
    r"stablehlo\.(dot_general|convolution)\b.*?:\s*\(([^)]*)\)")


def _count_f32_dots_jaxpr(jaxpr) -> int:
    n = 0
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name not in DOT_PRIMITIVES:
            continue
        dtypes = {str(getattr(a, "dtype", "?")) for a in eqn_in_avals(eqn)}
        if dtypes and all(d == "float32" for d in dtypes):
            n += 1
    return n


def _count_f32_dots_hlo(hlo: str) -> int:
    n = 0
    for m in _HLO_DOT_RE.finditer(hlo):
        operand_types = m.group(2)
        if "xf32>" in operand_types or "tensor<f32>" in operand_types:
            if "bf16" not in operand_types:
                n += 1
    return n


class ProgramPrecisionRule(ProgramRule):
    id = "JPX002"
    name = "program-precision"
    description = ("f32 dot/conv in the compute path of a bf16-policy "
                   "program — a dtype not threaded through one build "
                   "path runs the matmuls off the MXU fast path")

    def check_program(self, pctx: ProgramContext) -> List[Finding]:
        if pctx.boundary.policy != "bf16":
            return []
        if pctx.jaxpr is not None:
            count, via = _count_f32_dots_jaxpr(pctx.jaxpr), "jaxpr"
        elif pctx.hlo is not None:
            count, via = _count_f32_dots_hlo(pctx.hlo), "hlo"
        else:
            return []
        allow = pctx.boundary.f32_dot_allow
        if count <= allow:
            return []
        return [pctx.finding(
            self.id,
            f"{count} f32 dot/conv op(s) in a bf16-policy program "
            f"(allowlist {allow}, counted via {via}) — a compute dtype "
            "was not threaded into this build path",
            token="f32dot")]
