"""Rule registry.  Rule IDs are stable API: baselines, ``# noqa:`` codes
and CI configuration all key on them, so new rules append, never renumber."""

from __future__ import annotations

from hfrep_tpu.analysis.rules.base import Rule  # noqa: F401
from hfrep_tpu.analysis.rules.jax_host import HostOpsInJitRule
from hfrep_tpu.analysis.rules.jax_keys import KeyReuseRule
from hfrep_tpu.analysis.rules.jax_axes import AxisConsistencyRule
from hfrep_tpu.analysis.rules.jax_donation import DonationReuseRule
from hfrep_tpu.analysis.rules.py_mutation import MutationRule
from hfrep_tpu.analysis.rules.shape_contracts import ShapeContractRule

ALL_RULES = (
    HostOpsInJitRule(),
    KeyReuseRule(),
    AxisConsistencyRule(),
    DonationReuseRule(),
    MutationRule(),
    ShapeContractRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}
