"""Rule registry.  Rule IDs are stable API: baselines, ``# noqa:`` codes
and CI configuration all key on them, so new rules append, never renumber."""

from __future__ import annotations

from hfrep_tpu.analysis.rules.base import Rule  # noqa: F401
from hfrep_tpu.analysis.rules.jax_host import HostOpsInJitRule
from hfrep_tpu.analysis.rules.jax_keys import KeyReuseRule
from hfrep_tpu.analysis.rules.jax_axes import AxisConsistencyRule
from hfrep_tpu.analysis.rules.jax_donation import DonationReuseRule
from hfrep_tpu.analysis.rules.py_mutation import MutationRule
from hfrep_tpu.analysis.rules.shape_contracts import ShapeContractRule
from hfrep_tpu.analysis.rules.hf_gauge_thresholds import GaugeThresholdRule
from hfrep_tpu.analysis.rules.hf_fault_sites import FaultSiteRule
from hfrep_tpu.analysis.rules.hf_atomic_writes import AtomicWriteRule
from hfrep_tpu.analysis.rules.hf_obs_doc import ObsDocRule
from hfrep_tpu.analysis.rules.hf_version_gate import VersionGateRule
from hfrep_tpu.analysis.rules.hf_thread_signal import ThreadSignalRule
from hfrep_tpu.analysis.rules.hf_exit_codes import ExitCodeRule
from hfrep_tpu.analysis.rules.hf_mesh_launch import MeshLaunchRule
from hfrep_tpu.analysis.rules.hf_wallclock import WallClockRule
from hfrep_tpu.analysis.rules.hf_boundary_sync import BoundarySyncRule
from hfrep_tpu.analysis.rules.hf_drive_envelope import DriveEnvelopeRule
from hfrep_tpu.analysis.rules.jpx_base import ProgramRule  # noqa: F401
from hfrep_tpu.analysis.rules.jpx_donation import ProgramDonationRule
from hfrep_tpu.analysis.rules.jpx_precision import ProgramPrecisionRule
from hfrep_tpu.analysis.rules.jpx_hostsync import ProgramHostSyncRule
from hfrep_tpu.analysis.rules.jpx_retrace import ProgramRetraceRule
from hfrep_tpu.analysis.rules.jpx_sharding import ProgramShardingRule
from hfrep_tpu.analysis.rules.jpx_carry import ProgramCarryRule

ALL_RULES = (
    HostOpsInJitRule(),
    KeyReuseRule(),
    AxisConsistencyRule(),
    DonationReuseRule(),
    MutationRule(),
    ShapeContractRule(),
    # cross-layer rules (ISSUE 11): whole-project string-protocol
    # invariants, fed by the ProjectModel pre-pass
    GaugeThresholdRule(),
    FaultSiteRule(),
    AtomicWriteRule(),
    ObsDocRule(),
    VersionGateRule(),
    ThreadSignalRule(),
    ExitCodeRule(),
    MeshLaunchRule(),
    # the wall-clock ledger's monopoly (ISSUE 18): raw clock reads
    # outside hfrep_tpu/obs/ measure time the ledger cannot conserve
    WallClockRule(),
    # the async boundary engine's overlap contract (ISSUE 19): an eager
    # scalar sync inside a boundary loop re-serializes the drive
    BoundarySyncRule(),
    # the Drive runtime's monopoly (ISSUE 20): hand-rolled survival
    # envelopes outside resilience/drive.py regrow the copy-paste class
    DriveEnvelopeRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}

#: phase-3 program-audit rules (ISSUE 16): run over TRACED programs by
#: ``python -m hfrep_tpu.analysis audit``, never over source text —
#: deliberately not in ALL_RULES so `check` runs stay jax-trace-free
PROGRAM_RULES = (
    ProgramDonationRule(),
    ProgramPrecisionRule(),
    ProgramHostSyncRule(),
    ProgramRetraceRule(),
    ProgramShardingRule(),
    ProgramCarryRule(),
)

PROGRAM_RULES_BY_ID = {r.id: r for r in PROGRAM_RULES}
