"""HF006 — signal-handler and lock-discipline safety.

Two host-side concurrency classes the serving/resilience layers live
or die by:

**Signal handlers.**  A handler registered with ``signal.signal`` runs
re-entrantly at an arbitrary bytecode boundary.  The repo's sanctioned
handlers set a flag (``request_drain``) or raise a typed exception (the
selftest watchdog) — both async-signal-safe.  Flagged: *direct* calls
in a registered handler's body to non-reentrant machinery — ``open``,
file ``.write``/``.flush``, ``json.dump``, ``logging``, lock
``.acquire``/``with lock:``, ``time.sleep``, ``subprocess``/
``os.system``.  One level only, by design: transitive analysis would
flag the flag-setters themselves (``request_drain`` emits telemetry —
behind its own try/except, which is the sanctioned pattern).

**Lock discipline.**  A class that writes an attribute under ``with
self._lock:`` in one method has declared that attribute
lock-protected; writing it elsewhere WITHOUT the lock is a data race
that CPython's scheduling hides on laptops and the serve worker pool
hits under load.  Attributes are matched per class; ``__init__`` is
exempt (pre-concurrency construction), and a ``threading.Condition``
constructed over the lock counts as holding it (``with self._idle:``
in the server IS ``with self._lock:``).  Methods whose name ends in
``_locked`` are exempt — the caller-holds-the-lock convention.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name

_UNSAFE_CALL_TAILS = {"open", "acquire", "sleep", "dump", "dumps",
                      "write", "flush", "print", "system", "run",
                      "Popen", "call", "check_call", "check_output"}
_UNSAFE_PREFIXES = ("logging.", "subprocess.", "os.system")

_LOCK_FACTORIES = {"Lock", "RLock"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` (or ``self.x[...]``) -> "x"."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class ThreadSignalRule(Rule):
    id = "HF006"
    name = "signal-thread-safety"
    description = ("non-reentrant work in registered signal handlers; "
                   "lock-protected attributes written without the lock")

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        self._check_signal_handlers(ctx, findings)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._check_lock_discipline(ctx, node, findings)
        return findings

    # ------------------------------------------------------ signal safety
    def _check_signal_handlers(self, ctx: FileContext,
                               findings: List[Finding]) -> None:
        handlers: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname and fname.split(".")[-1] == "signal" \
                        and len(node.args) >= 2 \
                        and isinstance(node.args[1], ast.Name):
                    # signal.signal(SIG, handler) — only restore-shaped
                    # second args (names) register local handlers
                    handlers.add(node.args[1].id)
        if not handlers:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in handlers:
                self._scan_handler_body(ctx, node, findings)

    def _scan_handler_body(self, ctx: FileContext, fn: ast.AST,
                           findings: List[Finding]) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            tail = fname.split(".")[-1]
            if fname.startswith(_UNSAFE_PREFIXES) \
                    or tail in _UNSAFE_CALL_TAILS:
                findings.append(ctx.finding(
                    "HF006", node,
                    f"{fname or tail}() inside the registered signal "
                    f"handler {getattr(fn, 'name', '?')!r}: signal "
                    "handlers run re-entrantly at arbitrary bytecode "
                    "boundaries — set a flag or raise; do the work at a "
                    "safe boundary"))

    # ----------------------------------------------------- lock discipline
    def _check_lock_discipline(self, ctx: FileContext, cls: ast.ClassDef,
                               findings: List[Finding]) -> None:
        locks: Set[str] = set()
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        init = next((m for m in methods if m.name == "__init__"), None)
        if init is None:
            return
        # pass 1a: lock attrs (self.X = threading.Lock()/RLock()/Condition())
        cond_wraps: Dict[str, Optional[str]] = {}
        for node in ast.walk(init):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            attr = _self_attr(node.targets[0])
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            factory = dotted_name(node.value.func) or ""
            tail = factory.split(".")[-1]
            if tail in _LOCK_FACTORIES:
                locks.add(attr)
            elif tail == "Condition":
                inner = (_self_attr(node.value.args[0])
                         if node.value.args else None)
                cond_wraps[attr] = inner     # None = its own internal lock
        for attr, inner in cond_wraps.items():
            if inner is None or inner in locks:
                locks.add(attr)              # holding the cond = the lock
        if not locks:
            return

        # one pass per method: record every self-attr write and every
        # intra-class ``self._helper()`` call with its under-lock flag
        writes: Dict[str, List] = {}        # method -> [(attr, node, under)]
        calls: Dict[str, List] = {}         # method -> [(callee, under)]

        def scan_method(fn: ast.AST) -> None:
            def walk(node: ast.AST, under: bool) -> None:
                if isinstance(node, ast.With):
                    held = under or any(
                        _self_attr(item.context_expr) in locks
                        for item in node.items)
                    for child in node.body:
                        walk(child, held)
                    return
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is not None and attr not in locks:
                            writes[fn.name].append((attr, node, under))
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee is not None:
                        calls[fn.name].append((callee, under))
                for child in ast.iter_child_nodes(node):
                    if not isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                        walk(child, under)

            writes[fn.name], calls[fn.name] = [], []
            walk(fn, False)

        for m in methods:
            if m.name != "__init__":
                scan_method(m)

        # caller-holds-the-lock helpers: a PRIVATE method every
        # intra-class call site of which is lock-held runs under the
        # lock by contract (CircuitBreaker._trip's "# lock held by
        # caller" pattern — the pinned false-positive class); iterate to
        # a fixpoint so locked helpers calling locked helpers resolve
        locked_ctx: Set[str] = {m.name for m in methods
                                if m.name.endswith("_locked")}
        method_names = {m.name for m in methods}
        changed = True
        while changed:
            changed = False
            for name in method_names:
                if name in locked_ctx or not name.startswith("_") \
                        or name == "__init__":
                    continue
                sites = [(caller, under)
                         for caller, cs in calls.items()
                         for callee, under in cs if callee == name]
                if sites and all(under or caller in locked_ctx
                                 for caller, under in sites):
                    locked_ctx.add(name)
                    changed = True

        protected: Set[str] = set()
        unprotected: Dict[str, List] = {}
        for name, ws in writes.items():
            in_locked_helper = name in locked_ctx
            for attr, node, under in ws:
                if under or in_locked_helper:
                    protected.add(attr)
                else:
                    unprotected.setdefault(attr, []).append(node)
        for attr in sorted(protected & set(unprotected)):
            for node in unprotected[attr]:
                findings.append(ctx.finding(
                    "HF006", node,
                    f"self.{attr} is written under `with self.<lock>:` "
                    f"elsewhere in {cls.name} but written here without "
                    "it — a data race the GIL hides until the worker "
                    "pool is actually loaded"))
