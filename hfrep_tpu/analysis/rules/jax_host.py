"""JAX001 — host numpy / Python control flow on tracers inside jitted code.

Failure mode on TPU: inside a ``@jax.jit`` / ``shard_map`` function a
host ``np.*`` call silently pulls the tracer to the host
(``ConcretizationTypeError`` at best, a wrong constant baked into the
compiled program at worst), and a Python ``if``/``for`` on a traced
value either raises ``TracerBoolConversionError`` or — when the value
happens to be concrete at trace time — freezes one branch into the
compiled program for *all* future inputs.

Detection is deliberately conservative to stay useful as a CI gate:

* a function counts as *jitted* when a jit/pmap/shard_map decorator is
  attached, or its name is passed (possibly through
  ``functools.partial``) as the first argument to jit/pmap/shard_map
  anywhere in the file;
* only **parameter** names are treated as tracers, resolved per scope
  with proper shadowing (a static ``for i in range(n)`` loop variable
  shadows a same-named nested-function parameter, and vice versa);
  locals derived from params are not chased — too many static locals
  (``axis_size``, shapes) would drown the signal;
* ``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``x.size``, ``len(x)``,
  ``isinstance(x, …)`` and ``x is None`` tests are *static* at trace
  time and never flagged.
"""

from __future__ import annotations

import ast
from typing import List, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import (
    Rule, direct_nodes, dotted_name, import_aliases, jitted_defs,
    tracer_scopes,
)

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type", "range"}


def _tracer_loads(test: ast.AST, tracers: Set[str]) -> List[ast.Name]:
    """Name loads of tracer params in a test expr, minus static contexts."""
    hits: List[ast.Name] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return              # x.shape[...] etc: static under trace
            visit(node.value)
            return
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname and fname.split(".")[-1] in _STATIC_CALLS:
                return              # len(x), isinstance(x, T): static
            for child in ast.iter_child_nodes(node):
                visit(child)
            return
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None`: a static identity test
            if (len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.Is, ast.IsNot))):
                return
            for child in ast.iter_child_nodes(node):
                visit(child)
            return
        if isinstance(node, ast.Name) and node.id in tracers:
            hits.append(node)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return hits


class HostOpsInJitRule(Rule):
    id = "JAX001"
    name = "host-ops-in-jit"
    description = ("host numpy calls and Python if/for/while on traced "
                   "values inside jit/pmap/shard_map functions")

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        np_roots = import_aliases(ctx.tree, "numpy")
        seen_scopes: Set[int] = set()
        for fn in jitted_defs(ctx.tree):
            for scope, tracers in tracer_scopes(fn):
                # a jitted def nested in another jitted def would be
                # visited twice; report each scope once
                if id(scope) in seen_scopes:
                    continue
                seen_scopes.add(id(scope))
                findings.extend(self._check_scope(
                    ctx, scope, getattr(fn, "name", "<fn>"), tracers,
                    np_roots))
        return findings

    def _check_scope(self, ctx: FileContext, scope: ast.AST,
                     jit_name: str, tracers: Set[str],
                     np_roots: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        for node in direct_nodes(scope):
            if isinstance(node, (ast.If, ast.While)):
                for hit in _tracer_loads(node.test, tracers):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    findings.append(ctx.finding(
                        self.id, node,
                        f"Python `{kind}` on traced value {hit.id!r} inside "
                        f"jitted `{jit_name}`; use lax.cond/jnp.where or "
                        f"mark it static"))
            elif isinstance(node, ast.For):
                it = node.iter
                if isinstance(it, ast.Name) and it.id in tracers:
                    findings.append(ctx.finding(
                        self.id, node,
                        f"Python `for` over traced value {it.id!r} inside "
                        f"jitted `{jit_name}`; use lax.scan/fori_loop"))
                elif (isinstance(it, ast.Attribute)
                      and isinstance(it.value, ast.Name)
                      and it.value.id in tracers
                      and it.attr not in _STATIC_ATTRS):
                    findings.append(ctx.finding(
                        self.id, node,
                        f"Python `for` over traced value "
                        f"`{it.value.id}.{it.attr}` inside jitted "
                        f"`{jit_name}`; use lax.scan/fori_loop"))
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if not fname or "." not in fname:
                    continue
                root = fname.split(".")[0]
                if root not in np_roots:
                    continue
                arg_nodes = list(node.args) + [kw.value for kw in node.keywords]
                touched = sorted({
                    n.id for a in arg_nodes for n in ast.walk(a)
                    if isinstance(n, ast.Name) and n.id in tracers})
                if touched:
                    findings.append(ctx.finding(
                        self.id, node,
                        f"host numpy call `{fname}` on traced value(s) "
                        f"{', '.join(touched)} inside jitted "
                        f"`{jit_name}`; use jnp"))
        return findings
