"""JAX004 — donated buffer read after donation.

Failure mode: ``jax.jit(step, donate_argnums=(0,))`` lets XLA alias the
input buffer into the output; touching the Python-side array afterwards
reads freed/aliased device memory and raises
``RuntimeError: Array has been deleted`` — but only when the runtime
actually re-used the buffer, so CPU test runs pass and the TPU job dies.
The sanctioned pattern rebinds in one statement, ``state = step(state,
key)``; this rule flags any *later* load of a name that was passed in a
donated position and never rebound.

Scope model: donors (jit-wrapped callables with a literal
``donate_argnums``) are collected module-wide — both ``name = jax.jit(f,
donate_argnums=…)`` bindings and ``@partial(jax.jit, donate_argnums=…)``
decorated defs — then each function body is linearly scanned.  The scan
is straight-line only (no fixed-point over loop back-edges): a donation
and use in sequence is caught, exotic re-entrant flows are not.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import (
    Rule, dotted_name, decorator_jit_call, jit_call_info, scope_body,
    walk_scopes,
)


def _literal_argnums(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def collect_donors(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """name -> donated positions, for every jit wrapper visible by name."""
    donors: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = jit_call_info(node.value)
            nums = _donate_kw(call) if call is not None else None
            if nums:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donors[t.id] = nums
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = decorator_jit_call(dec)
                nums = _donate_kw(call) if call is not None else None
                if nums:
                    donors[node.name] = nums
    return donors


def _donate_kw(call: Optional[ast.Call]) -> Optional[Tuple[int, ...]]:
    if call is None:
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            if kw.arg == "donate_argnames":
                return None         # name-keyed donation: not tracked
            return _literal_argnums(kw.value)
    return None


class DonationReuseRule(Rule):
    id = "JAX004"
    name = "use-after-donation"
    description = ("a name passed in a donate_argnums position is read "
                   "again without being rebound")

    def check(self, ctx: FileContext) -> List[Finding]:
        donors = collect_donors(ctx.tree)
        if not donors:
            return []
        findings: List[Finding] = []
        for scope in walk_scopes(ctx.tree):
            findings.extend(self._scan_scope(ctx, scope, donors))
        return findings

    def _scan_scope(self, ctx: FileContext, scope: ast.AST,
                    donors: Dict[str, Tuple[int, ...]]) -> List[Finding]:
        findings: List[Finding] = []

        def scan_expr_uses(node: ast.AST, donated: Dict[str, int]) -> None:
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in donated):
                    findings.append(ctx.finding(
                        self.id, sub,
                        f"{sub.id!r} was donated on line {donated[sub.id]} "
                        f"(donate_argnums) and read again; its buffer may "
                        f"already be aliased — rebind the result instead"))

        def record_donations(node: ast.AST, donated: Dict[str, int]) -> None:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = dotted_name(sub.func)
                if callee not in donors:
                    continue
                for pos in donors[callee]:
                    if pos < len(sub.args) and isinstance(sub.args[pos], ast.Name):
                        donated[sub.args[pos].id] = sub.lineno

        def clear_bound(target: ast.AST, donated: Dict[str, int]) -> None:
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name) and isinstance(
                        getattr(sub, "ctx", None), ast.Store):
                    donated.pop(sub.id, None)

        def visit(stmts, donated: Dict[str, int]) -> None:
            """Source-order linear scan: each statement's own expressions
            are processed exactly once (use-check, then donation-record,
            then rebind).  ``if``/``else`` branches are mutually
            exclusive, so each scans a fork of the state and the join is
            the union of the forks (donated on either path ⇒ unsafe
            after); other compound bodies keep the straight-line
            approximation documented in the module docstring."""
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue        # separate scope
                headers = [c for c in ast.iter_child_nodes(stmt)
                           if isinstance(c, (ast.expr, ast.withitem))]
                for h in headers:
                    scan_expr_uses(h, donated)
                for h in headers:
                    record_donations(h, donated)
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        clear_bound(t, donated)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    clear_bound(stmt.target, donated)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    clear_bound(stmt.target, donated)
                if isinstance(stmt, ast.If):
                    body_d, else_d = dict(donated), dict(donated)
                    visit(stmt.body, body_d)
                    visit(stmt.orelse, else_d)
                    donated.clear()
                    donated.update(else_d)
                    donated.update(body_d)
                    continue
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        visit(sub, donated)
                for handler in getattr(stmt, "handlers", []) or []:
                    visit(handler.body, donated)

        visit(scope_body(scope), {})
        return findings

    # kept separate so tests can exercise it directly
    collect_donors = staticmethod(collect_donors)
