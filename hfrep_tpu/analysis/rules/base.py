"""Rule base class + shared AST helpers (dotted names, alias tracking).

Every rule is a stateless object with an ``id`` and a
``check(ctx) -> list[Finding]``; the helpers here answer the questions
all the JAX rules keep asking: "what dotted name is this expression?",
"what do `np` / `jax.random` resolve to in this file?", "is this call a
jit/shard_map wrapper?".
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from hfrep_tpu.analysis.engine import FileContext, Finding


class Rule:
    id: str = "JAX000"
    name: str = "base"
    description: str = ""

    def check(self, ctx: FileContext) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.random.split`` -> "jax.random.split"; None for non-name exprs."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.AST, module: str) -> Set[str]:
    """Dotted prefixes that denote ``module`` in this file: ``import numpy
    as np`` -> {"np"}, ``import numpy`` -> {"numpy"}, ``from jax import
    numpy as jnp`` (module="jax.numpy") -> {"jnp"}."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module:
                    names.add(a.asname if a.asname else a.name)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if f"{node.module}.{a.name}" == module:
                    names.add(a.asname or a.name)
    return names


def from_imports(tree: ast.AST, module: str) -> Dict[str, str]:
    """``from <module> import a as b`` -> {"b": "a"}."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            for a in node.names:
                out[a.asname or a.name] = a.name
    return out


_JIT_TAILS = ("jit", "pmap", "shard_map")


def is_jit_reference(node: ast.AST) -> bool:
    """True for a name expression denoting jit/pmap/shard_map."""
    name = dotted_name(node)
    if name is None:
        return False
    return name.split(".")[-1] in _JIT_TAILS


def jit_call_info(call: ast.Call) -> Optional[ast.Call]:
    """If ``call`` is ``jit(...)``/``shard_map(...)`` or
    ``partial(jax.jit, ...)``, return the call carrying the jit kwargs."""
    if is_jit_reference(call.func):
        return call
    fname = dotted_name(call.func)
    if fname and fname.split(".")[-1] == "partial" and call.args:
        if is_jit_reference(call.args[0]):
            return call
    return None


def decorator_jit_call(dec: ast.AST) -> Optional[ast.Call]:
    """jit-ish decorator -> the Call node carrying kwargs (or a synthetic
    marker Call for a bare ``@jax.jit``)."""
    if isinstance(dec, ast.Call):
        return jit_call_info(dec)
    if is_jit_reference(dec):
        # bare @jax.jit: synthesize an empty call so callers can treat
        # both shapes uniformly
        fake = ast.Call(func=dec, args=[], keywords=[])
        ast.copy_location(fake, dec)
        return fake
    return None


def literal_int_tuple(node: ast.AST) -> Optional[Tuple[object, ...]]:
    """Literal ``(4, 8, n)`` -> (4, 8, "n"); names become symbolic strs,
    anything else (calls, subscripts) becomes "_" (unknown).  Returns
    None when the node is not a tuple/list display at all."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims: List[object] = []
    for elt in node.elts:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
            dims.append(elt.value)
        elif isinstance(elt, ast.Name):
            dims.append(elt.id)
        elif isinstance(elt, ast.Starred):
            return None             # (*dims, 4): rank unknown
        else:
            dims.append("_")
    return tuple(dims)


def walk_scopes(tree: ast.AST) -> Iterable[ast.AST]:
    """Yield the module and every function/lambda node (each a scope)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield node


def scope_body(scope: ast.AST) -> List[ast.stmt]:
    if isinstance(scope, ast.Lambda):
        ret = ast.Return(value=scope.body)
        ast.copy_location(ret, scope.body)
        return [ret]
    return list(scope.body)


def param_names(scope: ast.AST) -> Set[str]:
    """Positional/keyword/vararg names of a function scope, minus
    self/cls."""
    if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return set()
    a = scope.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return {n for n in names if n not in ("self", "cls")}


def direct_nodes(scope: ast.AST) -> List[ast.AST]:
    """All AST nodes belonging to ``scope`` itself — traversal stops at
    nested function/lambda boundaries (their bodies are their own
    scopes).  The nested def node itself is included (for decorators),
    its body is not."""
    out: List[ast.AST] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            out.append(child)
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                walk(child)

    walk(scope)
    return out


def local_bound_names(scope: ast.AST) -> Set[str]:
    """Names bound by ``scope``'s own statements (assignments, loop and
    with targets, comprehension variables) — these shadow any same-named
    enclosing-scope parameter."""
    bound: Set[str] = set()
    for node in direct_nodes(scope):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
    return bound


def jit_wrapped_names(tree: ast.AST) -> Set[str]:
    """Function names passed as the wrapped callable to jit/pmap/shard_map
    (directly or through ``functools.partial``)."""
    wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        call = jit_call_info(node)
        if call is None:
            continue
        args = call.args
        fname = dotted_name(call.func)
        if fname and fname.split(".")[-1] == "partial":
            args = call.args[1:]    # partial(jax.jit, fn, …)
        for a in list(args[:1]) + [kw.value for kw in call.keywords
                                   if kw.arg in ("f", "fun", "func")]:
            if isinstance(a, ast.Name):
                wrapped.add(a.id)
    return wrapped


def jitted_defs(tree: ast.AST) -> List[ast.AST]:
    """Every function def that is jit/pmap/shard_map-compiled: decorated
    with one, or referenced by name as the wrapped callable."""
    wrapped = jit_wrapped_names(tree)
    defs: List[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in wrapped or any(decorator_jit_call(d) is not None
                                       for d in node.decorator_list):
            defs.append(node)
    return defs


def tracer_scopes(fn: ast.AST):
    """Yield ``(scope, tracer_names)`` for a jitted def and every function
    nested in it (nested functions trace too).  A scope's tracers are its
    own parameters plus the enclosing scopes' — minus any name the scope
    itself binds locally, which shadows the tracer (e.g. a static
    ``for i in range(n)`` loop variable over a nested fn's ``i`` param)."""

    def rec(scope: ast.AST, inherited: Set[str]):
        tracers = (inherited - local_bound_names(scope)) | param_names(scope)
        yield scope, tracers
        for node in direct_nodes(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                yield from rec(node, tracers)

    yield from rec(fn, set())
