"""Program-rule base + jaxpr walking helpers (phase 3, ISSUE 16).

JPX rules audit *traced programs*, not source text: the audit engine
(``hfrep_tpu/analysis/programs.py``) builds each registered compile
boundary at tiny abstract shapes, traces it to a jaxpr and (where the
runtime can) lowers it to StableHLO text, then hands both to every
``ProgramRule.check_program``.  Crucially the RULES themselves import no
jax: they duck-type the jaxpr object graph (``.eqns``, ``.params``,
``.aval``) and regex the HLO string, so the registry tests, the warm
cache path and the unit fixtures (which feed synthetic contexts) all
run on a bare CPython — only a cold trace pays the jax import.

Findings anchor at the boundary's registry row in ``programs.py`` (the
one source line a human can edit), with a *label-stable* snippet so the
fingerprint survives registry reshuffles, and ``# noqa: JPXnnn`` on
that row suppresses through the ordinary :class:`FileContext` path.
"""

from __future__ import annotations

import math
from typing import Any, Iterator, List, Optional, Tuple

from hfrep_tpu.analysis.engine import Finding
from hfrep_tpu.analysis.rules.base import Rule

#: where every program finding anchors: the registry row in programs.py
PROGRAMS_PATH = "hfrep_tpu/analysis/programs.py"

#: jaxpr higher-order primitives whose sub-jaxprs are LOOP BODIES —
#: an eqn found inside one executes per iteration, which is what makes
#: a host callback there a per-step sync instead of a one-off
LOOP_PRIMITIVES = frozenset({"scan", "while"})

#: higher-order primitives to recurse through WITHOUT entering a loop
#: scope (their bodies run at most once per call of the outer program)
TRANSPARENT_PRIMITIVES = frozenset({
    "pjit", "jit", "xla_call", "cond", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "remat", "remat2",
    "checkpoint", "closed_call", "core_call",
})


class ProgramContext:
    """Everything a JPX rule sees about one traced boundary.

    ``boundary`` is the registry row (``programs.Boundary``);
    ``jaxpr`` the ClosedJaxpr (or None when tracing failed but lowering
    succeeded); ``hlo`` the StableHLO text (or None — jaxpr-level rules
    still run); ``arg_avals`` one tuple of leaf avals per top-level
    positional argument (the donation rule's unit of account);
    ``out_avals`` the flat output avals.
    """

    def __init__(self, boundary, jaxpr=None, hlo: Optional[str] = None,
                 arg_avals: Tuple[Tuple[Any, ...], ...] = (),
                 out_avals: Tuple[Any, ...] = (), line: int = 1):
        self.boundary = boundary
        self.jaxpr = jaxpr
        self.hlo = hlo
        self.arg_avals = arg_avals
        self.out_avals = out_avals
        self.line = line

    def finding(self, rule: str, message: str, token: str = "") -> Finding:
        label = self.boundary.label
        snippet = f"{label} {token}".strip()
        return Finding(rule=rule, path=PROGRAMS_PATH, line=self.line,
                       col=0, message=f"[{label}] {message}",
                       snippet=snippet)


class ProgramRule(Rule):
    """A rule over traced programs.  ``check`` (the AST hook) is a no-op
    so JPX rules can share registries/CLI plumbing with the text rules;
    the real work happens in ``check_program``."""

    def check(self, ctx) -> List[Finding]:
        return []

    def check_program(self, pctx: ProgramContext) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


# ------------------------------------------------------------ jaxpr walks
def _as_open_jaxpr(obj):
    """ClosedJaxpr -> Jaxpr; Jaxpr -> itself; None otherwise."""
    if obj is None:
        return None
    inner = getattr(obj, "jaxpr", None)   # ClosedJaxpr carries .jaxpr
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return obj if hasattr(obj, "eqns") else None


def _sub_jaxprs(params: dict) -> Iterator[Any]:
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            open_jx = _as_open_jaxpr(item)
            if open_jx is not None:
                yield item


def iter_eqns(jaxpr, _in_loop: bool = False) -> Iterator[Tuple[Any, bool]]:
    """Yield ``(eqn, in_loop)`` over the whole nested program, entering
    scan/while/cond/pjit/custom_* sub-jaxprs; ``in_loop`` is True for
    eqns that execute per loop iteration."""
    open_jx = _as_open_jaxpr(jaxpr)
    if open_jx is None:
        return
    for eqn in open_jx.eqns:
        yield eqn, _in_loop
        name = eqn.primitive.name
        loop = _in_loop or name in LOOP_PRIMITIVES
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub, loop)


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize is None:
        return 0
    return int(math.prod(shape)) * int(itemsize) if shape else int(itemsize)


def aval_sig(aval) -> Tuple[Tuple[int, ...], str]:
    """(shape, dtype-name) signature used for carry-shape matching."""
    return (tuple(getattr(aval, "shape", ()) or ()),
            str(getattr(aval, "dtype", "?")))


def eqn_in_avals(eqn) -> List[Any]:
    out = []
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None:
            out.append(aval)
    return out


def scan_carry_avals(eqn) -> List[Any]:
    """The carry block of a ``scan`` eqn's body jaxpr (after the consts,
    before the per-iteration xs)."""
    body = eqn.params.get("jaxpr")
    if body is None or not hasattr(body, "in_avals"):
        return []
    n_consts = int(eqn.params.get("num_consts", 0))
    n_carry = int(eqn.params.get("num_carry", 0))
    return list(body.in_avals[n_consts:n_consts + n_carry])
