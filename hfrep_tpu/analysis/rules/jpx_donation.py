"""JPX001 — donation completeness at a compile boundary.

The failure mode: a jit boundary threads a large state pytree in and
out (the ``state -> state'`` carry shape every training step here has)
but the production launch never lists it in ``donate_argnums``.  XLA
must then hold TWO copies of the parameters + optimizer state live
across the dispatch — on a TPU at prod shapes that is the difference
between fitting and OOMing, and it is invisible on CPU (the CPU backend
does not implement donation at all, which is exactly why
``replication/engine.py::_donate_argnums`` returns ``()`` there and why
this must be a STATIC check on the declared production posture, not a
runtime observation).

The rule is structural: argument position ``i`` is *state-like* when
its flattened leaves all reappear — as a (shape, dtype) multiset — in
the program outputs, it carries at least ``MIN_STATE_LEAVES`` leaves
(a params+opt-state tree, not a stray scalar), and its total bytes
clear ``MIN_STATE_BYTES``.  Every state-like position must appear in
the boundary's declared ``donate`` tuple (the registry row documents
what production passes to ``donate_argnums`` on backends that honor
it).  A deliberate non-donation gets ``# noqa: JPX001`` on its registry
row with the justification in the row's ``notes``.

Negative fixtures pinned in tests/test_analysis_programs.py:
* pure programs (outputs share no leaf signature with any input);
* small scalar carries (a step counter in, step counter out);
* init programs (``(keys, xs) -> carry``: inputs never reappear);
* boundaries whose state-like args ARE declared donated.
"""

from __future__ import annotations

from collections import Counter
from typing import List

from hfrep_tpu.analysis.engine import Finding
from hfrep_tpu.analysis.rules.jpx_base import (ProgramContext, ProgramRule,
                                               aval_bytes, aval_sig)

#: a "state tree" here is params + optimizer state — always several
#: leaves; 3 keeps PRNG keys and (data, mask) pairs out of scope
MIN_STATE_LEAVES = 3
#: and it must be worth donating — tiny fixture trees still clear this,
#: loop counters and masks do not
MIN_STATE_BYTES = 512


class ProgramDonationRule(ProgramRule):
    id = "JPX001"
    name = "program-donation"
    description = ("jit boundary threads a large state pytree in and out "
                   "but the production launch does not donate it — XLA "
                   "holds two copies of params+opt state per dispatch")

    def check_program(self, pctx: ProgramContext) -> List[Finding]:
        out_sigs = Counter(aval_sig(a) for a in pctx.out_avals)
        findings: List[Finding] = []
        for i, leaves in enumerate(pctx.arg_avals):
            if i in pctx.boundary.donate:
                continue
            if len(leaves) < MIN_STATE_LEAVES:
                continue
            total = sum(aval_bytes(a) for a in leaves)
            if total < MIN_STATE_BYTES:
                continue
            arg_sigs = Counter(aval_sig(a) for a in leaves)
            if arg_sigs - out_sigs:       # some leaf never comes back out
                continue
            findings.append(pctx.finding(
                self.id,
                f"arg {i} is a state-like pytree ({len(leaves)} leaves, "
                f"{total} bytes) returned by the program but absent from "
                f"the declared donate_argnums {pctx.boundary.donate!r}; "
                "donate it (or justify with # noqa: JPX001 on the "
                "registry row)",
                token=f"arg{i}"))
        return findings
