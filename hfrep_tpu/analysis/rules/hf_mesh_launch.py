"""HF008 — single-mesh-API launch discipline.

ISSUE 15 replaced seven hand-built ``shard_map`` launch paths with the
one partition-rule-driven ``NamedSharding``/``pjit`` API
(:mod:`hfrep_tpu.parallel.rules.mesh_launch`).  The refactor only stays
done if no NEW manual SPMD region grows outside the sanctioned package:
a fresh ``shard_map(...)`` / ``jax.pmap(...)`` launch in a feature
module re-creates exactly the per-path plumbing (per-device sampling,
replication proofs, version-gated APIs) the migration deleted — and on
the pinned runtime (jax 0.4.37, no ``jax.shard_map``) it is dead code
from the day it lands.

Flagged: any CALL of ``shard_map`` or ``pmap`` — by bare name when the
file imports it from a jax module or the compat gates, as a dotted
``jax.*``/``jax.experimental.shard_map.*`` reference, or qualified
through a module alias (``from jax.experimental import shard_map`` →
``shard_map.shard_map(...)``, ``import jax.experimental.shard_map as
sm`` → ``sm.shard_map(...)``, ``from hfrep_tpu.parallel import
_compat`` → ``_compat.shard_map(...)``) — outside the allowlist:

* ``hfrep_tpu/parallel/`` — the mesh API's home, including
  ``layer_pipeline.py`` (the one schedule pjit cannot express: GPipe
  stage masking with per-superstep ppermutes) and the ``_compat`` gate;
* ``hfrep_tpu/utils/jax_compat.py`` — the gate's definition site.

Tests are exempt (fixtures exercise the rule itself); references
without a call (e.g. the HF005 registry's strings, ``HAS_SHARD_MAP``
feature probes) are not launches and are not flagged.
"""

from __future__ import annotations

import ast
from typing import List, Set

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name

#: repo-relative posix prefixes/files where manual SPMD launches remain
#: sanctioned
ALLOWED_PATHS = (
    "hfrep_tpu/parallel/",
    "hfrep_tpu/utils/jax_compat.py",
)

#: modules whose ``shard_map``/``pmap`` member is a launch constructor
_LAUNCH_MODULES = (
    "jax",
    "jax.experimental.shard_map",
    "jax.experimental",
    "hfrep_tpu.parallel._compat",
    "hfrep_tpu.utils.jax_compat",
)

_LAUNCH_NAMES = {"shard_map", "pmap"}


def _launch_aliases(tree: ast.AST) -> Set[str]:
    """Bare names this file binds to a shard_map/pmap constructor via
    ``from <launch module> import shard_map [as sm]``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in _LAUNCH_MODULES:
            for a in node.names:
                if a.name in _LAUNCH_NAMES:
                    out.add(a.asname or a.name)
    return out


def _module_aliases(tree: ast.AST) -> Set[str]:
    """Bare names this file binds to a MODULE that exports a launch
    constructor — ``<alias>.shard_map(...)`` is the same launch as the
    bare form: ``from jax.experimental import shard_map`` (the module),
    ``import jax.experimental.shard_map as sm``, ``from hfrep_tpu.parallel
    import _compat``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in _LAUNCH_MODULES and a.asname is not None:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module is not None:
            for a in node.names:
                if f"{node.module}.{a.name}" in _LAUNCH_MODULES:
                    out.add(a.asname or a.name)
    return out


class MeshLaunchRule(Rule):
    id = "HF008"
    name = "single-mesh-api"
    description = ("manual shard_map/pmap launch construction outside "
                   "hfrep_tpu/parallel/ — use the partition-rule mesh "
                   "API (parallel/rules.py mesh_launch) instead")

    def check(self, ctx: FileContext) -> List[Finding]:
        from hfrep_tpu.analysis.project import _is_test_path

        relpath = ctx.relpath.replace("\\", "/")
        if _is_test_path(relpath):
            return []
        if any(relpath == p or relpath.startswith(p) for p in ALLOWED_PATHS):
            return []
        aliases = _launch_aliases(ctx.tree)
        mod_aliases = _module_aliases(ctx.tree)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            head, _, tail = name.rpartition(".")
            launch = None
            if not head and name in aliases:
                launch = name
            elif tail in _LAUNCH_NAMES and (head.split(".")[0] == "jax"
                                            or head in mod_aliases):
                launch = name
            if launch is None:
                continue
            findings.append(ctx.finding(
                "HF008", node,
                f"direct {launch}(...) launch outside hfrep_tpu/parallel/: "
                "the single-mesh-API discipline (ISSUE 15) routes every "
                "multi-device launch through parallel/rules.py "
                "mesh_launch — partition rules + pjit, alive on every "
                "jax version"))
        return findings
