"""JPX005 — sharding-constraint loss between declaration and program.

The partition-rule layer (``parallel/rules.py``) declares per-leaf
layouts; the lowered program is where they either landed or silently
vanished (an ``in_shardings`` dropped by a refactor, a
``with_sharding_constraint`` dead because the mesh axis got stripped).
A boundary registered with ``expect_sharding=True`` promises its
lowered HLO carries sharding annotations (``mhlo.sharding`` /
``sharding =`` attributes); their total absence means GSPMD received a
program with no layout intent at all and will replicate everything —
correct, and quietly paying full-copy memory + all-gather traffic.

On this pinned runtime every ownable mesh is one CPU device and
``normalize_spec`` strips the axis names (no annotations CAN appear),
so live registry rows declare ``expect_sharding=False`` and the rule's
behavior is pinned by synthetic pos/neg fixtures; on a real pod the dp/
tp rows flip the flag and the audit holds the layout contract.
"""

from __future__ import annotations

from typing import List

from hfrep_tpu.analysis.engine import Finding
from hfrep_tpu.analysis.rules.jpx_base import ProgramContext, ProgramRule

#: how layout intent shows up in StableHLO/MHLO text across jax 0.4.x
SHARDING_MARKERS = ("mhlo.sharding", "sharding =", "sdy.sharding")


class ProgramShardingRule(ProgramRule):
    id = "JPX005"
    name = "program-sharding"
    description = ("boundary declares a partitioned layout but the "
                   "lowered HLO carries no sharding annotation — GSPMD "
                   "will silently replicate the whole state")

    def check_program(self, pctx: ProgramContext) -> List[Finding]:
        if not pctx.boundary.expect_sharding or pctx.hlo is None:
            return []
        if any(marker in pctx.hlo for marker in SHARDING_MARKERS):
            return []
        return [pctx.finding(
            self.id,
            "partition rules declare a sharded layout for this boundary "
            "but its lowered HLO has no sharding annotations — the "
            "constraint was lost between declaration and lowering",
            token="sharding")]
