"""HF004 — obs schema / README sync, both directions.

``obs/README.md``'s schema tables are the contract every downstream
consumer (the report parser, the history store, dashboards, humans
reading a verdict) programs against — and nothing connected them to the
code until now.  An event emitted but undocumented is invisible
protocol; a documented row whose emission was renamed away is a schema
lie that survives until someone greps.

Code side (per file, tests exempt): every *statically named* event
emission (direct ``.event("name", ...)`` or through a local forwarding
wrapper — the repo's ``_emit``/``_event``/``_obs_event`` pattern) and
every namespaced instrument (a name containing ``/``) must be
documented — an exact backtick mention or a wildcard schema row
(``bench/serve_qps_c{1k,10k,100k}``, ``train/<key>``).

Doc side (project-level): every structured schema-table row must match
an emission somewhere — an exact resolved name, or (for wildcard rows)
a dynamic emission site whose static prefix is compatible.  Un-prefixed
instruments (``steps_per_sec``) and dynamic emissions with no prefix
are out of scope: the rule enforces the namespaced vocabulary, not
every local counter.
"""

from __future__ import annotations

import re
from typing import List

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule


def _wildcard_compatible(row_name: str, prefixes) -> bool:
    """Does a dynamic emission prefix plausibly produce this documented
    (wildcard) row?  Compatibility = the row's static head and some
    emitted prefix extend each other."""
    head = re.split(r"[{<]", row_name, maxsplit=1)[0]
    return any(head.startswith(p) or p.startswith(head)
               for p in prefixes if p)


class ObsDocRule(Rule):
    id = "HF004"
    name = "obs-schema-doc-sync"
    description = ("emitted events/namespaced instruments and the "
                   "obs/README.md schema tables must agree, both "
                   "directions")

    def check(self, ctx: FileContext) -> List[Finding]:
        from hfrep_tpu.analysis.project import (_is_test_path,
                                                collect_emissions)

        project = ctx.project
        if project is None or (not project.doc.rows
                               and not project.doc.mentioned):
            return []
        if _is_test_path(ctx.relpath):
            return []
        summary = project.files.get(ctx.relpath)
        emissions = (summary.emissions if summary is not None
                     else collect_emissions(ctx.tree))
        findings: List[Finding] = []
        for e in emissions:
            for name in e.names:
                if e.kind != "event" and "/" not in name:
                    continue              # un-namespaced local instrument
                if project.doc.documents(name):
                    continue
                findings.append(Finding(
                    rule=self.id, path=ctx.relpath, line=e.line, col=0,
                    message=(
                        f"{e.kind} {name!r} is not documented in the "
                        "obs/README.md schema tables — undocumented "
                        "protocol every stream consumer has to reverse-"
                        "engineer"),
                    snippet=(ctx.lines[e.line - 1].strip()
                             if 0 < e.line <= len(ctx.lines) else "")))
        return findings

    def check_project(self, project) -> List[Finding]:
        from hfrep_tpu.analysis.project import OBS_README_PATH

        if not project.covers_doc_surface():
            # a scoped run cannot judge "nothing emits this row": the
            # emission could live in any file outside the run's horizon
            return []
        emitted = project.emitted_names()
        prefixes = project.emitted_prefixes()
        findings: List[Finding] = []
        for row in project.doc.rows:
            patterns = row.patterns
            if any(re.match(p, name) for p in patterns for name in emitted):
                continue
            if ("{" in row.name or "<" in row.name) \
                    and _wildcard_compatible(row.name, prefixes):
                continue
            findings.append(Finding(
                rule=self.id, path=OBS_README_PATH, line=row.line, col=0,
                message=(
                    f"documented schema row {row.name!r} matches no "
                    "emission in the project — stale docs (renamed or "
                    "removed emission)"),
                snippet=f"| `{row.name}` |"))
        return findings
