"""HF002 — fault-site consistency.

The fault-injection protocol is a cross-process string registry: a
drive crosses ``resilience.boundary("chunk")``, a writer passes
``write_atomic(..., io_site="queue_put")``, a selftest arms
``HFREP_FAULTS='sigterm@chunk=2'`` — and the only thing connecting them
is that the strings agree with :mod:`hfrep_tpu.resilience.faults`.  A
typo'd site used to parse fine and then simply never fire: the
silently-disarmed injection, the worst possible failure mode for the
machinery whose whole job is proving the failure paths work.

Three checks, orphans flagged in both directions:

* a literal site at a hook call must be registered for that hook's
  group (``boundary("chunk")`` → ``BOUNDARY_SITES``,
  ``io_site=`` → ``IO_SITES``, ``fault_site=`` → ``POST_SAVE_SITES``);
* an ``HFREP_FAULTS`` spec literal (any string constant whose every
  ``;``-separated part matches the ``kind@site=N[xCOUNT]`` grammar)
  must name a known kind AND a site registered for a group that kind
  can fire at (boundary kinds may target boundary/io/actor sites —
  the signal can land mid-I/O);
* a registry entry no non-test hook call references is dead and flagged
  at its registry line (the project-level direction).

Tests are exempt from the spec check: intentionally-malformed specs
(``what@chunk=1``) are how ``FaultSpecError`` behavior is pinned.
"""

from __future__ import annotations

import ast
import re
from typing import List

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule

_SPEC_PART = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<site>[a-z_]+)=[0-9]+(?:x[0-9]+)?$")

#: which site groups each *kind group* may target (mirrors the runtime
#: semantics: boundary kinds also fire at io and actor hooks)
KIND_GROUP_TARGETS = {
    "boundary": ("boundary", "io", "actor"),
    "io": ("io",),
    "post_save": ("post_save",),
    "actor": ("actor",),
}


def spec_parts(value: str):
    """``"sigterm@chunk=2;torn@ckpt=1"`` -> the matched directive parts;
    [] when the string is not entirely spec-shaped (so ordinary prose
    containing an ``@`` never matches)."""
    parts = [p.strip() for p in value.split(";") if p.strip()]
    matches = [_SPEC_PART.match(p) for p in parts]
    return matches if parts and all(matches) else []


class FaultSiteRule(Rule):
    id = "HF002"
    name = "fault-site-consistency"
    description = ("fault-injection sites at hooks and in HFREP_FAULTS "
                   "specs must round-trip against the faults.py registry")

    def check(self, ctx: FileContext) -> List[Finding]:
        from hfrep_tpu.analysis.project import (_is_test_path,
                                                collect_fault_sites)

        project = ctx.project
        if project is None or not project.fault_sites:
            return []
        if _is_test_path(ctx.relpath):
            return []
        findings: List[Finding] = []

        def finding(line: int, message: str) -> Finding:
            return Finding(
                rule=self.id, path=ctx.relpath, line=line, col=0,
                message=message,
                snippet=(ctx.lines[line - 1].strip()
                         if 0 < line <= len(ctx.lines) else ""))

        summary = project.files.get(ctx.relpath)
        used = (summary.fault_sites_used if summary is not None
                else collect_fault_sites(ctx.tree))
        for group, site, line in used:
            registry = project.fault_sites.get(group, {})
            if site not in registry:
                findings.append(finding(
                    line,
                    f"fault site {site!r} is not in the faults.py "
                    f"{group.upper()}_SITES registry — an HFREP_FAULTS "
                    "directive targeting it would silently never fire"))

        # spec literals (skip faults.py itself: its docstring grammar
        # examples are prose, and whole-string matching already filters
        # everything but genuine spec constants)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            for m in spec_parts(node.value):
                kind, site = m.group("kind"), m.group("site")
                kind_group = project.fault_kinds.get(kind)
                if kind_group is None:
                    findings.append(finding(
                        node.lineno,
                        f"HFREP_FAULTS spec kind {kind!r} is not a "
                        "registered fault kind"))
                    continue
                targets = KIND_GROUP_TARGETS.get(kind_group, ())
                if not any(site in project.fault_sites.get(g, {})
                           for g in targets):
                    findings.append(finding(
                        node.lineno,
                        f"HFREP_FAULTS spec site {site!r} is not "
                        f"registered for any group {kind!r} can fire at "
                        f"({'/'.join(targets)}) — the directive would "
                        "silently never fire"))
        return findings

    def check_project(self, project) -> List[Finding]:
        from hfrep_tpu.analysis.project import FAULTS_PATH, _is_test_path

        if FAULTS_PATH not in project.files:
            # a scoped run (single file, one package) cannot see the
            # registry's whole usage surface — "orphaned" would mean
            # "outside this run's horizon", not "dead"
            return []
        used = set()
        for path, s in project.files.items():
            if _is_test_path(path):
                continue
            for group, site, _line in s.fault_sites_used:
                used.add((group, site))
        findings: List[Finding] = []
        for group, registry in sorted(project.fault_sites.items()):
            for site, line in sorted(registry.items()):
                if (group, site) not in used:
                    findings.append(Finding(
                        rule=self.id, path=FAULTS_PATH, line=line, col=0,
                        message=(
                            f"registry site {site!r} ({group}) is "
                            "referenced by no hook call in the project — "
                            "dead registry entry (or the hook lost its "
                            "literal site)"),
                        snippet=f"{group.upper()}_SITES: {site}"))
        return findings
