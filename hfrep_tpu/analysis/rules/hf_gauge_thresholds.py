"""HF001 — gauge-direction completeness.

The historical bug, twice: ``serve/shed_rate`` (PR 8) and
``scenario/pad_waste_frac`` (PR 9) would each have gated AND cross-host
pod-folded INVERTED — a rising shed rate reading as an improvement —
because the regression engine's fallback rule guesses direction from a
name-suffix heuristic, and both names defeat it.  Both were caught by a
reviewer hand-adding explicit ``regress.DEFAULT_THRESHOLDS`` entries.
This rule kills the class by construction: every *statically named*
``bench/`` / ``serve/`` / ``scenario/``-prefixed gauge or counter
emission (the ``history.GAUGE_PREFIXES`` vocabulary that rides into the
committed history store) must have an explicit ``DEFAULT_THRESHOLDS``
row.

Resolution: string constants, loop-bound names and f-strings whose
every hole is loop-bound over literal collections all resolve (the
repo's dominant ``for name, value in ((...), ...)`` emission idiom).
Dynamic open vocabularies — ``f"bench/bf16_probe_h{h}_..."`` — are NOT
flagged: their per-cell series are open-ended by design, the README
documents them as wildcard rows, and demanding a table entry per cell
would be noise (the pinned false-positive class).

Tests are exempt: fixture emissions do not reach the history store.
"""

from __future__ import annotations

from typing import List

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule


class GaugeThresholdRule(Rule):
    id = "HF001"
    name = "gauge-direction-completeness"
    description = ("history-store gauges/counters (bench/|serve/|scenario/) "
                   "must have explicit regress.DEFAULT_THRESHOLDS entries")

    def check(self, ctx: FileContext) -> List[Finding]:
        from hfrep_tpu.analysis.project import (_is_test_path,
                                                collect_emissions)

        project = ctx.project
        if project is None or not project.gauge_prefixes:
            return []
        if _is_test_path(ctx.relpath):
            return []
        summary = project.files.get(ctx.relpath)
        emissions = (summary.emissions if summary is not None
                     else collect_emissions(ctx.tree))
        findings: List[Finding] = []
        for e in emissions:
            if e.kind not in ("gauge", "counter"):
                continue
            for name in e.names:
                if not name.startswith(tuple(project.gauge_prefixes)):
                    continue
                if name in project.thresholds:
                    continue
                findings.append(Finding(
                    rule=self.id, path=ctx.relpath, line=e.line, col=0,
                    message=(
                        f"{e.kind} {name!r} has no explicit "
                        "regress.DEFAULT_THRESHOLDS entry: it would gate "
                        "and cross-host fold by the name-suffix heuristic "
                        "— the class that inverted serve/shed_rate and "
                        "scenario/pad_waste_frac"),
                    snippet=(ctx.lines[e.line - 1].strip()
                             if 0 < e.line <= len(ctx.lines) else ""),
                ))
        return findings
