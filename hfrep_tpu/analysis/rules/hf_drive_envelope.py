"""HF011 — drive-envelope discipline: the survival envelope lives in
``resilience/drive.py``, nowhere else.

ISSUE 20 extracted the one fault-tolerant envelope every long-running
workload needs — ``graceful_drain`` outermost, the obs session INSIDE
it, watchdog, Preempted→75 / storage→74 through
``crash.bundle_if_enabled`` — into :func:`hfrep_tpu.resilience.drive.
run_drive`, precisely because the hand-copied version kept regressing
(HF007 was added for mis-exiting copies; chaos corpus entries 003 and
007 each pinned a bug a copy had and the shared envelope cannot).  This
rule keeps the copy-paste class from regrowing:

* **hand-rolled drain exit** — an ``except ...Preempted`` handler that
  terminates with an integer status (``return <int>``,
  ``sys.exit(<int>)``, ``raise SystemExit(<int>)``) outside the
  sanctioned runtime is a re-implementation of ``run_drive``'s exit
  mapping.  Handlers that re-raise, continue a loop, or assert (resume
  drills, the engine's context-enriched re-raise) are not exits and
  stay exempt — those are drain *points*, not envelopes;
* **hand-rolled envelope pairing** — one function that both enters
  ``resilience.graceful_drain()`` and opens ``obs.session(...)`` /
  ``session_or_off(...)`` is rebuilding the envelope's load-bearing
  nesting by hand (and history says it will eventually get the order
  wrong — corpus entry 003 was exactly a ``with session`` line outside
  the drain).  Bare ``graceful_drain`` without a session (library-level
  drain points: the engine's chunk loop, the trainer's block loop, the
  supervisor) is fine and not flagged.

Sanctioned: ``resilience/drive.py`` (the one implementation) and tests
wholesale.  Anything else routes through ``run_drive`` or carries an
explicit ``# noqa: HF011`` with its justification.
"""

from __future__ import annotations

import ast
from typing import List

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name
from hfrep_tpu.analysis.rules.hf_exit_codes import (
    _catches_preempted,
    _module_int_constants,
    _resolve_int,
)

#: the one file allowed to implement the envelope
_SANCTIONED_SUFFIXES = ("resilience/drive.py",)

#: context managers that ARE the envelope's two layers
_DRAIN_NAMES = ("graceful_drain",)
_SESSION_NAMES = ("session", "session_or_off")


def _own_nodes(fn: ast.AST):
    """Walk a function's own body, not nested function/class defs —
    a helper closure opening a session inside a function that drains
    is a different scope's decision."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _call_short(node: ast.AST) -> str:
    if isinstance(node, ast.Call):
        name = dotted_name(node.func) or ""
        return name.split(".")[-1]
    return ""


class DriveEnvelopeRule(Rule):
    id = "HF011"
    name = "drive-envelope-discipline"
    description = ("hand-rolled drive envelopes (Preempted→exit mapping, "
                   "graceful_drain+session pairing) outside "
                   "resilience/drive.py must route through run_drive")

    def check(self, ctx: FileContext) -> List[Finding]:
        from hfrep_tpu.analysis.project import _is_test_path

        if _is_test_path(ctx.relpath) \
                or ctx.relpath.replace("\\", "/").endswith(
                    _SANCTIONED_SUFFIXES):
            return []
        consts = _module_int_constants(ctx.tree)
        findings: List[Finding] = []

        # A: hand-rolled drain exits (the HF007 shape, relocated)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler) \
                    or not _catches_preempted(node):
                continue
            exit_at = None
            for sub in ast.walk(ast.Module(body=node.body,
                                           type_ignores=[])):
                if isinstance(sub, ast.Return) and sub.value is not None \
                        and _resolve_int(sub.value, consts) is not None:
                    exit_at = exit_at or sub
                elif isinstance(sub, ast.Call):
                    short = _call_short(sub)
                    if short in ("exit", "_exit", "SystemExit") \
                            and sub.args \
                            and _resolve_int(sub.args[0], consts) is not None:
                        exit_at = exit_at or sub
            if exit_at is not None:
                findings.append(ctx.finding(
                    "HF011", exit_at,
                    "hand-rolled drain exit: an except-Preempted handler "
                    "terminating with a status re-implements the drive "
                    "envelope — declare a DriveSpec and route through "
                    "resilience.drive.run_drive"))

        # B: hand-rolled envelope pairing (graceful_drain + obs session
        # in one function — corpus-003's bug class)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            drain_at = None
            session_at = None
            for node in _own_nodes(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        short = _call_short(item.context_expr)
                        if short in _DRAIN_NAMES and drain_at is None:
                            drain_at = node
                        elif short in _SESSION_NAMES and session_at is None:
                            session_at = node
                elif _call_short(node) in _SESSION_NAMES \
                        and session_at is None:
                    session_at = node
            if drain_at is not None and session_at is not None:
                findings.append(ctx.finding(
                    "HF011", session_at,
                    f"function {fn.name!r} pairs graceful_drain with an "
                    "obs session by hand — the envelope's nesting order "
                    "is load-bearing (chaos corpus 003); route through "
                    "resilience.drive.run_drive"))
        return findings
