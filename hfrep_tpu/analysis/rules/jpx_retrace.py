"""JPX004 — recompile hazards visible in the traced program interface.

jax caches compiled executables by abstract signature, and weak types
are PART of that signature: a program whose input or output avals carry
``weak_type=True`` was traced from a bare Python scalar, and the same
call site later fed a concrete array (or a scalar of the other flavor)
retraces and recompiles — the "two executables for what the author
thinks is one program" hazard the perf microscope's
``backend_compiles`` counter catches only after it has cost a compile
storm.  Closure-captured Python scalars show up the same way: as
weak-typed 0-d constvars baked into the jaxpr, where a config change
that SHOULD have been a traced operand (or a static_argnum) silently
recompiles per value.

Flagged per boundary:
* top-level input avals with ``weak_type=True`` (the caller passes a
  raw Python number where production passes an array — signature
  split);
* output avals with ``weak_type=True`` (the program bakes a promotion
  split into downstream consumers);
* weak-typed 0-d constvars (closure-captured Python scalars).

Inner literal constants (``x * 2`` inlines a weak f32 literal into an
eqn) are NOT flagged — they are inside one executable and cannot split
the cache; that false-positive class is pinned as a negative fixture.
"""

from __future__ import annotations

from typing import List

from hfrep_tpu.analysis.engine import Finding
from hfrep_tpu.analysis.rules.jpx_base import ProgramContext, ProgramRule


def _weak(aval) -> bool:
    return bool(getattr(aval, "weak_type", False))


class ProgramRetraceRule(ProgramRule):
    id = "JPX004"
    name = "program-retrace"
    description = ("weak-typed program interface or closure-captured "
                   "Python scalar — the executable cache splits on "
                   "promotion flavor and recompiles per scalar value")

    def check_program(self, pctx: ProgramContext) -> List[Finding]:
        findings: List[Finding] = []
        weak_in = sum(1 for leaves in pctx.arg_avals for a in leaves
                      if _weak(a))
        if weak_in:
            findings.append(pctx.finding(
                self.id,
                f"{weak_in} weak-typed input aval(s): a Python scalar "
                "reached the boundary where production passes an array — "
                "`jnp.asarray` it (or make it a static_argnum)",
                token="weak-in"))
        weak_out = sum(1 for a in pctx.out_avals if _weak(a))
        if weak_out:
            findings.append(pctx.finding(
                self.id,
                f"{weak_out} weak-typed output aval(s): the program "
                "publishes a promotion-split value downstream consumers "
                "will retrace on",
                token="weak-out"))
        jaxpr = getattr(pctx.jaxpr, "jaxpr", None)
        if jaxpr is not None:
            weak_consts = sum(
                1 for v in getattr(jaxpr, "constvars", ())
                if _weak(getattr(v, "aval", None))
                and not getattr(getattr(v, "aval", None), "shape", ()))
            if weak_consts:
                findings.append(pctx.finding(
                    self.id,
                    f"{weak_consts} closure-captured Python scalar(s) "
                    "baked in as weak-typed constants — a config value "
                    "that recompiles per change; thread it as a traced "
                    "operand or a static_argnum",
                    token="weak-const"))
        return findings
