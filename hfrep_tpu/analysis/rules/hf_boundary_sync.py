"""HF010 — eager scalar syncs inside boundary loops.

The async boundary engine (ISSUE 19) earns its overlap by keeping the
host one step behind the device: the chunked AE drive syncs a chunk's
stop flag through a one-slot pending future, the GAN block loop commits
staged checkpoint writes after the next dispatch, and the walk-forward
eval loop routes its score fetch through one named, ledgered helper.
An *eager* scalar sync added inside one of those loops — ``.item()``,
``jax.device_get(...)``, ``jax.block_until_ready(...)``, or
``np.asarray(<computed value>)`` — silently re-serializes the boundary:
the host parks on the device every iteration and ``timeline/
overlap_frac`` collapses back to the pre-engine wall, with nothing in
review to show for it but an innocent-looking conversion.

A *boundary loop* is recognized by the markers every drive loop in this
codebase already carries: a call to ``resilience.boundary(...)`` /
``resilience.tick(...)`` (the preemption boundary) or
``timeline.flush_window(...)`` (the ledger boundary) anywhere in the
loop body.  Loops without those markers — fingerprint digests, host-side
assembly over numpy — are not drive loops and stay legal.

Flagged inside a boundary loop's body:

* any zero-argument ``.item()`` call (a device scalar pulled eagerly);
* ``jax.device_get`` / ``jax.block_until_ready`` through any import
  spelling (``import jax``, ``from jax import device_get as dg``);
* ``np.asarray(f(...))`` where the argument is itself a call — fetching
  a computed (possibly device) value, as opposed to viewing an array.

The fix is to route the sync through a named helper defined OUTSIDE the
loop (``_boundary_sync``, ``_synced_scores``, ``_log_block`` — the
sanctioned sync points, each of which times and ledgers its wait) or to
defer it behind a one-slot pending future like the engine's.  A
deliberate in-loop sync — the engine's own deferred-flag read is one —
carries ``# noqa: HF010``.
"""

from __future__ import annotations

import ast
from typing import List

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name, from_imports, \
    import_aliases

#: attribute tails that mark a loop as a drive-boundary loop
_BOUNDARY_MARKS = ("boundary", "tick", "flush_window")

_JAX_BANNED = ("device_get", "block_until_ready")


def _is_exempt_path(path: str) -> bool:
    p = path.replace("\\", "/")
    return ("hfrep_tpu/obs/" in p or p.startswith("tests/")
            or "/tests/" in p or p.split("/")[-1].startswith("test_")
            or p.startswith("tools/") or "/tools/" in p)


def _is_boundary_loop(loop) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname is not None and "." in fname \
                    and fname.split(".")[-1] in _BOUNDARY_MARKS:
                return True
    return False


class BoundarySyncRule(Rule):
    id = "HF010"
    name = "eager-boundary-sync"
    description = ("eager scalar sync (.item()/jax.device_get/"
                   "block_until_ready/np.asarray-on-call) inside a "
                   "boundary loop — re-serializes the async engine")

    def check(self, ctx: FileContext) -> List[Finding]:
        if _is_exempt_path(ctx.path):
            return []
        tree = ctx.tree
        jax_mods = import_aliases(tree, "jax")
        jax_direct = {alias: orig for alias, orig
                      in from_imports(tree, "jax").items()
                      if orig in _JAX_BANNED}
        np_mods = import_aliases(tree, "numpy")
        np_direct = {alias: orig for alias, orig
                     in from_imports(tree, "numpy").items()
                     if orig == "asarray"}
        jax_banned = {f"{mod}.{attr}" for mod in jax_mods
                      for attr in _JAX_BANNED}
        np_banned = {f"{mod}.asarray" for mod in np_mods}
        findings: List[Finding] = []
        seen: set = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            if not _is_boundary_loop(loop):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                msg = self._classify(node, jax_banned, jax_direct,
                                     np_banned, np_direct)
                if msg is not None:
                    seen.add(id(node))
                    findings.append(ctx.finding(
                        "HF010", node,
                        f"{msg} inside a boundary loop: the host parks "
                        "on the device every iteration and the async "
                        "engine's overlap collapses — route it through "
                        "a named sync helper defined outside the loop "
                        "(like _boundary_sync / _synced_scores) or "
                        "defer it behind a one-slot pending future"))
        return findings

    @staticmethod
    def _classify(node: ast.Call, jax_banned, jax_direct,
                  np_banned, np_direct):
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "item"
                and not node.args and not node.keywords):
            return "eager .item() scalar pull"
        fname = dotted_name(func)
        if fname is None:
            return None
        if fname in jax_banned or (fname in jax_direct
                                   and "." not in fname):
            tail = fname.split(".")[-1] if "." in fname \
                else jax_direct[fname]
            return f"eager jax.{tail}()"
        is_asarray = fname in np_banned or (fname in np_direct
                                            and "." not in fname)
        if is_asarray and node.args and isinstance(node.args[0], ast.Call):
            return "np.asarray() over a computed value (device fetch)"
        return None
