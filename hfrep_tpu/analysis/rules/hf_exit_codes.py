"""HF007 — exit-code-contract discipline for drain handlers.

The repo-wide contract (selftest, orchestration actors, the chaos
oracles): a drive that catches :class:`~hfrep_tpu.resilience.Preempted`
and converts it into a process exit must exit **75** (``EX_TEMPFAIL`` —
drained at a safe boundary, resumable) and must route through
:func:`hfrep_tpu.obs.crash.bundle_if_enabled` first, so the flight
recorder's drain forensics land (PR 12 moved handled-drain bundling to
exactly these handlers).  A new CLI entry that maps Preempted to
``return 1`` — or forgets the bundle — silently breaks both the
supervisor/driver retry story and ``report --crash``; the chaos
engine's exit-contract oracle catches it dynamically, this rule keeps
new entry points honest statically.

Scope: ``except ...Preempted`` handlers that *terminate with an integer
status* — a ``return <int>``, ``sys.exit(<int>)`` or ``raise
SystemExit(<int>)`` anywhere in the handler body (module-level integer
constants like ``EXIT_DRAINED`` resolve).  Handlers that re-raise,
continue a loop, or assert (tests, resume drills, the engine's
context-enriched re-raise) are not exits and are exempt.  Tests are
exempt wholesale.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from hfrep_tpu.analysis.engine import FileContext, Finding
from hfrep_tpu.analysis.rules.base import Rule, dotted_name

EXIT_DRAINED = 75


def _module_int_constants(tree: ast.AST) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int) \
                and not isinstance(node.value.value, bool):
            out[node.targets[0].id] = node.value.value
    return out


def _catches_preempted(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        name = dotted_name(e)
        if name and name.split(".")[-1] == "Preempted":
            return True
    return False


def _resolve_int(node: Optional[ast.AST],
                 consts: Dict[str, int]) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


class ExitCodeRule(Rule):
    id = "HF007"
    name = "preempted-exit-contract"
    description = ("except-Preempted handlers that exit with a status "
                   "must exit 75 and route through crash.bundle_if_enabled")

    def check(self, ctx: FileContext) -> List[Finding]:
        from hfrep_tpu.analysis.project import _is_test_path

        if _is_test_path(ctx.relpath):
            return []
        consts = _module_int_constants(ctx.tree)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler) \
                    or not _catches_preempted(node):
                continue
            exits = []          # (ast node, resolved int or None)
            bundled = False
            for sub in ast.walk(ast.Module(body=node.body,
                                           type_ignores=[])):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    val = _resolve_int(sub.value, consts)
                    if val is not None:
                        exits.append((sub, val))
                elif isinstance(sub, ast.Call):
                    fname = dotted_name(sub.func) or ""
                    short = fname.split(".")[-1]
                    if short == "bundle_if_enabled":
                        bundled = True
                    elif short in ("exit", "_exit") and sub.args:
                        val = _resolve_int(sub.args[0], consts)
                        if val is not None:
                            exits.append((sub, val))
                    elif short == "SystemExit" and sub.args:
                        val = _resolve_int(sub.args[0], consts)
                        if val is not None:
                            exits.append((sub, val))
            if not exits:
                continue        # re-raise / loop / assert handler
            for site, val in exits:
                if val != EXIT_DRAINED:
                    findings.append(ctx.finding(
                        "HF007", site,
                        f"Preempted handler exits {val}, not 75 "
                        "(EX_TEMPFAIL): a drained drive must signal "
                        "resumable, or the driver retry story breaks"))
            if all(val == EXIT_DRAINED for _, val in exits) and not bundled:
                findings.append(ctx.finding(
                    "HF007", node,
                    "Preempted handler exits 75 without routing through "
                    "crash.bundle_if_enabled — the drain leaves no "
                    "flight-recorder forensics (report --crash finds "
                    "nothing)"))
        return findings
