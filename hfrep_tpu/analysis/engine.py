"""Rule engine: file contexts, findings, ``# noqa`` suppression, baselines.

The engine is deliberately flake8-shaped — parse once per file, hand the
tree to every rule, post-filter by per-line suppressions — because that
shape is what lets new JAX rules be ~50-line visitors instead of
frameworks.  Two extensions matter here:

* a **project pre-pass** (:func:`analyze_paths`) that collects mesh axis
  declarations across *all* files before any rule runs, so the
  axis-consistency rule can cross-check a ``lax.psum(x, 'dp')`` call in
  ``train/steps.py`` against the axes declared in ``parallel/mesh.py``;
* a **baseline file** keyed by content fingerprints (rule + path +
  normalized source line, with multiplicity) so pre-existing violations
  can be burned down incrementally without blocking CI on day one —
  line numbers are deliberately *not* part of the fingerprint, so
  unrelated edits above a baselined site don't resurrect it.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from collections import Counter
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: Repo root assumed two levels above this package (``<root>/hfrep_tpu/analysis``);
#: fingerprint paths are made relative to it so baselines are CWD-independent.
REPO_ROOT = Path(__file__).resolve().parents[2]

_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*))?",
    re.IGNORECASE,
)


class AnalysisError(Exception):
    """Unrecoverable analyzer failure (bad baseline file, unknown rule id)."""


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation at one source location."""

    rule: str            # "JAX001" … "JAX006" (or "JAX000" for parse errors)
    path: str            # posix path, relative to the repo root when under it
    line: int            # 1-based
    col: int             # 0-based
    message: str
    snippet: str = ""    # stripped source line, used in the fingerprint

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching: where-independent of
        line numbers, so edits elsewhere in the file don't churn it."""
        return f"{self.rule}::{self.path}::{self.snippet}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"


def _normalize_path(path) -> str:
    p = Path(path).resolve()
    try:
        return p.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return p.as_posix()


class FileContext:
    """Everything a rule needs about one file: source, AST, suppressions,
    plus the project-wide ``known_axes`` set collected by the pre-pass."""

    def __init__(self, path, source: str,
                 known_axes: Optional[Set[str]] = None,
                 relpath: Optional[str] = None):
        self.path = str(path)
        self.relpath = relpath if relpath is not None else _normalize_path(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.path)
        self.known_axes: Set[str] = set(known_axes or ())
        #: line -> comment text (tokenizer-accurate, so ``# noqa`` or
        #: ``# shape:`` *inside a docstring* never counts)
        self.comments: Dict[int, str] = self._scan_comments()
        self._noqa: Dict[int, Optional[Set[str]]] = self._scan_noqa()

    def _scan_comments(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            pass                    # partial map is fine; ast.parse gates worse
        return out

    def _scan_noqa(self) -> Dict[int, Optional[Set[str]]]:
        """line -> None (bare ``# noqa``: suppress all) or a code set."""
        out: Dict[int, Optional[Set[str]]] = {}
        for i, text in self.comments.items():
            m = _NOQA_RE.search(text)
            if not m:
                continue
            codes = m.group("codes")
            out[i] = ({c.strip().upper() for c in codes.split(",")}
                      if codes else None)
        return out

    def suppressed(self, finding: Finding) -> bool:
        codes = self._noqa.get(finding.line, False)
        if codes is False:
            return False
        return codes is None or finding.rule in codes

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, snippet=snippet)


# --------------------------------------------------------------- running
def _iter_py_files(paths: Sequence) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
        elif p.exists():
            # an explicitly named non-.py file would be silently skipped —
            # "clean" on an unanalyzed target is worse than an error
            raise AnalysisError(f"not a Python file: {p}")
        else:
            raise AnalysisError(f"no such path: {p}")
    # de-dup while keeping order
    seen, out = set(), []
    for f in files:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            out.append(f)
    return out


def _syntax_finding(e: SyntaxError, relpath: str) -> Finding:
    return Finding(rule="JAX000", path=relpath, line=e.lineno or 1,
                   col=(e.offset or 1) - 1,
                   message=f"syntax error: {e.msg}",
                   snippet=(e.text or "").strip())


def _run_rules(ctx: "FileContext", rules: Sequence) -> List[Finding]:
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    findings = [f for f in findings if not ctx.suppressed(f)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence] = None,
                   known_axes: Optional[Set[str]] = None,
                   relpath: Optional[str] = None) -> List[Finding]:
    """Run ``rules`` (default: all) over one source blob.  Returns findings
    already filtered by ``# noqa`` suppressions.  A syntax error yields a
    single JAX000 finding rather than raising, so one broken file can't
    take down a whole-tree run."""
    from hfrep_tpu.analysis.rules import ALL_RULES

    rules = list(rules) if rules is not None else list(ALL_RULES)
    try:
        ctx = FileContext(path, source, known_axes=known_axes, relpath=relpath)
    except SyntaxError as e:
        rel = relpath if relpath is not None else _normalize_path(path)
        return [_syntax_finding(e, rel)]
    return _run_rules(ctx, rules)


def analyze_paths(paths: Sequence, rules: Optional[Sequence] = None,
                  known_axes: Optional[Set[str]] = None) -> List[Finding]:
    """Two-pass whole-project run: every file is parsed ONCE into a
    FileContext, mesh-axis declarations are collected across all of them,
    then the rules run with the union in context — so a collective in
    ``train/steps.py`` checks against the axes ``parallel/mesh.py``
    declares."""
    from hfrep_tpu.analysis.rules import ALL_RULES
    from hfrep_tpu.analysis.rules.jax_axes import collect_declared_axes

    rules = list(rules) if rules is not None else list(ALL_RULES)
    findings: List[Finding] = []
    ctxs: List[FileContext] = []
    axes: Set[str] = set(known_axes or ())
    for f in _iter_py_files(paths):
        try:
            text = f.read_text(encoding="utf-8")
        except OSError as e:
            raise AnalysisError(f"cannot read {f}: {e}")
        try:
            ctx = FileContext(f, text)
        except SyntaxError as e:
            findings.append(_syntax_finding(e, _normalize_path(f)))
            continue
        ctxs.append(ctx)
        axes |= collect_declared_axes(ctx.tree)
    for ctx in ctxs:
        ctx.known_axes = axes
        findings.extend(_run_rules(ctx, rules))
    return findings


# -------------------------------------------------------------- baseline
def load_baseline(path) -> Counter:
    """Baseline file -> fingerprint multiset.  Format::

        {"version": 1,
         "entries": [{"fingerprint": "...", "justification": "..."}, ...]}

    Each entry absorbs exactly one matching finding; if the code grows a
    second identical violation on the same path it is *not* silently
    covered.
    """
    p = Path(path)
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except OSError as e:
        raise AnalysisError(f"cannot read baseline {p}: {e}")
    except json.JSONDecodeError as e:
        raise AnalysisError(f"baseline {p} is not valid JSON: {e}")
    if not isinstance(data, dict) or "entries" not in data:
        raise AnalysisError(f"baseline {p}: expected {{'entries': [...]}}")
    fps = Counter()
    for entry in data["entries"]:
        if not isinstance(entry, dict) or "fingerprint" not in entry:
            raise AnalysisError(f"baseline {p}: malformed entry {entry!r}")
        fps[entry["fingerprint"]] += 1
    return fps


def apply_baseline(findings: Iterable[Finding],
                   baseline: Counter) -> Tuple[List[Finding], List[Finding], Counter]:
    """Split findings into (new, baselined); also return the unconsumed
    baseline entries (stale — the violation was fixed or moved)."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        if remaining[f.fingerprint] > 0:
            remaining[f.fingerprint] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = Counter({fp: n for fp, n in remaining.items() if n > 0})
    return new, matched, stale


def write_baseline(findings: Iterable[Finding], path,
                   justifications: Optional[Dict[str, str]] = None) -> int:
    """Serialize findings as a baseline.  ``justifications`` maps
    fingerprints to one-line reasons; unknown fingerprints get a TODO so
    review pressure is visible in the diff."""
    justifications = justifications or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,          # informational only; not matched on
            "justification": justifications.get(
                f.fingerprint, "TODO: justify or fix"),
        })
    payload = {"version": 1, "entries": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n",
                          encoding="utf-8")
    return len(entries)
