"""Rule engine: file contexts, findings, ``# noqa`` suppression, baselines.

The engine is deliberately flake8-shaped — parse once per file, hand the
tree to every rule, post-filter by per-line suppressions — because that
shape is what lets new JAX rules be ~50-line visitors instead of
frameworks.  Three extensions matter here:

* a **project pre-pass** (:func:`analyze_paths`): every file is
  summarized once (axes declared, instruments/events emitted, fault
  sites referenced — :mod:`hfrep_tpu.analysis.project`), the summaries
  plus the extracted registries (fault sites, ``DEFAULT_THRESHOLDS``,
  ``GAUGE_PREFIXES``, the ``obs/README.md`` schema, the atomic-writer
  entry points, the absent-jax-API table) are assembled into a
  :class:`~hfrep_tpu.analysis.project.ProjectModel`, and every rule
  runs with it in context — so a gauge emitted in
  ``tools/bench_serve.py`` checks against the table in
  ``obs/regress.py``.  Rules may additionally implement
  ``check_project(model)`` for findings that belong to no single
  analyzed file (a dead registry entry, an undocumented-schema row);
* a **fingerprint cache** (:data:`DEFAULT_CACHE`): per-file summaries
  and findings keyed by (file sha, analyzer self-hash, project digest),
  so the repo-wide two-phase run costs parse+rules only for files that
  changed — the whole-tree gate stays inside the tier-1 budget as the
  codebase grows.  Any registry/doc edit changes the project digest and
  invalidates every cached verdict: correctness over cleverness;
* a **baseline file** keyed by content fingerprints (rule + path +
  normalized source line, with multiplicity) so pre-existing violations
  can be burned down incrementally without blocking CI on day one —
  line numbers are deliberately *not* part of the fingerprint, so
  unrelated edits above a baselined site don't resurrect it.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import re
import tokenize
from collections import Counter
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: Repo root assumed two levels above this package (``<root>/hfrep_tpu/analysis``);
#: fingerprint paths are made relative to it so baselines are CWD-independent.
REPO_ROOT = Path(__file__).resolve().parents[2]

#: default per-file fingerprint cache (gitignored; safe to delete any time)
DEFAULT_CACHE = REPO_ROOT / ".analysis-cache.json"
CACHE_VERSION = 1

_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*))?",
    re.IGNORECASE,
)


class AnalysisError(Exception):
    """Unrecoverable analyzer failure (bad baseline file, unknown rule id)."""


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation at one source location."""

    rule: str            # "JAX001" … "JAX006" (or "JAX000" for parse errors)
    path: str            # posix path, relative to the repo root when under it
    line: int            # 1-based
    col: int             # 0-based
    message: str
    snippet: str = ""    # stripped source line, used in the fingerprint

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching: where-independent of
        line numbers, so edits elsewhere in the file don't churn it."""
        return f"{self.rule}::{self.path}::{self.snippet}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"


def _normalize_path(path) -> str:
    p = Path(path).resolve()
    try:
        return p.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return p.as_posix()


class FileContext:
    """Everything a rule needs about one file: source, AST, suppressions,
    plus the project-wide ``known_axes`` set collected by the pre-pass."""

    def __init__(self, path, source: str,
                 known_axes: Optional[Set[str]] = None,
                 relpath: Optional[str] = None,
                 project=None, tree: Optional[ast.AST] = None):
        self.path = str(path)
        self.relpath = relpath if relpath is not None else _normalize_path(path)
        self.source = source
        self.lines = source.splitlines()
        # ``tree`` lets the two-phase runner hand over its phase-1 parse
        # instead of paying ast.parse twice per file
        self.tree = tree if tree is not None \
            else ast.parse(source, filename=self.path)
        self.known_axes: Set[str] = set(known_axes or ())
        #: the assembled ProjectModel on whole-project runs; None on
        #: single-snippet runs, where the cross-layer rules no-op unless
        #: handed a model explicitly (the unit-test path)
        self.project = project
        #: line -> comment text (tokenizer-accurate, so ``# noqa`` or
        #: ``# shape:`` *inside a docstring* never counts)
        self.comments: Dict[int, str] = self._scan_comments()
        self._noqa: Dict[int, Optional[Set[str]]] = self._scan_noqa()

    def _scan_comments(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            pass                    # partial map is fine; ast.parse gates worse
        return out

    def _scan_noqa(self) -> Dict[int, Optional[Set[str]]]:
        """line -> None (bare ``# noqa``: suppress all) or a code set."""
        out: Dict[int, Optional[Set[str]]] = {}
        for i, text in self.comments.items():
            m = _NOQA_RE.search(text)
            if not m:
                continue
            codes = m.group("codes")
            out[i] = ({c.strip().upper() for c in codes.split(",")}
                      if codes else None)
        return out

    def suppressed(self, finding: Finding) -> bool:
        codes = self._noqa.get(finding.line, False)
        if codes is False:
            return False
        return codes is None or finding.rule in codes

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, snippet=snippet)


# --------------------------------------------------------------- running
def _iter_py_files(paths: Sequence) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
        elif p.exists():
            # an explicitly named non-.py file would be silently skipped —
            # "clean" on an unanalyzed target is worse than an error
            raise AnalysisError(f"not a Python file: {p}")
        else:
            raise AnalysisError(f"no such path: {p}")
    # de-dup while keeping order
    seen, out = set(), []
    for f in files:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            out.append(f)
    return out


def _syntax_finding(e: SyntaxError, relpath: str) -> Finding:
    return Finding(rule="JAX000", path=relpath, line=e.lineno or 1,
                   col=(e.offset or 1) - 1,
                   message=f"syntax error: {e.msg}",
                   snippet=(e.text or "").strip())


def _run_rules(ctx: "FileContext", rules: Sequence) -> List[Finding]:
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    findings = [f for f in findings if not ctx.suppressed(f)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence] = None,
                   known_axes: Optional[Set[str]] = None,
                   relpath: Optional[str] = None,
                   project=None) -> List[Finding]:
    """Run ``rules`` (default: all) over one source blob.  Returns findings
    already filtered by ``# noqa`` suppressions.  A syntax error yields a
    single JAX000 finding rather than raising, so one broken file can't
    take down a whole-tree run.  ``project`` injects a
    :class:`~hfrep_tpu.analysis.project.ProjectModel` for the
    cross-layer rules (they no-op without one)."""
    from hfrep_tpu.analysis.rules import ALL_RULES

    rules = list(rules) if rules is not None else list(ALL_RULES)
    try:
        ctx = FileContext(path, source, known_axes=known_axes,
                          relpath=relpath, project=project)
    except SyntaxError as e:
        rel = relpath if relpath is not None else _normalize_path(path)
        return [_syntax_finding(e, rel)]
    return _run_rules(ctx, rules)


# ---------------------------------------------------------------- caching
def _self_hash() -> str:
    """Hash of the analyzer's own source: any rule/engine/project edit
    must invalidate every cached verdict, without anyone remembering to
    bump a version constant."""
    h = hashlib.sha256()
    pkg = Path(__file__).resolve().parent
    for f in sorted(pkg.rglob("*.py")):
        h.update(f.name.encode())
        h.update(f.read_bytes())
    return h.hexdigest()


def jax_version() -> str:
    """The installed jax version WITHOUT importing jax (the analysis
    package stays jax-import-free; warm cache paths must not pay the
    import).  Cache documents are keyed on this: registry verdicts that
    read the absent-API table — and every phase-3 traced jaxpr — are
    facts about a specific jax, and an upgrade must cold-start them
    rather than silently replaying the old runtime's answers."""
    try:
        from importlib.metadata import version
        return version("jax")
    except Exception:
        return "unknown"


def load_cache(path) -> dict:
    try:
        data = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("version") != CACHE_VERSION:
        return {}
    if data.get("jax") != jax_version():
        return {}                  # jax upgrade: every cached verdict cold
    entries = data.get("entries")
    if not isinstance(entries, dict):
        return {}
    # a malformed per-file entry (hand-edited, foreign writer) is a
    # cache MISS, never a crash — degrade-to-cold is the contract
    return {rel: e for rel, e in entries.items() if isinstance(e, dict)}


def save_cache(path, entries: dict) -> None:
    """Best-effort: an unwritable cache degrades to a cold run, never an
    error.  Published by rename so a killed run cannot leave a torn
    cache (a corrupt cache also just degrades to cold — belt and
    braces, not load-bearing)."""
    import os
    p = Path(path)
    tmp = p.parent / f".{p.name}.tmp-{os.getpid()}"
    try:
        tmp.write_text(
            json.dumps({"version": CACHE_VERSION, "jax": jax_version(),
                        "entries": entries}),
            encoding="utf-8")
        os.replace(tmp, p)
    except OSError:
        tmp.unlink(missing_ok=True)


def analyze_paths(paths: Sequence, rules: Optional[Sequence] = None,
                  known_axes: Optional[Set[str]] = None,
                  cache_path=None, use_cache: bool = True,
                  restrict_to: Optional[Set[str]] = None) -> List[Finding]:
    """Two-phase whole-project run.

    Phase 1 summarizes every file (axes, emissions, fault-site
    references — from the cache when the file is unchanged) and
    assembles the :class:`~hfrep_tpu.analysis.project.ProjectModel`
    (registries are read from their canonical files, so a scoped run
    still sees them).  Phase 2 runs the per-file rules with the model in
    context, then each rule's ``check_project`` hook once.

    ``restrict_to``: repo-relative posix paths — when given, per-file
    findings are reported only for those files (the ``--changed`` mode);
    phase 1 still covers the full path set so cross-layer facts stay
    whole-project.  Project-level findings are always reported: they are
    global invariants, not properties of any one changed file.
    """
    from hfrep_tpu.analysis.project import (FileSummary, ProjectModel,
                                            summarize_file)
    from hfrep_tpu.analysis.rules import ALL_RULES

    rules = list(rules) if rules is not None else list(ALL_RULES)
    cache_file = Path(cache_path) if cache_path else DEFAULT_CACHE
    cache = load_cache(cache_file) if use_cache else {}
    self_hash = _self_hash()

    findings: List[Finding] = []
    sources: Dict[str, str] = {}          # relpath -> source text
    shas: Dict[str, str] = {}
    trees: Dict[str, ast.AST] = {}
    summaries: Dict[str, FileSummary] = {}

    # ------------------------------------------------------------ phase 1
    for f in _iter_py_files(paths):
        try:
            text = f.read_text(encoding="utf-8")
        except OSError as e:
            raise AnalysisError(f"cannot read {f}: {e}")
        rel = _normalize_path(f)
        sources[rel] = text
        shas[rel] = hashlib.sha256(text.encode()).hexdigest()
        entry = cache.get(rel)
        if (entry and entry.get("sha") == shas[rel]
                and entry.get("self") == self_hash
                and not entry.get("syntax_error")
                and isinstance(entry.get("summary"), dict)):
            try:
                summaries[rel] = FileSummary.from_dict(entry["summary"])
                continue
            except (KeyError, TypeError, AttributeError):
                cache.pop(rel, None)      # malformed inner shape: a MISS
        try:
            tree = ast.parse(text, filename=str(f))
        except SyntaxError as e:
            if restrict_to is None or rel in restrict_to:
                # same reporting scope as phase 2: a --changed run must
                # not fail on an unchanged file's (pre-existing) error
                findings.append(_syntax_finding(e, rel))
            summaries[rel] = FileSummary()
            cache[rel] = {"sha": shas[rel], "self": self_hash,
                          "summary": summaries[rel].to_dict(),
                          "syntax_error": True}
            continue
        trees[rel] = tree
        summaries[rel] = summarize_file(tree)
        cache[rel] = {"sha": shas[rel], "self": self_hash,
                      "summary": summaries[rel].to_dict()}

    model = ProjectModel.from_file_summaries(summaries)
    model.known_axes |= set(known_axes or ())
    rule_ids = ",".join(r.id for r in rules)
    digest = hashlib.sha256(
        f"{self_hash}:{rule_ids}:{model.digest()}".encode()).hexdigest()

    # ------------------------------------------------------------ phase 2
    for rel, text in sources.items():
        if restrict_to is not None and rel not in restrict_to:
            continue
        entry = cache.get(rel, {})
        if entry.get("syntax_error"):
            continue                      # the JAX000 finding is emitted above
        if entry.get("digest") == digest and isinstance(
                entry.get("findings"), list):
            try:
                cached = [Finding(**fd) for fd in entry["findings"]]
            except TypeError:             # malformed inner shape: a MISS
                pass
            else:
                findings.extend(cached)
                continue
        try:
            ctx = FileContext(REPO_ROOT / rel, text, relpath=rel,
                              known_axes=model.known_axes, project=model,
                              tree=trees.get(rel))
        except SyntaxError as e:          # unreachable after phase 1, belt
            findings.append(_syntax_finding(e, rel))
            continue
        file_findings = _run_rules(ctx, rules)
        findings.extend(file_findings)
        entry = cache.setdefault(rel, {"sha": shas[rel], "self": self_hash,
                                       "summary": summaries[rel].to_dict()})
        entry["digest"] = digest
        entry["findings"] = [dataclasses.asdict(f) for f in file_findings]

    # ------------------------------------------------- project-level pass
    for rule in rules:
        check_project = getattr(rule, "check_project", None)
        if check_project is None:
            continue
        for finding in check_project(model):
            # project findings carry no per-file noqa scope; they are
            # suppressed only by fixing the registry/doc they point at
            findings.append(finding)

    if use_cache:
        # keep entries for files OUTSIDE this run's scope (a scoped
        # `check hfrep_tpu/serve` must not wipe the repo-wide warm
        # cache); prune only entries whose file is gone from disk, so
        # the cache cannot grow without bound
        save_cache(cache_file, {
            rel: e for rel, e in cache.items()
            if rel in sources or (REPO_ROOT / rel).exists()})
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# -------------------------------------------------------------- baseline
def load_baseline(path) -> Counter:
    """Baseline file -> fingerprint multiset.  Format::

        {"version": 1,
         "entries": [{"fingerprint": "...", "justification": "..."}, ...]}

    Each entry absorbs exactly one matching finding; if the code grows a
    second identical violation on the same path it is *not* silently
    covered.
    """
    p = Path(path)
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except OSError as e:
        raise AnalysisError(f"cannot read baseline {p}: {e}")
    except json.JSONDecodeError as e:
        raise AnalysisError(f"baseline {p} is not valid JSON: {e}")
    if not isinstance(data, dict) or "entries" not in data:
        raise AnalysisError(f"baseline {p}: expected {{'entries': [...]}}")
    fps = Counter()
    for entry in data["entries"]:
        if not isinstance(entry, dict) or "fingerprint" not in entry:
            raise AnalysisError(f"baseline {p}: malformed entry {entry!r}")
        fps[entry["fingerprint"]] += 1
    return fps


def apply_baseline(findings: Iterable[Finding],
                   baseline: Counter) -> Tuple[List[Finding], List[Finding], Counter]:
    """Split findings into (new, baselined); also return the unconsumed
    baseline entries (stale — the violation was fixed or moved)."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        if remaining[f.fingerprint] > 0:
            remaining[f.fingerprint] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = Counter({fp: n for fp, n in remaining.items() if n > 0})
    return new, matched, stale


def write_baseline(findings: Iterable[Finding], path,
                   justifications: Optional[Dict[str, str]] = None) -> int:
    """Serialize findings as a baseline.  ``justifications`` maps
    fingerprints to one-line reasons; unknown fingerprints get a TODO so
    review pressure is visible in the diff."""
    justifications = justifications or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,          # informational only; not matched on
            "justification": justifications.get(
                f.fingerprint, "TODO: justify or fix"),
        })
    payload = {"version": 1, "entries": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n",
                          encoding="utf-8")
    return len(entries)
