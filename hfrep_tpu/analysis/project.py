"""The whole-project model behind the cross-layer rules (HF001–HF006).

PR 1's rules are file-local: every fact they check lives in the file
they are checking.  The failure classes this repo has actually hit
since then are *cross-file string protocols* — a gauge emitted in
``tools/bench_serve.py`` whose fold direction is decided by a table in
``obs/regress.py``; a fault site injected in ``orchestrate/queue.py``
that must round-trip against the registry in ``resilience/faults.py``;
an event emitted in ``serve/server.py`` whose schema row lives in
``obs/README.md``.  A per-file linter structurally cannot see any of
them.

This module is phase one of the two-phase analyzer: it extracts, by
AST (never by importing the live modules — the analyzer must run on a
bare CPython with no jax), the registries those protocols are defined
in, plus a per-file summary of what each analyzed file *contributes*
(axes declared, instruments/events emitted, fault sites referenced).
Phase two hands the assembled :class:`ProjectModel` to every rule via
``FileContext.project``.

Extraction is pinned against the live modules by
``tests/test_analysis_project.py`` — a registry refactor breaks the
analyzer loudly there instead of silently emptying a rule.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from hfrep_tpu.analysis.engine import REPO_ROOT

# --------------------------------------------------------------------------
# Registry source locations (repo-relative).  The extractors below read
# these files directly, so a ``--changed``-scoped run still sees the full
# registries even when none of them is in the analyzed file set.
FAULTS_PATH = "hfrep_tpu/resilience/faults.py"
REGRESS_PATH = "hfrep_tpu/obs/regress.py"
HISTORY_PATH = "hfrep_tpu/obs/history.py"
CHECKPOINT_PATH = "hfrep_tpu/utils/checkpoint.py"
MANIFEST_PATH = "hfrep_tpu/obs/manifest.py"
OBS_README_PATH = "hfrep_tpu/obs/README.md"

#: the sanctioned crash-consistent writer entry points (HF003).  Each is
#: ``(repo-relative defining file, function name)``; extraction verifies
#: the function still exists there, so a rename breaks the analyzer
#: loudly instead of silently blessing nothing.
ATOMIC_WRITER_DEFS = (
    (CHECKPOINT_PATH, "write_atomic"),
    (CHECKPOINT_PATH, "atomic_text"),
    (CHECKPOINT_PATH, "_atomic_publish"),
    (MANIFEST_PATH, "_write_with_retry"),
)

#: the emission surface the obs/README.md schema documents: every
#: non-test .py file under these roots can emit documented rows, so the
#: HF004 doc-side (stale-row) check runs only when the analyzed file
#: set covers ALL of them — a single-file or package-scoped run cannot
#: judge "nothing emits this row" (it would flag every row stale).
DOC_SYNC_ROOTS = ("hfrep_tpu", "tools", "bench.py", "bench_extra.py")


def doc_surface_files(root: Optional[Path] = None) -> Set[str]:
    """Repo-relative posix paths of every file the HF004 doc-side check
    needs in scope (the full emission surface under
    :data:`DOC_SYNC_ROOTS`)."""
    root = Path(root) if root is not None else REPO_ROOT
    out: Set[str] = set()
    for entry in DOC_SYNC_ROOTS:
        p = root / entry
        if p.is_dir():
            out.update(f.relative_to(root).as_posix()
                       for f in p.rglob("*.py"))
        elif p.exists():
            out.add(entry)
    return out

#: the pinned accelerator runtime this image bakes in; the HF005 registry
#: below is curated against it (verified by tests/test_analysis_project.py
#: introspecting the installed jax)
PINNED_JAX = "0.4.37"

#: jax attributes this codebase references that do NOT exist on the
#: pinned runtime — the version-gated-API class behind the seed 38F/5E
#: tier-1 failures (`from jax import shard_map` at module top killed
#: five whole test files at collection).  Dotted name -> the sanctioned
#: alternative the finding message points at.
ABSENT_JAX_APIS: Dict[str, str] = {
    "jax.shard_map":
        "route through hfrep_tpu.parallel._compat.shard_map "
        "(guarded import; typed ShardMapUnavailable at call time)",
    "jax.typeof":
        "guard with try/except AttributeError "
        "(hfrep_tpu.utils.vma.vma_of is the sanctioned reader)",
    "jax.lax.axis_size":
        "use hfrep_tpu.parallel._compat.axis_size "
        "(lax.psum(1, axis) fallback)",
    "jax.lax.pcast":
        "guard with try/except ImportError "
        "(see hfrep_tpu.utils.vma._pcast)",
    "jax.sharding.use_mesh":
        "no equivalent on the pinned runtime; gate behind a guarded "
        "import",
}

#: hook-callable name -> the fault-site group its literal site argument
#: must belong to (HF002).  ``write_atomic`` sites arrive as keywords.
FAULT_HOOKS = {
    "tick": "boundary",
    "boundary": "boundary",
    "io_point": "io",
    "io_hook": "io",
    "post_save": "post_save",
    "actor_kill_point": "actor",
}
FAULT_KEYWORDS = {"io_site": "io", "fault_site": "post_save"}


# ------------------------------------------------------------ doc schema
@dataclasses.dataclass(frozen=True)
class DocRow:
    """One schema-table row of ``obs/README.md``: a name the docs claim
    the code emits."""

    name: str           # as written, e.g. "bench/serve_qps_c{1k,10k,100k}"
    line: int           # 1-based line in the README

    @property
    def patterns(self) -> Tuple[str, ...]:
        return expand_doc_name(self.name)


def expand_doc_name(name: str) -> Tuple[str, ...]:
    """A documented name -> regex pattern(s).

    ``{a,b,c}`` brace sets expand to alternatives; single-token holes —
    ``{H}``, ``<key>``, ``<n>`` — become wildcards.  Plain names yield
    one exact-match pattern.
    """
    variants = [""]
    i = 0
    while i < len(name):
        c = name[i]
        # an unbalanced brace/angle is prose, not a hole — fall through
        # to the literal branch rather than raising (one stray `x < y`
        # in README backticks must not kill the whole analyzer run)
        j = name.find("}", i) if c == "{" else \
            name.find(">", i) if c == "<" else -1
        if c == "{" and j != -1:
            body = name[i + 1:j]
            if "," in body:
                opts = [re.escape(o) for o in body.split(",")]
                variants = [v + f"(?:{'|'.join(opts)})" for v in variants]
            else:
                variants = [v + r"[^\s]+" for v in variants]
            i = j + 1
        elif c == "<" and j != -1:
            variants = [v + r"[^\s]+" for v in variants]
            i = j + 1
        else:
            variants = [v + re.escape(c) for v in variants]
            i += 1
    return tuple(v + "$" for v in variants)


@dataclasses.dataclass
class DocSchema:
    """What ``obs/README.md`` documents: the structured table rows (names
    the docs *claim* are emitted — checked both directions) and every
    backticked token anywhere (the weaker "documented somewhere" set)."""

    rows: List[DocRow] = dataclasses.field(default_factory=list)
    mentioned: Set[str] = dataclasses.field(default_factory=set)

    def documents(self, emitted_name: str) -> bool:
        """Is ``emitted_name`` covered — an exact backtick mention or a
        structured-row pattern match?"""
        if emitted_name in self.mentioned:
            return True
        for row in self.rows:
            for pat in row.patterns:
                if re.match(pat, emitted_name):
                    return True
        for token in self.mentioned:
            if ("{" in token or "<" in token) and any(
                    re.match(p, emitted_name)
                    for p in expand_doc_name(token)):
                return True
        return False


_BACKTICK_RE = re.compile(r"`([^`\n]+)`")
_ROW_RE = re.compile(r"^\|\s*`([^`|]+)`")


def parse_obs_readme(text: str) -> DocSchema:
    """Extract the schema vocabulary from ``obs/README.md``.

    Structured rows: inside a markdown table whose header's first column
    is one of the schema-table headers (``event name``, ``instrument``,
    ``counter``, ``name``), each data row's first backticked cell is a
    documented emission.  Cells carrying multiple backticked names
    (``serve/p50_ms`, `serve/p95_ms``) contribute each.
    """
    schema = DocSchema()
    in_schema_table = False
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        for token in _BACKTICK_RE.findall(line):
            schema.mentioned.add(token.strip())
        if stripped.startswith("|"):
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            first = cells[0] if cells else ""
            if set(first) <= {"-", " ", ":"} and first:
                continue                     # the |---|---| separator row
            header = first.lower().strip("*")
            if header in ("event name", "instrument", "counter", "name",
                          "metric"):
                in_schema_table = True
                continue
            if in_schema_table and _ROW_RE.match(stripped):
                for name in _BACKTICK_RE.findall(first):
                    schema.rows.append(DocRow(name=name.strip(),
                                              line=lineno))
        else:
            in_schema_table = False
    return schema


# ------------------------------------------------------- per-file summary
@dataclasses.dataclass
class Emission:
    """One instrument/event emission site observed in a file."""

    kind: str                      # "gauge" | "counter" | "histogram" | "event"
    line: int
    names: Tuple[str, ...] = ()    # statically resolved full names
    prefix: Optional[str] = None   # static prefix when dynamic (f"bench/{x}")

    def to_dict(self) -> dict:
        return {"kind": self.kind, "line": self.line,
                "names": list(self.names), "prefix": self.prefix}

    @classmethod
    def from_dict(cls, d: dict) -> "Emission":
        return cls(kind=d["kind"], line=d["line"],
                   names=tuple(d.get("names") or ()),
                   prefix=d.get("prefix"))


@dataclasses.dataclass
class FileSummary:
    """Everything the project model needs from one analyzed file —
    cacheable as JSON keyed by the file's content hash."""

    axes: Tuple[str, ...] = ()
    emissions: List[Emission] = dataclasses.field(default_factory=list)
    #: fault-site strings referenced at hook calls: (group, site, line)
    fault_sites_used: List[Tuple[str, str, int]] = \
        dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"axes": list(self.axes),
                "emissions": [e.to_dict() for e in self.emissions],
                "fault_sites_used": [list(t) for t in self.fault_sites_used]}

    @classmethod
    def from_dict(cls, d: dict) -> "FileSummary":
        return cls(axes=tuple(d.get("axes") or ()),
                   emissions=[Emission.from_dict(e)
                              for e in d.get("emissions") or []],
                   fault_sites_used=[tuple(t) for t in
                                     d.get("fault_sites_used") or []])


# ------------------------------------------------- static string resolution
def loop_constant_bindings(scope: ast.AST) -> Dict[str, Set[str]]:
    """Names bound by ``for`` loops over literal collections in ``scope``
    -> the set of string constants they range over.

    Handles the repo's dominant emission idiom::

        for name, value in (("qps", qps), ("p95_ms", p95)):
            obs.gauge(f"bench/serve_{name}").set(value)

    — ``name`` resolves to {"qps", "p95_ms"}.  Both plain targets over
    tuples of constants and tuple-targets over tuples of tuples (the
    constant positions) are resolved; anything else is absent from the
    map (= unresolvable).
    """
    from hfrep_tpu.analysis.rules.base import direct_nodes

    out: Dict[str, Set[str]] = {}
    for node in direct_nodes(scope):
        if not isinstance(node, ast.For):
            continue
        if not isinstance(node.iter, (ast.Tuple, ast.List)):
            continue
        elts = node.iter.elts
        if isinstance(node.target, ast.Name):
            vals = {e.value for e in elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)}
            if vals and len(vals) == len(elts):
                out[node.target.id] = vals
        elif isinstance(node.target, ast.Tuple) and all(
                isinstance(t, ast.Name) for t in node.target.elts):
            width = len(node.target.elts)
            rows = [e for e in elts
                    if isinstance(e, (ast.Tuple, ast.List))
                    and len(e.elts) == width]
            if len(rows) != len(elts):
                continue
            for pos, tgt in enumerate(node.target.elts):
                vals = {r.elts[pos].value for r in rows
                        if isinstance(r.elts[pos], ast.Constant)
                        and isinstance(r.elts[pos].value, str)}
                if len(vals) == len(rows):
                    out[tgt.id] = vals
    return out


def resolve_names(expr: ast.AST,
                  bindings: Dict[str, Set[str]]) -> Tuple[Tuple[str, ...],
                                                          Optional[str]]:
    """Statically resolve a string-valued expression.

    Returns ``(names, prefix)``: the full set of values when resolvable
    (constants, loop-bound names, f-strings whose every hole is
    loop-bound), else ``((), static_prefix_or_None)`` — the leading
    constant text of an f-string, for prefix-scoped checks.
    """
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,), None
    if isinstance(expr, ast.Name):
        vals = bindings.get(expr.id)
        return (tuple(sorted(vals)), None) if vals else ((), None)
    if isinstance(expr, ast.JoinedStr):
        parts: List[Set[str]] = []
        resolvable = True
        for piece in expr.values:
            if isinstance(piece, ast.Constant):
                parts.append({str(piece.value)})
            elif isinstance(piece, ast.FormattedValue):
                hole = piece.value
                if isinstance(hole, ast.Name) and hole.id in bindings:
                    parts.append(bindings[hole.id])
                else:
                    resolvable = False
                    break
            else:
                resolvable = False
                break
        if resolvable:
            names = [""]
            for opts in parts:
                names = [n + o for n in names for o in sorted(opts)]
            return tuple(names), None
        first = expr.values[0] if expr.values else None
        prefix = (str(first.value)
                  if isinstance(first, ast.Constant) else None)
        return (), prefix
    return (), None


# ----------------------------------------------------- emission collection
def _wrapper_names(tree: ast.AST) -> Set[str]:
    """Local event-forwarding wrappers: any function whose first
    non-self parameter is forwarded as the literal first argument of an
    ``.event(...)`` call in its body (the repo's ``_emit`` / ``_event`` /
    ``_obs_event`` pattern)."""
    wrappers: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = [a.arg for a in node.args.args if a.arg not in ("self", "cls")]
        if not params:
            continue
        first = params[0]
        for call in ast.walk(node):
            if (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "event"
                    and call.args
                    and isinstance(call.args[0], ast.Name)
                    and call.args[0].id == first):
                wrappers.add(node.name)
                break
    return wrappers


_INSTRUMENT_ATTRS = ("gauge", "counter", "histogram")


def classify_emission_call(node: ast.Call,
                           wrappers: Set[str]) -> Optional[str]:
    """``.gauge(...)``/``.counter(...)``/``.histogram(...)`` ->
    instrument kind; ``.event(...)`` or a call to a local event wrapper
    -> ``"event"``; anything else -> None."""
    if not isinstance(node, ast.Call) or not node.args:
        return None
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in _INSTRUMENT_ATTRS:
            return func.attr
        if func.attr == "event" or func.attr in wrappers:
            return "event"
    elif isinstance(func, ast.Name) and func.id in wrappers:
        return "event"
    return None


def collect_emissions(tree: ast.AST) -> List[Emission]:
    """Every instrument/event emission in a file, with static name
    resolution (one level of local-wrapper indirection for events).
    Each scope resolves against its OWN loop bindings — a loop variable
    in one function must not leak into another."""
    from hfrep_tpu.analysis.rules.base import direct_nodes, walk_scopes

    wrappers = _wrapper_names(tree)
    out: List[Emission] = []
    for scope in walk_scopes(tree):
        bindings = loop_constant_bindings(scope)
        for node in direct_nodes(scope):
            kind = classify_emission_call(node, wrappers) \
                if isinstance(node, ast.Call) else None
            if kind is None:
                continue
            names, prefix = resolve_names(node.args[0], bindings)
            out.append(Emission(kind=kind, line=node.lineno,
                                names=names, prefix=prefix))
    return out


def collect_fault_sites(tree: ast.AST) -> List[Tuple[str, str, int]]:
    """Literal site strings at fault-hook calls: (group, site, line).

    Covers the module-level hooks (``resilience.boundary("chunk")``,
    bare ``boundary(...)`` via from-import), the plan methods of the
    same names, and the ``io_site=`` / ``fault_site=`` keywords of
    ``write_atomic``-shaped writers.  ``self._tick(...)`` internal
    bookkeeping is excluded (its first argument is a hook *group*, not
    a site).
    """
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = None
        if isinstance(func, ast.Attribute):
            if func.attr.startswith("_"):
                continue                      # self._tick etc.
            fname = func.attr
        elif isinstance(func, ast.Name):
            fname = func.id
        if fname in FAULT_HOOKS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((FAULT_HOOKS[fname], arg.value, node.lineno))
        for kw in node.keywords:
            if kw.arg in FAULT_KEYWORDS and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                out.append((FAULT_KEYWORDS[kw.arg], kw.value.value,
                            node.lineno))
    # parameter DEFAULTS named io_site=/fault_site= count as usage too:
    # ``write_atomic(path, writer)`` reaches "ckpt_save"/"ckpt" through
    # its signature, not through any call-site literal
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        a = node.args
        pairs = list(zip(a.args[len(a.args) - len(a.defaults):], a.defaults))
        pairs += [(arg, d) for arg, d in zip(a.kwonlyargs, a.kw_defaults)
                  if d is not None]
        for arg, default in pairs:
            if arg.arg in FAULT_KEYWORDS and isinstance(default, ast.Constant) \
                    and isinstance(default.value, str):
                out.append((FAULT_KEYWORDS[arg.arg], default.value,
                            arg.lineno))
    return out


def summarize_file(tree: ast.AST) -> FileSummary:
    """The whole per-file contribution the project model aggregates."""
    from hfrep_tpu.analysis.rules.jax_axes import collect_declared_axes
    return FileSummary(
        axes=tuple(sorted(collect_declared_axes(tree))),
        emissions=collect_emissions(tree),
        fault_sites_used=collect_fault_sites(tree),
    )


# -------------------------------------------------------- registry readers
def _parse_repo_file(root: Path, relpath: str) -> Optional[ast.AST]:
    p = root / relpath
    if not p.exists():
        return None
    try:
        return ast.parse(p.read_text(encoding="utf-8"), filename=str(p))
    except SyntaxError:
        return None


def extract_string_tuple(tree: ast.AST, varname: str) -> Tuple[
        Dict[str, int], int]:
    """Module-level ``NAME = ("a", "b", ...)`` -> ``({value:
    element_lineno}, assign_lineno)`` — per-ELEMENT lines, so a finding
    about one entry of a multi-line registry tuple points at that
    entry's row, not the assignment header; ``({}, 0)`` when absent."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == varname \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            vals = {e.value: e.lineno for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
            return vals, node.lineno
    return {}, 0


def extract_dict_str_keys(tree: ast.AST, varname: str) -> Tuple[
        Dict[str, int], int]:
    """Module-level ``NAME: ... = {"k": ..., ...}`` -> ({key: key_line},
    assign_line)."""
    body = tree.body if isinstance(tree, ast.Module) else []
    for node in body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if not (isinstance(target, ast.Name) and target.id == varname):
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            keys = {k.value: k.lineno for k in value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)}
            return keys, node.lineno
    return {}, 0


# ------------------------------------------------------------ the model
@dataclasses.dataclass
class ProjectModel:
    """Phase-one output: every cross-file registry plus the per-file
    contributions, handed to the rules via ``FileContext.project``."""

    #: declared mesh axes across all analyzed files (JAX003)
    known_axes: Set[str] = dataclasses.field(default_factory=set)
    #: fault-site registry: group -> {site: registry_line} (HF002)
    fault_sites: Dict[str, Dict[str, int]] = \
        dataclasses.field(default_factory=dict)
    #: fault kinds: kind -> group (HF002 spec checking)
    fault_kinds: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: regress.DEFAULT_THRESHOLDS keys -> line (HF001)
    thresholds: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: history.GAUGE_PREFIXES (HF001 scope)
    gauge_prefixes: Tuple[str, ...] = ()
    #: obs/README.md schema (HF004)
    doc: DocSchema = dataclasses.field(default_factory=DocSchema)
    #: sanctioned atomic-writer function names (HF003)
    atomic_writers: Set[str] = dataclasses.field(default_factory=set)
    #: absent-on-pinned-runtime jax APIs (HF005)
    absent_jax: Dict[str, str] = \
        dataclasses.field(default_factory=lambda: dict(ABSENT_JAX_APIS))
    #: per-file summaries, keyed by repo-relative posix path
    files: Dict[str, FileSummary] = dataclasses.field(default_factory=dict)
    #: HF004 doc-side gating: None = decide by comparing ``files``
    #: against :func:`doc_surface_files` on disk; tests inject True/False
    doc_surface_complete: Optional[bool] = None

    def covers_doc_surface(self) -> bool:
        if self.doc_surface_complete is not None:
            return self.doc_surface_complete
        return doc_surface_files() <= set(self.files)

    # ------------------------------------------------------------ assembly
    @classmethod
    def from_file_summaries(cls, summaries: Dict[str, FileSummary],
                            root: Optional[Path] = None) -> "ProjectModel":
        """Build the model: registries read from their canonical files
        under ``root`` (default: the repo root), per-file contributions
        from ``summaries``."""
        root = Path(root) if root is not None else REPO_ROOT
        model = cls(files=dict(summaries))
        for s in summaries.values():
            model.known_axes |= set(s.axes)

        parsed: Dict[str, Optional[ast.AST]] = {}

        def parse_once(relpath: str) -> Optional[ast.AST]:
            if relpath not in parsed:
                parsed[relpath] = _parse_repo_file(root, relpath)
            return parsed[relpath]

        faults = parse_once(FAULTS_PATH)
        if faults is not None:
            for group, var in (("boundary", "BOUNDARY_SITES"),
                               ("io", "IO_SITES"),
                               ("post_save", "POST_SAVE_SITES"),
                               ("actor", "ACTOR_SITES")):
                sites, _ = extract_string_tuple(faults, var)
                model.fault_sites[group] = sites
            for group, var in (("boundary", "BOUNDARY_KINDS"),
                               ("io", "IO_KINDS"),
                               ("post_save", "POST_SAVE_KINDS"),
                               ("actor", "ACTOR_KINDS")):
                vals, _ = extract_string_tuple(faults, var)
                for v in vals:
                    model.fault_kinds[v] = group

        regress = parse_once(REGRESS_PATH)
        if regress is not None:
            keys, _ = extract_dict_str_keys(regress, "DEFAULT_THRESHOLDS")
            model.thresholds = keys

        history = parse_once(HISTORY_PATH)
        if history is not None:
            vals, _ = extract_string_tuple(history, "GAUGE_PREFIXES")
            model.gauge_prefixes = tuple(vals)

        readme = root / OBS_README_PATH
        if readme.exists():
            model.doc = parse_obs_readme(readme.read_text(encoding="utf-8"))

        for relpath, name in ATOMIC_WRITER_DEFS:
            tree = parse_once(relpath)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node.name == name:
                    model.atomic_writers.add(name)
                    break
        return model

    # ------------------------------------------------------- aggregations
    def all_fault_sites(self) -> Set[str]:
        return {s for group in self.fault_sites.values() for s in group}

    def emitted_names(self, kinds: Sequence[str] = ("gauge", "counter",
                                                    "histogram", "event"),
                      exclude_tests: bool = True) -> Set[str]:
        """Every statically resolved emitted name across the project."""
        out: Set[str] = set()
        for path, s in self.files.items():
            if exclude_tests and _is_test_path(path):
                continue
            for e in s.emissions:
                if e.kind in kinds:
                    out.update(e.names)
        return out

    def emitted_prefixes(self, kinds: Sequence[str] = ("gauge", "counter",
                                                       "histogram", "event"),
                         exclude_tests: bool = True) -> Set[str]:
        """Static prefixes of dynamic (unresolvable) emission sites."""
        out: Set[str] = set()
        for path, s in self.files.items():
            if exclude_tests and _is_test_path(path):
                continue
            for e in s.emissions:
                if e.kind in kinds and not e.names and e.prefix:
                    out.add(e.prefix)
        return out

    def digest(self) -> str:
        """Stable hash over everything a cached PER-FILE verdict depends
        on besides the file itself: registries, doc schema, axes, the
        absent-API table.  Any registry edit invalidates every cached
        finding — correctness over cleverness.  Cross-file emission
        aggregates are deliberately NOT part of it: only the (never
        cached) project-level pass reads them, so one new gauge in one
        file must not cold-start the other ~140 files' verdicts."""
        payload = {
            "axes": sorted(self.known_axes),
            "fault_sites": {g: sorted(d) for g, d in
                            sorted(self.fault_sites.items())},
            "fault_kinds": dict(sorted(self.fault_kinds.items())),
            "thresholds": sorted(self.thresholds),
            "gauge_prefixes": list(self.gauge_prefixes),
            "doc_rows": sorted((r.name for r in self.doc.rows)),
            "doc_mentioned": sorted(self.doc.mentioned),
            "atomic_writers": sorted(self.atomic_writers),
            "absent_jax": dict(sorted(self.absent_jax.items())),
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()


def _is_test_path(relpath: str) -> bool:
    return relpath.startswith("tests/") or "/tests/" in relpath
