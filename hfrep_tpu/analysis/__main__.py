"""``python -m hfrep_tpu.analysis`` entry point."""

from __future__ import annotations

import sys

from hfrep_tpu.analysis.cli import main

sys.exit(main())
