"""Phase 3 (ISSUE 16): the program auditor — jaxpr/IR-level static
analysis over every compile boundary the repo owns.

The AST phases judge SOURCE; this phase judges the TRACED PROGRAM.  A
declarative registry (:data:`PROGRAM_BOUNDARIES`) names each ownable
compile boundary — the GAN multi/single/conditional steps per family ×
dtype policy, the AE chunk/init programs (dense, laned, padded, multi-
dataset), the serve AOT heads, the mesh-launched variant through
``parallel/rules.py`` — with a factory that builds it at tiny fixture
shapes.  The engine traces each factory's program to a ClosedJaxpr
(``jax.make_jaxpr``) and lowers it to StableHLO text (through the
version-gated ``utils/jax_compat.py`` stage helpers), then runs the
JPX program rules (``rules/jpx_*.py``) over both:

==========  ============================================================
JPX001      donation completeness — state pytree in AND out, not donated
JPX002      precision-policy conformance — f32 dots in a bf16 program
JPX003      host callback/sync inside a scan/while body
JPX004      recompile hazards — weak-typed interface, captured scalars
JPX005      sharding-constraint loss — declared layout, unannotated HLO
JPX006      scan-carry bloat past the boundary's declared byte budget
==========  ============================================================

Findings anchor at the boundary's registry row HERE (``label=...``
line), flow through the shared machinery — ``# noqa: JPXnnn`` on that
row, the audit baseline (``audit_baseline.json``), SARIF with a
``properties.boundary`` join key the perf microscope's ``obs explain``
reads — and are cached per boundary in ``.analysis-programs-cache.json``
keyed by (defining-module shas, analyzer self-hash, installed jax
version), so a warm audit never imports jax at all.

Tracing is per-boundary fault-isolated: a runtime that cannot build or
lower one boundary records a *skip note* for it and keeps auditing the
rest — graceful degradation, never a crash.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from hfrep_tpu.analysis.engine import (REPO_ROOT, FileContext, Finding,
                                       _self_hash, jax_version)
from hfrep_tpu.analysis.rules.jpx_base import PROGRAMS_PATH

#: per-boundary finding cache (gitignored; safe to delete any time).
#: Separate from ``.analysis-cache.json``: the file cache prunes entries
#: by on-disk path existence, and boundary labels are not paths.
DEFAULT_AUDIT_CACHE = REPO_ROOT / ".analysis-programs-cache.json"
AUDIT_CACHE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Boundary:
    """One registered compile boundary.

    ``label`` — unique registry id, ``<runtime vocabulary>[@variant]``;
    the part before ``@`` is the perf-microscope label the same program
    is fingerprinted under at runtime (the ``obs explain`` join key).
    ``modules`` — repo-relative files whose content defines the traced
    program (the cache/``--changed`` scope).  ``factory`` — zero-arg
    callable returning ``(fn, args)`` ready to trace; it (not this
    module) imports jax and the subject modules, so registry
    introspection stays import-free.  ``donate`` — the argnums the
    PRODUCTION launch donates (declared, because the CPU backend the
    audit runs on does not implement donation: ``replication/
    engine.py::_donate_argnums``).  ``policy`` — "fp32" | "bf16", the
    compute-dtype promise JPX002 holds the trace to, with
    ``f32_dot_allow`` exemptions for deliberate fp32 stages.
    ``carry_budget_bytes`` — JPX006 ceiling at these fixture shapes.
    ``expect_sharding`` — JPX005 contract (False on this 1-device
    runtime: ``normalize_spec`` strips the axes, no annotation can
    appear).  ``site`` — the RUNTIME_SITES row this boundary audits.
    """

    label: str
    kind: str
    modules: Tuple[str, ...]
    site: str
    factory: Optional[Callable[[], Tuple[Callable, tuple]]] = None
    donate: Tuple[int, ...] = ()
    policy: str = "fp32"
    f32_dot_allow: int = 0
    carry_budget_bytes: Optional[int] = None
    expect_sharding: bool = False
    notes: str = ""

    @property
    def runtime_label(self) -> str:
        return self.label.split("@", 1)[0]


# ------------------------------------------------------------- factories
# Tiny fixture shapes throughout: window=6, features=4, hidden=8,
# batch=4, 8 training windows, steps_per_call=2, n_critic=2 (keeps the
# critic fori_loop — the production program shape); AE n_factors=4,
# latent_dim=3, epochs=chunk_epochs=2.  Small enough that a cold audit
# of every boundary traces in seconds on one CPU, big enough that the
# state trees clear the JPX001 state-likeness thresholds.

def _gan_fixture(family: str, dtype: str):
    import jax
    import jax.numpy as jnp

    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.states import init_gan_state

    mcfg = ModelConfig(family=family, hidden=8, features=4, window=6,
                       dtype=dtype)
    tcfg = TrainConfig(epochs=2, batch_size=4, n_critic=2, steps_per_call=2)
    pair = build_gan(mcfg)
    dataset = jnp.zeros((8, 6, 4), jnp.float32)
    state = init_gan_state(jax.random.PRNGKey(0), mcfg, tcfg, pair)
    return mcfg, tcfg, pair, dataset, state


def _gan_multi_factory(family: str, dtype: str = "float32"):
    def build():
        import jax

        from hfrep_tpu.train.steps import make_multi_step
        _, tcfg, pair, dataset, state = _gan_fixture(family, dtype)
        fn = make_multi_step(pair, tcfg, dataset, jit=False)
        return fn, (state, jax.random.PRNGKey(1))
    return build


def _gan_train_step_factory(family: str, dtype: str = "float32"):
    def build():
        import jax

        from hfrep_tpu.train.steps import make_train_step
        _, tcfg, pair, dataset, state = _gan_fixture(family, dtype)
        fn = make_train_step(pair, tcfg, dataset)
        return fn, (state, jax.random.PRNGKey(1))
    return build


def _conditional_factory():
    def build():
        import jax
        import jax.numpy as jnp

        from hfrep_tpu.config import ModelConfig, TrainConfig
        from hfrep_tpu.models.registry import build_conditional_gan
        from hfrep_tpu.train.states import init_conditional_state
        from hfrep_tpu.train.steps import make_conditional_step, make_multi_step

        mcfg = ModelConfig(family="gan", hidden=8, features=4, window=6)
        tcfg = TrainConfig(epochs=2, batch_size=4, n_critic=2,
                           steps_per_call=2)
        pair = build_conditional_gan(mcfg, cond_dim=3)
        dataset = jnp.zeros((8, 6, 4), jnp.float32)
        conds = jnp.zeros((8, 3), jnp.float32)
        step = make_conditional_step(pair, tcfg, dataset, conds)
        state = init_conditional_state(jax.random.PRNGKey(0), mcfg, tcfg,
                                       pair, 3)
        fn = make_multi_step(pair, tcfg, dataset, jit=False, step=step)
        return fn, (state, jax.random.PRNGKey(1))
    return build


def _mesh_multi_factory():
    def build():
        import jax

        from hfrep_tpu.parallel.rules import (MeshSpec, build_mesh,
                                              make_gan_multi_step)
        _, tcfg, pair, dataset, state = _gan_fixture("gan", "float32")
        mesh = build_mesh(MeshSpec(dp=1))
        fn = make_gan_multi_step(pair, tcfg, dataset, mesh, jit=False)
        return fn, (state, jax.random.PRNGKey(1))
    return build


def _ae_cfg():
    from hfrep_tpu.config import AEConfig
    return AEConfig(n_factors=4, latent_dim=3, epochs=2, chunk_epochs=2,
                    batch_size=4, patience=1)


def _ae_chunk_factory(kind: str, padded: bool = False, n_lanes: int = 2,
                      n_datasets: int = 2):
    def build():
        import jax
        import jax.numpy as jnp

        from hfrep_tpu.replication import engine as rep

        cfg = _ae_cfg()
        x = jnp.zeros((10, 4), jnp.float32)
        fn = rep._chunk_fn(cfg, kind)
        if kind == "single":
            carry, keys = rep._init_program(cfg, "single")(
                jax.random.PRNGKey(0), x)
            rows = ((jnp.asarray(8, jnp.int32), jnp.asarray(6, jnp.int32))
                    if padded else None)
            return fn, (carry, keys, x, None, rows)
        if kind == "lanes":
            lane_keys = jax.random.split(jax.random.PRNGKey(0), n_lanes)
            carry, keys = rep._init_program(cfg, "lanes")(lane_keys, x)
            masks = jnp.ones((n_lanes, cfg.latent_dim), jnp.float32)
            rows = ((jnp.asarray(8, jnp.int32), jnp.asarray(6, jnp.int32))
                    if padded else None)
            return fn, (carry, keys, x, masks, rows)
        # multi: D stacked padded datasets × L latent lanes
        xs = jnp.zeros((n_datasets, 10, 4), jnp.float32)
        dkeys = jax.random.split(jax.random.PRNGKey(0), n_datasets)
        carry, keys = rep._init_program(cfg, "multi", n_lanes=n_lanes)(
            dkeys, xs)
        masks = jnp.ones((n_lanes, cfg.latent_dim), jnp.float32)
        rows = (jnp.full((n_datasets,), 8, jnp.int32),
                jnp.full((n_datasets,), 6, jnp.int32))
        return fn, (carry, keys, xs, masks, rows)
    return build


def _ae_init_factory():
    def build():
        import jax
        import jax.numpy as jnp

        from hfrep_tpu.replication import engine as rep

        cfg = _ae_cfg()
        x = jnp.zeros((10, 4), jnp.float32)
        fn = rep._init_program(cfg, "single")
        return fn, (jax.random.PRNGKey(0), x)
    return build


def _serve_replicate_factory(dtype: str = "float32"):
    def build():
        import dataclasses as _dc

        import jax
        import jax.numpy as jnp

        from hfrep_tpu.serve.aot import AEServeModel, ae_batch_fn, full_mask

        cfg = _dc.replace(_ae_cfg(), dtype=dtype)
        params = {"encoder_kernel": jnp.zeros((4, 3), jnp.float32),
                  "decoder_kernel": jnp.zeros((3, 4), jnp.float32)}
        model = AEServeModel.create(cfg, params)
        fn = ae_batch_fn(model)
        x = jnp.zeros((2, 32, 4), jnp.float32)
        n_rows = jnp.full((2,), 32, jnp.int32)
        return fn, (model.params, x, n_rows, full_mask(cfg))
    return build


def _serve_sample_factory():
    def build():
        import jax
        import jax.numpy as jnp

        from hfrep_tpu.config import ModelConfig
        from hfrep_tpu.models.registry import build_gan
        from hfrep_tpu.serve.aot import GenServeModel, gen_batch_fn

        mcfg = ModelConfig(family="gan", hidden=8, features=4, window=6)
        pair = build_gan(mcfg)
        params = pair.generator.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 6, 4), jnp.float32))["params"]
        model = GenServeModel.create(mcfg, params)
        fn = gen_batch_fn(model)
        noise = jnp.zeros((2, 6, 4), jnp.float32)
        return fn, (model.params, noise)
    return build


# -------------------------------------------------------------- registry
_TRAIN_MODULES = ("hfrep_tpu/train/steps.py", "hfrep_tpu/train/states.py",
                  "hfrep_tpu/models/registry.py", "hfrep_tpu/config.py")
_AE_MODULES = ("hfrep_tpu/replication/engine.py",
               "hfrep_tpu/models/autoencoder.py", "hfrep_tpu/config.py")
_SERVE_MODULES = ("hfrep_tpu/serve/aot.py",
                  "hfrep_tpu/models/autoencoder.py",
                  "hfrep_tpu/models/registry.py", "hfrep_tpu/config.py")

#: Carry budgets (JPX006) are measured-at-fixture-shapes × ~1.5 — see
#: the burn-down table in the PR that landed this phase; a budget is a
#: per-scan ceiling, and vmapped lane grids multiply the leaf sizes.
PROGRAM_BOUNDARIES: Tuple[Boundary, ...] = (
    Boundary(label="compile:multi_step@gan", kind="gan_multi",
             modules=_TRAIN_MODULES, site="trainer_multi_step",
             factory=_gan_multi_factory("gan"), donate=(0,),
             carry_budget_bytes=5_500),
    Boundary(label="compile:multi_step@wgan", kind="gan_multi",
             modules=_TRAIN_MODULES, site="trainer_multi_step",
             factory=_gan_multi_factory("wgan"), donate=(0,),
             carry_budget_bytes=5_500),
    Boundary(label="compile:multi_step@wgan_gp", kind="gan_multi",
             modules=_TRAIN_MODULES, site="trainer_multi_step",
             factory=_gan_multi_factory("wgan_gp"), donate=(0,),
             carry_budget_bytes=5_500),
    Boundary(label="compile:multi_step@wgan_gp_bf16", kind="gan_multi",
             modules=_TRAIN_MODULES, site="trainer_multi_step",
             factory=_gan_multi_factory("wgan_gp", "bfloat16"),
             donate=(0,), policy="bf16", carry_budget_bytes=5_500),
    Boundary(label="compile:multi_step@mtss_bf16", kind="gan_multi",
             modules=_TRAIN_MODULES, site="trainer_multi_step",
             factory=_gan_multi_factory("mtss_wgan_gp", "bfloat16"),
             donate=(0,), policy="bf16", carry_budget_bytes=25_000),
    Boundary(label="compile:train_step@gan", kind="gan_step",
             modules=_TRAIN_MODULES, site="trainer_single_step",
             factory=_gan_train_step_factory("gan"), donate=(0,),
             carry_budget_bytes=5_500),
    Boundary(label="compile:conditional_step@gan", kind="gan_multi",
             modules=_TRAIN_MODULES + ("hfrep_tpu/scenario/conditional.py",),
             site="conditional_multi_step",
             factory=_conditional_factory(), donate=(0,),
             carry_budget_bytes=6_500),
    Boundary(label="compile:dp_multi_step@gan", kind="gan_mesh",
             modules=_TRAIN_MODULES + ("hfrep_tpu/parallel/rules.py",),
             site="mesh_launch",
             factory=_mesh_multi_factory(), donate=(0,),
             carry_budget_bytes=5_500,
             notes="1-device dp mesh on this runtime: axes stripped, "
                   "expect_sharding False by design"),
    Boundary(label="ae_chunk:single", kind="ae_chunk",
             modules=_AE_MODULES, site="ae_chunk",
             factory=_ae_chunk_factory("single"), donate=(0,),
             carry_budget_bytes=512),
    Boundary(label="ae_chunk:lanes", kind="ae_chunk",
             modules=_AE_MODULES, site="ae_chunk",
             factory=_ae_chunk_factory("lanes"), donate=(0,),
             carry_budget_bytes=1_024),
    Boundary(label="ae_chunk:lanes@padded", kind="ae_chunk",
             modules=_AE_MODULES, site="ae_chunk",
             factory=_ae_chunk_factory("lanes", padded=True), donate=(0,),
             carry_budget_bytes=1_024),
    Boundary(label="ae_chunk:multi", kind="ae_chunk",
             modules=_AE_MODULES, site="ae_chunk",
             factory=_ae_chunk_factory("multi"), donate=(0,),
             carry_budget_bytes=2_048),
    Boundary(label="ae_chunk:init", kind="ae_init",
             modules=_AE_MODULES, site="ae_chunk",
             factory=_ae_init_factory(),
             notes="(keys, xs) -> carry: nothing recurs, nothing to "
                   "donate — the JPX001 negative shape"),
    Boundary(label="serve:replicate", kind="serve",
             modules=_SERVE_MODULES, site="serve_replicate",
             factory=_serve_replicate_factory(),
             notes="params stay device-resident across requests: "
                   "donation would free the registered weights"),
    Boundary(label="serve:replicate@bf16", kind="serve",
             modules=_SERVE_MODULES, site="serve_replicate",
             factory=_serve_replicate_factory("bfloat16"), policy="bf16"),
    Boundary(label="serve:sample", kind="serve",
             modules=_SERVE_MODULES, site="serve_sample",
             factory=_serve_sample_factory()),
)

BOUNDARIES_BY_LABEL = {b.label: b for b in PROGRAM_BOUNDARIES}


# ---------------------------------------------------- runtime-site table
#: Every place the RUNTIME fingerprints a compiled program (the perf
#: microscope's label vocabulary) or dispatches an owned compile
#: boundary.  ``token`` must appear verbatim in ``file`` — the
#: registry-completeness test greps the live source, so a refactor that
#: moves or renames a boundary breaks THIS table loudly instead of
#: silently dropping audit coverage.  ``audited=True`` rows must be
#: covered by >= 1 PROGRAM_BOUNDARIES entry (matched on ``site``);
#: ``audited=False`` rows carry the reason no static row exists.
RUNTIME_SITES: Dict[str, Dict[str, Any]] = {
    "trainer_multi_step": {
        "file": "hfrep_tpu/train/trainer.py",
        "token": 'instrument_step(',
        "audited": True},
    "trainer_single_step": {
        "file": "hfrep_tpu/train/trainer.py",
        "token": "donate_argnums=(0,)",
        "audited": True},
    "conditional_multi_step": {
        "file": "hfrep_tpu/scenario/conditional.py",
        "token": "make_multi_step(",
        "audited": True},
    "mesh_launch": {
        "file": "hfrep_tpu/parallel/rules.py",
        "token": "_launch_name(mesh, kind)",
        "audited": True},
    "ae_chunk": {
        "file": "hfrep_tpu/replication/engine.py",
        "token": 'f"ae_chunk:{kind}"',
        "audited": True},
    "serve_replicate": {
        "file": "hfrep_tpu/serve/server.py",
        "token": 'f"serve:replicate:b',
        "audited": True},
    "serve_sample": {
        "file": "hfrep_tpu/serve/server.py",
        "token": 'f"serve:sample:b',
        "audited": True},
    "pp_train_step": {
        "file": "hfrep_tpu/parallel/layer_pipeline.py",
        "token": '"pp_train_step"',
        "audited": False,
        "why": "manual shard_map layer pipeline — dead on the pinned "
               "runtime (HAS_SHARD_MAP gate, HF005_KILL_LIST.md); "
               "cannot be traced here"},
    "bench_multi_step": {
        "file": "bench.py",
        "token": 'f"bench:{label}"',
        "audited": False,
        "why": "profiles the SAME make_multi_step program the "
               "trainer_multi_step rows audit, at bench shapes"},
    "perf_probe": {
        "file": "tools/perf_probe.py",
        "token": '"perf_probe:',
        "audited": False,
        "why": "ad-hoc calibration probes, not production dispatch "
               "paths; each wraps a program another site owns"},
}


def discover_label_calls(
        paths: Optional[Sequence[Path]] = None) -> List[Tuple[str, str, str]]:
    """AST-scan the runtime tree for compile-boundary *creation* sites:
    calls to ``instrument_step`` / ``instrument_launch`` /
    ``profile_jitted`` / ``profile_stage`` / ``aot_compile``.  Returns
    ``(repo-relative file, callee, label-prefix)`` triples, where the
    label prefix is the leading literal text of the label argument (""
    when fully dynamic).  The completeness test asserts every triple is
    accounted for by a RUNTIME_SITES row in the same file — a NEW
    runtime boundary added without registry coverage fails tier-1.
    """
    callees = {"instrument_step", "instrument_launch", "profile_jitted",
               "profile_stage", "aot_compile"}
    # the defining/forwarding modules: calls there are the mechanism,
    # not a boundary of their own
    skip = {"hfrep_tpu/obs/__init__.py", "hfrep_tpu/obs/attrib.py",
            "hfrep_tpu/serve/aot.py"}
    if paths is None:
        paths = ([*sorted((REPO_ROOT / "hfrep_tpu").rglob("*.py")),
                  *sorted((REPO_ROOT / "tools").glob("*.py")),
                  *sorted(REPO_ROOT.glob("bench*.py"))])
    out: List[Tuple[str, str, str]] = []
    for f in paths:
        rel = f.resolve().relative_to(REPO_ROOT).as_posix()
        if rel in skip or rel.startswith("hfrep_tpu/analysis/"):
            continue
        try:
            tree = ast.parse(f.read_text(encoding="utf-8"))
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name not in callees:
                continue
            out.append((rel, name, _label_prefix(node, name)))
    return out


def _label_prefix(call: ast.Call, callee: str) -> str:
    """Leading literal text of the call's label argument."""
    label: Optional[ast.AST] = None
    for kw in call.keywords:
        if kw.arg in ("label", "name"):
            label = kw.value
    if label is None:
        pos = {"instrument_step": 1, "instrument_launch": 1,
               "profile_jitted": 1, "profile_stage": 0}.get(callee)
        if pos is not None and len(call.args) > pos:
            label = call.args[pos]
    if isinstance(label, ast.Constant) and isinstance(label.value, str):
        return label.value
    if isinstance(label, ast.JoinedStr) and label.values:
        first = label.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return ""


# --------------------------------------------------------------- tracing
def registry_lines() -> Dict[str, int]:
    """label -> 1-based line of its ``label="..."`` registry row here —
    the anchor (and ``# noqa``) line for that boundary's findings."""
    out: Dict[str, int] = {}
    src = Path(__file__).read_text(encoding="utf-8")
    for i, line in enumerate(src.splitlines(), 1):
        for label in BOUNDARIES_BY_LABEL:
            if f'label="{label}"' in line:
                out.setdefault(label, i)
    return out


def _leaf_avals(tree) -> Tuple[Any, ...]:
    import jax

    def aval(x):
        get = getattr(x, "aval", None)
        if get is not None:
            return get
        return jax.api_util.shaped_abstractify(x)
    return tuple(aval(leaf) for leaf in jax.tree_util.tree_leaves(tree))


def trace_boundary(boundary: Boundary, line: int = 1):
    """Build + trace one boundary; returns a ``ProgramContext`` or
    raises — the caller owns the graceful-skip policy."""
    import jax

    from hfrep_tpu.analysis.rules.jpx_base import ProgramContext
    from hfrep_tpu.utils import jax_compat

    fn, args = boundary.factory()
    # prefer the plain python function for make_jaxpr so rules see the
    # real eqns, not one opaque outer pjit (jax.jit exposes __wrapped__)
    plain = getattr(fn, "__wrapped__", fn)
    closed = jax.make_jaxpr(plain)(*args)
    arg_avals = tuple(_leaf_avals(a) for a in args)
    out_avals = tuple(closed.out_avals)
    # lowering can legitimately fail where tracing succeeded (backend-
    # specific ops); HLO-level rules degrade, jaxpr-level rules still run
    lowered = (jax_compat.lower_jitted(fn, *args)
               if hasattr(fn, "lower")
               else jax_compat.lower_jitted(jax.jit(plain), *args))
    hlo = jax_compat.stage_hlo_text(lowered) if lowered is not None else None
    return ProgramContext(boundary, jaxpr=closed, hlo=hlo,
                          arg_avals=arg_avals, out_avals=out_avals,
                          line=line)


@dataclasses.dataclass
class AuditResult:
    findings: List[Finding]
    traced: List[str]                       # labels actually traced/cached
    skipped: Dict[str, str]                 # label -> reason

    @property
    def boundary_of(self) -> Dict[str, str]:
        """finding fingerprint -> runtime label (the SARIF/obs join);
        snippets lead with the registry label by construction."""
        return {f.fingerprint: f.snippet.split(" ", 1)[0].split("@", 1)[0]
                for f in self.findings}


# ---------------------------------------------------------------- caching
def _boundary_key(boundary: Boundary) -> str:
    h = hashlib.sha256()
    for rel in boundary.modules:
        p = REPO_ROOT / rel
        h.update(rel.encode())
        try:
            h.update(hashlib.sha256(p.read_bytes()).hexdigest().encode())
        except OSError:
            h.update(b"<missing>")
    return h.hexdigest()


def load_audit_cache(path) -> dict:
    try:
        data = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError, ValueError):
        return {}
    if (not isinstance(data, dict)
            or data.get("version") != AUDIT_CACHE_VERSION
            or data.get("self") != _self_hash()
            or data.get("jax") != jax_version()):
        return {}
    entries = data.get("entries")
    if not isinstance(entries, dict):
        return {}
    return {k: e for k, e in entries.items() if isinstance(e, dict)}


def save_audit_cache(path, entries: dict) -> None:
    p = Path(path)
    tmp = p.parent / f".{p.name}.tmp-{os.getpid()}"
    try:
        tmp.write_text(json.dumps({
            "version": AUDIT_CACHE_VERSION, "self": _self_hash(),
            "jax": jax_version(), "entries": entries}), encoding="utf-8")
        os.replace(tmp, p)
    except OSError:
        tmp.unlink(missing_ok=True)


# ------------------------------------------------------------- the audit
def audit_boundaries(boundaries: Optional[Sequence[Boundary]] = None,
                     rules: Optional[Sequence] = None,
                     cache_path=None, use_cache: bool = True,
                     restrict_to: Optional[Set[str]] = None) -> AuditResult:
    """Trace + rule-check every registered boundary.

    ``restrict_to`` (the ``--changed`` scope): repo-relative paths —
    only boundaries whose ``modules`` intersect it are audited.  Per-
    boundary results are cached keyed on the defining modules' shas
    (plus, at the document level, the analyzer self-hash and the
    installed jax version); an all-warm audit therefore never imports
    jax.  ``# noqa: JPXnnn`` on a registry row filters that row's
    findings here, at report time, through the ordinary FileContext.
    """
    from hfrep_tpu.analysis.rules import PROGRAM_RULES

    boundaries = (list(boundaries) if boundaries is not None
                  else list(PROGRAM_BOUNDARIES))
    rules = list(rules) if rules is not None else list(PROGRAM_RULES)
    cache_file = Path(cache_path) if cache_path else DEFAULT_AUDIT_CACHE
    cache = load_audit_cache(cache_file) if use_cache else {}
    lines = registry_lines()

    findings: List[Finding] = []
    traced: List[str] = []
    skipped: Dict[str, str] = {}
    rule_ids = ",".join(r.id for r in rules)

    for b in boundaries:
        if restrict_to is not None and not set(b.modules) & restrict_to:
            continue
        if b.factory is None:
            skipped[b.label] = b.notes or "no factory registered"
            continue
        key = f"{_boundary_key(b)}:{rule_ids}"
        # cache slot per (label, rule set): a ``--select`` run must not
        # evict the full-rule entries check.sh's warm path relies on
        slot = f"{b.label}::{rule_ids}"
        entry = cache.get(slot)
        if entry and entry.get("key") == key:
            try:
                cached = [Finding(**fd) for fd in entry.get("findings", [])]
            except TypeError:
                cached = None
            if cached is not None:
                if entry.get("skip"):
                    skipped[b.label] = str(entry["skip"])
                else:
                    traced.append(b.label)
                findings.extend(cached)
                continue
        line = lines.get(b.label, 1)
        try:
            pctx = trace_boundary(b, line=line)
        except Exception as e:     # graceful per-boundary skip, by contract
            reason = f"{type(e).__name__}: {e}"
            skipped[b.label] = reason
            cache[slot] = {"key": key, "findings": [], "skip": reason}
            continue
        b_findings: List[Finding] = []
        for rule in rules:
            b_findings.extend(rule.check_program(pctx))
        traced.append(b.label)
        cache[slot] = {"key": key, "skip": None,
                       "findings": [dataclasses.asdict(f)
                                    for f in b_findings]}
        findings.extend(b_findings)

    if use_cache:
        save_audit_cache(cache_file, {
            slot: e for slot, e in cache.items()
            if slot.split("::", 1)[0] in BOUNDARIES_BY_LABEL})

    findings = _apply_registry_noqa(findings)
    findings.sort(key=lambda f: (f.line, f.rule, f.snippet))
    return AuditResult(findings=findings, traced=traced, skipped=skipped)


def _apply_registry_noqa(findings: List[Finding]) -> List[Finding]:
    src_path = REPO_ROOT / PROGRAMS_PATH
    try:
        ctx = FileContext(src_path, src_path.read_text(encoding="utf-8"),
                          relpath=PROGRAMS_PATH)
    except (OSError, SyntaxError):
        return findings
    return [f for f in findings if not ctx.suppressed(f)]
