
from __future__ import annotations
from hfrep_tpu.ops.layers import KerasDense, KerasLayerNorm, leaky_relu  # noqa: F401
from hfrep_tpu.ops.lstm import KerasLSTM  # noqa: F401
from hfrep_tpu.ops.rolling import rolling_ols_beta  # noqa: F401
from hfrep_tpu.ops.sqrtm import sqrtm_product_trace  # noqa: F401
