"""Keras-semantics LSTM as a time-major `lax.scan` over one fused cell.

Why not `flax.linen.LSTMCell`: the reference's generators are built with
``LSTM(100, activation='sigmoid')`` (``GAN/MTSS_WGAN_GP.py:224-226``) and
the MTSS-WGAN critic with ``LSTM(100, activation=None)``
(``GAN/MTSS_WGAN.py:148-151``).  In Keras, ``activation=`` replaces the
**tanh** used for the candidate cell state and the output transform — the
three gates keep ``recurrent_activation`` (sigmoid).  Flax's cell
hard-wires tanh, so distributional parity would silently fail (SURVEY §7
hard part (a)).  This cell exposes both activations.

TPU mapping: the input projection for *all* timesteps is hoisted out of
the recurrence into a single (B·W, F) × (F, 4H) matmul — one large MXU
op — leaving only the (B, H) × (H, 4H) recurrent matmul inside the scan.
The scan is time-major and the compiler pipelines it; with W ≤ 168 and
H = 100 the whole recurrence lives comfortably in VMEM.

Parameter layout matches Keras: ``kernel`` (F, 4H), ``recurrent_kernel``
(H, 4H), ``bias`` (4H,) with gate blocks ordered [input, forget,
candidate, output] and a unit forget-gate bias.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from hfrep_tpu.ops.layers import ACTIVATIONS


def lstm_cell_step(carry, xz_t, *, recurrent, act, rec_act):
    """One fused LSTM step from a pre-projected input slice.

    ``xz_t`` is the already-projected input ``x_t @ kernel + bias`` with
    shape (..., 4H); gate blocks stay KERAS-ordered [input, forget,
    candidate, output].  The cell applies ONE ``rec_act`` over the full
    contiguous 4H block and slices the three gates out AFTERWARDS — the
    jaxpr carries a single ``logistic`` per step (pinned) and XLA fuses
    the cell body instead of scheduling per-gate kernels.  The sigmoid
    computed over the candidate's H columns is dead (only ``act`` of
    that slice is consumed) and costs one fused element-wise lane; each
    LIVE element receives exactly the per-gate arithmetic, so the cell
    is per-element bit-identical to the per-gate form.  A column
    permutation packing the sigmoid gates contiguous was rejected: the
    slice+concat it traces is exactly the layout XLA's SPMD partitioner
    miscompiles on meshes with free axes (the ``gp_critic_loss`` concat
    re-pin class — see tests/test_mesh_rules.py), and a mesh-agnostic
    cell cannot re-pin.  Shared by :class:`KerasLSTM` and the pipelined
    sequence-parallel scan (``hfrep_tpu.parallel.sequence``) so the two
    paths cannot drift apart arithmetically.
    """
    h_prev, c_prev = carry
    z = xz_t + h_prev @ recurrent
    h = z.shape[-1] // 4
    gates = rec_act(z)                     # ONE activation over i, f, _, o
    i, fgt, o = gates[..., :h], gates[..., h:2 * h], gates[..., 3 * h:]
    c = fgt * c_prev + i * act(z[..., 2 * h:3 * h])
    h_t = o * act(c)
    return (h_t, c), h_t


def _unit_forget_bias(key, shape, dtype=jnp.float32):
    h = shape[0] // 4
    return jnp.concatenate([
        jnp.zeros((h,), dtype), jnp.ones((h,), dtype), jnp.zeros((2 * h,), dtype)
    ])


class KerasLSTM(nn.Module):
    """``keras.layers.LSTM(features, return_sequences=True)`` equivalent.

    ``backend`` selects the recurrence implementation:

    * ``"xla"`` (default) — time-major `lax.scan`; arbitrarily
      differentiable.
    * ``"pallas"`` — fused TPU kernel (:mod:`hfrep_tpu.ops.pallas_lstm`),
      ~10× faster per traversal; twice-differentiable via nested
      custom_vjps (second-order residue runs a scan twin), so it also
      serves the WGAN-GP gradient-penalty path; interpreted (slow)
      off-TPU.

    The call-time ``backend=`` kwarg overrides the module field so one
    set of params can be applied through either path per call site.
    """

    features: int
    activation: Optional[str] = "tanh"            # candidate/output transform
    recurrent_activation: str = "sigmoid"          # gates
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32           # master weights; the
    backend: str = "xla"                           # per-use astype below is
                                                   # the compute-dtype cast

    @nn.compact
    def __call__(self, x: Optional[jnp.ndarray] = None,
                 backend: Optional[str] = None,
                 materialize: Optional[int] = None):
        """(B, W, F) → (B, W, H) full hidden-state sequence.

        ``materialize=<in_features>`` instead returns this layer's raw
        param dict without running it — for fused multi-layer kernels
        (:mod:`hfrep_tpu.ops.pallas_lstm_stack`).  Param names/shapes/
        inits are identical either way, so the tree is mode-independent.
        """
        h = self.features
        f = materialize if materialize is not None else x.shape[-1]
        kernel = self.param("kernel", nn.initializers.glorot_uniform(),
                            (f, 4 * h), self.param_dtype)
        recurrent = self.param("recurrent_kernel", nn.initializers.orthogonal(),
                               (h, 4 * h), self.param_dtype)
        bias = self.param("bias", _unit_forget_bias, (4 * h,), self.param_dtype)
        if materialize is not None:
            return {"kernel": kernel, "recurrent_kernel": recurrent, "bias": bias}
        b, w, _ = x.shape

        from hfrep_tpu.ops.pallas_lstm import kernel_eligible, pallas_keras_lstm
        if kernel_eligible(backend or self.backend, self.dtype or x.dtype,
                           hidden=h):
            return pallas_keras_lstm(kernel, recurrent, bias, x,
                                     self.activation or "linear",
                                     self.recurrent_activation,
                                     dtype=self.dtype or x.dtype)

        act = ACTIVATIONS[self.activation]
        rec_act = ACTIVATIONS[self.recurrent_activation]

        dtype = self.dtype or x.dtype
        x = x.astype(dtype)
        # One big MXU matmul for every timestep's input projection.
        xz = (x.reshape(b * w, f) @ kernel.astype(dtype) + bias.astype(dtype)).reshape(b, w, 4 * h)
        xz = jnp.swapaxes(xz, 0, 1)                # time-major (W, B, 4H)
        rec = recurrent.astype(dtype)

        def cell(carry, xz_t):
            return lstm_cell_step(carry, xz_t, recurrent=rec, act=act, rec_act=rec_act)

        from hfrep_tpu.utils.vma import match_vma
        init = match_vma((jnp.zeros((b, h), dtype), jnp.zeros((b, h), dtype)), xz)
        _, hs = lax.scan(cell, init, xz)
        return jnp.swapaxes(hs, 0, 1)              # back to (B, W, H)
