"""Keras-exact Nadam as an optax transformation.

The reference's AE compiles with a bare ``Nadam()``
(``Autoencoder_encapsulate.py:80``) under 2022-era tf.keras, whose
defaults are lr=1e-3, beta_1=0.9, beta_2=0.999, epsilon=1e-7 and whose
update rule is Dozat's Nadam *with the momentum-decay schedule*
(``u_t = beta1 * (1 - 0.5 * 0.96**t)``,
tensorflow/python/keras/optimizer_v2/nadam.py; identical formula in
Keras 3's ``keras/src/optimizers/nadam.py`` — note tf.keras dropped
standalone-Keras-1.x's ``schedule_decay=0.004`` exponent factor).  ``optax.nadam`` implements
the schedule-free simplification, so rounds 1-4 carried two silent
semantic deltas vs the reference: a 2x learning rate (0.002, the
standalone-Keras-1.x default) and a slightly different momentum
bias-correction.  This module removes both: :func:`keras_nadam` is a
step-for-step port of the tf.keras update rule, oracle-tested against
``tf.keras.optimizers.Nadam`` in ``tests/test_replication.py``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class KerasNadamState(NamedTuple):
    count: jnp.ndarray        # scalar int32, number of completed steps
    m_schedule: jnp.ndarray   # scalar f32, prod_{i<=t} u_i
    mu: optax.Updates         # first moment
    nu: optax.Updates         # second moment


def keras_nadam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-7) -> optax.GradientTransformation:
    """tf.keras ``Nadam`` (optimizer_v2/nadam.py) as a GradientTransformation.

    Per step t (1-based), with ``u_t = b1 * (1 - 0.5 * 0.96**t)``::

        m_sched_t   = m_sched_{t-1} * u_t
        g' = g / (1 - m_sched_t)
        m  = b1 m + (1-b1) g;    m' = m / (1 - m_sched_t * u_{t+1})
        v  = b2 v + (1-b2) g^2;  v' = v / (1 - b2**t)
        update = -lr * ((1-u_t) g' + u_{t+1} m') / (sqrt(v') + eps)

    Note epsilon sits *outside* the sqrt, as in Keras (optax puts its
    ``eps`` inside ``bias_correction`` differently).
    """

    def init_fn(params):
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        return KerasNadamState(
            count=jnp.zeros((), jnp.int32),
            m_schedule=jnp.ones((), jnp.float32),
            mu=zeros,
            nu=jax.tree_util.tree_map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        del params
        t = state.count + 1
        tf_ = t.astype(jnp.float32)
        decay = jnp.float32(0.96)
        u_t = b1 * (1.0 - 0.5 * decay ** tf_)
        u_t1 = b1 * (1.0 - 0.5 * decay ** (tf_ + 1.0))
        m_sched_t = state.m_schedule * u_t
        m_sched_next = m_sched_t * u_t1

        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1.0 - b1) * g, state.mu, updates)
        nu = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1.0 - b2) * g * g, state.nu, updates)
        v_corr = 1.0 - b2 ** tf_

        def one(g, m, v):
            g_prime = g / (1.0 - m_sched_t)
            m_prime = m / (1.0 - m_sched_next)
            v_prime = v / v_corr
            m_bar = (1.0 - u_t) * g_prime + u_t1 * m_prime
            return -learning_rate * m_bar / (jnp.sqrt(v_prime) + eps)

        new_updates = jax.tree_util.tree_map(one, updates, mu, nu)
        return new_updates, KerasNadamState(t, m_sched_t, mu, nu)

    return optax.GradientTransformation(init_fn, update_fn)
