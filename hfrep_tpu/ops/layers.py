"""Keras-default layer primitives on Flax.

The reference models are built from exactly four Keras layers — ``Dense``,
``LSTM``, ``LayerNormalization``, ``LeakyReLU`` (e.g.
``GAN/MTSS_WGAN_GP.py:221-252``).  Flax's defaults differ from Keras's in
initializer (lecun_normal vs glorot_uniform) and LayerNorm epsilon (1e-6
vs 1e-3); these wrappers pin the Keras defaults so a fresh model here is
distributionally the same model as a fresh model there.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp


def leaky_relu(x: jnp.ndarray, slope: float = 0.2) -> jnp.ndarray:
    """Keras ``LeakyReLU(alpha=.2)`` (``GAN/GAN.py:130`` et al.)."""
    return jnp.where(x >= 0, x, slope * x)


ACTIVATIONS: dict[Optional[str], Callable] = {
    None: lambda x: x,
    "linear": lambda x: x,
    "sigmoid": nn.sigmoid,
    "tanh": nn.tanh,
    "relu": nn.relu,
}


class KerasDense(nn.Module):
    """``keras.layers.Dense``: glorot_uniform kernel, zeros bias.

    Applied to the trailing axis — on (B, W, F) inputs it acts
    per-timestep, exactly as Keras ``Dense`` does on 3-D tensors (this is
    why the reference's vanilla discriminator emits (B, W, 1) validity
    scores, ``GAN/GAN.py:144-158``).
    """

    features: int
    activation: Optional[str] = None
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32   # master weights (Policy.param_dtype)

    @nn.compact
    def __call__(self, x):
        y = nn.Dense(
            self.features,
            use_bias=self.use_bias,
            kernel_init=nn.initializers.glorot_uniform(),
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(x)
        return ACTIVATIONS[self.activation](y)


class KerasLayerNorm(nn.Module):
    """``keras.layers.LayerNormalization`` defaults: axis=-1, eps=1e-3."""

    epsilon: float = 1e-3
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.LayerNorm(epsilon=self.epsilon, dtype=self.dtype,
                            param_dtype=self.param_dtype)(x)
