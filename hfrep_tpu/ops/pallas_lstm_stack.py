"""Fused two-layer (plain-stack) LSTM pallas kernels.

The MTSS critics are plain stacks — ``LSTM(100) → LSTM(100)``
(``GAN/MTSS_WGAN_GP.py:237-252``, ``GAN/MTSS_GAN.py:143-157``) — and are
applied ~6× per WGAN-GP critic iteration (scoring fwd/bwd + the gradient
penalty's fwd/inner-reverse/adjoint).  Fusing both layers into single
kernels (layer 2 consumes layer 1's h at the same timestep:
``z2_t = h1_t@K2 + b2 + h2_{t-1}@R2``) halves kernel launches and keeps
the inter-layer activation in VMEM.

Same differentiation structure as the single-layer module
(:mod:`hfrep_tpu.ops.pallas_lstm`): ``stack_seq`` (primal) →
``stack_fwd_res`` (residual-producing forward, extended backward with
direct cotangent streams) → ``stack_bwd_seq`` (backward primitive whose
VJP is the hand-derived fused adjoint kernel).  Every formula is
oracle-tested against JAX AD over pure-JAX scan twins
(tests/test_pallas_stack.py).

Generators are NOT fused: their stacks have LayerNorm/LeakyReLU between
the layers and keep the per-layer kernels.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hfrep_tpu.ops.pallas_lstm import (
    LANE,
    _ACT,
    _act_prime_from_value as P,
    _act_prime_prime_from_value as PP,
    _cast_like,
    _interpret,
    _shifted,
    _supported,
    pad_keras_params,
)
from hfrep_tpu.utils.vma import shape_struct


def _gates(z, act_name):
    hp = z.shape[-1] // 4
    zi, zf, zc, zo = (z[:, :hp], z[:, hp:2 * hp], z[:, 2 * hp:3 * hp], z[:, 3 * hp:])
    act = _ACT[act_name]
    return (jax.nn.sigmoid(zi), jax.nn.sigmoid(zf), act(zc), jax.nn.sigmoid(zo))


def _bwd_step(act_name, i, f, g, o, c_prev, c, dhs_t, dh, dc):
    """Shared primal-backward step from gate values; returns
    (dz, dcT, dhT) — dh'/dc' derived by the caller."""
    a_c = _ACT[act_name](c)
    dhT = dhs_t + dh
    do = dhT * a_c
    dzo = do * o * (1.0 - o)
    dcT = dc + dhT * o * P(act_name, a_c)
    dzi = dcT * g * i * (1.0 - i)
    dzf = dcT * c_prev * f * (1.0 - f)
    dzc = dcT * i * P(act_name, g)
    return jnp.concatenate([dzi, dzf, dzc, dzo], axis=-1), dcT, dhT


# --------------------------------------------------------------- forward

def _stack_fwd_kernel(act_name, with_res, xz1_ref, rec1_ref, k2_ref, b2_ref,
                      rec2_ref, hs2_ref, *rest):
    if with_res:
        hs1_ref, cs1_ref, cs2_ref = rest[0], rest[1], rest[2]
    h1s, c1s, h2s, c2s = rest[-4:]
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        for s in (h1s, c1s, h2s, c2s):
            s[:] = jnp.zeros_like(s)

    act = _ACT[act_name]
    # Operands may be bf16 (f32 state/gate math, f32 accumulation —
    # same mixed-precision contract as the single-layer kernels).
    z1 = (xz1_ref[0].astype(jnp.float32)
          + jnp.dot(h1s[:].astype(rec1_ref.dtype), rec1_ref[:],
                    preferred_element_type=jnp.float32))
    i1, f1, g1, o1 = _gates(z1, act_name)
    c1 = f1 * c1s[:] + i1 * g1
    h1 = o1 * act(c1)
    z2 = (b2_ref[0].astype(jnp.float32)
          + jnp.dot(h1.astype(k2_ref.dtype), k2_ref[:],
                    preferred_element_type=jnp.float32)
          + jnp.dot(h2s[:].astype(rec2_ref.dtype), rec2_ref[:],
                    preferred_element_type=jnp.float32))
    i2, f2, g2, o2 = _gates(z2, act_name)
    c2 = f2 * c2s[:] + i2 * g2
    h2 = o2 * act(c2)
    h1s[:], c1s[:], h2s[:], c2s[:] = h1, c1, h2, c2
    hs2_ref[0] = h2
    if with_res:
        hs1_ref[0] = h1
        cs1_ref[0] = c1
        cs2_ref[0] = c2


def _stack_fwd_impl(xz1, rec1, k2, b2, rec2, activation, with_res):
    w, b, g = xz1.shape
    hp = g // 4
    t_h = pl.BlockSpec((1, b, hp), lambda t: (t, 0, 0), memory_space=pltpu.VMEM)
    sh_h = shape_struct((w, b, hp), jnp.float32, (xz1, rec1, k2, b2, rec2))
    mat = pl.BlockSpec((hp, g), lambda t: (0, 0), memory_space=pltpu.VMEM)
    row = pl.BlockSpec((1, g), lambda t: (0, 0), memory_space=pltpu.VMEM)
    n_out = 4 if with_res else 1
    out = pl.pallas_call(
        functools.partial(_stack_fwd_kernel, activation, with_res),
        grid=(w,),
        in_specs=[pl.BlockSpec((1, b, g), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
                  mat, mat, row, mat],
        out_specs=[t_h] * n_out,
        out_shape=[sh_h] * n_out,
        scratch_shapes=[pltpu.VMEM((b, hp), jnp.float32)] * 4,
        interpret=_interpret(),
    )(xz1, rec1, k2, b2.reshape(1, g), rec2)
    if with_res:
        hs2, hs1, cs1, cs2 = out         # kernel emits hs2 first
        return hs1, cs1, hs2, cs2
    return out[0]


# -------------------------------------------------------------- backward

def _stack_bwd_kernel(act_name, with_direct, with_carries,
                      xz1_ref, rec1_ref, rec1_t_ref, k2_ref, k2_t_ref, b2_ref,
                      rec2_ref, rec2_t_ref,
                      h1p_ref, c1p_ref, cs1_ref, hs1_ref,
                      h2p_ref, c2p_ref, cs2_ref, dhs2_ref, *rest):
    k = 3 if with_direct else 0
    if with_direct:        # direct cotangents on the residual streams
        dhs1_ref, dcs1_ref, dcs2_ref = rest[0], rest[1], rest[2]
    dxz1_ref, drec1_ref, dk2_ref, db2_ref, drec2_ref = rest[k:k + 5]
    if with_carries:
        dhT1_ref, dcT1_ref, dhT2_ref, dcT2_ref = rest[k + 5:k + 9]
    dh1s, dc1s, dh2s, dc2s = rest[-4:]
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        for s in (dh1s, dc1s, dh2s, dc2s):
            s[:] = jnp.zeros_like(s)
        drec1_ref[:] = jnp.zeros_like(drec1_ref)
        dk2_ref[:] = jnp.zeros_like(dk2_ref)
        db2_ref[:] = jnp.zeros_like(db2_ref)
        drec2_ref[:] = jnp.zeros_like(drec2_ref)

    h1p, c1p, c1, h1 = h1p_ref[0], c1p_ref[0], cs1_ref[0], hs1_ref[0]
    h2p, c2p, c2 = h2p_ref[0], c2p_ref[0], cs2_ref[0]

    # recompute gates for both layers (bf16 operands: cast f32 residuals
    # to the matrix dtype at each dot, f32 accumulation)
    z1 = (xz1_ref[0].astype(jnp.float32)
          + jnp.dot(h1p.astype(rec1_ref.dtype), rec1_ref[:],
                    preferred_element_type=jnp.float32))
    i1, f1, g1, o1 = _gates(z1, act_name)
    z2 = (b2_ref[0].astype(jnp.float32)
          + jnp.dot(h1.astype(k2_ref.dtype), k2_ref[:],
                    preferred_element_type=jnp.float32)
          + jnp.dot(h2p.astype(rec2_ref.dtype), rec2_ref[:],
                    preferred_element_type=jnp.float32))
    i2, f2, g2, o2 = _gates(z2, act_name)

    dc2_in = dc2s[:] + (dcs2_ref[0] if with_direct else 0.0)
    dz2, dcT2, dhT2 = _bwd_step(act_name, i2, f2, g2, o2, c2p, c2,
                                dhs2_ref[0], dh2s[:], dc2_in)
    dk2_ref[:] += lax.dot_general(h1, dz2, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    db2_ref[:] += jnp.sum(dz2, axis=0, keepdims=True)
    drec2_ref[:] += lax.dot_general(h2p, dz2, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    dh1_in = jnp.dot(dz2.astype(k2_t_ref.dtype), k2_t_ref[:],
                     preferred_element_type=jnp.float32)
    if with_direct:
        dh1_in = dh1_in + dhs1_ref[0]
    dc1_in = dc1s[:] + (dcs1_ref[0] if with_direct else 0.0)
    dz1, dcT1, dhT1 = _bwd_step(act_name, i1, f1, g1, o1, c1p, c1,
                                dh1_in, dh1s[:], dc1_in)
    drec1_ref[:] += lax.dot_general(h1p, dz1, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    dxz1_ref[0] = dz1
    if with_carries:
        dhT1_ref[0], dcT1_ref[0] = dhT1, dcT1
        dhT2_ref[0], dcT2_ref[0] = dhT2, dcT2
    dh1s[:] = jnp.dot(dz1.astype(rec1_t_ref.dtype), rec1_t_ref[:],
                      preferred_element_type=jnp.float32)
    dc1s[:] = dcT1 * f1
    dh2s[:] = jnp.dot(dz2.astype(rec2_t_ref.dtype), rec2_t_ref[:],
                      preferred_element_type=jnp.float32)
    dc2s[:] = dcT2 * f2


def _shift1(a):
    return _shifted(a, a)[0]


def _stack_bwd_call(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2, dhs2,
                    directs, activation, with_carries=False):
    w, b, g = xz1.shape
    hp = g // 4
    rev = lambda t: (w - 1 - t, 0, 0)
    t_h = pl.BlockSpec((1, b, hp), rev, memory_space=pltpu.VMEM)
    t_g = pl.BlockSpec((1, b, g), rev, memory_space=pltpu.VMEM)
    mat = pl.BlockSpec((hp, g), lambda t: (0, 0), memory_space=pltpu.VMEM)
    mat_t = pl.BlockSpec((g, hp), lambda t: (0, 0), memory_space=pltpu.VMEM)
    row = pl.BlockSpec((1, g), lambda t: (0, 0), memory_space=pltpu.VMEM)
    with_direct = directs is not None
    operands = [xz1, rec1, rec1.T, k2, k2.T, b2.reshape(1, g), rec2, rec2.T,
                _shift1(hs1), _shift1(cs1), cs1, hs1,
                _shift1(hs2), _shift1(cs2), cs2, dhs2]
    in_specs = [t_g, mat, mat_t, mat, mat_t, row, mat, mat_t] + [t_h] * 8
    if with_direct:
        operands += list(directs)        # (dhs1, dcs1, dcs2)
        in_specs += [t_h] * 3
    out_specs = [t_g, mat, mat, row, mat]
    out_shape = [shape_struct((w, b, g), jnp.float32, operands),
                 shape_struct((hp, g), jnp.float32, operands),
                 shape_struct((hp, g), jnp.float32, operands),
                 shape_struct((1, g), jnp.float32, operands),
                 shape_struct((hp, g), jnp.float32, operands)]
    if with_carries:
        out_specs += [t_h] * 4
        out_shape += [shape_struct((w, b, hp), jnp.float32, operands)] * 4
    out = pl.pallas_call(
        functools.partial(_stack_bwd_kernel, activation, with_direct,
                          with_carries),
        grid=(w,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((b, hp), jnp.float32)] * 4,
        interpret=_interpret(),
    )(*operands)
    out = list(out)
    out[3] = out[3].reshape(g)           # db2 (1, G) → (G,)
    return tuple(out)


# --------------------------------------------------------------- adjoint

def _stack_adj_kernel(act_name, xz1_ref, rec1_ref, rec1_t_ref, k2_ref,
                      k2_t_ref, b2_ref, rec2_ref, rec2_t_ref,
                      vr1_ref, vr1_t_ref, vk2_ref, vk2_t_ref, vb2_ref,
                      vr2_ref, vr2_t_ref,
                      h1p_ref, c1p_ref, cs1_ref, hs1_ref,
                      h2p_ref, c2p_ref, cs2_ref, u1_ref,
                      dhT1_ref, dcT1_ref, dhT2_ref, dcT2_ref,
                      uxz1_ref, uh1_ref, uh1p_ref, uc1p_ref, uc1_ref,
                      uh2p_ref, uc2p_ref, uc2_ref, udhs2_ref,
                      ur1_ref, uk2_ref, ub2_ref, ur2_ref,
                      muh1_s, muc1_s, muh2_s, muc2_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        for s in (muh1_s, muc1_s, muh2_s, muc2_s):
            s[:] = jnp.zeros_like(s)
        for r in (ur1_ref, uk2_ref, ub2_ref, ur2_ref):
            r[:] = jnp.zeros_like(r)

    act = _ACT[act_name]
    h1p, c1p, c1, h1 = h1p_ref[0], c1p_ref[0], cs1_ref[0], hs1_ref[0]
    h2p, c2p, c2 = h2p_ref[0], c2p_ref[0], cs2_ref[0]
    dhT1, dcT1 = dhT1_ref[0], dcT1_ref[0]
    dhT2, dcT2 = dhT2_ref[0], dcT2_ref[0]

    def adj_layer(z, c_t, cp_t, hp_t, dhT, dcT, muh, muc, U_t, v, v_t, rec,
                  rec_t):
        """Shared single-layer adjoint step; returns
        (zbar, hpbar, cpbar, cbar, dhTbar, dcTbar, urec_step, dz)."""
        i, f, g, o = _gates(z, act_name)
        a_c = act(c_t)
        qi, qf, qo = i * (1 - i), f * (1 - f), o * (1 - o)
        do = dhT * a_c
        hp_dim = z.shape[-1] // 4
        dzi = dcT * g * qi
        dzf = dcT * cp_t * qf
        dzc = dcT * i * P(act_name, g)
        dzo = do * qo
        dz = jnp.concatenate([dzi, dzf, dzc, dzo], axis=-1)
        dzbar = (U_t.astype(jnp.float32)
                 + jnp.dot(muh.astype(rec.dtype), rec,
                           preferred_element_type=jnp.float32)
                 + jnp.dot(hp_t.astype(v.dtype), v,
                           preferred_element_type=jnp.float32))
        dcTbar = muc * f
        fbar = muc * dcT
        hpbar = jnp.dot(dz.astype(v_t.dtype), v_t,
                        preferred_element_type=jnp.float32)
        urec = lax.dot_general(muh, dz, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
        dzbi, dzbf, dzbc, dzbo = (dzbar[:, :hp_dim], dzbar[:, hp_dim:2 * hp_dim],
                                  dzbar[:, 2 * hp_dim:3 * hp_dim], dzbar[:, 3 * hp_dim:])
        dcTbar += dzbi * g * qi
        gbar = dzbi * dcT * qi
        ibar = dzbi * dcT * g * (1 - 2 * i)
        dcTbar += dzbf * cp_t * qf
        cpbar = dzbf * dcT * qf
        fbar += dzbf * dcT * cp_t * (1 - 2 * f)
        dcTbar += dzbc * i * P(act_name, g)
        ibar += dzbc * dcT * P(act_name, g)
        gbar += dzbc * dcT * i * PP(act_name, g)
        dobar = dzbo * qo
        obar = dzbo * do * (1 - 2 * o)
        dhTbar = dcTbar * o * P(act_name, a_c)
        obar += dcTbar * dhT * P(act_name, a_c)
        aCbar = dcTbar * dhT * o * PP(act_name, a_c)
        dhTbar += dobar * a_c
        aCbar += dobar * dhT
        zbar = jnp.concatenate([ibar * qi, fbar * qf, gbar * P(act_name, g),
                                obar * qo], axis=-1)
        hpbar = hpbar + jnp.dot(zbar.astype(rec_t.dtype), rec_t,
                                preferred_element_type=jnp.float32)
        urec = urec + lax.dot_general(hp_t, zbar, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        cbar = aCbar * P(act_name, a_c)
        return zbar, hpbar, cpbar, cbar, dhTbar, dcTbar, urec, dz

    z1 = (xz1_ref[0].astype(jnp.float32)
          + jnp.dot(h1p.astype(rec1_ref.dtype), rec1_ref[:],
                    preferred_element_type=jnp.float32))
    z2 = (b2_ref[0].astype(jnp.float32)
          + jnp.dot(h1.astype(k2_ref.dtype), k2_ref[:],
                    preferred_element_type=jnp.float32)
          + jnp.dot(h2p.astype(rec2_ref.dtype), rec2_ref[:],
                    preferred_element_type=jnp.float32))

    # layer1 adjoint first (it ran last in the backward step)
    (zbar1, hp1bar, cp1bar, c1bar, dhTbar1, dcTbar1, ur1_s, dz1) = adj_layer(
        z1, c1, c1p, h1p, dhT1, dcT1, muh1_s[:], muc1_s[:], u1_ref[0],
        vr1_ref[:], vr1_t_ref[:], rec1_ref[:], rec1_t_ref[:])
    ur1_ref[:] += ur1_s
    # layer2's dz2 cotangent: via dh1_in = dz2@K2ᵀ, dk2 = h1ᵀdz2, db2 = Σdz2
    u2 = (jnp.dot(dhTbar1.astype(k2_ref.dtype), k2_ref[:],
                  preferred_element_type=jnp.float32)
          + jnp.dot(h1.astype(vk2_ref.dtype), vk2_ref[:],
                    preferred_element_type=jnp.float32)
          + vb2_ref[0].astype(jnp.float32))
    (zbar2, hp2bar, cp2bar, c2bar, dhTbar2, dcTbar2, ur2_s, dz2) = adj_layer(
        z2, cs2_ref[0], c2p, h2p, dhT2, dcT2, muh2_s[:], muc2_s[:], u2,
        vr2_ref[:], vr2_t_ref[:], rec2_ref[:], rec2_t_ref[:])
    ur2_ref[:] += ur2_s
    # zbar2 is the cotangent of z2's additive inputs: h1@K2 (+b2)
    uh1 = (jnp.dot(zbar2.astype(k2_t_ref.dtype), k2_t_ref[:],
                   preferred_element_type=jnp.float32)
           + jnp.dot(dz2.astype(vk2_t_ref.dtype), vk2_t_ref[:],
                     preferred_element_type=jnp.float32))
    uk2_ref[:] += (lax.dot_general(h1, zbar2, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
                   + lax.dot_general(dhTbar1, dz2, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32))
    ub2_ref[:] += jnp.sum(zbar2, axis=0, keepdims=True)

    uxz1_ref[0] = zbar1
    uh1_ref[0] = uh1
    uh1p_ref[0] = hp1bar
    uc1p_ref[0] = cp1bar
    uc1_ref[0] = c1bar
    uh2p_ref[0] = hp2bar
    uc2p_ref[0] = cp2bar
    uc2_ref[0] = c2bar
    udhs2_ref[0] = dhTbar2
    muh1_s[:], muc1_s[:] = dhTbar1, dcTbar1
    muh2_s[:], muc2_s[:] = dhTbar2, dcTbar2


def _stack_adj_call(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2,
                    dhT1s, dcT1s, dhT2s, dcT2s, u1, vr1, vk2, vb2, vr2,
                    activation):
    w, b, g = xz1.shape
    hp = g // 4
    nat = lambda t: (t, 0, 0)
    t_h = pl.BlockSpec((1, b, hp), nat, memory_space=pltpu.VMEM)
    t_g = pl.BlockSpec((1, b, g), nat, memory_space=pltpu.VMEM)
    mat = pl.BlockSpec((hp, g), lambda t: (0, 0), memory_space=pltpu.VMEM)
    mat_t = pl.BlockSpec((g, hp), lambda t: (0, 0), memory_space=pltpu.VMEM)
    row = pl.BlockSpec((1, g), lambda t: (0, 0), memory_space=pltpu.VMEM)
    _ops = (xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2,
            dhT1s, dcT1s, dhT2s, dcT2s, u1, vr1, vk2, vb2, vr2)
    sh_h = shape_struct((w, b, hp), jnp.float32, _ops)
    outs = pl.pallas_call(
        functools.partial(_stack_adj_kernel, activation),
        grid=(w,),
        in_specs=[t_g, mat, mat_t, mat, mat_t, row, mat, mat_t,
                  mat, mat_t, mat, mat_t, row, mat, mat_t]
                 + [t_h] * 7 + [t_g] + [t_h] * 4,
        out_specs=[t_g] + [t_h] * 8 + [mat, mat, row, mat],
        out_shape=[shape_struct((w, b, g), jnp.float32, _ops)]
                  + [sh_h] * 8
                  + [shape_struct((hp, g), jnp.float32, _ops),
                     shape_struct((hp, g), jnp.float32, _ops),
                     shape_struct((1, g), jnp.float32, _ops),
                     shape_struct((hp, g), jnp.float32, _ops)],
        scratch_shapes=[pltpu.VMEM((b, hp), jnp.float32)] * 4,
        interpret=_interpret(),
    )(xz1, rec1, rec1.T, k2, k2.T, b2.reshape(1, g), rec2, rec2.T,
      vr1, vr1.T, vk2, vk2.T, vb2.reshape(1, g), vr2, vr2.T,
      _shift1(hs1), _shift1(cs1), cs1, hs1,
      _shift1(hs2), _shift1(cs2), cs2, u1,
      dhT1s, dcT1s, dhT2s, dcT2s)
    (uxz1, uh1, uh1p, uc1p, uc1, uh2p, uc2p, uc2, udhs2,
     ur1, uk2, ub2, ur2) = outs
    zero = jnp.zeros_like(uh1p[:1])
    uhs1 = uh1 + jnp.concatenate([uh1p[1:], zero], axis=0)
    ucs1 = uc1 + jnp.concatenate([uc1p[1:], zero], axis=0)
    uhs2 = jnp.concatenate([uh2p[1:], zero], axis=0)
    ucs2 = uc2 + jnp.concatenate([uc2p[1:], zero], axis=0)
    return uxz1, ur1, uk2, ub2.reshape(g), ur2, uhs1, ucs1, uhs2, ucs2, udhs2


# ------------------------------------------------------ custom_vjp layers

@functools.partial(jax.custom_vjp, nondiff_argnums=(10,))
def stack_bwd_seq(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2, dhs2,
                  activation):
    """Fused backward as a differentiable-once primitive (pallas primal,
    hand-derived pallas adjoint as its VJP)."""
    return _stack_bwd_call(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2,
                           dhs2, None, activation)[:5]


def _stack_bwd_seq_fwd(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2, dhs2,
                       activation):
    out = _stack_bwd_call(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2,
                          dhs2, None, activation, with_carries=True)
    res = (xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2) + out[5:]
    return out[:5], res


def _stack_bwd_seq_bwd(activation, res, cots):
    (xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2,
     dhT1s, dcT1s, dhT2s, dcT2s) = res
    u1, vr1, vk2, vb2, vr2 = cots
    out = _stack_adj_call(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2,
                          dhT1s, dcT1s, dhT2s, dcT2s, u1, vr1, vk2, vb2,
                          vr2, activation)
    return _cast_like(out[:5], (xz1, rec1, k2, b2, rec2)) + out[5:]


stack_bwd_seq.defvjp(_stack_bwd_seq_fwd, _stack_bwd_seq_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def stack_fwd_res(xz1, rec1, k2, b2, rec2, activation):
    """Forward producing (hs1, cs1, hs2, cs2) with a pallas VJP (extended
    backward accepting direct cotangents on every residual stream)."""
    return _stack_fwd_impl(xz1, rec1, k2, b2, rec2, activation, with_res=True)


def _stack_fwd_res_fwd(xz1, rec1, k2, b2, rec2, activation):
    hs1, cs1, hs2, cs2 = _stack_fwd_impl(xz1, rec1, k2, b2, rec2, activation,
                                         with_res=True)
    return (hs1, cs1, hs2, cs2), (xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2)


def _stack_fwd_res_bwd(activation, res, cots):
    xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2 = res
    dhs1, dcs1, dhs2, dcs2 = cots
    out = _stack_bwd_call(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2,
                          dhs2, (dhs1, dcs1, dcs2), activation)
    return _cast_like(out[:5], (xz1, rec1, k2, b2, rec2))


stack_fwd_res.defvjp(_stack_fwd_res_fwd, _stack_fwd_res_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def stack_seq(xz1, rec1, k2, b2, rec2, activation):
    """Fused two-layer recurrence: (W, B, 4Hp) → layer2 hidden (W, B, Hp)."""
    return _stack_fwd_impl(xz1, rec1, k2, b2, rec2, activation, with_res=False)


def _stack_seq_fwd(xz1, rec1, k2, b2, rec2, activation):
    hs1, cs1, hs2, cs2 = stack_fwd_res(xz1, rec1, k2, b2, rec2, activation)
    return hs2, (xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2)


def _stack_seq_bwd(activation, res, dhs2):
    xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2 = res
    return _cast_like(
        stack_bwd_seq(xz1, rec1, k2, b2, rec2, hs1, cs1, hs2, cs2, dhs2,
                      activation),
        (xz1, rec1, k2, b2, rec2))


stack_seq.defvjp(_stack_seq_fwd, _stack_seq_bwd)


# ----------------------------------------------------- Keras-layout entry

def pallas_keras_lstm_stack(params1: dict, params2: dict, x: jnp.ndarray,
                            activation: Optional[str] = "tanh",
                            recurrent_activation: str = "sigmoid",
                            dtype=None) -> jnp.ndarray:
    """Fused plain stack from two Keras-layout param dicts
    ({kernel, recurrent_kernel, bias}); (B, W, F) → (B, W, H2).

    Numerically matches two chained :class:`~hfrep_tpu.ops.lstm.KerasLSTM`
    applications; twice-differentiable like the single-layer path.
    ``dtype`` is the effective compute dtype (default ``x.dtype``); bf16
    streams the weight matrices/projection at half width (f32 gate math)
    and returns bf16, matching the scan path's dtype contract.
    """
    _supported(activation, recurrent_activation)
    act = activation or "linear"
    b, w, f = x.shape
    h1 = params1["recurrent_kernel"].shape[0]
    h2 = params2["recurrent_kernel"].shape[0]
    if h1 != h2:
        raise NotImplementedError("fused stack requires equal layer widths")
    hp = ((h1 + LANE - 1) // LANE) * LANE
    dt = jnp.dtype(dtype or x.dtype)
    if dt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise NotImplementedError(f"pallas LSTM stack streams f32/bf16, got {dt}")

    k1p, r1p, b1p = pad_keras_params(params1, h1, hp)
    _, r2p, b2p = pad_keras_params(params2, h2, hp)
    # layer 2's input kernel consumes the padded hidden state, so it pads
    # rows exactly like a recurrent matrix (the helper's rec treatment).
    k2p = pad_keras_params({**params2, "recurrent_kernel": params2["kernel"]},
                           h2, hp)[1]

    x = x.astype(dt)
    xz1 = (x.reshape(b * w, f) @ k1p.astype(dt) + b1p.astype(dt)
           ).reshape(b, w, 4 * hp)
    xz1 = jnp.swapaxes(xz1, 0, 1).astype(dt)
    hs2 = stack_seq(xz1, r1p.astype(dt), k2p.astype(dt),
                    b2p.astype(dt), r2p.astype(dt), act)
    return jnp.swapaxes(hs2, 0, 1)[..., :h2].astype(dt)
