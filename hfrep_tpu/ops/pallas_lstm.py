"""Pallas TPU kernels for the fused LSTM recurrence.

The LSTM traversal is the framework's hot op: every MTSS model apply
(``GAN/MTSS_WGAN_GP.py:221-252`` semantics) is a 48-168-step serial
recurrence of (B, H)×(H, 4H) matmuls — far too small to fill the MXU, so
the XLA `lax.scan` path is bound by per-step loop latency, not FLOPs.
These kernels run the whole recurrence as ONE ``pallas_call``: weights
stay resident in VMEM, the per-step state (h, c) lives in VMEM scratch,
and the grid walks the time axis with the time-sliced operands streamed
per step — measured +81% on the end-to-end flagship train epoch vs the
scan path on a v5e chip (419 vs 232 steps/s, RESULTS.md "bf16: measured
decision"; isolated-traversal micro-timings are closer to parity — the
win lives in the whole-epoch fusion context), bit-exact vs the scan in
forward.

Layout: gates are padded per-block from H=100 to Hp=128 lanes (the MXU
lane width), so every in-kernel slice is 128-aligned.  Zero-padded
recurrent rows/cols keep the padding lanes from ever influencing the
real lanes (padding lanes of h evolve to garbage, but their outgoing
weights are zero); outputs are sliced back to H.

Differentiation: :func:`lstm_seq` carries a ``jax.custom_vjp`` whose
backward is itself a Pallas kernel (reverse-time grid, gate recompute
from saved h/c — one extra matmul per step instead of storing (W, B, 4H)
pre-activations).  A single ``custom_vjp`` is not twice-differentiable,
so second-order AD — the WGAN-GP gradient penalty's ∂/∂θ ∇_x c path —
is supported through *nesting*: the VJP rule's residual-producing
forward (:func:`lstm_fwd_res`) and the backward itself
(:func:`lstm_bwd_seq`) are each their own differentiable-once
primitives; ``lstm_bwd_seq``'s VJP is a hand-derived pallas *adjoint*
kernel (:func:`_adj_kernel` — forward-time sweep over the backward's
dataflow, recomputing gates and the primal cotangents from saved
per-step carries).  Each custom_vjp is differentiated at most once, so
grad-of-grad through the pallas backend is legal; the adjoint formulas
are oracle-tested against JAX AD over the pure-JAX scan twin
(:func:`_lstm_bwd_scan`) and against the XLA double backward (tests).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hfrep_tpu.utils.vma import shape_struct

LANE = 128

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "linear": lambda x: x,
    None: lambda x: x,
}


def _act_prime_from_value(name, a):
    """d act / d z expressed through the activation *value* a = act(z)."""
    if name == "sigmoid":
        return a * (1.0 - a)
    if name == "tanh":
        return 1.0 - a * a
    return jnp.ones_like(a)


def _supported(activation, recurrent_activation):
    if recurrent_activation != "sigmoid":
        raise NotImplementedError(
            f"pallas LSTM supports sigmoid gates only, got {recurrent_activation!r}")
    if activation not in ("sigmoid", "tanh", "linear", None):
        raise NotImplementedError(f"pallas LSTM: unsupported activation {activation!r}")


def pad_gate_cols(m: jnp.ndarray, h: int, hp: int) -> jnp.ndarray:
    """(..., 4h) → (..., 4hp): zero-pad each of the 4 gate blocks to hp."""
    parts = jnp.split(m, 4, axis=-1)
    pad = [(0, 0)] * (m.ndim - 1) + [(0, hp - h)]
    return jnp.concatenate([jnp.pad(p, pad) for p in parts], axis=-1)


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


VMEM_BUDGET_BYTES = 16 * 2**20
"""Mosaic's scoped-vmem bound as measured on the v5e (RESULTS.md: the
H=512 f32 adjoint OOMs wanting ~20 MB against a 16 MB limit)."""

FUSED_STACK_BUDGET_BYTES = 8 * 2**20
"""The fused two-layer stack's *preference* threshold — half the
feasibility budget.  Fusion is an optimization, not a capacity need
(per-layer kernels always remain available below their own ceiling),
and it stops paying well before it stops fitting: measured on chip
(RESULTS.md round 4), the fused stack wins at Hp=128 (~3-3.75 MB
resident, +4% over per-layer, both dtypes) and LOSES at Hp=256
(12.6-15.7 MB, −7% both dtypes) — the near-budget residency squeezes
the compiler's scheduling headroom.  8 MB separates the two measured
regimes."""


def adjoint_vmem_bytes(hidden: int, eff_dtype, layers: int = 1) -> int:
    """VMEM residency of the heaviest kernel on the dispatch path — the
    adjoint — counting its resident (Hp, 4Hp) gate matrices.  Per
    single-layer module: rec, recᵀ, v, vᵀ, urec (5); the fused stack
    holds both layers' primal matrices + their v-streams + 3 gradient
    accumulators (15).  Primal matrices ride at the operand dtype (bf16
    halves them); cotangent/accumulator matrices are always f32.
    Per-timestep blocks (≤ ~100 KB at B=32) ride inside the budget
    margin.  Calibrated against measurement: f32 Hp=512 single → 20 MB
    (the observed OOM), f32 Hp=256 stack → 15.7 MB (observed to fit)."""
    hp = ((hidden + LANE - 1) // LANE) * LANE
    mat = 4 * hp * hp                        # elements per (Hp, 4Hp) matrix
    item = jnp.dtype(eff_dtype).itemsize
    if layers == 1:
        return mat * (2 * item + 3 * 4)      # rec, recT @ operand; v, vT, urec @ f32
    return mat * (6 * item + 9 * 4)          # 6 primal mats; 6 v-streams + 3 accums


def kernel_eligible(backend, eff_dtype, hidden: int = None,
                    layers: int = 1) -> bool:
    """Single source of truth for pallas-kernel dispatch.

    Three gates, each measured rather than assumed (RESULTS.md):

    * explicit ``pallas`` backend;
    * operand dtype f32 or bf16 — the kernels stream either (f32
      scratch/gate math/accumulation in both cases); other dtypes take
      the scan path so configured precision is honored;
    * the adjoint's VMEM residency fits the relevant budget.  For
      single-layer kernels (``layers=1``) that is the measured
      scoped-vmem bound — feasibility (round-3 finding: the default
      ``auto`` dispatch OOM'd at H=512 f32 instead of falling back;
      shape-blind eligibility was the bug).  For the FUSED stack
      (``layers=2``) it is the tighter *preference* threshold
      :data:`FUSED_STACK_BUDGET_BYTES`: past it the fusion measures
      slower than the per-layer kernels it would replace, so the caller
      falls through to chained per-layer dispatch.  ``hidden=None``
      (legacy callers) keeps the flagship-size behavior: eligible.
    """
    if backend != "pallas" or eff_dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if hidden is None:
        return True
    budget = VMEM_BUDGET_BYTES if layers == 1 else FUSED_STACK_BUDGET_BYTES
    return adjoint_vmem_bytes(hidden, eff_dtype, layers) <= budget


def pad_keras_params(params: dict, h: int, hp: int) -> tuple:
    """Keras-layout {kernel, recurrent_kernel, bias} to padded-gate layout
    (kernel_p, rec_p, bias_p) shared by the single-layer and fused-stack
    entry points.  ``rec_p`` pads both gate columns and input rows; use it
    for any weight whose input is a padded hidden state."""
    kernel_p = pad_gate_cols(params["kernel"], h, hp)
    bias_p = pad_gate_cols(params["bias"], h, hp)
    rec_p = jnp.pad(pad_gate_cols(params["recurrent_kernel"], h, hp),
                    ((0, hp - params["recurrent_kernel"].shape[0]), (0, 0)))
    return kernel_p, rec_p, bias_p


# --------------------------------------------------------------- forward

def _fwd_kernel(act_name, with_cs, with_carry, xz_ref, rec_ref, *rest):
    # operand tail: [h0, c0]? ; outputs: hs, [cs]?, [c_fin]? ; scratch last 2
    k = 2 if with_carry else 0
    h0_ref, c0_ref = (rest[0], rest[1]) if with_carry else (None, None)
    hs_ref = rest[k]
    cs_ref = rest[k + 1] if with_cs else None
    cfin_ref = rest[k + 1] if (with_carry and not with_cs) else None
    h_scr, c_scr = rest[-2], rest[-1]
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        if with_carry:
            h_scr[:] = h0_ref[:]
            c_scr[:] = c0_ref[:]
        else:
            h_scr[:] = jnp.zeros_like(h_scr)
            c_scr[:] = jnp.zeros_like(c_scr)

    act = _ACT[act_name]
    # Mixed precision: xz/rec may arrive bf16 (halved HBM stream for the
    # (W, B, 4Hp) projection, MXU-rate matmul); state and gate math stay
    # f32 in VMEM/registers.
    lhs = h_scr[:]
    if rec_ref.dtype != lhs.dtype:
        lhs = lhs.astype(rec_ref.dtype)
    z = (xz_ref[0].astype(jnp.float32)
         + jnp.dot(lhs, rec_ref[:], preferred_element_type=jnp.float32))
    hp = z.shape[-1] // 4        # gate blocks are hp-padded → slices stay 128-aligned
    zi, zf, zc, zo = (z[:, :hp], z[:, hp:2 * hp], z[:, 2 * hp:3 * hp], z[:, 3 * hp:])
    i = jax.nn.sigmoid(zi)
    f = jax.nn.sigmoid(zf)
    c = f * c_scr[:] + i * act(zc)
    h = jax.nn.sigmoid(zo) * act(c)
    h_scr[:] = h
    c_scr[:] = c
    hs_ref[0] = h
    if with_cs:
        cs_ref[0] = c
    if cfin_ref is not None:
        # Constant-index output block: overwritten every step, the final
        # flush leaves c_{W-1} — the cell carry handed to the next chunk.
        cfin_ref[:] = c


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def lstm_seq(xz: jnp.ndarray, rec: jnp.ndarray, activation: str = "tanh"):
    """Padded-gate LSTM recurrence: (W, B, 4Hp) × (Hp, 4Hp) → (W, B, Hp).

    ``xz`` is the hoisted input projection ``x @ kernel + bias`` in
    time-major padded-gate layout; ``rec`` the zero-padded recurrent
    matrix.  Gates are sigmoid; ``activation`` transforms candidate and
    output (Keras ``LSTM(activation=...)`` semantics).
    """
    # Primal (no-AD) call: skip the cell-state output entirely — c lives
    # only in VMEM scratch, halving the kernel's HBM write traffic on
    # sampling/inference paths.  The AD rule below uses the cs-saving
    # variant as its residual-producing forward.
    return _lstm_seq_fwd_impl(xz, rec, activation, with_cs=False)


def _lstm_seq_fwd_impl(xz, rec, activation, with_cs=True, carry=None):
    w, b, g = xz.shape
    hp = g // 4
    t_spec = pl.BlockSpec((1, b, hp), lambda t: (t, 0, 0), memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((b, hp), lambda t: (0, 0), memory_space=pltpu.VMEM)
    operands = [xz, rec] + (list(carry) if carry is not None else [])
    t_shape = shape_struct((w, b, hp), jnp.float32, operands)
    st_shape = shape_struct((b, hp), jnp.float32, operands)
    out_specs, out_shape = [t_spec], [t_shape]
    if with_cs:
        out_specs, out_shape = out_specs + [t_spec], out_shape + [t_shape]
    elif carry is not None:                      # emit the final cell carry
        out_specs, out_shape = out_specs + [st_spec], out_shape + [st_shape]
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, activation, with_cs, carry is not None),
        grid=(w,),
        in_specs=[pl.BlockSpec((1, b, g), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
                  pl.BlockSpec((hp, g), lambda t: (0, 0), memory_space=pltpu.VMEM)]
                 + [st_spec] * (2 if carry is not None else 0),
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((b, hp), jnp.float32),
                        pltpu.VMEM((b, hp), jnp.float32)],
        interpret=_interpret(),
    )(*operands)
    if with_cs:
        return out[0], out[1]
    if carry is not None:
        return out[0], out[1]
    return out[0]


# -------------------------------------------------------------- backward

def _bwd_kernel(act_name, with_dcs, with_carries, with_carry0, xz_ref, rec_ref,
                rec_t_ref, h_prev_ref, c_prev_ref, cs_ref, dhs_ref, *rest):
    # rest = [dcs?] + [dcfin?] + [dxz, drec] + [dhT, dcT]? + [dh0, dc0]?
    #        + [dh_scr, dc_scr]
    k = int(with_dcs) + int(with_carry0)
    dcs_ref = rest[0] if with_dcs else None
    dcfin_ref = rest[int(with_dcs)] if with_carry0 else None
    dxz_ref, drec_ref = rest[k], rest[k + 1]
    if with_carries:   # second-order residuals: per-step dhT/dcT
        dhT_ref, dcT_ref = rest[k + 2], rest[k + 3]
    if with_carry0:    # cotangents of the injected initial (h0, c0)
        dh0_ref, dc0_ref = rest[-4], rest[-3]
    dh_scr, dc_scr = rest[-2], rest[-1]
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        if with_carry0:
            # cotangent arriving on the emitted final cell carry seeds the
            # reverse sweep (the final hidden carry is hs[-1], so its
            # cotangent reaches us through dhs[-1] instead)
            dc_scr[:] = dcfin_ref[:]
        else:
            dc_scr[:] = jnp.zeros_like(dc_scr)
        drec_ref[:] = jnp.zeros_like(drec_ref)

    act = _ACT[act_name]
    h_prev = h_prev_ref[0]
    c_prev = c_prev_ref[0]

    # Recompute this step's gates from the residuals (cheaper than
    # saving (W, B, 4Hp) pre-activations from the forward).  xz/rec may
    # arrive bf16 (halved VMEM residency and HBM streams); the f32
    # residual is cast to the operand dtype at each dot so the MXU runs
    # at operand rate, with f32 accumulation — gate math stays f32.
    z = (xz_ref[0].astype(jnp.float32)
         + jnp.dot(h_prev.astype(rec_ref.dtype), rec_ref[:],
                   preferred_element_type=jnp.float32))
    hp = z.shape[-1] // 4
    zi, zf, zc, zo = (z[:, :hp], z[:, hp:2 * hp], z[:, 2 * hp:3 * hp], z[:, 3 * hp:])
    i = jax.nn.sigmoid(zi)
    f = jax.nn.sigmoid(zf)
    gcell = act(zc)
    o = jax.nn.sigmoid(zo)
    c = cs_ref[0]
    a_c = act(c)

    dh = dhs_ref[0] + dh_scr[:]
    do = dh * a_c
    dzo = do * o * (1.0 - o)
    dc = dc_scr[:] + dh * o * _act_prime_from_value(act_name, a_c)
    if with_dcs:                    # cotangent flowing into cs directly
        dc = dc + dcs_ref[0]
    dzi = dc * gcell * i * (1.0 - i)
    dzf = dc * c_prev * f * (1.0 - f)
    dzc = dc * i * _act_prime_from_value(act_name, gcell)
    dz = jnp.concatenate([dzi, dzf, dzc, dzo], axis=-1)

    dxz_ref[0] = dz
    if with_carries:
        dhT_ref[0] = dh
        dcT_ref[0] = dc
    dh_scr[:] = jnp.dot(dz.astype(rec_t_ref.dtype), rec_t_ref[:],
                        preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f
    if with_carry0:
        # Constant-index outputs: the reverse grid's LAST iteration is
        # timestep 0, whose outgoing carries ARE (dh0, dc0); earlier
        # writes are overwritten before the final flush.
        dh0_ref[:] = dh_scr[:]
        dc0_ref[:] = dc_scr[:]
    # (Hp, B) @ (B, 4Hp) accumulated across the reverse sweep.
    drec_ref[:] += lax.dot_general(h_prev, dz, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)


def _shifted(hs, cs, carry=None):
    """Per-step previous-state sequences; step 0 sees the injected carry
    (zeros in the carry-free recurrence)."""
    if carry is None:
        h_first = c_first = jnp.zeros_like(hs[:1])
    else:
        h_first, c_first = carry[0][None], carry[1][None]
    return (jnp.concatenate([h_first, hs[:-1]], axis=0),
            jnp.concatenate([c_first, cs[:-1]], axis=0))


def _bwd_call(xz, rec, hs, cs, dhs, dcs, activation, with_carries=False,
              carry=None, dc_fin=None):
    """Reverse-time pallas sweep: (dxz, drec) from output cotangents.

    ``dcs`` (optional) is a direct cotangent on the cell-state sequence —
    nonzero only when ``cs`` escapes as a residual (second-order paths).
    ``with_carries`` additionally returns the per-step (dhT, dcT) carries,
    the residuals the adjoint kernel (:func:`_adj_call`) needs.
    ``carry`` = injected initial (h0, c0): timestep 0 recomputes its gates
    from them, and two extra outputs (dh0, dc0) — their cotangents — are
    appended.  ``dc_fin`` (carry mode only) is the cotangent on the
    emitted final cell state, seeding the reverse sweep's dc carry.
    """
    w, b, g = xz.shape
    hp = g // 4
    h_prev, c_prev = _shifted(hs, cs, carry)
    rev = lambda t: (w - 1 - t, 0, 0)
    t_in = pl.BlockSpec((1, b, hp), rev, memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((b, hp), lambda t: (0, 0), memory_space=pltpu.VMEM)
    with_dcs = dcs is not None
    with_carry0 = carry is not None
    if with_carry0 and dc_fin is None:
        dc_fin = jnp.zeros((b, hp), jnp.float32)
    operands = ([xz, rec, rec.T, h_prev, c_prev, cs, dhs]
                + ([dcs] if with_dcs else [])
                + ([dc_fin] if with_carry0 else []))
    st_shape = shape_struct((b, hp), jnp.float32, operands)
    out_specs = [pl.BlockSpec((1, b, g), rev, memory_space=pltpu.VMEM),
                 pl.BlockSpec((hp, g), lambda t: (0, 0), memory_space=pltpu.VMEM)]
    out_shape = [shape_struct((w, b, g), jnp.float32, operands),
                 shape_struct((hp, g), jnp.float32, operands)]
    if with_carries:
        out_specs += [t_in, t_in]
        out_shape += [shape_struct((w, b, hp), jnp.float32, operands)] * 2
    if with_carry0:
        out_specs += [st_spec, st_spec]
        out_shape += [st_shape, st_shape]
    out = pl.pallas_call(
        functools.partial(_bwd_kernel, activation, with_dcs, with_carries,
                          with_carry0),
        grid=(w,),
        in_specs=[pl.BlockSpec((1, b, g), rev, memory_space=pltpu.VMEM),
                  pl.BlockSpec((hp, g), lambda t: (0, 0), memory_space=pltpu.VMEM),
                  pl.BlockSpec((g, hp), lambda t: (0, 0), memory_space=pltpu.VMEM)]
                 + [t_in] * (4 + int(with_dcs))
                 + ([st_spec] if with_carry0 else []),
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((b, hp), jnp.float32),
                        pltpu.VMEM((b, hp), jnp.float32)],
        interpret=_interpret(),
    )(*operands)
    return tuple(out)


def _act_prime_prime_from_value(name, v):
    """d(act-prime)/d(value): sigmoid p(v)=v(1−v) → 1−2v; tanh → −2v."""
    if name == "sigmoid":
        return 1.0 - 2.0 * v
    if name == "tanh":
        return -2.0 * v
    return jnp.zeros_like(v)


def _adj_kernel(act_name, with_carry0, xz_ref, rec_ref, rec_t_ref, v_ref,
                v_t_ref, h_prev_ref, c_prev_ref, cs_ref, u_ref,
                dhT_ref, dcT_ref, *rest):
    """Adjoint of one backward step (hand-derived, oracle-validated
    against ``jax.vjp`` over :func:`_lstm_bwd_scan`).  Runs forward-time
    t = 0..W-1 — the reverse of the primal backward's execution order —
    with the adjoint carries (μh, μc) = cotangents of the primal step's
    (dh′, dc′) carry outputs in VMEM scratch.

    Carry mode (``with_carry0``): the primal backward's final carry
    outputs ARE (dh0, dc0), so their cotangents (μh0, μc0) seed the
    adjoint carries at t=0; symmetrically the backward's *initial* dc
    carry was seeded with dc_fin, so its cotangent — the final μc — is
    emitted as one extra constant-index output."""
    k = 2 if with_carry0 else 0
    muh0_ref, muc0_ref = (rest[0], rest[1]) if with_carry0 else (None, None)
    uxz_ref, uhp_ref, ucp_ref, uc_ref, udhs_ref, urec_ref = rest[k:k + 6]
    udcfin_ref = rest[k + 6] if with_carry0 else None
    muh_scr, muc_scr = rest[-2], rest[-1]
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        if with_carry0:
            muh_scr[:] = muh0_ref[:]
            muc_scr[:] = muc0_ref[:]
        else:
            muh_scr[:] = jnp.zeros_like(muh_scr)
            muc_scr[:] = jnp.zeros_like(muc_scr)
        urec_ref[:] = jnp.zeros_like(urec_ref)

    act = _ACT[act_name]
    p = lambda v: _act_prime_from_value(act_name, v)
    pp = lambda v: _act_prime_prime_from_value(act_name, v)
    hp_s = h_prev_ref[0]
    cp_s = c_prev_ref[0]
    c_s = cs_ref[0]
    dhT = dhT_ref[0]
    dcT = dcT_ref[0]
    muh = muh_scr[:]
    muc = muc_scr[:]
    rec = rec_ref[:]
    v_mat = v_ref[:]

    # ---- recompute the primal backward step-s intermediates
    # (bf16 operand support mirrors _bwd_kernel: f32 values cast to the
    # matrix dtype at each dot, f32 accumulation)
    z = (xz_ref[0].astype(jnp.float32)
         + jnp.dot(hp_s.astype(rec.dtype), rec,
                   preferred_element_type=jnp.float32))
    hp_dim = z.shape[-1] // 4
    zi, zf, zc, zo = (z[:, :hp_dim], z[:, hp_dim:2 * hp_dim],
                      z[:, 2 * hp_dim:3 * hp_dim], z[:, 3 * hp_dim:])
    i = jax.nn.sigmoid(zi)
    f = jax.nn.sigmoid(zf)
    gcell = act(zc)
    o = jax.nn.sigmoid(zo)
    a_c = act(c_s)
    qi, qf, qo = i * (1.0 - i), f * (1.0 - f), o * (1.0 - o)
    do = dhT * a_c
    dzi = dcT * gcell * qi
    dzf = dcT * cp_s * qf
    dzc = dcT * i * p(gcell)
    dzo = do * qo
    dz = jnp.concatenate([dzi, dzf, dzc, dzo], axis=-1)

    # ---- adjoint
    dzbar = (u_ref[0].astype(jnp.float32)
             + jnp.dot(muh.astype(rec.dtype), rec,
                       preferred_element_type=jnp.float32)
             + jnp.dot(hp_s.astype(v_mat.dtype), v_mat,
                       preferred_element_type=jnp.float32))
    dcTbar = muc * f
    fbar = muc * dcT
    hpbar = jnp.dot(dz.astype(v_t_ref.dtype), v_t_ref[:],
                    preferred_element_type=jnp.float32)
    urec = lax.dot_general(muh, dz, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    dzbi, dzbf, dzbc, dzbo = (dzbar[:, :hp_dim], dzbar[:, hp_dim:2 * hp_dim],
                              dzbar[:, 2 * hp_dim:3 * hp_dim], dzbar[:, 3 * hp_dim:])
    dcTbar += dzbi * gcell * qi
    gbar = dzbi * dcT * qi
    ibar = dzbi * dcT * gcell * (1.0 - 2.0 * i)
    dcTbar += dzbf * cp_s * qf
    cpbar = dzbf * dcT * qf
    fbar += dzbf * dcT * cp_s * (1.0 - 2.0 * f)
    dcTbar += dzbc * i * p(gcell)
    ibar += dzbc * dcT * p(gcell)
    gbar += dzbc * dcT * i * pp(gcell)
    dobar = dzbo * qo
    obar = dzbo * do * (1.0 - 2.0 * o)
    dhTbar = dcTbar * o * p(a_c)
    obar += dcTbar * dhT * p(a_c)
    aCbar = dcTbar * dhT * o * pp(a_c)
    dhTbar += dobar * a_c
    aCbar += dobar * dhT
    zbar = jnp.concatenate([ibar * qi, fbar * qf, gbar * p(gcell), obar * qo],
                           axis=-1)

    uxz_ref[0] = zbar
    udhs_ref[0] = dhTbar
    uhp_ref[0] = hpbar + jnp.dot(zbar.astype(rec_t_ref.dtype), rec_t_ref[:],
                                 preferred_element_type=jnp.float32)
    ucp_ref[0] = cpbar
    uc_ref[0] = aCbar * p(a_c)
    urec_ref[:] += urec + lax.dot_general(hp_s, zbar, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
    muh_scr[:] = dhTbar                  # cot of carry-in dh → next step
    muc_scr[:] = dcTbar                  # cot of carry-in dc → next step
    if with_carry0:
        # After the last step this is cot of the backward's initial dc
        # carry — i.e. cot(dc_fin); earlier writes are overwritten.
        udcfin_ref[:] = dcTbar


def _adj_call(xz, rec, hs, cs, dhT_seq, dcT_seq, u, v_mat, activation,
              carry=None, mu0=None):
    """Cotangents of (xz, rec, hs, cs, dhs) for the backward sweep, given
    ``u`` = cot(dxz) and ``v_mat`` = cot(drec).  ``dhs`` itself is not an
    operand: the kernel recovers each step's dh total from the saved
    ``dhT_seq`` carries (and ``cot(dhs) = cot(dh)`` falls out directly).

    Carry mode: ``carry`` = the injected (h0, c0) and ``mu0`` = the
    cotangents of the backward's (dh0, dc0) outputs; three extra
    cotangents are appended — cot(dc_fin), cot(h0), cot(c0)."""
    w, b, g = xz.shape
    hp = g // 4
    with_carry0 = carry is not None
    h_prev, c_prev = _shifted(hs, cs, carry)
    nat = lambda t: (t, 0, 0)
    const = lambda t: (0, 0)
    t_h = pl.BlockSpec((1, b, hp), nat, memory_space=pltpu.VMEM)
    t_g = pl.BlockSpec((1, b, g), nat, memory_space=pltpu.VMEM)
    mat_hg = pl.BlockSpec((hp, g), const, memory_space=pltpu.VMEM)
    mat_gh = pl.BlockSpec((g, hp), const, memory_space=pltpu.VMEM)
    st = pl.BlockSpec((b, hp), const, memory_space=pltpu.VMEM)
    _ops = ((xz, rec, v_mat, h_prev, c_prev, cs, u, dhT_seq, dcT_seq)
            + (tuple(mu0) if with_carry0 else ()))
    sh_h = shape_struct((w, b, hp), jnp.float32, _ops)
    sh_g = shape_struct((w, b, g), jnp.float32, _ops)
    sh_st = shape_struct((b, hp), jnp.float32, _ops)
    out = pl.pallas_call(
        functools.partial(_adj_kernel, activation, with_carry0),
        grid=(w,),
        in_specs=[t_g, mat_hg, mat_gh, mat_hg, mat_gh,
                  t_h, t_h, t_h, t_g, t_h, t_h]
                 + [st, st] * int(with_carry0),
        out_specs=[t_g, t_h, t_h, t_h, t_h, mat_hg]
                  + [st] * int(with_carry0),
        out_shape=[sh_g, sh_h, sh_h, sh_h, sh_h,
                   shape_struct((hp, g), jnp.float32, _ops)]
                  + [sh_st] * int(with_carry0),
        scratch_shapes=[pltpu.VMEM((b, hp), jnp.float32),
                        pltpu.VMEM((b, hp), jnp.float32)],
        interpret=_interpret(),
    )(xz, rec, rec.T, v_mat, v_mat.T, h_prev, c_prev, cs, u,
      dhT_seq, dcT_seq, *(tuple(mu0) if with_carry0 else ()))
    uxz, uhp, ucp, uc, udhs, urec = out[:6]
    # uhp_s is the cotangent of hs_{s-1}; ucp_s of cs_{s-1}; uc_s of cs_s.
    zero = jnp.zeros_like(uhp[:1])
    uhs = jnp.concatenate([uhp[1:], zero], axis=0)
    ucs = uc + jnp.concatenate([ucp[1:], zero], axis=0)
    if not with_carry0:
        return uxz, urec, uhs, ucs, udhs
    # step 0's "previous state" is the injected carry itself
    return uxz, urec, uhs, ucs, udhs, out[6], uhp[0], ucp[0]


def _lstm_bwd_scan(xz, rec, hs, cs, dhs, dcs, activation, carry=None,
                   dc_fin=None):
    """Pure-JAX twin of :func:`_bwd_call` (same arithmetic, `lax.scan`).

    This is the second-order fallback: :func:`lstm_bwd_seq`'s own VJP is
    derived by JAX AD over this implementation, so hand-written kernels
    never need their derivatives hand-derived.  ``carry``/``dc_fin``
    mirror the carry-injection kernel mode (two extra outputs: dh0, dc0).
    """
    act = _ACT[activation]
    h_prev, c_prev = _shifted(hs, cs, carry)
    b, hp = hs.shape[1], hs.shape[2]
    g = xz.shape[2]
    if dcs is None:
        dcs = jnp.zeros_like(cs)

    def step(carry, inp):
        dh_c, dc_c, drec = carry
        xz_s, hp_s, cp_s, c_s, dhs_s, dcs_s = inp
        z = xz_s + hp_s @ rec
        zi, zf, zc, zo = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        gcell = act(zc)
        o = jax.nn.sigmoid(zo)
        a_c = act(c_s)
        dh = dhs_s + dh_c
        do = dh * a_c
        dzo = do * o * (1.0 - o)
        dc = dc_c + dh * o * _act_prime_from_value(activation, a_c) + dcs_s
        dzi = dc * gcell * i * (1.0 - i)
        dzf = dc * cp_s * f * (1.0 - f)
        dzc = dc * i * _act_prime_from_value(activation, gcell)
        dz = jnp.concatenate([dzi, dzf, dzc, dzo], axis=-1)
        drec = drec + lax.dot_general(hp_s, dz, (((0,), (0,)), ((), ())))
        return (dz @ rec.T, dc * f, drec), dz

    # f32 carries regardless of operand dtype — mirrors the kernel's f32
    # scratch/accumulation so the twin stays a valid oracle for bf16
    # operand streams too
    init = (jnp.zeros((b, hp), jnp.float32),
            jnp.zeros((b, hp), jnp.float32) if dc_fin is None else dc_fin,
            jnp.zeros((hp, g), jnp.float32))
    (dh0, dc0, drec), dz_rev = lax.scan(
        step, init,
        (xz[::-1], h_prev[::-1], c_prev[::-1], cs[::-1], dhs[::-1], dcs[::-1]))
    if carry is None:
        return dz_rev[::-1], drec
    return dz_rev[::-1], drec, dh0, dc0


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def lstm_bwd_seq(xz, rec, hs, cs, dhs, activation):
    """First-order LSTM backward as a differentiable-once primitive:
    pallas primal, and a hand-derived pallas *adjoint* kernel as its VJP
    — the genuine second-order math the WGAN-GP gradient penalty's
    ∂/∂θ ∇_x c path needs.  The adjoint formulas are oracle-tested
    against JAX AD over the scan twin (:func:`_lstm_bwd_scan`)."""
    return _bwd_call(xz, rec, hs, cs, dhs, None, activation)


def _lstm_bwd_seq_fwd(xz, rec, hs, cs, dhs, activation):
    dxz, drec, dhT_seq, dcT_seq = _bwd_call(
        xz, rec, hs, cs, dhs, None, activation, with_carries=True)
    return (dxz, drec), (xz, rec, hs, cs, dhs, dhT_seq, dcT_seq)


def _cast_like(cots, primals):
    """Kernel cotangents (always f32) → the primal operands' dtypes, as
    `custom_vjp` requires.  The cast is the entire bf16 boundary: kernels
    compute and emit f32; bf16 exists only in the operand streams."""
    return tuple(c.astype(p.dtype) if c.dtype != p.dtype else c
                 for c, p in zip(cots, primals))


def _lstm_bwd_seq_bwd(activation, residuals, cotangents):
    xz, rec, hs, cs, dhs, dhT_seq, dcT_seq = residuals
    u, v_mat = cotangents
    uxz, urec, uhs, ucs, udhs = _adj_call(
        xz, rec, hs, cs, dhT_seq, dcT_seq, u, v_mat, activation)
    return _cast_like((uxz, urec), (xz, rec)) + (uhs, ucs, udhs)


lstm_bwd_seq.defvjp(_lstm_bwd_seq_fwd, _lstm_bwd_seq_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def lstm_fwd_res(xz, rec, activation):
    """Forward producing (hs, cs) with a pallas VJP (dcs-extended backward
    kernel).  Used as the residual-producing forward inside
    :func:`lstm_seq`'s VJP so that second-order traces never hit a raw,
    non-differentiable ``pallas_call``."""
    return _lstm_seq_fwd_impl(xz, rec, activation, with_cs=True)


def _lstm_fwd_res_fwd(xz, rec, activation):
    hs, cs = _lstm_seq_fwd_impl(xz, rec, activation, with_cs=True)
    return (hs, cs), (xz, rec, hs, cs)


def _lstm_fwd_res_bwd(activation, residuals, cotangents):
    xz, rec, hs, cs = residuals
    dhs, dcs = cotangents
    return _cast_like(_bwd_call(xz, rec, hs, cs, dhs, dcs, activation),
                      (xz, rec))


lstm_fwd_res.defvjp(_lstm_fwd_res_fwd, _lstm_fwd_res_bwd)


def _lstm_seq_fwd(xz, rec, activation):
    # Residuals come from the differentiable lstm_fwd_res, not a raw
    # pallas_call, so an outer grad over this VJP's trace stays legal.
    hs, cs = lstm_fwd_res(xz, rec, activation)
    return hs, (xz, rec, hs, cs)


def _lstm_seq_bwd(activation, residuals, dhs):
    xz, rec, hs, cs = residuals
    return _cast_like(lstm_bwd_seq(xz, rec, hs, cs, dhs, activation),
                      (xz, rec))


lstm_seq.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)


# ------------------------------------------- carry-injection entry points
#
# The sequence-parallel pipeline (hfrep_tpu.parallel.sequence) shards the
# window axis: device k receives the (h, c) carry computed by device k-1
# and must run its local chunk from that state, then hand its own final
# carry onward.  These variants extend the kernels above with nonzero
# initial state in and final state out, with the same nested-custom_vjp
# structure so the WGAN-GP second-order path stays kernel-resident.

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_seq_carry(xz: jnp.ndarray, rec: jnp.ndarray, h0: jnp.ndarray,
                   c0: jnp.ndarray, activation: str = "tanh"):
    """Carry-injected LSTM recurrence: (W, B, 4Hp) chunk from initial
    state (h0, c0) — returns ``(hs, c_fin)``; the final hidden carry is
    ``hs[-1]``.  Twice-differentiable like :func:`lstm_seq` (nested
    custom_vjps; the second-order residue runs the carry adjoint
    kernel)."""
    return _lstm_seq_fwd_impl(xz, rec, activation, with_cs=False,
                              carry=(h0, c0))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_fwd_res_carry(xz, rec, h0, c0, activation):
    """Residual-producing forward for the carry recurrence: (hs, cs) with
    a pallas VJP (dcs-extended carry backward)."""
    return _lstm_seq_fwd_impl(xz, rec, activation, with_cs=True,
                              carry=(h0, c0))


def _lstm_fwd_res_carry_fwd(xz, rec, h0, c0, activation):
    hs, cs = _lstm_seq_fwd_impl(xz, rec, activation, with_cs=True,
                                carry=(h0, c0))
    return (hs, cs), (xz, rec, h0, c0, hs, cs)


def _lstm_fwd_res_carry_bwd(activation, residuals, cotangents):
    xz, rec, h0, c0, hs, cs = residuals
    dhs, dcs = cotangents
    dxz, drec, dh0, dc0 = _bwd_call(xz, rec, hs, cs, dhs, dcs, activation,
                                    carry=(h0, c0))
    return _cast_like((dxz, drec), (xz, rec)) + (dh0, dc0)


lstm_fwd_res_carry.defvjp(_lstm_fwd_res_carry_fwd, _lstm_fwd_res_carry_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8,))
def lstm_bwd_seq_carry(xz, rec, hs, cs, dhs, dc_fin, h0, c0, activation):
    """First-order carry backward as a differentiable-once primitive:
    returns (dxz, drec, dh0, dc0).  Its own VJP is the carry-mode adjoint
    kernel — the second-order path of sequence-parallel WGAN-GP."""
    return _bwd_call(xz, rec, hs, cs, dhs, None, activation,
                     carry=(h0, c0), dc_fin=dc_fin)


def _lstm_bwd_seq_carry_fwd(xz, rec, hs, cs, dhs, dc_fin, h0, c0, activation):
    dxz, drec, dhT_seq, dcT_seq, dh0, dc0 = _bwd_call(
        xz, rec, hs, cs, dhs, None, activation, with_carries=True,
        carry=(h0, c0), dc_fin=dc_fin)
    return ((dxz, drec, dh0, dc0),
            (xz, rec, hs, cs, h0, c0, dhT_seq, dcT_seq))


def _lstm_bwd_seq_carry_bwd(activation, residuals, cotangents):
    xz, rec, hs, cs, h0, c0, dhT_seq, dcT_seq = residuals
    u, v_mat, muh0, muc0 = cotangents
    out = _adj_call(xz, rec, hs, cs, dhT_seq, dcT_seq, u, v_mat, activation,
                    carry=(h0, c0), mu0=(muh0, muc0))
    return _cast_like(out[:2], (xz, rec)) + out[2:]


lstm_bwd_seq_carry.defvjp(_lstm_bwd_seq_carry_fwd, _lstm_bwd_seq_carry_bwd)


def _lstm_seq_carry_fwd(xz, rec, h0, c0, activation):
    hs, cs = lstm_fwd_res_carry(xz, rec, h0, c0, activation)
    return (hs, cs[-1]), (xz, rec, h0, c0, hs, cs)


def _lstm_seq_carry_bwd(activation, residuals, cotangents):
    xz, rec, h0, c0, hs, cs = residuals
    dhs, dc_fin = cotangents
    dxz, drec, dh0, dc0 = lstm_bwd_seq_carry(xz, rec, hs, cs, dhs, dc_fin,
                                             h0, c0, activation)
    return _cast_like((dxz, drec), (xz, rec)) + (dh0, dc0)


lstm_seq_carry.defvjp(_lstm_seq_carry_fwd, _lstm_seq_carry_bwd)


# ----------------------------------------------------- Keras-layout entry

def pallas_keras_lstm(kernel: jnp.ndarray, recurrent: jnp.ndarray,
                      bias: jnp.ndarray, x: jnp.ndarray,
                      activation: str = "tanh",
                      recurrent_activation: str = "sigmoid",
                      dtype=None) -> jnp.ndarray:
    """Drop-in recurrence for Keras-layout params: (B, W, F) → (B, W, H).

    Numerically matches :class:`hfrep_tpu.ops.lstm.KerasLSTM`'s scan path
    (same hoisted projection, same cell arithmetic); first-order
    differentiable via the Pallas backward kernel.

    ``dtype`` is the effective compute dtype (defaults to ``x.dtype``,
    mirroring the scan path): bf16 streams the projection/recurrent
    operands at half width through the kernels (f32 scratch/gate math)
    and returns bf16 hidden states, exactly the scan path's output
    dtype contract.
    """
    _supported(activation, recurrent_activation)
    b, w, f = x.shape
    h = recurrent.shape[0]
    hp = ((h + LANE - 1) // LANE) * LANE
    dt = jnp.dtype(dtype or x.dtype)
    if dt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise NotImplementedError(f"pallas LSTM streams f32/bf16, got {dt}")

    kernel_p, rec_p, bias_p = pad_keras_params(
        {"kernel": kernel, "recurrent_kernel": recurrent, "bias": bias}, h, hp)

    x = x.astype(dt)
    xz = (x.reshape(b * w, f) @ kernel_p.astype(dt) + bias_p.astype(dt)
          ).reshape(b, w, 4 * hp)
    xz = jnp.swapaxes(xz, 0, 1).astype(dt)                        # (W, B, 4Hp)
    hs = lstm_seq(xz, rec_p.astype(dt), activation if activation else "linear")
    return jnp.swapaxes(hs, 0, 1)[..., :h].astype(dt)
