"""Matrix square root pieces needed by FID, in pure jnp.

``GAN/GAN_eval.py:55`` computes ``scipy.linalg.sqrtm(sigma1 @ sigma2)``
and only ever uses its **trace** (``:60``).  The trace of the square root
of a diagonalizable matrix is the sum of the square roots of its
eigenvalues, so the Schur decomposition scipy performs is unnecessary:
``trace(sqrtm(A@B)) = Σ sqrt(eig(A@B))``.  For covariance products the
eigenvalues are real and non-negative up to roundoff; imaginary residue
is discarded exactly as the reference discards ``covmean.imag`` (``:57-58``).

A general eigendecomposition is not implemented on TPU backends for
non-symmetric matrices, so we use the similarity trick: with
``S1 = L @ L.T`` (Cholesky), ``eig(S1 @ S2) = eig(L.T @ S2 @ L)`` and the
right-hand side is symmetric PSD → `eigh`, which is TPU-native.
"""

from __future__ import annotations

import jax.numpy as jnp

from hfrep_tpu.analysis.contracts import contract


@contract("(F,F),(F,F)->()")
def sqrtm_product_trace(sigma1: jnp.ndarray, sigma2: jnp.ndarray) -> jnp.ndarray:
    """trace(sqrtm(sigma1 @ sigma2)) for symmetric PSD inputs."""
    # Jitter for Cholesky on rank-deficient sample covariances.
    eps = 1e-10 * jnp.trace(sigma1) / sigma1.shape[0]
    c = jnp.linalg.cholesky(sigma1 + eps * jnp.eye(sigma1.shape[0], dtype=sigma1.dtype))
    m = c.T @ sigma2 @ c
    m = 0.5 * (m + m.T)
    eig = jnp.linalg.eigvalsh(m)
    return jnp.sum(jnp.sqrt(jnp.clip(eig, 0.0, None)))
