"""Rolling-window linear algebra: batched OLS instead of host loops.

The reference runs a 24-month rolling OLS as 143 sequential
``statsmodels.OLS(Y, X).fit()`` calls (``Autoencoder_encapsulate.py:148-157``)
and the OOS metric loop refits a MinMax scaler per expanding window
(``:115-131``).  On TPU all windows are materialized as one batch and
solved together — a single vmapped least-squares, one compiled program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from hfrep_tpu.analysis.contracts import contract


@contract("(T,F)->(N,W,F)")
def _window_stack(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """(T, F) → (T - window + 1, window, F) sliding windows."""
    t, f = x.shape
    starts = jnp.arange(t - window + 1)
    return jax.vmap(lambda s: lax.dynamic_slice(x, (s, 0), (window, f)))(starts)


@contract("(T,S),(T,K)->(N,K,S)")
def rolling_ols_beta(y: jnp.ndarray, x: jnp.ndarray, window: int) -> jnp.ndarray:
    """Rolling no-intercept OLS betas for every window start.

    ``y`` (T, S), ``x`` (T, K) → betas (T - window + 1, K, S), where slice
    ``i`` regresses ``y[i:i+window]`` on ``x[i:i+window]`` —
    ``statsmodels.OLS(Y, X)`` includes no constant unless added, matching
    ``Autoencoder_encapsulate.py:151``.

    Solved via normal equations with a pseudoinverse (statsmodels also
    uses pinv), batched over windows: two (N_win, W, K)-shaped einsums —
    MXU-friendly — plus a vmapped solve.
    """
    xw = _window_stack(x, window)                  # (N, W, K)
    yw = _window_stack(y, window)                  # (N, W, S)
    xtx = jnp.einsum("nwk,nwl->nkl", xw, xw)
    xty = jnp.einsum("nwk,nws->nks", xw, yw)
    return jax.vmap(lambda a, b: jnp.linalg.pinv(a) @ b)(xtx, xty)


@contract("(T,S),(T,K)->(_,S)")
def ols_beta(y: jnp.ndarray, x: jnp.ndarray, add_constant: bool = False) -> jnp.ndarray:
    """Single OLS fit via pinv; with ``add_constant`` the intercept is
    column 0, matching ``sm.add_constant`` (``autoencoder_v4.ipynb`` cell
    23 ``OLS_alpha``)."""
    if add_constant:
        x = jnp.concatenate([jnp.ones((x.shape[0], 1), x.dtype), x], axis=1)
    return jnp.linalg.pinv(x.T @ x) @ (x.T @ y)


@contract("(T,F)->(T,F),(T,F)")
def expanding_minmax_scale(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """For each prefix length i, MinMax params fit on ``x[:i]``.

    Vectorizes the reference's per-step ``MinMaxScaler().fit_transform
    (x_test[:i])`` (``Autoencoder_encapsulate.py:115-131``): running
    columnwise min/max via cumulative reductions gives every prefix's
    scaler at once.  Returns (mins, maxs), each (T, F), where row i holds
    the params of the prefix ending at (and including) row i.
    """
    mins = lax.associative_scan(jnp.minimum, x, axis=0)
    maxs = lax.associative_scan(jnp.maximum, x, axis=0)
    return mins, maxs
