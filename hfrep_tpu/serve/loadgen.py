"""Load generation + outcome classification for the serving layer.

Shared by the ``serve`` CLI subcommand and ``tools/bench_serve.py`` so
the two can never disagree about what "p95 under load" means: requests
are submitted open-loop in waves (each wave is a burst of *simulated
concurrent queries* offered to the admission layer; whatever exceeds the
envelope must come back as a typed rejection, not a hang), every future
is awaited to its terminal outcome, and the report classifies all of
them — the zero-silent-drop bookkeeping is the same code the chaos
selftest asserts against.

Latency numbers are the **server-side** per-request latencies of fresh
results (admission → result publish, queue wait included): that is the
figure a client experiences and the one the sentinel tracks as
``serve/p50_ms`` / ``serve/p95_ms``.
"""

from __future__ import annotations

from concurrent.futures import Future, wait
from typing import Callable, List, Optional, Sequence

import numpy as np

from hfrep_tpu.obs import timeline
from hfrep_tpu.serve.admission import (
    DeadlineExceeded,
    Draining,
    InvalidRequest,
    Overloaded,
    ServerClosed,
    WorkerFault,
)

#: exception class → report bucket (anything else — including a bare
#: ServeError, which the server never hands out — lands in ``errors``,
#: which a healthy envelope keeps at zero)
_BUCKETS = ((Overloaded, "shed"), (DeadlineExceeded, "deadline"),
            (Draining, "draining"), (WorkerFault, "worker_faults"),
            (ServerClosed, "closed"), (InvalidRequest, "invalid"))

#: every terminal bucket a future can land in — report["terminal"] sums
#: these, and the zero-silent-drop check is terminal == submitted
TERMINAL_KEYS = ("results", "stale", "shed", "deadline", "draining",
                 "worker_faults", "closed", "invalid", "errors")


def percentile(sorted_vals, pct: int) -> Optional[float]:
    """Nearest-rank percentile (rank ``ceil(pct/100 * n)``) — THE p50/p95
    definition the server's reservoir, this report and the bench all
    share, so they can never disagree about what p95 means."""
    n = len(sorted_vals)
    if not n:
        return None
    return sorted_vals[max(0, (n * pct + 99) // 100 - 1)]


def make_panels(seed: int, feats: int, rows_choices: Sequence[int],
                variants: int = 8) -> List[np.ndarray]:
    """A deterministic pool of tenant panels with mixed row counts —
    enough shape diversity to exercise the bucket ladder, small enough
    to reuse across every wave (the load is the point, not the data)."""
    g = np.random.default_rng(seed)
    out = []
    for i in range(variants):
        rows = int(rows_choices[i % len(rows_choices)])
        z = g.normal(size=(rows, 3))
        out.append((z @ g.normal(size=(3, feats))
                    + 0.05 * g.normal(size=(rows, feats))
                    ).astype(np.float32) * 0.02)
    return out


def classify(futures: List[Future]) -> dict:
    """Every future into exactly one bucket; latencies from fresh
    results.  Futures must all be done (the caller waited)."""
    doc = {k: 0 for k in TERMINAL_KEYS}
    latencies: List[float] = []
    for f in futures:
        err = f.exception()
        if err is None:
            res = f.result()
            if getattr(res, "stale", False):
                doc["stale"] += 1
            else:
                doc["results"] += 1
                latencies.append(float(res.latency_ms))
            continue
        for cls, bucket in _BUCKETS:
            if isinstance(err, cls):
                doc[bucket] += 1
                break
        else:
            doc["errors"] += 1
    doc["latencies_ms"] = latencies
    return doc


def drive_load(server, total: int, panels: Sequence[np.ndarray], *,
               timeout_ms: Optional[float] = None,
               sample_every: int = 0,
               wave: int = 512,
               on_wave: Optional[Callable[[int], None]] = None,
               trace_prefix: Optional[str] = None) -> dict:
    """Offer ``total`` queries and account for every terminal outcome.

    ``sample_every > 0`` turns every Nth request into a generator
    ``sample`` query (when the server carries one); ``on_wave(i)`` runs
    between waves — the CLI's drain-poll hook (it may raise to stop the
    load, e.g. :class:`~hfrep_tpu.resilience.Preempted`; already-offered
    futures are still awaited and classified by the caller's drain).

    ``trace_prefix`` threads flight-recorder trace IDs through the load:
    request ``j`` submits as ``<prefix><j:06d>`` and the report gains a
    ``trace_ids`` list — what the bench's zero-orphan-trace self-check
    (every submitted ID reaches a terminal event reachable by
    ``report --trace``) keys on.  None (the default) lets the server
    mint per-request IDs and adds no per-request bookkeeping.
    """
    futures: List[Future] = []
    trace_ids: List[str] = []
    t0 = timeline.clock()
    submitted = 0
    try:
        while submitted < total:
            n = min(wave, total - submitted)
            for i in range(n):
                j = submitted + i
                tid = None
                if trace_prefix is not None:
                    tid = f"{trace_prefix}{j:06d}"
                    trace_ids.append(tid)
                if (sample_every and server.gen_model is not None
                        and j % sample_every == sample_every - 1):
                    futures.append(server.sample(1, timeout_ms=timeout_ms,
                                                 trace_id=tid))
                else:
                    futures.append(server.replicate(
                        panels[j % len(panels)], timeout_ms=timeout_ms,
                        trace_id=tid))
            submitted += n
            if on_wave is not None:
                on_wave(submitted)
    finally:
        wait(futures)
        wall = timeline.clock() - t0
    # one ledger window for the whole offered load: the worker threads
    # booked queue_wait/dispatch/device_compute into the shared ledger as
    # they served it, so the flush here closes the drive's books against
    # the load's wall (parallel workers can legitimately oversum — the
    # flush clamps and flags that)
    timeline.flush_window(wall, drive="serve_load", steps=submitted)
    doc = classify(futures)
    if trace_prefix is not None:
        doc["trace_ids"] = trace_ids
    lat = sorted(doc.pop("latencies_ms"))
    done = doc["results"] + doc["stale"]
    doc.update({
        "submitted": submitted,
        "wall_s": round(wall, 4),
        "qps": round(done / wall, 2) if wall > 0 else None,
        "p50_ms": percentile(lat, 50),
        "p95_ms": percentile(lat, 95),
        "shed_rate": round((doc["shed"] + doc["draining"]) / submitted, 4)
        if submitted else 0.0,
        "terminal": sum(doc[k] for k in TERMINAL_KEYS),
    })
    return doc
