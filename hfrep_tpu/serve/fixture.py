"""Fixture model bundles for serving drills and benches.

The serving layer needs a *trained* AE replication head to be
meaningful, but the CLI drill, ``tools/bench_serve.py`` and the chaos
paths must all come up in seconds on CPU with no cleaned data.  This
module really trains a small head (the chunked early-exit drive — the
same code path production params come from) on a deterministic
synthetic panel, once per process, and wraps it for serving.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

from hfrep_tpu.config import AEConfig
from hfrep_tpu.serve.aot import AEServeModel
from hfrep_tpu.serve.server import ReplicationServer, ServeConfig


@functools.lru_cache(maxsize=4)
def fixture_ae_model(feats: int = 16, rows: int = 96, latent: int = 8,
                     epochs: int = 30, seed: int = 0) -> AEServeModel:
    """Train the fixture replication head (cached per shape — the bench
    and the self-test reuse one training)."""
    import jax
    from hfrep_tpu.replication.engine import train_autoencoder_chunked
    from hfrep_tpu.utils.fixture_data import scaled_panel

    # shared builder; seed+17 is this fixture's pinned stream (the AOT
    # export round-trip pins compare programs built on these exact bytes)
    scaled = scaled_panel(rows, feats, seed=seed + 17)
    cfg = AEConfig(n_factors=feats, latent_dim=min(latent, feats),
                   epochs=epochs, batch_size=32, patience=3, seed=seed,
                   chunk_epochs=10)
    res, _ = train_autoencoder_chunked(jax.random.PRNGKey(seed), scaled, cfg)
    return AEServeModel.create(cfg, res.params)


def fixture_server(cfg: ServeConfig, feats: int = 16,
                   gen_model=None) -> ReplicationServer:
    return ReplicationServer(cfg, ae_model=fixture_ae_model(feats=feats),
                             gen_model=gen_model).start()


def warm_server(server: ReplicationServer,
                panels: Sequence[np.ndarray]) -> int:
    """Pre-compile the full program grid AND push one real batch through
    each path, OUTSIDE the measured/chaos window — a serving bench that
    times first-request XLA compiles measures the cache being cold, not
    the envelope.  Returns the number of programs resident."""
    from concurrent.futures import wait

    n = server.warm()
    futs = [server.replicate(panels[i % len(panels)], timeout_ms=60000)
            for i in range(server.cfg.max_batch)]
    wait(futs, timeout=60)
    return n
