"""AOT compilation + compiled-program LRU for the serving layer.

Training amortizes one compile over hours of steps; serving cannot — an
XLA compile in the request path is a multi-second p99 outlier and, under
a shape-diverse tenant mix, a compile *storm*.  Following the
serving-vs-training split of the Gemma-on-TPU comparison (PAPERS.md,
arxiv 2605.25645), this module moves every compile ahead of time:

* **padded-shape buckets** — tenant panels arrive with arbitrary row
  counts; requests are padded up to a small fixed ladder of row buckets
  (the PR-4 ``stack_padded`` masking discipline: zero rows after the
  true tail + an ``n_rows`` operand the program masks by), so ONE
  compiled program serves every tenant whose shape falls in the bucket;
* **AOT programs** — ``jax.jit(fn).lower(*specs).compile()`` produces an
  executable before the first request; where this jax version carries
  ``jax.export``, the lowered program additionally round-trips through
  ``export → serialize → deserialize`` so the artifact the server runs
  is the one a model registry could ship (bitwise-equal outputs pinned
  by test, with a clean fallback to the plain compiled path);
* **LRU of compiled programs + device-resident weights** — model
  parameters are ``device_put`` once at registration and shared by every
  bucket's program; compiled executables live in a bounded
  least-recently-used cache whose evictions and compiles are visible to
  the circuit breaker (a thrashing cache IS the compile-storm signal).

Nothing here touches the request path's locks: the cache has its own,
and programs execute outside it.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hfrep_tpu.config import AEConfig, ModelConfig
from hfrep_tpu.models.autoencoder import Autoencoder, latent_mask

#: default row-bucket ladder (tenant panels up to 512 rows); the serve
#: config can override.  Buckets are few on purpose: programs scale with
#: the ladder, and each one is an AOT compile held resident.
DEFAULT_ROW_BUCKETS = (32, 64, 128, 256, 512)


class BucketError(ValueError):
    """A request shape no bucket covers (rows beyond the ladder)."""


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """The smallest bucket >= n — the padded shape the request runs at."""
    for b in buckets:
        if n <= b:
            return int(b)
    raise BucketError(f"{n} rows exceeds the largest serve bucket "
                      f"{max(buckets)}; raise ServeConfig.row_buckets")


def jax_export_supported() -> bool:
    """Does this jax carry a usable ``jax.export`` serialize/deserialize
    pair?  (0.4.3x does; older runtimes fall back to plain AOT.)"""
    try:
        from jax import export  # noqa: F401
        return hasattr(export, "export") and hasattr(export, "deserialize")
    except ImportError:
        return False


def aot_compile(fn: Callable, *example_args, via_export: bool = True,
                label: Optional[str] = None) -> Tuple[Callable, str]:
    """Ahead-of-time compile ``fn`` against ``example_args``.

    Returns ``(callable, mode)`` with ``mode`` one of ``"export"`` (the
    program ran through ``jax.export`` serialize→deserialize — the
    shippable-artifact path) or ``"compiled"`` (plain
    ``lower().compile()``).  The export round-trip is attempted first
    when supported and asked for; any failure degrades silently to the
    compiled path — serving must come up on every runtime, and the
    round-trip equivalence is pinned separately by test.

    Either way the program is EXECUTED once on the example operands
    before this returns: a rehydrated ``Exported.call`` defers its real
    XLA compile to the first invocation, which would silently move the
    compile back into the request path that "ahead of time" exists to
    protect (measured: the first serve of a "warmed" program paid
    ~0.5s).

    ``label`` opts the compile into the perf microscope: with telemetry
    enabled the lowered program's fingerprint + cost/memory analysis
    land as a ``program_profile`` event and a ``run.json`` ``programs``
    entry (``hfrep_tpu/obs/attrib.py``), so two serve runs' compiled
    fleets are machine-diffable.  Compile-time only — nothing touches
    the request path.
    """
    from hfrep_tpu.obs import attrib, get_obs

    if via_export and jax_export_supported():
        try:
            from jax import export
            exported = export.export(jax.jit(fn))(*example_args)
            rehydrated = export.deserialize(exported.serialize())
            jax.block_until_ready(rehydrated.call(*example_args))
            if label and get_obs().enabled:
                # the Exported carries no cost API; re-lower (trace
                # only) for the fingerprint — serving startup, not the
                # request path
                attrib.profile_jitted(jax.jit(fn), f"{label}:export",
                                      *example_args)
            return rehydrated.call, "export"
        except Exception:
            pass
    lowered = jax.jit(fn).lower(*example_args)
    compiled = lowered.compile()
    jax.block_until_ready(compiled(*example_args))
    if label:
        attrib.profile_stage(f"{label}:compiled", lowered, compiled)
    return compiled, "compiled"


# ------------------------------------------------------------ serve models
@dataclasses.dataclass(frozen=True)
class AEServeModel:
    """The trained replication head, weights device-resident.

    ``params`` is the engine's ``{encoder_kernel, decoder_kernel}`` dict
    (one lane of a sweep, or a full-latent train); ``mask`` the optional
    latent mask of the lane being served.  ``decoder_host`` is the ONE
    host copy of the replication weights every response carries —
    fetched at registration, not per request (the params never change
    after create, and a device→host pull per request would put a
    blocking transfer in the hot dispatch path).
    """

    cfg: AEConfig
    params: dict
    decoder_host: np.ndarray
    mask: Optional[jnp.ndarray] = None

    @classmethod
    def create(cls, cfg: AEConfig, params: dict,
               mask=None) -> "AEServeModel":
        dev = jax.tree_util.tree_map(jnp.asarray, params)
        dev = jax.device_put(dev)
        m = None if mask is None else jax.device_put(jnp.asarray(mask))
        host = np.asarray(jax.device_get(dev["decoder_kernel"]))
        return cls(cfg=cfg, params=dev, decoder_host=host, mask=m)


@dataclasses.dataclass(frozen=True)
class GenServeModel:
    """A trained GAN generator (any family), weights device-resident."""

    cfg: ModelConfig
    params: dict

    @classmethod
    def create(cls, cfg: ModelConfig, params: dict) -> "GenServeModel":
        dev = jax.device_put(jax.tree_util.tree_map(jnp.asarray, params))
        return cls(cfg=cfg, params=dev)


# ------------------------------------------------------- batch programs
def ae_batch_fn(model: AEServeModel) -> Callable:
    """The AE replication program one (batch, rows) bucket runs.

    ``fn(params, x (B, T, F), n_rows (B,), mask)`` → ``(recon (B, T, F),
    err (B,))``: each request's panel is MinMax-scaled with its OWN
    masked column ranges (rows past ``n_rows`` excluded — the
    ``stack_padded`` discipline), encoded/decoded through the head, and
    scored with a row-masked reconstruction MSE.  Pure in every operand,
    so the padded program is identical for every tenant in the bucket.
    """
    # thread the model's compute dtype exactly like the training-side
    # builder (replication/engine.py::_ae_model): without it a bf16-
    # policy head silently serves full-f32 matmuls — found by the
    # JPX002 program audit (serve:replicate@bf16), regression-pinned in
    # tests/test_analysis_programs.py
    dt = (None if model.cfg.dtype in (None, "float32")
          else jnp.dtype(model.cfg.dtype))
    ae = Autoencoder(n_features=model.cfg.n_factors,
                     latent_dim=model.cfg.latent_dim,
                     slope=model.cfg.leaky_slope,
                     dtype=dt)

    def one(params, x, n_rows, mask):
        t = x.shape[0]
        rows = (jnp.arange(t) < n_rows)[:, None].astype(jnp.float32)
        n = jnp.maximum(n_rows.astype(jnp.float32), 1.0)
        # masked per-column min/max over the true rows only: padding
        # zeros must not widen a tenant's scale range
        big = jnp.float32(3.4e38)
        mins = jnp.min(jnp.where(rows > 0, x, big), axis=0)
        maxs = jnp.max(jnp.where(rows > 0, x, -big), axis=0)
        scale = jnp.where(maxs - mins == 0.0, 1.0, maxs - mins)
        scaled = (x - mins) / scale * rows
        recon = ae.apply({"params": params}, scaled, mask)
        err = jnp.sum(jnp.mean((recon - scaled) ** 2, axis=1) * rows[:, 0]) / n
        return recon * rows, err

    def batch(params, x, n_rows, mask):
        return jax.vmap(lambda xb, nb: one(params, xb, nb, mask))(x, n_rows)

    return batch


def gen_batch_fn(model: GenServeModel) -> Callable:
    """The generator sampling program: ``fn(params, noise (B, W, F))`` →
    ``(B, W, F)`` windows in scaler space (the CLI inverse-scales where
    a dataset scaler exists, like ``GanTrainer.generate``)."""
    from hfrep_tpu.models.registry import build_gan

    pair = build_gan(model.cfg)

    def batch(params, noise):
        return pair.generator.apply({"params": params}, noise)

    return batch


# ---------------------------------------------------------------- the LRU
class ProgramCache:
    """Bounded LRU of AOT-compiled programs.

    Keys are ``(kind, batch, bucket)`` triples; values the compiled
    callables.  ``get_or_compile`` is the only entry point: a hit
    refreshes recency; a miss builds + AOT-compiles under the lock
    (callers on other keys are briefly serialized — acceptable, because
    steady state is all hits) and reports the compile to ``on_compile``
    (the circuit breaker's compile-storm signal).  Evictions emit a
    ``serve_evict`` event: a cache thrashing at steady state is
    mis-sized, and silence would hide it.
    """

    def __init__(self, capacity: int = 8,
                 on_compile: Optional[Callable[[], None]] = None):
        self.capacity = max(1, int(capacity))
        self.on_compile = on_compile
        #: True while an intentional pre-traffic warm() fills the grid:
        #: those compiles are the operator's choice, not a storm, and
        #: must not count toward the breaker's compile-storm signal
        self.warming = False
        self._lock = threading.Lock()
        self._programs: "OrderedDict[tuple, Callable]" = OrderedDict()
        self.compiles = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)

    def get_or_compile(self, key: tuple, build: Callable[[], Callable]):
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                self._programs.move_to_end(key)
                return fn
            fn = build()
            self.compiles += 1
            self._programs[key] = fn
            evicted = None
            if len(self._programs) > self.capacity:
                evicted, _ = self._programs.popitem(last=False)
                self.evictions += 1
        if self.on_compile is not None and not self.warming:
            self.on_compile()
        try:
            from hfrep_tpu.obs import get_obs
            obs = get_obs()
            obs.counter("serve/compiles").inc(key=str(key))
            if evicted is not None:
                obs.event("serve_evict", key=str(evicted),
                          capacity=self.capacity)
        except Exception:
            pass
        return fn


def pad_panel_batch(panels: Sequence[np.ndarray], batch: int, rows: int,
                    feats: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stack request panels into the bucket's ``(batch, rows, feats)``
    operand + the ``(batch,)`` true-row-count vector — the serving twin
    of :func:`hfrep_tpu.replication.engine.stack_padded` (fixed target
    shape instead of the max of the stack; empty slots are all-padding
    with ``n_rows == 0``, which the masked program reduces to zero)."""
    x = np.zeros((batch, rows, feats), np.float32)
    n = np.zeros((batch,), np.int32)
    for i, p in enumerate(panels):
        arr = np.asarray(p, np.float32)
        if arr.ndim != 2 or arr.shape[1] != feats:
            raise ValueError(f"panel {i}: want (rows, {feats}), "
                             f"got {arr.shape}")
        if arr.shape[0] > rows:
            raise ValueError(f"panel {i}: {arr.shape[0]} rows exceeds "
                             f"bucket {rows}")
        x[i, : arr.shape[0]] = arr
        n[i] = arr.shape[0]
    return jnp.asarray(x), jnp.asarray(n)


def full_mask(cfg: AEConfig) -> jnp.ndarray:
    """The all-ones latent mask a full-latent AE head serves with."""
    return latent_mask(cfg.latent_dim, cfg.latent_dim)
