"""Deadline-aware micro-batching with admission control.

The replication programs are batched XLA computations — serving one
request per dispatch wastes the whole width of the machine, while
waiting forever for a full batch wastes the client's deadline.  The
micro-batcher holds the standard middle: accumulate requests for the
same ``(kind, bucket)`` program until **``max_batch`` requests are
ready or ``batch_window_ms`` has elapsed since the oldest arrival,
whichever comes first**.

Two SRE properties live here because this is the only place they can:

* **admission control** — :meth:`MicroBatcher.submit` is the bounded
  front door: at ``max_queue`` waiting requests the submit is shed
  immediately with a typed :class:`~hfrep_tpu.serve.admission.
  Overloaded` (never parked, never dropped).  ``requeue`` (the worker
  fail-over path) bypasses the bound: an admitted request's retry must
  not be shed by its own failure.
* **deadline cancellation** — every request carries an absolute
  deadline; a request still queued when it expires is completed with
  :class:`~hfrep_tpu.serve.admission.DeadlineExceeded` *at the batcher*
  (a ``serve_deadline_miss`` event), before any device work is paid for
  it.  The expiry check runs on every wait wake-up AND after the
  fault-injection boundary (``stall@batcher`` wedges the batch-formation
  path exactly like a GC pause or a noisy neighbor would; the requests
  it delayed past their deadlines must miss loudly, not ride into a
  dispatch nobody awaits).

The batcher never computes: workers call :meth:`next_batch` and own the
dispatch.  All state lives under one condition variable.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Callable, List, Optional, Tuple

from hfrep_tpu import resilience
from hfrep_tpu.serve.admission import (
    DeadlineExceeded,
    Draining,
    Overloaded,
    ServerClosed,
)


@dataclasses.dataclass
class ServeRequest:
    """One admitted query and its lifecycle state.

    ``bucket`` keys the compiled program the request can join
    (``("replicate", rows_bucket)`` / ``("sample", n_windows)``);
    ``deadline`` is absolute on the server clock.  ``future`` resolves
    to a :class:`~hfrep_tpu.serve.server.ServeResult` or raises one of
    the typed :class:`~hfrep_tpu.serve.admission.ServeError` outcomes —
    exactly once, which is the zero-silent-drop contract the chaos
    selftest asserts.
    """

    id: str
    kind: str                       # "replicate" | "sample"
    payload: object                 # (rows, F) panel | n_windows
    bucket: Tuple
    arrival: float
    deadline: float
    future: Future = dataclasses.field(default_factory=Future)
    retries: int = 0
    #: flight-recorder correlation ID: minted (or caller-supplied) at
    #: submit, carried on every event this request's lifecycle emits so
    #: ``obs report --trace`` reconstructs admit → batch-wait → dispatch
    #: → complete with per-hop durations
    trace_id: str = ""
    #: whether this request's lifecycle events enter the stream (the
    #: 1-in-N ``event_log_every`` sampling decision, made once at admit)
    log: bool = True

    def finish(self, value=None, error: Optional[Exception] = None) -> bool:
        """Resolve the request exactly once; False if already terminal.
        Ownership hand-offs (queue → batch → fail-over) are strictly
        serialized, so the done/set pair cannot actually race — the
        InvalidStateError guard makes a future ownership bug surface as
        a counted double-finish instead of an exception inside a worker
        loop that must keep serving."""
        if self.future.done():
            return False
        try:
            if error is not None:
                self.future.set_exception(error)
            else:
                self.future.set_result(value)
        except InvalidStateError:
            return False
        return True


class MicroBatcher:
    """The bounded, deadline-aware accumulation queue.

    ``on_deadline_miss(req, late_ms)`` lets the server keep its outcome
    accounting without the batcher knowing about counters; the batcher
    still completes the future itself (the miss is terminal HERE).
    """

    def __init__(self, max_batch: int, batch_window_ms: float,
                 max_queue: int,
                 on_deadline_miss: Optional[Callable] = None,
                 on_forced_close: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_batch = max(1, int(max_batch))
        self.batch_window_s = max(0.0, float(batch_window_ms)) / 1e3
        self.max_queue = max(1, int(max_queue))
        self.on_deadline_miss = on_deadline_miss
        #: called for each request close()/requeue-after-close resolves
        #: with ServerClosed — the server's outcome ledger must count
        #: these too, or a timed-out drain breaks terminal == submitted
        self.on_forced_close = on_forced_close
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: List[ServeRequest] = []
        self._closed = False
        self._draining: Optional[str] = None

    # ------------------------------------------------------------ admission
    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def submit(self, req: ServeRequest) -> None:
        """Admit or shed; raising IS the shed (typed, immediate)."""
        with self._cond:
            if self._closed:
                raise ServerClosed("server is closed")
            if self._draining is not None:
                raise Draining(self._draining)
            if len(self._queue) >= self.max_queue:
                raise Overloaded(depth=len(self._queue), bound=self.max_queue)
            self._queue.append(req)
            self._cond.notify_all()

    def requeue(self, reqs: List[ServeRequest]) -> None:
        """Fail-over re-entry for already-admitted requests (a killed
        worker's batch): front of the queue, bound NOT enforced — the
        alternative is shedding a request the server already accepted
        responsibility for."""
        with self._cond:
            if self._closed:
                for r in reqs:
                    if (r.finish(error=ServerClosed("closed during "
                                                    "fail-over"))
                            and self.on_forced_close is not None):
                        self.on_forced_close(r)
                return
            self._queue[:0] = reqs
            self._cond.notify_all()

    # ------------------------------------------------------------- batching
    def next_batch(self, timeout: Optional[float] = None,
                   ) -> Optional[List[ServeRequest]]:
        """Block until one program's batch is ready (or ``timeout``
        passes with an empty queue → ``None``, the worker's idle tick).

        A batch is all queued requests sharing the OLDEST request's
        ``(kind, bucket)`` key, capped at ``max_batch``; it closes when
        the cap is hit or the oldest member has waited the window out.
        """
        deadline_wait = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                self._expire_locked()
                if self._queue:
                    now = self._clock()
                    head = self._queue[0]
                    group = [r for r in self._queue
                             if (r.kind, r.bucket) == (head.kind, head.bucket)]
                    batch = group[: self.max_batch]
                    window_up = now - head.arrival >= self.batch_window_s
                    if len(batch) >= self.max_batch or window_up:
                        for r in batch:
                            self._queue.remove(r)
                        break
                    wake = head.arrival + self.batch_window_s
                    wake = min(wake, min(r.deadline for r in self._queue))
                    self._cond.wait(max(0.0, min(wake - now, 0.05)))
                    continue
                if self._closed:
                    return None
                if deadline_wait is not None:
                    remaining = deadline_wait - self._clock()
                    if remaining <= 0:
                        return None
                    self._cond.wait(min(remaining, 0.05))
                else:
                    self._cond.wait(0.05)
        # fault-injection boundary: ``stall@batcher`` sleeps here and
        # ``sigterm@batcher``/``preempt@batcher`` land a drain — batch
        # formation is the serving loop's natural boundary site
        resilience.tick("batcher")
        # a stall may have pushed batch members past their deadlines;
        # they must miss NOW, not ride into the dispatch
        live = [r for r in batch if not self._expired(r)]
        return live if live else []

    def _expired(self, req: ServeRequest) -> bool:
        now = self._clock()
        if now < req.deadline:
            return False
        late_ms = (now - req.deadline) * 1e3
        if req.finish(error=DeadlineExceeded(req.id, late_ms)):
            self._emit_miss(req, late_ms)
        return True

    def _expire_locked(self) -> None:
        self._queue = [r for r in self._queue if not self._expired(r)]

    def _emit_miss(self, req: ServeRequest, late_ms: float) -> None:
        if self.on_deadline_miss is not None:
            self.on_deadline_miss(req, late_ms)
        if not req.log:
            return
        try:
            from hfrep_tpu.obs import get_obs
            get_obs().event("serve_deadline_miss", request=req.id,
                            kind=req.kind, late_ms=round(late_ms, 3),
                            trace=req.trace_id)
        except Exception:
            pass

    # ------------------------------------------------------------ lifecycle
    def start_drain(self, reason: str) -> None:
        """Stop admitting (submits now get :class:`Draining`); queued
        work keeps flowing to the workers until flushed."""
        with self._cond:
            self._draining = reason
            self._cond.notify_all()

    def wait_empty(self, timeout: float) -> bool:
        """True once the queue is fully flushed (drain step 2)."""
        end = self._clock() + timeout
        with self._cond:
            while self._queue:
                remaining = end - self._clock()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.05))
            return True

    def close(self) -> None:
        """Terminal: wake every waiter; anything still queued is
        completed with :class:`ServerClosed` (typed, never silent)."""
        with self._cond:
            self._closed = True
            leftovers, self._queue = self._queue, []
            self._cond.notify_all()
        for r in leftovers:
            if (r.finish(error=ServerClosed("server closed with request "
                                            "queued"))
                    and self.on_forced_close is not None):
                self.on_forced_close(r)
