"""Admission control + circuit breaking: the overload-protection law.

A serving layer that melts under load is worse than none — the SRE
failure modes are queueing to death (every request admitted, every
request late), silent drops (a request that never gets an answer), and
retry storms against a struggling backend.  The primitives here encode
the counter-doctrine:

* **typed terminal outcomes** — every admitted request ends in exactly
  one of: a result, an explicit :class:`Overloaded` / :class:`Draining`
  rejection, a :class:`DeadlineExceeded`, or a :class:`WorkerFault`.
  Rejections are *values of the protocol*, not exceptions of the
  implementation: a shed request is the system working as designed.
* **bounded queues** — admission is decided at submit time against a
  fixed queue-depth bound (the micro-batcher enforces it); beyond the
  bound the request is shed immediately with :class:`Overloaded` and a
  ``serve_shed`` event, never parked on an unbounded deque.
* **circuit breaker** — repeated worker faults or a compile storm trip
  the breaker OPEN: the server stops dispatching fresh computation and
  serves degraded answers (last-good cached outputs, flagged stale)
  until a cooldown elapses, then HALF-OPEN lets one probe batch through;
  success closes the breaker, failure re-opens it.  The clock is
  injectable so the state machine is unit-testable without sleeping.

Everything here is host-side, stdlib-only, and thread-safe where it
needs to be (the breaker is shared by worker threads and the submit
path).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class ServeError(RuntimeError):
    """Base of every typed terminal rejection the server can hand back.

    ``code`` is the machine-readable outcome class (the counters and the
    chaos selftest key off it); the message is for humans."""

    code = "error"


class Overloaded(ServeError):
    """Load shed at admission: the bounded queue is full.  Explicit by
    design — the client learns *immediately* that it should back off,
    instead of waiting out a deadline in a queue that cannot drain."""

    code = "overloaded"

    def __init__(self, depth: int, bound: int):
        self.depth, self.bound = depth, bound
        super().__init__(f"shed: queue depth {depth} at bound {bound}")


class Draining(ServeError):
    """Admission refused because a graceful drain is in progress: the
    server is flushing in-flight work and will exit 75.  New work must
    go to another replica."""

    code = "draining"

    def __init__(self, reason: Optional[str] = None):
        super().__init__(f"draining{f' ({reason})' if reason else ''}; "
                         "not admitting new requests")


class ServerClosed(ServeError):
    """Submit after shutdown — a caller bug, but still a typed outcome."""

    code = "closed"


class InvalidRequest(ServeError):
    """The request itself is unservable (wrong panel width, rows beyond
    the bucket ladder, unknown kind) — a client error, rejected typed at
    admission before any queueing."""

    code = "invalid"


class DeadlineExceeded(ServeError):
    """The request's deadline expired while it sat in the batcher; it
    was cancelled *before* dispatch (no point computing an answer nobody
    is waiting for) and this is its terminal outcome."""

    code = "deadline"

    def __init__(self, request_id: str, late_ms: float):
        self.request_id, self.late_ms = request_id, late_ms
        super().__init__(f"request {request_id} missed its deadline "
                         f"by {late_ms:.1f}ms (cancelled at the batcher)")


class WorkerFault(ServeError):
    """The batch carrying this request died (worker killed mid-batch, or
    the result publish raised EIO) and the retry budget is spent.  The
    typed alternative to a silent drop."""

    code = "worker_fault"

    def __init__(self, request_id: str, cause: str):
        self.request_id, self.cause = request_id, cause
        super().__init__(f"request {request_id} failed in a worker: {cause}")


# ------------------------------------------------------------------ breaker
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Three-state breaker shared by the submit path and the workers.

    Trips OPEN on either of two signals:

    * ``failure_threshold`` **consecutive** worker faults (a batch that
      died, a result publish that raised) — the backend is sick, and
      dispatching more work to it queues requests to death;
    * a **compile storm**: more than ``compile_storm`` program compiles
      inside ``compile_window_s`` seconds.  An LRU of compiled programs
      thrashing (adversarial shape mix, cache sized wrong) turns every
      request into a multi-second XLA compile; serving stale answers is
      strictly better than compiling in the request path.

    While OPEN, :meth:`allow` is False — the server answers from the
    last-good cache (flagged stale) instead of dispatching.  After
    ``cooldown_s`` the breaker moves to HALF_OPEN and :meth:`allow`
    passes exactly one probe; :meth:`record_success` closes,
    :meth:`record_failure` re-opens (fresh cooldown).

    ``clock`` is injectable (monotonic seconds) so tests can drive the
    cooldown without sleeping.
    """

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 1.0,
                 compile_storm: int = 8, compile_window_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self.compile_storm = max(1, int(compile_storm))
        self.compile_window_s = float(compile_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_out = False
        self._compiles: list = []       # timestamps inside the storm window
        self.trips = 0
        self.last_trip_reason: Optional[str] = None

    # ------------------------------------------------------------- queries
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def allow(self) -> bool:
        """May the server dispatch fresh computation right now?  In
        HALF_OPEN, True exactly once (the probe); its outcome decides."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_out:
                self._probe_out = True
                return True
            return False

    # ------------------------------------------------------------- signals
    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state in (HALF_OPEN, OPEN):
                self._state = CLOSED
                self._probe_out = False
                self._emit("serve_breaker_close")

    def record_failure(self, cause: str = "worker_fault") -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._trip(f"probe failed ({cause})")
            elif (self._state == CLOSED
                  and self._consecutive_failures >= self.failure_threshold):
                self._trip(f"{self._consecutive_failures} consecutive "
                           f"faults ({cause})")

    def record_compile(self) -> None:
        """One program compile happened; trips on a storm."""
        now = self._clock()
        with self._lock:
            self._compiles.append(now)
            cutoff = now - self.compile_window_s
            self._compiles = [t for t in self._compiles if t >= cutoff]
            if self._state == CLOSED and len(self._compiles) > self.compile_storm:
                self._trip(f"compile storm: {len(self._compiles)} compiles "
                           f"in {self.compile_window_s:.0f}s")

    # ------------------------------------------------------------ plumbing
    def _trip(self, reason: str) -> None:
        # lock held by caller
        self._state = OPEN
        self._opened_at = self._clock()
        self._probe_out = False
        self.trips += 1
        self.last_trip_reason = reason
        self._emit("serve_breaker_open", reason=reason)

    def _maybe_half_open(self) -> None:
        # lock held by caller
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._state = HALF_OPEN
            self._probe_out = False

    @staticmethod
    def _emit(name: str, **attrs) -> None:
        try:
            from hfrep_tpu.obs import get_obs
            get_obs().event(name, **attrs)
        except Exception:
            pass
