"""The replication server: AOT programs behind an SRE-grade envelope.

``ReplicationServer`` ties the pieces together into the ROADMAP's
"replication-as-a-service" north-star workload:

* requests (``replicate`` = run a tenant panel through the trained AE
  replication head; ``sample`` = draw windows from a trained GAN
  generator) enter through :meth:`submit`, which returns a ``Future``
  resolving to a :class:`ServeResult` or raising one typed
  :class:`~hfrep_tpu.serve.admission.ServeError` — **exactly one
  terminal outcome per submitted request, always**;
* admission + deadline policy live in the
  :class:`~hfrep_tpu.serve.batcher.MicroBatcher`; compiled programs +
  device-resident weights in the :class:`~hfrep_tpu.serve.aot.
  ProgramCache`; overload state in the
  :class:`~hfrep_tpu.serve.admission.CircuitBreaker`;
* worker threads dispatch batches; a worker that dies mid-batch (the
  ``kill@serve_worker`` chaos scenario) is detected by its own shell,
  its in-flight batch re-queued once (then failed typed — at-most-one
  retry, because unbounded retry of a poisoned batch is a livelock),
  and a replacement worker spawned;
* with the breaker OPEN the server answers from the **last-good cache**
  — the most recent successful output per request kind, flagged
  ``stale=True`` — instead of queueing fresh work it cannot serve
  (degraded > dead; cold-start with an empty cache sheds typed);
* SIGTERM (via :func:`hfrep_tpu.resilience.graceful_drain`) triggers
  :meth:`drain`: admission stops (typed ``Draining`` rejections),
  in-flight work flushes, the ``serve_drain`` event lands, and the CLI
  maps the resulting :class:`~hfrep_tpu.resilience.Preempted` to
  exit 75 like every other drive in the repo.

Outcome accounting is a first-class object (:class:`Outcomes`): the
chaos selftest's zero-silent-drop assertion is just
``outcomes.terminal == outcomes.submitted`` after the storm.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hfrep_tpu import resilience
from hfrep_tpu.obs import timeline
from hfrep_tpu.serve import aot
from hfrep_tpu.serve.admission import (
    OPEN,
    CircuitBreaker,
    Draining,
    InvalidRequest,
    Overloaded,
    ServerClosed,
    WorkerFault,
)
from hfrep_tpu.serve.batcher import MicroBatcher, ServeRequest


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The serving envelope's knobs (one frozen dataclass, repo-style)."""

    max_batch: int = 8              # requests per dispatched program
    batch_window_ms: float = 5.0    # micro-batch accumulation deadline
    request_timeout_ms: float = 250.0   # default per-request deadline
    max_queue: int = 64             # admission bound (queued requests)
    workers: int = 2                # dispatch threads
    row_buckets: Tuple[int, ...] = aot.DEFAULT_ROW_BUCKETS
    sample_buckets: Tuple[int, ...] = (8, 16, 32, 64)
    cache_capacity: int = 32        # compiled programs held resident;
                                    # size it >= the warmed program grid
                                    # (batch buckets x shape buckets) or
                                    # steady state recompiles — the LRU
                                    # protects memory, warm() + capacity
                                    # protect latency
    breaker_failures: int = 3       # consecutive faults that trip OPEN
    breaker_cooldown_s: float = 1.0
    compile_storm: int = 16         # compiles per window that trip OPEN
    compile_window_s: float = 10.0
    via_export: bool = True         # jax.export round-trip when available
    seed: int = 0                   # noise stream for `sample` requests
    event_log_every: int = 1        # per-request obs events (admit/shed/
                                    # degraded) sampled 1-in-N: a 100k-query
                                    # load test must not write 200k JSONL
                                    # lines just to be observed.  Counters
                                    # and the outcome ledger stay exact;
                                    # only the event stream is sampled


@dataclasses.dataclass(frozen=True)
class ServeResult:
    """A successful terminal outcome.  ``stale=True`` marks a degraded
    answer served from the last-good cache while the breaker was open —
    flagged, never silent, so a client can distinguish 'fresh
    replication of MY panel' from 'the best the server could do'."""

    request_id: str
    kind: str
    value: dict
    latency_ms: float
    stale: bool = False
    batch_size: int = 1


class Outcomes:
    """Thread-safe terminal-outcome ledger.

    ``submitted == terminal`` is THE invariant: every request that
    entered :meth:`ReplicationServer.submit` ends in exactly one of the
    buckets below, and the chaos selftest fails the build if a single
    one goes missing.
    """

    FIELDS = ("submitted", "admitted", "results", "degraded", "shed",
              "invalid", "drain_rejected", "deadline_missed",
              "worker_faults", "closed_rejected", "requeues",
              "worker_kills")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)

    def inc(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    #: the terminal buckets (everything except the transition counters
    #: requeues/worker_kills and the non-terminal submitted/admitted)
    TERMINAL_FIELDS = ("results", "degraded", "shed", "invalid",
                       "drain_rejected", "deadline_missed",
                       "worker_faults", "closed_rejected")

    @property
    def terminal(self) -> int:
        """Requests that reached a terminal outcome (requeues and
        worker_kills are transitions, not terminals)."""
        with self._lock:
            return sum(getattr(self, f) for f in self.TERMINAL_FIELDS)

    def as_dict(self) -> dict:
        with self._lock:
            d = {f: getattr(self, f) for f in self.FIELDS}
        d["terminal"] = sum(d[f] for f in self.TERMINAL_FIELDS)
        return d


class _WorkerKilled(BaseException):
    """Injected abrupt worker death (``kill@serve_worker``).  A
    BaseException so no except-Exception recovery path inside the
    dispatch can accidentally 'survive' the kill — the shell is the
    only catcher, exactly like a real thread death."""


class ReplicationServer:
    """See module docstring.  Construct, :meth:`start`, :meth:`submit`
    futures, :meth:`drain`/:meth:`stop`."""

    def __init__(self, cfg: ServeConfig,
                 ae_model: Optional[aot.AEServeModel] = None,
                 gen_model: Optional[aot.GenServeModel] = None,
                 clock: Callable[[], float] = time.monotonic):
        if ae_model is None and gen_model is None:
            raise ValueError("serve needs at least one model "
                             "(ae_model and/or gen_model)")
        self.cfg = cfg
        self.ae_model = ae_model
        self.gen_model = gen_model
        self._clock = clock
        self.outcomes = Outcomes()
        self.breaker = CircuitBreaker(
            failure_threshold=cfg.breaker_failures,
            cooldown_s=cfg.breaker_cooldown_s,
            compile_storm=cfg.compile_storm,
            compile_window_s=cfg.compile_window_s,
            clock=clock)
        self.cache = aot.ProgramCache(capacity=cfg.cache_capacity,
                                      on_compile=self.breaker.record_compile)
        self.batcher = MicroBatcher(
            max_batch=cfg.max_batch, batch_window_ms=cfg.batch_window_ms,
            max_queue=cfg.max_queue, on_deadline_miss=self._count_miss,
            on_forced_close=lambda req: self.outcomes.inc("closed_rejected"),
            clock=clock)
        self._lock = threading.Lock()
        self._last_good: Dict[str, dict] = {}
        self._latencies: List[float] = []       # bounded reservoir
        self._ids = itertools.count()
        self._dispatch_seq = itertools.count()  # sample-noise stream index
        self._in_flight = 0
        self._idle = threading.Condition(self._lock)
        self._running = False
        self._workers: List[threading.Thread] = []
        self._worker_ids = itertools.count()
        self._batch_buckets = tuple(
            b for b in (1, 2, 4, 8, 16, 32, 64, 128) if b < cfg.max_batch
        ) + (cfg.max_batch,)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ReplicationServer":
        with self._lock:
            if self._running:
                return self
            self._running = True
        for _ in range(max(1, self.cfg.workers)):
            self._spawn_worker()
        return self

    def _spawn_worker(self) -> None:
        idx = next(self._worker_ids)
        t = threading.Thread(target=self._worker_shell, args=(idx,),
                             name=f"serve-worker-{idx}", daemon=True)
        self._workers.append(t)
        t.start()

    def stop(self) -> None:
        with self._lock:
            self._running = False
        self.batcher.close()
        for t in self._workers:
            t.join(timeout=5.0)

    def drain(self, reason: str = "drain", timeout: float = 30.0) -> dict:
        """Graceful SIGTERM semantics: stop admitting, flush in-flight,
        report.  The caller (CLI) raises Preempted → exit 75.  Drain
        state is owned by the batcher (the admission front door) — one
        flag, no chance of submit() and the batcher disagreeing."""
        self.batcher.start_drain(reason)
        flushed = self.batcher.wait_empty(timeout)
        end = self._clock() + timeout
        with self._idle:
            while self._in_flight > 0 and self._clock() < end:
                self._idle.wait(0.05)
            flushed = flushed and self._in_flight == 0
        self.stop()
        doc = {"reason": reason, "flushed": bool(flushed),
               **self.outcomes.as_dict()}
        self._emit("serve_drain", reason=reason, flushed=bool(flushed),
                   terminal=doc["terminal"], submitted=doc["submitted"])
        return doc

    # ------------------------------------------------------------ admission
    def submit(self, kind: str, payload,
               timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None) -> Future:
        """Admit one query; ALWAYS returns a future that terminates.

        Typed rejections (shed, draining, closed) resolve the future
        immediately — raising at the submit call site would make the
        sync and async client paths behave differently under overload,
        which is exactly when behavior must be boring.

        ``trace_id`` is the flight-recorder correlation ID a caller
        (load generator, upstream gateway) threads through; None mints
        one from the request id.  Every lifecycle event this request
        produces — admit, dispatch, complete, shed, miss, fault — then
        carries it, so ``obs report --trace <id>`` reconstructs the
        request's critical path with per-hop durations.
        """
        self.outcomes.inc("submitted")
        now = self._clock()
        idnum = next(self._ids)
        rid = f"r{idnum}"
        trace = trace_id or rid
        log = (self.cfg.event_log_every <= 1
               or idnum % self.cfg.event_log_every == 0)
        budget = (self.cfg.request_timeout_ms
                  if timeout_ms is None else float(timeout_ms))
        try:
            bucket = self._bucket(kind, payload)
        except (ValueError, aot.BucketError) as e:
            self.outcomes.inc("invalid")
            if log:
                self._emit("serve_fault", request=rid, trace=trace,
                           cause=f"invalid: {e}")
            return self._rejected(InvalidRequest(str(e)))
        req = ServeRequest(id=rid, kind=kind, payload=payload, bucket=bucket,
                           arrival=now, deadline=now + budget / 1e3,
                           trace_id=trace, log=log)
        if log:
            self._emit("serve_admit", request=rid, kind=kind,
                       bucket=str(bucket), timeout_ms=budget, trace=trace)

        # breaker-open fast path: degraded answer over queueing to death
        if self.breaker.state == OPEN:
            return self._degrade_or_shed(req, "breaker open", log=log)
        try:
            self.batcher.submit(req)
        except Overloaded as e:
            self.outcomes.inc("shed")
            if log:
                self._emit("serve_shed", request=rid, reason="queue_full",
                           depth=e.depth, bound=e.bound, trace=trace)
            req.finish(error=e)
            return req.future
        except Draining as e:
            self.outcomes.inc("drain_rejected")
            if log:
                self._emit("serve_shed", request=rid, reason="draining",
                           trace=trace)
            req.finish(error=e)
            return req.future
        except ServerClosed as e:
            self.outcomes.inc("closed_rejected")
            req.finish(error=e)
            return req.future
        self.outcomes.inc("admitted")
        self._gauge_depth()
        return req.future

    def replicate(self, panel, timeout_ms: Optional[float] = None,
                  trace_id: Optional[str] = None) -> Future:
        return self.submit("replicate", np.asarray(panel, np.float32),
                           timeout_ms=timeout_ms, trace_id=trace_id)

    def sample(self, n_windows: int,
               timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None) -> Future:
        return self.submit("sample", int(n_windows), timeout_ms=timeout_ms,
                           trace_id=trace_id)

    def _bucket(self, kind: str, payload) -> Tuple:
        if kind == "replicate":
            if self.ae_model is None:
                raise ValueError("no AE replication head registered")
            arr = np.asarray(payload)
            if arr.ndim != 2 or arr.shape[1] != self.ae_model.cfg.n_factors:
                raise ValueError(
                    f"replicate wants (rows, {self.ae_model.cfg.n_factors}) "
                    f"panels, got {arr.shape}")
            return ("replicate",
                    aot.bucket_for(arr.shape[0], self.cfg.row_buckets))
        if kind == "sample":
            if self.gen_model is None:
                raise ValueError("no generator registered")
            n = int(payload)
            if n < 1:
                raise ValueError(f"sample wants n_windows >= 1, got {n}")
            return ("sample", aot.bucket_for(n, self.cfg.sample_buckets))
        raise ValueError(f"unknown request kind {kind!r}")

    def _rejected(self, err: ServeError) -> Future:
        f: Future = Future()
        f.set_exception(err)
        return f

    def _degrade_or_shed(self, req: ServeRequest, why: str,
                         log: bool = True) -> Future:
        with self._lock:
            cached = self._last_good.get(req.kind)
        if cached is not None:
            self.outcomes.inc("degraded")
            latency = (self._clock() - req.arrival) * 1e3
            req.finish(value=ServeResult(
                request_id=req.id, kind=req.kind, value=cached,
                latency_ms=latency, stale=True))
            if log:
                self._emit("serve_degraded", request=req.id, reason=why,
                           trace=req.trace_id)
        else:
            self.outcomes.inc("shed")
            if log:
                self._emit("serve_shed", request=req.id, reason=why,
                           trace=req.trace_id)
            req.finish(error=Overloaded(depth=self.batcher.depth,
                                        bound=self.cfg.max_queue))
        return req.future

    # -------------------------------------------------------------- workers
    def _worker_shell(self, idx: int) -> None:
        """Supervision boundary of one worker thread: translate abrupt
        death into fail-over + replacement, so a killed worker costs one
        retry, never an answer."""
        try:
            self._worker_loop(idx)
        except _WorkerKilled as e:
            batch = e.args[0] if e.args else []
            self.outcomes.inc("worker_kills")
            self.breaker.record_failure(cause="worker killed")
            self._emit("serve_worker_exit", worker=idx, kind="killed",
                       in_flight=len(batch))
            self._fail_over(batch)
            with self._lock:
                self._in_flight -= len(batch)
                respawn = self._running
                self._idle.notify_all()
            if respawn:
                self._spawn_worker()

    def _worker_loop(self, idx: int) -> None:
        while True:
            with self._lock:
                if not self._running:
                    return
            # measure the batch wait unconditionally, book it only when a
            # batch actually arrived: an idle worker's empty polls are
            # not queue_wait in any drive's ledger window
            with timeline.timed(None) as tm_wait:
                batch = self.batcher.next_batch(timeout=0.05)
            if not batch:
                continue
            timeline.account("queue_wait", tm_wait.s)
            with self._lock:
                self._in_flight += len(batch)
            # the injected-chaos hook: a ``kill@serve_worker`` directive
            # fires at the Nth batch ANY worker picked up — the thread
            # dies abruptly with its batch in flight.  The kill MUST
            # raise here, outside the try/finally below: the shell owns
            # the in_flight decrement on this path, and a kill inside
            # the try would decrement twice
            if resilience.actor_kill_point("serve_worker"):
                raise _WorkerKilled(batch)
            try:
                self._dispatch(batch)
            finally:
                with self._lock:
                    self._in_flight -= len(batch)
                    self._idle.notify_all()
            self._gauge_depth()

    def _fail_over(self, batch: List[ServeRequest]) -> None:
        """A batch whose worker died: retry once, then typed failure."""
        retry, dead = [], []
        for r in batch:
            if r.future.done():
                continue
            (retry if r.retries < 1 else dead).append(r)
        for r in retry:
            r.retries += 1
        if retry:
            self.outcomes.inc("requeues", len(retry))
            self.batcher.requeue(retry)
        for r in dead:
            self.outcomes.inc("worker_faults")
            if r.log:
                self._emit("serve_fault", request=r.id, trace=r.trace_id,
                           cause="worker died twice")
            r.finish(error=WorkerFault(r.id, "worker died twice"))

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, batch: List[ServeRequest]) -> None:
        kind = batch[0].kind
        if not self.breaker.allow():
            # tripped (or half-open with the probe already out) while
            # these were queued: degrade rather than dispatch
            for r in batch:
                self._degrade_or_shed(r, "breaker open at dispatch")
            return
        t_disp = self._clock()
        if any(r.log for r in batch):
            # one batch-level hop event (not per-request): the traces
            # list lets `report --trace` attribute the batch-wait →
            # dispatch hop to every member without 8x the event volume
            self._emit("serve_dispatch", kind=kind,
                       bucket=str(batch[0].bucket), batch=len(batch),
                       traces=[r.trace_id for r in batch if r.log],
                       max_wait_ms=round(
                           (t_disp - min(r.arrival for r in batch)) * 1e3, 3))
        try:
            with timeline.timed("dispatch"):
                # the run helpers note their device_get separately, so
                # this frame's exclusive remainder is pure host dispatch
                if kind == "replicate":
                    values = self._run_replicate(batch)
                else:
                    values = self._run_sample(batch)
        except Exception as e:           # compile/execute failure of the batch
            self.breaker.record_failure(cause=type(e).__name__)
            for r in batch:
                self.outcomes.inc("worker_faults")
                if r.log:
                    self._emit("serve_fault", request=r.id, trace=r.trace_id,
                               cause=f"{type(e).__name__}: {e}")
                r.finish(error=WorkerFault(r.id, f"{type(e).__name__}: {e}"))
            return
        # Two passes, breaker first, futures LAST: a client that observes
        # its future done may immediately read `breaker.state` (the
        # selftest does), so every breaker/ledger transition this batch
        # causes must be visible BEFORE any member future resolves —
        # resolving first opened a race that per-request event emission
        # (the flight recorder's serve_complete) widened into a reliably
        # flaky half-open read.
        ok = True
        now = self._clock()
        settled: List[Tuple[ServeRequest, object, Optional[float]]] = []
        for r, value in zip(batch, values):
            try:
                # the result-publish boundary: ``io_fail@serve_result``
                # raises the injected EIO here — the request then fails
                # TYPED (WorkerFault), never silently
                resilience.io_point("serve_result")
            except OSError as e:
                ok = False
                self.breaker.record_failure(cause="serve_result EIO")
                self.outcomes.inc("worker_faults")
                if r.log:
                    self._emit("serve_fault", request=r.id, trace=r.trace_id,
                               cause=f"result publish: {e}")
                settled.append((r, WorkerFault(r.id, f"result publish: {e}"),
                                None))
                continue
            settled.append((r, value, (now - r.arrival) * 1e3))
        if ok:
            self.breaker.record_success()
            with self._lock:
                self._last_good[kind] = values[-1]
        for r, value, latency in settled:
            if latency is None:
                r.finish(error=value)
                continue
            if r.finish(value=ServeResult(request_id=r.id, kind=kind,
                                          value=value, latency_ms=latency,
                                          batch_size=len(batch))):
                self.outcomes.inc("results")
                self._note_latency(latency)
                if r.log:
                    self._emit("serve_complete", request=r.id,
                               trace=r.trace_id, kind=kind,
                               queue_ms=round((t_disp - r.arrival) * 1e3, 3),
                               exec_ms=round((now - t_disp) * 1e3, 3),
                               latency_ms=round(latency, 3),
                               batch=len(batch))

    def warm(self) -> int:
        """AOT-compile the full program grid — every (kind, batch
        bucket, shape bucket) the config admits — ahead of traffic, and
        report the programs resident.  The serving contract is that a
        request never waits on XLA in steady state; warm() is how a
        deployment buys that before taking load (the compile-storm
        breaker is the backstop for the grid the operator got wrong).
        Warm compiles are intentional and do NOT count toward the
        breaker's compile-storm signal."""
        self.cache.warming = True
        try:
            if self.ae_model is not None:
                for rows in self.cfg.row_buckets:
                    for bsz in self._batch_buckets:
                        self._replicate_program(bsz, rows)
            if self.gen_model is not None:
                for bucket in self.cfg.sample_buckets:
                    self._sample_program(bucket)
        finally:
            self.cache.warming = False
        return len(self.cache)

    def _ae_mask(self):
        model = self.ae_model
        return (model.mask if model.mask is not None
                else aot.full_mask(model.cfg))

    def _replicate_program(self, bsz: int, rows: int):
        model = self.ae_model
        feats = model.cfg.n_factors
        return self.cache.get_or_compile(
            ("replicate", bsz, rows),
            lambda: aot.aot_compile(aot.ae_batch_fn(model), model.params,
                                    jnp.zeros((bsz, rows, feats), jnp.float32),
                                    jnp.zeros((bsz,), jnp.int32),
                                    self._ae_mask(),
                                    via_export=self.cfg.via_export,
                                    label=f"serve:replicate:b{bsz}r{rows}")[0])

    def _sample_program(self, bucket: int):
        model = self.gen_model
        w, f = model.cfg.window, model.cfg.features
        return self.cache.get_or_compile(
            ("sample", bucket),
            lambda: aot.aot_compile(
                aot.gen_batch_fn(model), model.params,
                jnp.zeros((bucket, w, f), jnp.float32),
                via_export=self.cfg.via_export,
                label=f"serve:sample:b{bucket}")[0])

    def _run_replicate(self, batch: List[ServeRequest]) -> List[dict]:
        model = self.ae_model
        rows = batch[0].bucket[1]
        bsz = aot.bucket_for(len(batch), self._batch_buckets)
        feats = model.cfg.n_factors
        x, n_rows = aot.pad_panel_batch([r.payload for r in batch],
                                        bsz, rows, feats)
        mask = self._ae_mask()
        fn = self._replicate_program(bsz, rows)
        recon, err = fn(model.params, x, n_rows, mask)
        t_s = timeline.clock()
        recon, err, rows_h = jax.device_get((recon, err, n_rows))
        timeline.note_sync(timeline.clock() - t_s)
        return [{"reconstruction": np.asarray(recon[i][: int(rows_h[i])]),
                 "recon_mse": float(err[i]),
                 "weights": model.decoder_host}
                for i in range(len(batch))]

    def _run_sample(self, batch: List[ServeRequest]) -> List[dict]:
        """Each request claims ``payload`` window slots; the batch runs
        in slot-bounded chunks so a wide batch can never overflow the
        largest compiled noise bucket (each request alone fits — the
        submit-time bucket check guarantees it)."""
        model = self.gen_model
        max_slots = max(self.cfg.sample_buckets)
        w, f = model.cfg.window, model.cfg.features
        chunks: List[List[ServeRequest]] = [[]]
        slots = 0
        for r in batch:
            n = int(r.payload)
            if chunks[-1] and slots + n > max_slots:
                chunks.append([])
                slots = 0
            chunks[-1].append(r)
            slots += n
        out = []
        for chunk in chunks:
            total = sum(int(r.payload) for r in chunk)
            bucket = aot.bucket_for(total, self.cfg.sample_buckets)
            fn = self._sample_program(bucket)
            key = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed),
                                     next(self._dispatch_seq))
            noise = jax.random.normal(key, (bucket, w, f))
            t_s = timeline.clock()
            windows = np.asarray(jax.device_get(fn(model.params, noise)))
            timeline.note_sync(timeline.clock() - t_s)
            off = 0
            for r in chunk:
                n = int(r.payload)
                out.append({"windows": windows[off: off + n]})
                off += n
        return out

    # ------------------------------------------------------------ telemetry
    def _count_miss(self, req: ServeRequest, late_ms: float) -> None:
        self.outcomes.inc("deadline_missed")

    def _note_latency(self, ms: float) -> None:
        with self._lock:
            if len(self._latencies) < 65536:
                self._latencies.append(ms)
        try:
            from hfrep_tpu.obs import get_obs
            get_obs().histogram("serve/latency_ms").observe(ms)
        except Exception:
            pass

    def _gauge_depth(self) -> None:
        try:
            from hfrep_tpu.obs import get_obs
            obs = get_obs()
            if obs.enabled:
                obs.gauge("serve/queue_depth").set(self.batcher.depth)
        except Exception:
            pass

    @staticmethod
    def _emit(name: str, **attrs) -> None:
        try:
            from hfrep_tpu.obs import get_obs
            get_obs().event(name, **attrs)
        except Exception:
            pass

    def latency_percentiles(self) -> dict:
        from hfrep_tpu.serve.loadgen import percentile
        with self._lock:
            s = sorted(self._latencies)
        if not s:
            return {"n": 0, "p50_ms": None, "p95_ms": None, "max_ms": None}
        return {"n": len(s), "p50_ms": percentile(s, 50),
                "p95_ms": percentile(s, 95), "max_ms": s[-1]}

    def stats(self) -> dict:
        doc = self.outcomes.as_dict()
        doc.update(self.latency_percentiles())
        doc["breaker"] = {"state": self.breaker.state,
                          "trips": self.breaker.trips,
                          "reason": self.breaker.last_trip_reason}
        doc["cache"] = {"programs": len(self.cache),
                        "compiles": self.cache.compiles,
                        "evictions": self.cache.evictions}
        doc["queue_depth"] = self.batcher.depth
        return doc
