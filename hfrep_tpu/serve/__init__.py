"""hfrep_tpu.serve — replication-as-a-service with overload protection.

Everything else in the repo answers one *batch* question; the ROADMAP
north star is answering portfolio-replication *queries* for millions of
users.  This package is that serving layer, built robustness-first —
a server that melts under load or drops a request silently on a fault
is worse than no server:

* **AOT programs** (:mod:`~hfrep_tpu.serve.aot`) — the trained AE
  replication head and GAN generators compiled ahead of time
  (``jax.jit(...).lower().compile()``, with a ``jax.export``
  serialize→deserialize round-trip where this jax carries it), behind a
  bounded LRU of compiled programs + device-resident weights keyed by
  padded-shape bucket (the PR-4 ``stack_padded`` masking fabric: one
  program serves every tenant shape in a bucket);
* **deadline-aware micro-batching** (:mod:`~hfrep_tpu.serve.batcher`)
  — accumulate up to ``max_batch`` or ``batch_window_ms``, whichever
  first; per-request deadlines propagate end-to-end and expire AT the
  batcher (typed ``DeadlineExceeded``, never a dispatch nobody awaits);
* **admission control + load shedding** (:mod:`~hfrep_tpu.serve.
  admission`) — a bounded queue; beyond it requests are shed
  immediately with a typed ``Overloaded`` rejection;
* **circuit breaking + degraded answers** — repeated worker faults or
  a compile storm trip the breaker; while open the server answers from
  the last-good cache *flagged stale* instead of queueing to death;
* **graceful drain** — SIGTERM (via :func:`hfrep_tpu.resilience.
  graceful_drain`) stops admission, flushes in-flight work and exits 75,
  like every other drive in the repo;
* **chaos-tested** — ``HFREP_FAULTS`` grows serve sites
  (``kill@serve_worker``, ``io_fail@serve_result``, ``stall@batcher``)
  and the resilience selftest drives a worker kill + EIO + deadline
  storm, asserting every admitted request reaches exactly one terminal
  outcome (zero silent drops).

Entry points: ``python -m hfrep_tpu serve`` (fixture-driven service
drill) and ``tools/bench_serve.py`` (p50/p95/QPS at 1k/10k/100k
simulated concurrent queries, gated through the PR-3 sentinel).
"""

from __future__ import annotations

from hfrep_tpu.serve.admission import (  # noqa: F401  (public re-exports)
    CircuitBreaker,
    DeadlineExceeded,
    Draining,
    InvalidRequest,
    Overloaded,
    ServeError,
    ServerClosed,
    WorkerFault,
)
from hfrep_tpu.serve.aot import (  # noqa: F401
    AEServeModel,
    GenServeModel,
    jax_export_supported,
)
from hfrep_tpu.serve.batcher import MicroBatcher, ServeRequest  # noqa: F401
from hfrep_tpu.serve.server import (  # noqa: F401
    ReplicationServer,
    ServeConfig,
    ServeResult,
)
