"""hfrep_tpu.orchestrate — supervised async actor fabric.

The paper's pipeline (GAN synthesis feeding the AE replication sweep)
runs decoupled instead of serialized: a generator pool streams synthetic
panels into a bounded host-side spool queue and AE sweep consumers pull
from it, under a supervisor that restarts any lost member and drains the
whole pod at a coordinated barrier on SIGTERM.  Podracer architectures
(arxiv 2104.06272) supply the supervision pattern; the generator/
consumer split is where the throughput lives (arxiv 2111.04628).

The three layers:

* :mod:`~hfrep_tpu.orchestrate.queue` — :class:`SpoolQueue`, a bounded
  crash-safe file-backed queue: atomic item publication with embedded
  ``(source, seq, digest)``, rename-based claims, requeue of orphans,
  backpressure instead of unbounded buffering;
* :mod:`~hfrep_tpu.orchestrate.actors` — the member processes
  (generator: deterministic per-``(source, seq)`` items + sub-block
  :class:`~hfrep_tpu.resilience.snapshot.ProgressSnapshot`; consumer:
  idempotent per-item AE sweeps published atomically);
* :mod:`~hfrep_tpu.orchestrate.supervisor` — spawn/watch/restart with
  full-jitter bounded backoff, the ``kill@actor`` fault hook (REAL
  SIGKILL of a live member), and the drain barrier with timeout
  escalation;

plus :mod:`~hfrep_tpu.orchestrate.pipeline` (:func:`run_pipeline`), the
end-to-end drive behind ``python -m hfrep_tpu pipeline`` — whose
kill→resume bit-identity the resilience selftest pins with real signals.
"""

from __future__ import annotations

from hfrep_tpu.orchestrate.pipeline import (  # noqa: F401  (public API)
    PipelinePlan,
    PipelineStateError,
    SourceSpec,
    assemble,
    run_pipeline,
)
from hfrep_tpu.orchestrate.queue import QueueItem, SpoolQueue  # noqa: F401
from hfrep_tpu.orchestrate.supervisor import (  # noqa: F401
    ActorSpec,
    OrchestrationError,
    Supervisor,
)
