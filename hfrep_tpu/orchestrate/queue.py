"""Bounded, crash-safe work queue between generator and consumer actors.

The fabric's members are OS processes that may be SIGKILLed at any
instruction, so the queue cannot live in process memory or in a
``multiprocessing.Queue`` (a member killed holding the feeder lock or
mid-pipe-write corrupts it for everyone).  Instead the queue is a spool
directory whose every transition is a single atomic filesystem rename:

* **put** — the item is materialized through the crash-consistent
  checkpoint writer (:func:`hfrep_tpu.utils.checkpoint.write_atomic`:
  payload + checksum'd ``meta.json``, published in one rename into
  ``ready/``).  A kill mid-put leaves a hidden tmp dir, never a torn
  item.  The embedded checksum IS the item digest — every item carries
  ``(source, seq, digest)``.
* **claim** — a consumer renames ``ready/<item>`` to
  ``claimed/<consumer>__<item>``; rename is atomic, so exactly one
  claimant wins a race and the loser just moves to the next item.  The
  claim is digest-verified before use.
* **ack** — the claimed dir is deleted after the consumer has published
  its result (result first, ack second: a kill between the two leaves a
  claimed item whose reprocessing is idempotent).
* **requeue** — the supervisor moves a dead consumer's claimed items
  back to ``ready/`` before restarting it; nothing is lost, nothing is
  processed twice (results are keyed by ``(source, seq)``).

**Backpressure, not buffering**: :meth:`SpoolQueue.put` blocks while
``ready/`` holds ``capacity`` items, so a fast generator pool cannot
balloon host memory/disk ahead of the consumers — the Podracer
decoupling (arxiv 2104.06272) with a bounded channel.  A put blocked
during a pod drain raises :class:`~hfrep_tpu.resilience.Preempted`
instead of deadlocking the barrier (the undelivered item is regenerated
on resume — the producer's snapshot still points at it).

**Exactly-once delivery** is split honestly between the two ends: a
restarted producer re-offers at most the one item it was killed around,
and :meth:`put` detects the duplicate by its ``(source, seq)`` name
(still spooled → skipped); an item that was already consumed and acked
re-enters the spool, but the consumer side skips recomputation because
the result artifact for that ``(source, seq)`` already exists.  Gaps —
an eof count larger than the delivered range — are detected by the
consumers' exit check and the pipeline assembly
(:func:`hfrep_tpu.orchestrate.pipeline.assemble`).

Fault sites: ``io_fail@queue_get`` raises the injected EIO straight out
of :meth:`SpoolQueue.claim` — the consumer crashes and the supervisor's
restart path is exercised.  ``io_fail@queue_put`` lands inside the
atomic item write, which runs under the bounded retry policy like every
other durable write — a single EIO is absorbed as an ``io_retry`` (flaky
shared storage must not kill a producer), so crashing a producer takes a
burst at least ``HFREP_IO_RETRIES`` long (e.g. ``io_fail@queue_put=1x3``).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional

import numpy as np

from hfrep_tpu import resilience
from hfrep_tpu.obs import timeline
from hfrep_tpu.utils import checkpoint as ckpt

READY = "ready"
CLAIMED = "claimed"
_CLAIM_SEP = "__"
_EOF_PREFIX = "eof_"


class QueueItem(NamedTuple):
    """A claimed item: identity, payload location and verified metadata."""

    source: str
    seq: int
    path: Path           # the claimed directory holding payload.npz
    meta: dict           # verified meta.json (checksum = the digest)

    def arrays(self) -> Dict[str, np.ndarray]:
        with np.load(self.path / "payload.npz") as z:
            return {k: z[k] for k in z.files}


def item_name(source: str, seq: int) -> str:
    return f"item_{source}_{seq:05d}"


def item_trace_id(stream_seed: int, source: str, seq: int) -> str:
    """The pipeline item's trace/correlation ID — a PURE function of the
    item coordinate, like the item itself: a producer restarted after
    SIGKILL re-emits the same ID for a replayed item, so ``obs report
    --trace`` reconstructs one critical path spanning the restart
    (queue-wait → claim → sweep → publish) instead of two orphan halves.
    Every process's events for the item carry it as the ``trace`` attr.
    """
    return f"t{int(stream_seed)}-{source}-{int(seq):05d}"


def _parse_item_name(name: str):
    """``item_<source>_<seq>`` → (source, seq); None for foreign names."""
    if not name.startswith("item_"):
        return None
    body = name[len("item_"):]
    head, _, tail = body.rpartition("_")
    if not head or not tail.isdigit():
        return None
    return head, int(tail)


def _obs_event(name: str, **attrs) -> None:
    try:
        from hfrep_tpu.obs import get_obs
        obs = get_obs()
        obs.event(name, **attrs)
        # item-granular durability: a SIGKILLed member loses its write
        # buffer, and the flight recorder's cross-restart trace
        # reconstruction depends on the pre-kill queue hops being ON
        # DISK — queue events are per-item (seconds of work each), so a
        # flush per event is noise next to the sweep it brackets
        obs.flush()
    except Exception:
        pass


class SpoolQueue:
    """One spool directory shared by every member of the fabric."""

    def __init__(self, dirpath, capacity: int = 8, poll: float = 0.02):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.dir = Path(dirpath)
        self.ready = self.dir / READY
        self.claimed = self.dir / CLAIMED
        self.capacity = int(capacity)
        self.poll = float(poll)
        self.ready.mkdir(parents=True, exist_ok=True)
        self.claimed.mkdir(parents=True, exist_ok=True)

    # -------------------------------------------------------------- state
    def ready_names(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.ready)
                          if _parse_item_name(n) is not None)
        except OSError:
            return []

    def depth(self) -> int:
        """Spooled-and-unclaimed items — the backpressure measure and the
        ``orchestrate/queue_depth`` gauge's value."""
        return len(self.ready_names())

    def claimed_names(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.claimed)
                          if _CLAIM_SEP in n)
        except OSError:
            return []

    def spooled(self, source: str, seq: int) -> bool:
        """Is the item currently in flight (ready or claimed)?"""
        name = item_name(source, seq)
        if (self.ready / name).exists():
            return True
        suffix = _CLAIM_SEP + name
        return any(n.endswith(suffix) for n in self.claimed_names())

    # ---------------------------------------------------------------- put
    def put(self, source: str, seq: int, arrays: Dict[str, np.ndarray],
            extra_meta: Optional[dict] = None) -> bool:
        """Spool one item; blocks on backpressure; False = duplicate.

        The duplicate check makes a restarted producer's re-offer of its
        kill-window item a no-op while the original is still in flight.
        A blocked put aborts with :class:`~hfrep_tpu.resilience.
        Preempted` once a drain is requested — the producer's snapshot
        has not advanced past ``seq``, so resume regenerates it.
        """
        name = item_name(source, seq)
        trace = (extra_meta or {}).get("trace")
        if self.spooled(source, seq):
            _obs_event("queue_put", source=source, seq=seq, duplicate=True,
                       trace=trace)
            return False
        with timeline.timed("queue_wait") as tm:
            while self.depth() >= self.capacity:
                if resilience.drain_requested():
                    raise resilience.Preempted(
                        site="queue_put", reason="drain requested while "
                        f"blocked on backpressure (capacity {self.capacity})")
                time.sleep(self.poll)
        waited = tm.s

        def writer(tmp: Path) -> None:
            np.savez(tmp / "payload.npz", **arrays)

        meta = {"source": source, "seq": int(seq)}
        if extra_meta:
            meta.update(extra_meta)
        ckpt.write_atomic(self.ready / name, writer, metadata=meta,
                          io_site="queue_put", fault_site="queue_item")
        _obs_event("queue_put", source=source, seq=seq,
                   wait_s=round(waited, 4), depth=self.depth(), trace=trace)
        return True

    # --------------------------------------------------------------- claim
    def claim(self, consumer: str) -> Optional[QueueItem]:
        """Atomically claim the first ready item, digest-verified.

        Rename decides races: of N consumers trying the same item,
        exactly one rename succeeds, the rest move on.  A claim that
        fails verification (torn/rotted payload) is discarded with a
        ``queue_item_corrupt`` event — the completeness check at exit
        reports the resulting gap rather than training on damaged data.
        """
        if _CLAIM_SEP in consumer:
            raise ValueError(f"consumer name must not contain "
                             f"{_CLAIM_SEP!r}: {consumer!r}")
        resilience.io_point("queue_get")
        for name in self.ready_names():
            dst = self.claimed / f"{consumer}{_CLAIM_SEP}{name}"
            try:
                os.rename(self.ready / name, dst)
            except OSError:
                continue                    # raced: another consumer won
            source, seq = _parse_item_name(name)
            try:
                meta = ckpt.verify(dst)
            except ckpt.CheckpointCorrupt as e:
                _obs_event("queue_item_corrupt", source=source, seq=seq,
                           error=str(e))
                shutil.rmtree(dst, ignore_errors=True)
                continue
            _obs_event("queue_get", source=source, seq=seq,
                       consumer=consumer, depth=self.depth(),
                       trace=(meta or {}).get("trace"))
            return QueueItem(source=source, seq=seq, path=dst,
                             meta=meta or {})
        return None

    def ack(self, item: QueueItem) -> None:
        """Delete a processed claim (call AFTER publishing the result)."""
        shutil.rmtree(item.path, ignore_errors=True)

    def requeue_claims(self, consumer: Optional[str] = None) -> List[str]:
        """Move claimed items back to ``ready/`` — the supervisor's
        recovery step for a crashed consumer (``consumer=<name>``) and
        the pipeline's resume step for an entire killed pod (None =
        every claim is orphaned)."""
        moved = []
        for name in self.claimed_names():
            owner, _, item = name.partition(_CLAIM_SEP)
            if consumer is not None and owner != consumer:
                continue
            dst = self.ready / item
            try:
                if dst.exists():            # duplicate already re-spooled
                    shutil.rmtree(self.claimed / name, ignore_errors=True)
                else:
                    os.rename(self.claimed / name, dst)
                moved.append(item)
            except OSError:
                continue
        if moved:
            _obs_event("queue_requeue", consumer=consumer, items=len(moved))
        return moved

    # ----------------------------------------------------------------- eof
    def put_eof(self, source: str, count: int) -> None:
        """Publish a source's end-of-stream marker (+ item count) — the
        consumers' termination signal and the gap check's ground truth."""
        path = self.dir / f"{_EOF_PREFIX}{source}.json"
        tmp = path.with_name(path.name + f".tmp-{os.getpid()}")
        tmp.write_text(json.dumps({"source": source, "count": int(count)}))
        os.replace(tmp, path)

    def clear_eof(self, source: str) -> None:
        """Retract a source's end-of-stream marker — the resume-time
        repair path replays a block by clearing its eof + snapshot."""
        try:
            os.remove(self.dir / f"{_EOF_PREFIX}{source}.json")
        except OSError:
            pass

    def eof_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            if n.startswith(_EOF_PREFIX) and n.endswith(".json"):
                try:
                    doc = json.loads((self.dir / n).read_text())
                    out[str(doc["source"])] = int(doc["count"])
                except (OSError, ValueError, KeyError):
                    continue
        return out

    def drained(self, sources) -> bool:
        """Every source has published eof AND nothing is spooled or
        claimed — the consumers' safe-exit condition (claims held by a
        live sibling block the exit; orphaned claims are requeued by the
        supervisor before this can deadlock)."""
        eofs = self.eof_counts()
        if any(s not in eofs for s in sources):
            return False
        return not self.ready_names() and not self.claimed_names()
