"""The orchestrated pipeline: GAN synthesis streaming into AE sweeps.

Sequentially, the paper's flow is *generate every synthetic panel, then
sweep every dataset* — two phases whose hardware profiles (sampling a
generator vs training 21 AE lanes) serialize for no reason.  Here the
phases run as decoupled actor pools over the spool queue: generator
members stream ``(source, seq)`` panels in while consumer members pull
and sweep them, so phase 2 starts seconds into phase 1 and a lost
member costs one item, not the pipeline (the highly-parallel-GAN
producer/consumer split of arxiv 2111.04628 + the Podracer supervision
of arxiv 2104.06272).

Determinism contract — the whole point of the plumbing: every item is a
pure function of ``(stream_seed, source, seq)``, every result a pure
function of its item, every artifact atomically published and keyed by
``(source, seq)``.  Therefore ANY interleaving of members, restarts,
kills and resumes assembles the same bytes — kill→resume bit-identity
is pinned by ``python -m hfrep_tpu.resilience selftest`` (ensemble
scenarios) rather than hoped for.

Layout under ``plan.out_dir``::

    _work/queue/        the spool (ready/, claimed/, eof markers)
    _work/snapshots/    generator sub-block ProgressSnapshots
    results/r_<source>_<seq>/   per-item artifacts (atomic dirs)
    pipeline.json       the assembled summary (sources, digests, stats)

Resume: run the same plan with ``resume=True`` — orphaned claims are
requeued, producers fast-forward via their snapshots, consumers skip
published results.  Without ``resume`` a dirty ``_work/`` refuses to
run (mixing two pipelines' state would be silent corruption).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from hfrep_tpu.config import AEConfig
from hfrep_tpu.orchestrate.actors import result_name
from hfrep_tpu.orchestrate.queue import SpoolQueue
from hfrep_tpu.orchestrate.supervisor import ActorSpec, Supervisor
from hfrep_tpu.utils import checkpoint as ckpt

WORK_DIR = "_work"
PLAN_MARKER = "plan.json"        # under results/: which plan produced them


class PipelineStateError(RuntimeError):
    """Dirty state without ``resume=True``, or state belonging to a
    different plan — refuse rather than guess (mixing two pipelines'
    artifacts would be silent corruption)."""


@dataclasses.dataclass(frozen=True)
class SourceSpec:
    """One generator member's stream: ``mode`` "fixture" (deterministic
    synthetic panels — selftest/bench), "gan" (sample a trained
    checkpoint) or "scenario" (one regime's conditional bank blocks —
    the scenario factory fanning a bank out across the actor pool);
    ``params`` feeds the worker's ``_make_generator``."""

    name: str
    mode: str = "fixture"
    params: Optional[dict] = None


@dataclasses.dataclass(frozen=True)
class PipelinePlan:
    """Everything :func:`run_pipeline` needs, picklable end to end."""

    out_dir: str
    sources: Sequence[SourceSpec]
    blocks: int                      # items per source
    consumers: int = 1
    capacity: int = 4                # spool bound (backpressure)
    ae_cfg: AEConfig = AEConfig()
    latent_dims: Sequence[int] = tuple(range(1, 22))
    consume_mode: str = "direct"     # "direct" | "augment"
    cleaned_dir: Optional[str] = None
    stream_seed: int = 0
    platform: Optional[str] = None   # child JAX backend; None = parent's
    drain_timeout: float = 30.0
    max_restarts: int = 3
    timeout: Optional[float] = 600.0


def _resolve_platform(plan: PipelinePlan) -> str:
    if plan.platform:
        return plan.platform
    import jax
    return jax.default_backend()


def _actor_specs(plan: PipelinePlan, paths: dict,
                 obs_root: Optional[Path]) -> List[ActorSpec]:
    platform = _resolve_platform(plan)
    common = {"queue_dir": str(paths["queue"]), "capacity": plan.capacity,
              "platform": platform, "stream_seed": plan.stream_seed}
    specs: List[ActorSpec] = []
    for idx, src in enumerate(plan.sources):
        payload = dict(common)
        payload.update(src.params or {})
        payload.update({"mode": src.mode, "source": src.name,
                        "source_idx": idx, "blocks": plan.blocks,
                        "snapshot_dir": str(paths["snapshots"]),
                        "cleaned_dir": plan.cleaned_dir})
        if obs_root is not None:
            payload["obs_dir"] = str(obs_root / f"gen_{src.name}")
        specs.append(ActorSpec(name=f"gen_{src.name}", role="generator",
                               payload=payload,
                               max_restarts=plan.max_restarts))
    for c in range(plan.consumers):
        payload = dict(common)
        payload.update({"results_dir": str(paths["results"]),
                        "sources": [s.name for s in plan.sources],
                        "ae_cfg": plan.ae_cfg,
                        "latent_dims": list(plan.latent_dims),
                        "consume_mode": plan.consume_mode,
                        "cleaned_dir": plan.cleaned_dir})
        if obs_root is not None:
            payload["obs_dir"] = str(obs_root / f"cons{c}")
        specs.append(ActorSpec(name=f"cons{c}", role="consumer",
                               payload=payload,
                               max_restarts=plan.max_restarts))
    return specs


def _paths(plan: PipelinePlan) -> dict:
    out = Path(plan.out_dir)
    work = out / WORK_DIR
    return {"out": out, "work": work, "queue": work / "queue",
            "snapshots": work / "snapshots", "results": out / "results"}


def _plan_fingerprint(plan: PipelinePlan) -> dict:
    """Everything that determines the artifact BYTES (member counts and
    timeouts deliberately excluded — they change scheduling, not
    results), JSON-normalized for stable comparison."""
    doc = {"sources": [[s.name, s.mode, s.params or {}]
                       for s in plan.sources],
           "blocks": plan.blocks,
           "ae_cfg": list(dataclasses.astuple(plan.ae_cfg)),
           "latent_dims": list(plan.latent_dims),
           "consume_mode": plan.consume_mode,
           "cleaned_dir": plan.cleaned_dir,
           "stream_seed": plan.stream_seed}
    return json.loads(json.dumps(doc, default=str))


def _result_dirs(paths: dict) -> list:
    res = paths["results"]
    if not res.exists():
        return []
    from hfrep_tpu.orchestrate.actors import RESULT_PREFIX
    return sorted(p for p in res.iterdir()
                  if p.is_dir() and p.name.startswith(RESULT_PREFIX))


def _check_plan_marker(plan: PipelinePlan, paths: dict) -> None:
    """Write-or-verify ``results/plan.json``: existing artifacts may only
    be reused (consumers skip published ``(source, seq)`` results by
    name) when they came from THIS plan — a different stream seed or AE
    config silently assembling the previous run's bytes is exactly the
    corruption the resume path must refuse."""
    marker = paths["results"] / PLAN_MARKER
    fp = _plan_fingerprint(plan)
    if marker.exists():
        try:
            have = json.loads(marker.read_text())
        except (OSError, json.JSONDecodeError):
            have = None
        if have != fp:
            raise PipelineStateError(
                f"{paths['results']} holds artifacts from a DIFFERENT "
                "pipeline plan (stream seed / sources / AE config "
                "differ) — remove the out dir or use a fresh one")
        return
    tmp = marker.with_name(marker.name + f".tmp-{os.getpid()}")
    tmp.write_text(json.dumps(fp, indent=2, sort_keys=True))
    os.replace(tmp, marker)


def _heal_corrupt_results(plan: PipelinePlan, paths: dict,
                          queue: SpoolQueue) -> List[str]:
    """Resume-time self-repair: a published result that no longer
    verifies (torn write that survived a crash, bit rot) is deleted and
    its source's block replayed — eof marker and sub-block snapshot
    cleared, so the producer re-delivers every item of the block;
    consumers skip the intact results idempotently and recompute only
    the damaged ones.  Without this a rotted artifact would wedge the
    pipeline permanently (consumers skip by existence, ``assemble``
    raises forever)."""
    from hfrep_tpu.resilience.snapshot import ProgressSnapshot

    healed: List[str] = []
    for src in plan.sources:
        replay = False
        for seq in range(plan.blocks):
            res = paths["results"] / result_name(src.name, seq)
            if not res.exists():
                continue
            try:
                ckpt.verify(res)
            except ckpt.CheckpointCorrupt:
                shutil.rmtree(res, ignore_errors=True)
                healed.append(res.name)
                replay = True
        if replay:
            ProgressSnapshot(paths["snapshots"], fingerprint={},
                             name=f"gen_{src.name}").clear()
            queue.clear_eof(src.name)
    if healed:
        from hfrep_tpu.obs import get_obs
        get_obs().event("result_healed", items=healed)
    return healed


def assemble(plan: PipelinePlan) -> Dict[str, dict]:
    """Verify completeness + integrity of every per-item result and write
    the deterministic ``pipeline.json`` summary (per-item content
    digests, sorted keys — byte-stable across any member interleaving).
    Raises on gaps or corrupt artifacts: an incomplete pipeline must
    never assemble silently."""
    paths = _paths(plan)
    doc: Dict[str, dict] = {}
    for src in plan.sources:
        items = {}
        for seq in range(plan.blocks):
            res = paths["results"] / result_name(src.name, seq)
            meta = ckpt.verify(res)      # raises CheckpointCorrupt on rot
            if meta is None:
                raise PipelineStateError(
                    f"missing result {res.name} — the stream has a gap")
            items[f"{seq:05d}"] = meta["checksum"]["digest"]
        doc[src.name] = {"mode": src.mode, "blocks": plan.blocks,
                         "items": items}
    summary = {"sources": doc, "consume_mode": plan.consume_mode,
               "latent_dims": list(plan.latent_dims)}
    (paths["out"] / "pipeline.json").write_text(
        json.dumps(summary, indent=2, sort_keys=True))
    return summary


def run_pipeline(plan: PipelinePlan, resume: bool = False) -> dict:
    """Drive the fabric end to end; returns ``{"summary", "stats"}``.

    Raises :class:`~hfrep_tpu.resilience.Preempted` on a pod drain (the
    CLI maps it to exit 75; re-run with ``resume=True`` to continue) and
    :class:`~hfrep_tpu.orchestrate.supervisor.OrchestrationError` when
    the fabric cannot make progress.
    """
    from hfrep_tpu.obs import get_obs

    paths = _paths(plan)
    if not resume and (paths["work"].exists() or _result_dirs(paths)):
        raise PipelineStateError(
            f"{plan.out_dir} holds previous pipeline state (_work/ or "
            "published results) — resume=True to continue it, or remove "
            "the out dir for a fresh start")
    for key in ("queue", "snapshots", "results"):
        paths[key].mkdir(parents=True, exist_ok=True)
    _check_plan_marker(plan, paths)

    queue = SpoolQueue(paths["queue"], capacity=plan.capacity)
    if resume:
        # claims orphaned by the killed pod go back on the spool before
        # any member can conclude the stream is complete, and results
        # that no longer verify are deleted with their block scheduled
        # for replay
        queue.requeue_claims(None)
        _heal_corrupt_results(plan, paths, queue)

    obs = get_obs()
    obs_root = (Path(obs.run_dir) / "actors") if obs.enabled else None
    sup = Supervisor(_actor_specs(plan, paths, obs_root), queue,
                     drain_timeout=plan.drain_timeout, timeout=plan.timeout)
    with obs.span("pipeline", sources=len(plan.sources),
                  blocks=plan.blocks, consumers=plan.consumers):
        stats = sup.run()
    summary = assemble(plan)
    # a finished pipeline leaves no live state behind: stale snapshots or
    # eof markers must not fast-forward an unrelated later run
    shutil.rmtree(paths["work"], ignore_errors=True)
    if obs.enabled:
        obs.event("pipeline_complete", restarts=stats["restarts"],
                  secs=stats["secs"])
    return {"summary": summary, "stats": stats}
