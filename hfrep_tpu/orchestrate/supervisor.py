"""The fabric supervisor: spawn, watch, restart, drain.

One parent process owns the member pool (the Podracer supervisor role,
arxiv 2104.06272).  Its contract:

* **losing any member costs one member's in-flight work, not the run** —
  a crashed/killed member's claimed items are requeued, then the member
  is restarted with bounded full-jitter exponential backoff
  (:func:`hfrep_tpu.resilience.backoff_delay` — deterministic backoff
  would march every restarted member back onto shared storage in
  lockstep); after ``max_restarts`` total crashes of one member over
  the run the supervisor gives up loudly (:class:`OrchestrationError`)
  — a member that keeps dying is a bug or a poisoned input, not
  preemption noise, and a run's restart budget should not be unbounded.
* **coordinated drain barrier** — SIGTERM to the supervisor (the pod)
  forwards SIGTERM to every live member; each drains at its item
  boundary (producers with their sub-block snapshot already persisted,
  consumers after publishing the current result) and exits 75.  The
  supervisor waits up to ``drain_timeout`` for the barrier; members
  that fail to arrive (e.g. an injected ``stall@drain_barrier``) are
  escalated with SIGKILL — safe, because every member's durable state
  precedes its barrier crossing — and the supervisor raises
  :class:`~hfrep_tpu.resilience.Preempted` for the CLI's exit 75.
* **deterministic fault surface** — ``kill@actor=N`` in ``HFREP_FAULTS``
  makes the supervisor SIGKILL the producer of the Nth queue item it
  observes (:func:`~hfrep_tpu.resilience.actor_kill_point`): the
  REAL-SIGKILL ensemble scenario the resilience selftest pins.

Telemetry (parent-side, one stream): ``actor_start`` / ``actor_exit`` /
``actor_restart`` / ``drain_barrier`` events, the
``orchestrate/queue_depth`` gauge sampled on change, and the
``orchestrate/actor_restarts`` counter.
"""

from __future__ import annotations

import dataclasses
import multiprocessing as mp
import os
import random
import signal
import time
from typing import Callable, Dict, List, Optional

from hfrep_tpu import resilience
from hfrep_tpu.orchestrate.actors import EXIT_DRAINED, EXIT_GAP, actor_main
from hfrep_tpu.orchestrate.queue import SpoolQueue, _parse_item_name


class OrchestrationError(RuntimeError):
    """The fabric cannot make progress: a member exceeded its restart
    budget, reported an unrecoverable gap, or the run timed out."""


@dataclasses.dataclass
class ActorSpec:
    """One member's identity and spawn payload (payload must pickle —
    the spawn context ships it to a fresh interpreter).  ``env`` entries
    are applied to the child's environment at spawn time (every
    incarnation, restarts included) — how tests aim an ``HFREP_FAULTS``
    plan at ONE member of the pod instead of all of them."""

    name: str
    role: str                    # "generator" | "consumer"
    payload: dict
    max_restarts: int = 3
    env: Optional[dict] = None


class _Member:
    def __init__(self, spec: ActorSpec):
        self.spec = spec
        self.proc: Optional[mp.process.BaseProcess] = None
        self.restarts = 0
        self.done = False
        self.drained = False
        self.restart_at: Optional[float] = None   # pending backoff deadline

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.is_alive()


class Supervisor:
    def __init__(self, specs: List[ActorSpec], queue: SpoolQueue, *,
                 poll: float = 0.05, backoff_base: float = 0.25,
                 backoff_cap: float = 5.0, drain_timeout: float = 30.0,
                 timeout: Optional[float] = 600.0,
                 backoff_rng: Callable[[], float] = random.random):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate actor names: {names}")
        self.specs = list(specs)
        self.queue = queue
        self.poll = float(poll)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.drain_timeout = float(drain_timeout)
        self.timeout = timeout
        self.backoff_rng = backoff_rng
        self._ctx = mp.get_context("spawn")
        self._members: Dict[str, _Member] = {s.name: _Member(s)
                                             for s in self.specs}
        self._seen_items: set = set()
        self._last_depth: Optional[int] = None
        self.total_restarts = 0

    # ------------------------------------------------------------ obs
    def _obs(self):
        from hfrep_tpu.obs import get_obs
        return get_obs()

    # ------------------------------------------------------- lifecycle
    def _start(self, m: _Member) -> None:
        m.proc = self._ctx.Process(
            target=actor_main,
            args=(m.spec.name, m.spec.role, m.spec.payload),
            name=m.spec.name)
        # spawn serializes the parent environment at start(): scoping the
        # member's env overrides around it gives per-actor env without a
        # shell layer (the supervisor loop is single-threaded)
        saved = {}
        for k, v in (m.spec.env or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        try:
            m.proc.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        m.restart_at = None
        self._obs().event("actor_start", actor=m.spec.name,
                          role=m.spec.role, pid=m.proc.pid,
                          restarts=m.restarts)

    def _handle_exit(self, m: _Member, code: int, draining: bool) -> None:
        self._obs().event("actor_exit", actor=m.spec.name, code=code,
                          restarts=m.restarts)
        if code == 0:
            m.done = True
            return
        if code == EXIT_DRAINED:
            # only meaningful mid-drain; a stray 75 outside one is a
            # member that was SIGTERM'd individually — treat as drained
            # too (its state is at a safe boundary by construction)
            m.drained = True
            return
        if draining:
            # exits during the barrier are escalation fodder, not restart
            # (or abort) material: the drain wants the pod DOWN, and a
            # half-drained stream re-checks completeness on resume anyway
            m.drained = True
            return
        if code == EXIT_GAP:
            raise OrchestrationError(
                f"actor {m.spec.name} found an unrecoverable stream gap "
                "(missing results after eof) — aborting the run")
        # crash (includes SIGKILL: negative exitcode)
        m.restarts += 1
        self.total_restarts += 1
        if m.restarts > m.spec.max_restarts:
            raise OrchestrationError(
                f"actor {m.spec.name} crashed {m.restarts} times "
                f"(last exit {code}); restart budget "
                f"{m.spec.max_restarts} exhausted")
        # a dead consumer's claims would deadlock the drained() check —
        # requeue before the restart can matter
        if m.spec.role == "consumer":
            self.queue.requeue_claims(m.spec.name)
        delay = resilience.backoff_delay(m.restarts - 1,
                                         base=self.backoff_base,
                                         cap=self.backoff_cap,
                                         rng=self.backoff_rng)
        m.restart_at = time.monotonic() + delay
        obs = self._obs()
        obs.counter("orchestrate/actor_restarts").inc(actor=m.spec.name)
        obs.event("actor_restart", actor=m.spec.name, exit_code=code,
                  restarts=m.restarts, backoff_s=round(delay, 4))

    def _poll_members(self, draining: bool = False) -> None:
        # exits first, restarts second: a crash handled this pass never
        # respawns in the same pass, even when the jitter draws ~0
        for m in self._members.values():
            if m.proc is not None and not m.proc.is_alive():
                code = m.proc.exitcode
                m.proc = None
                self._handle_exit(m, code if code is not None else 1,
                                  draining)
        if draining:
            return
        for m in self._members.values():
            if (m.restart_at is not None
                    and time.monotonic() >= m.restart_at):
                self._start(m)

    # -------------------------------------------------- fault injection
    def _observe_items(self) -> None:
        """Tick the ``actor`` fault site once per newly observed queue
        item; a firing ``kill`` directive SIGKILLs the item's producer —
        REAL SIGKILL, mid-stream, with its sub-block snapshot on disk."""
        for name in self.queue.ready_names():
            if name in self._seen_items:
                continue
            self._seen_items.add(name)
            if not resilience.actor_kill_point("actor"):
                continue
            parsed = _parse_item_name(name)
            if parsed is None:
                continue
            source = parsed[0]
            for m in self._members.values():
                if (m.spec.role == "generator" and m.alive
                        and m.spec.payload.get("source") == source):
                    self._obs().event("actor_kill_injected",
                                      actor=m.spec.name, item=name,
                                      pid=m.proc.pid)
                    m.proc.kill()            # SIGKILL — no cleanup, no mercy
                    break

    def _sample_depth(self) -> None:
        depth = self.queue.depth()
        if depth != self._last_depth:
            self._last_depth = depth
            self._obs().gauge("orchestrate/queue_depth").set(depth)

    # ------------------------------------------------------------ drain
    def _drain_barrier(self) -> None:
        obs = self._obs()
        live = [m for m in self._members.values() if m.alive]
        obs.event("drain_barrier", phase="begin",
                  members=[m.spec.name for m in live])
        t0 = time.monotonic()
        for m in live:
            try:
                os.kill(m.proc.pid, signal.SIGTERM)
            except (OSError, AttributeError):
                pass
        deadline = t0 + self.drain_timeout
        while (time.monotonic() < deadline
               and any(m.alive for m in self._members.values())):
            self._poll_members(draining=True)
            time.sleep(self.poll)
        self._poll_members(draining=True)
        escalated = []
        for m in self._members.values():
            if m.alive:
                # a member that missed the barrier (hung, stalled): its
                # durable state precedes the barrier crossing, so SIGKILL
                # is safe — resume replays at most its in-flight item
                escalated.append(m.spec.name)
                m.proc.kill()
                m.proc.join(timeout=5.0)
                m.proc = None
        obs.event("drain_barrier", phase="end",
                  drained=[m.spec.name for m in self._members.values()
                           if m.drained or m.done],
                  escalated=escalated,
                  secs=round(time.monotonic() - t0, 4))
        raise resilience.Preempted(
            site="drain_barrier",
            reason=(f"pod drain: {len(escalated)} member(s) escalated"
                    if escalated else "pod drain: all members at barrier"),
            snapshot=str(self.queue.dir))

    # -------------------------------------------------------------- run
    def run(self) -> dict:
        """Supervise until every member completes; raises
        :class:`~hfrep_tpu.resilience.Preempted` on a pod drain and
        :class:`OrchestrationError` on unrecoverable failure."""
        t0 = time.monotonic()
        with resilience.graceful_drain():
            for m in self._members.values():
                self._start(m)
            try:
                while True:
                    resilience.tick("supervise")   # sigterm/preempt site
                    if resilience.drain_requested():
                        self._drain_barrier()      # raises Preempted
                    self._poll_members()
                    self._observe_items()
                    self._sample_depth()
                    if all(m.done for m in self._members.values()):
                        break
                    if (self.timeout is not None
                            and time.monotonic() - t0 > self.timeout):
                        states = {
                            n: ("done" if m.done
                                else "live" if m.alive else "dead")
                            for n, m in self._members.items()}
                        raise OrchestrationError(
                            f"fabric did not complete within "
                            f"{self.timeout}s (members: {states})")
                    time.sleep(self.poll)
            finally:
                # never leak children, whatever tore us out of the loop
                for m in self._members.values():
                    if m.alive:
                        m.proc.kill()
                        m.proc.join(timeout=5.0)
        return {"restarts": self.total_restarts,
                "members": len(self._members),
                "secs": round(time.monotonic() - t0, 4)}
