"""Actor worker processes: the code that runs inside a fabric member.

Each member is an OS process (``multiprocessing`` spawn context — a
fresh interpreter, no forked JAX runtime) executing :func:`actor_main`
with a role and a picklable payload dict:

* **generator** — streams its block of ``(source, seq)`` items into the
  spool queue.  Every item is a pure function of
  ``(stream_seed, source_idx, seq)``, so a restarted member regenerates
  exactly what the killed one would have produced; after every put it
  persists a sub-block :class:`~hfrep_tpu.resilience.snapshot.
  ProgressSnapshot`, so the restart *resumes mid-block* instead of
  replaying delivered items.
* **consumer** — claims items, runs the AE sweep for each, publishes
  the result artifact atomically under ``results/<source>_<seq>``, then
  acks.  Results are keyed by ``(source, seq)`` and the computation is
  a pure function of the item, so reprocessing after a crash (or a
  duplicate delivery) skips work it finds already published —
  idempotence is what turns at-least-once delivery into exactly-once
  results.

Drain contract: SIGTERM (forwarded member-wise by the supervisor's
barrier) sets the drain flag via the member's own
:func:`~hfrep_tpu.resilience.graceful_drain` handler; the loops honor
it at their **item boundary** — the fabric-wide common checkpoint
boundary — then cross the ``drain_barrier`` fault site (where an
injected ``stall`` simulates a member that hangs instead of draining)
and exit :data:`EXIT_DRAINED` (75).  A consumer that proves the stream
complete-with-gaps exits :data:`EXIT_GAP` so the supervisor can abort
loudly instead of assembling a silently incomplete run.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

EXIT_DRAINED = 75        # EX_TEMPFAIL: drained at a safe boundary, resumable
EXIT_GAP = 3             # stream complete but items are missing — fatal

RESULT_PREFIX = "r_"


def result_name(source: str, seq: int) -> str:
    return f"{RESULT_PREFIX}{source}_{seq:05d}"


class QueueGap(RuntimeError):
    """Every source hit eof and the spool is empty, yet results for some
    ``(source, seq)`` pairs are missing — a dropped (e.g. corrupt,
    discarded) item nobody can regenerate at this layer."""


# --------------------------------------------------------------- payloads
def _fixture_panel(stream_seed: int, source_idx: int, seq: int,
                   rows: int, feats: int, rank: int = 3) -> np.ndarray:
    """Deterministic low-rank scaled panel for the fixture source — the
    selftest/bench stand-in for GAN synthesis.  Seeded by the full
    (stream, source, seq) coordinate so every item is unique yet
    reproducible on any member (shared builder: utils/fixture_data)."""
    from hfrep_tpu.utils.fixture_data import keyed_scaled_panel
    return keyed_scaled_panel(stream_seed, source_idx, seq, rows, feats,
                              rank=rank)


def _make_generator(payload: dict):
    """``fn(seq) -> {name: array}`` for the payload's source mode."""
    mode = payload["mode"]
    stream_seed = int(payload.get("stream_seed", 0))
    source_idx = int(payload["source_idx"])
    if mode == "fixture":
        import time

        rows, feats = int(payload["rows"]), int(payload["feats"])
        # models the latency of real GAN sampling (tools/bench_async.py's
        # overlap measurement): pure wall clock, never touches the bytes
        gen_delay = float(payload.get("gen_delay", 0.0))

        def gen(seq: int) -> Dict[str, np.ndarray]:
            if gen_delay > 0.0:
                time.sleep(gen_delay)
            return {"panel": _fixture_panel(stream_seed, source_idx, seq,
                                            rows, feats)}
        return gen
    if mode == "scenario":
        # conditional scenario-bank blocks as pipeline items: each source
        # streams ONE regime's blocks, so a bank's regimes fan out across
        # the actor pool; items stay pure functions of
        # (stream_seed, source, seq) — the regime folds into the block key
        from hfrep_tpu.scenario.conditional import scenario_item_panel

        rows, feats = int(payload["rows"]), int(payload["feats"])
        regime = int(payload["regime"])
        n_regimes = int(payload.get("n_regimes", 3))
        window = int(payload.get("scenario_window", 12))

        def gen(seq: int) -> Dict[str, np.ndarray]:
            return {"panel": scenario_item_panel(
                stream_seed, source_idx, seq, regime=regime,
                n_regimes=n_regimes, rows=rows, feats=feats,
                window=window)}
        return gen
    if mode == "gan":
        # build once per process: a restart pays one rebuild, items after
        # that stream at generate() cost
        from hfrep_tpu.experiments.cli import _make_trainer
        trainer, _, _, _ = _make_trainer(payload["preset"],
                                         payload["cleaned_dir"], quiet=True)
        trainer.restore_checkpoint(payload["checkpoint"])
        n_windows = int(payload["n_gen_windows"])

        def gen(seq: int) -> Dict[str, np.ndarray]:
            cube = trainer.generate_block(seq, n_windows,
                                          stream_seed=stream_seed
                                          + 1009 * source_idx)
            return {"cube": np.asarray(cube)}
        return gen
    raise ValueError(f"unknown generator mode {mode!r}")


def _make_consumer(payload: dict):
    """``fn(source_idx, seq, arrays, tmp_dir) -> None`` writing the item's
    result artifact into ``tmp_dir`` (published atomically around it)."""
    import jax

    from hfrep_tpu.replication import engine as eng

    cfg = payload["ae_cfg"]
    latent_dims = list(payload["latent_dims"])
    mode = payload["consume_mode"]
    if mode == "direct":

        def consume(source_idx: int, seq: int, arrays, tmp_dir: Path) -> None:
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(cfg.seed), source_idx),
                seq)
            out = eng.sweep_item_arrays(key, arrays["panel"], cfg,
                                        latent_dims)
            np.savez(tmp_dir / "sweep.npz", **out)
        return consume
    if mode == "augment":
        from hfrep_tpu.core.data import load_panel
        from hfrep_tpu.experiments.augment import (
            augment_training_set,
            split_cube,
        )
        from hfrep_tpu.experiments.sweep import run_sweep

        panel = load_panel(payload["cleaned_dir"])
        x_train, x_test, y_train, y_test = panel.train_test_split()
        rf_test = panel.rf[x_train.shape[0]:]

        def consume(source_idx: int, seq: int, arrays, tmp_dir: Path) -> None:
            aug = split_cube(arrays["cube"], n_factors=x_train.shape[1],
                             n_hf=y_train.shape[1])
            x_aug, y_aug = augment_training_set(x_train, y_train, aug)
            res = run_sweep(x_aug, y_aug, x_test, y_test, rf_test,
                            panel.factors, cfg, latent_dims,
                            strategy_names=panel.hf_names)
            res.save(str(tmp_dir))
        return consume
    raise ValueError(f"unknown consume mode {mode!r}")


# ------------------------------------------------------------- the loops
def _generator_loop(name: str, payload: dict) -> None:
    from hfrep_tpu import resilience
    from hfrep_tpu.orchestrate.queue import SpoolQueue, item_trace_id
    from hfrep_tpu.resilience.snapshot import ProgressSnapshot

    q = SpoolQueue(payload["queue_dir"], capacity=int(payload["capacity"]))
    source, blocks = payload["source"], int(payload["blocks"])
    stream_seed = int(payload.get("stream_seed", 0))
    snap = ProgressSnapshot(
        payload["snapshot_dir"],
        fingerprint={"source": source, "blocks": blocks,
                     "mode": payload["mode"],
                     "stream_seed": stream_seed},
        name=f"gen_{source}")
    start = 0
    state = snap.load()
    if state is not None:
        start = int(state.get("next", 0))
    gen = _make_generator(payload)
    for seq in range(start, blocks):
        # the trace ID is a pure function of the item coordinate, like
        # the item itself — a restarted member's replayed item carries
        # the SAME id, so the cross-process reconstruction spans the kill
        extra = {"source_idx": int(payload["source_idx"]),
                 "trace": item_trace_id(stream_seed, source, seq)}
        q.put(source, seq, gen(seq), extra_meta=extra)
        snap.save({"next": seq + 1})
        # the sub-block boundary: injected faults fire here, and a
        # requested drain raises with the snapshot already on disk
        resilience.boundary("item")
    q.put_eof(source, blocks)
    snap.save({"next": blocks, "eof": True})


def _missing_results(eofs: Dict[str, int], results_dir: Path) -> List[str]:
    from hfrep_tpu.utils import checkpoint as ckpt

    missing = []
    for source, count in sorted(eofs.items()):
        for seq in range(count):
            res = results_dir / result_name(source, seq)
            if not (res / ckpt.META_NAME).exists():
                missing.append(result_name(source, seq))
    return missing


def _consumer_loop(name: str, payload: dict) -> None:
    import shutil
    import time

    from hfrep_tpu import resilience
    from hfrep_tpu.orchestrate.queue import SpoolQueue
    from hfrep_tpu.utils import checkpoint as ckpt

    q = SpoolQueue(payload["queue_dir"], capacity=int(payload["capacity"]))
    results_dir = Path(payload["results_dir"])
    results_dir.mkdir(parents=True, exist_ok=True)
    sources = list(payload["sources"])
    consume = _make_consumer(payload)
    while True:
        item = q.claim(name)
        if item is None:
            if q.drained(sources):
                missing = _missing_results(q.eof_counts(), results_dir)
                if missing:
                    raise QueueGap(
                        f"stream complete but {len(missing)} results "
                        f"missing: {', '.join(missing[:5])}"
                        + ("..." if len(missing) > 5 else ""))
                return
            # idle poll is also a safe boundary — nothing is claimed
            resilience.boundary("idle")
            time.sleep(q.poll)
            continue
        res_dir = results_dir / result_name(item.source, item.seq)
        trace = item.meta.get("trace")
        # skip only a result that VERIFIES: a duplicate delivery whose
        # published artifact rotted in the meantime is recomputed (same
        # degrade-don't-trust pattern as every snapshot loader here)
        published = (res_dir / ckpt.META_NAME).exists()
        if published:
            try:
                ckpt.verify(res_dir)
            except ckpt.CheckpointCorrupt:
                shutil.rmtree(res_dir, ignore_errors=True)
                published = False
        if not published:
            from hfrep_tpu.obs import get_obs
            arrays = item.arrays()
            source_idx = int(item.meta.get("source_idx", 0))
            # the trace attr stitches this consumer's hop into the item's
            # cross-process critical path (claim → sweep → publish)
            with get_obs().span("item_sweep", trace=trace,
                                source=item.source, seq=item.seq):
                ckpt.write_atomic(
                    res_dir,
                    lambda tmp: consume(source_idx, item.seq, arrays, tmp),
                    metadata={"source": item.source, "seq": item.seq,
                              "trace": trace},
                    io_site="result_save", fault_site="result")
            get_obs().event("result_publish", trace=trace,
                            source=item.source, seq=item.seq)
            get_obs().flush()      # item-granular durability (see queue)
        q.ack(item)
        # the item boundary: result published + claim acked = the common
        # checkpoint boundary every member drains at
        resilience.boundary("item")


# ------------------------------------------------------------- bootstrap
def actor_main(name: str, role: str, payload: dict) -> None:
    """Entry point of a spawned member process.

    Pins the JAX platform before anything initializes it (children must
    match the pod's backend, and a spawned interpreter re-resolves it
    from scratch), opens a per-actor obs session when the supervisor
    handed one down, and maps the drain contract onto exit codes.
    """
    platform = payload.get("platform")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)
    # a spawned member is a fresh interpreter: without the persistent
    # cache every consumer restart re-pays its AE chunk-program compile
    from hfrep_tpu.utils.xla_cache import enable_compilation_cache
    enable_compilation_cache()
    from hfrep_tpu import resilience
    from hfrep_tpu.resilience.drive import DRIVE_REGISTRY, run_drive

    def work() -> int:
        try:
            if role == "generator":
                _generator_loop(name, payload)
            elif role == "consumer":
                _consumer_loop(name, payload)
            else:
                raise ValueError(f"unknown actor role {role!r}")
        except QueueGap as e:
            print(f"{name}: {e}", file=sys.stderr)
            return EXIT_GAP
        return 0

    def on_preempt(e) -> None:
        from hfrep_tpu.obs import get_obs
        get_obs().event("actor_drained", actor=name)
        # the barrier crossing: an injected stall@drain_barrier hangs
        # HERE, driving the supervisor's timeout/escalation path
        resilience.tick("drain_barrier")

    # run_drive maps Preempted→EXIT_DRAINED(75) for the supervisor; the
    # member rides the pipeline spec but drains under its own name, and
    # since ISSUE 20 the session opens INSIDE graceful_drain: a SIGTERM
    # during the member's session bring-up now drains instead of
    # killing the fresh interpreter raw (the corpus-003 class).
    sys.exit(run_drive(DRIVE_REGISTRY["pipeline"], work,
                       obs_dir=payload.get("obs_dir"),
                       session_meta={"command": f"actor:{role}",
                                     "actor": name},
                       drain_hint="",
                       watchdog_name=f"actor {name}", on_preempt=on_preempt))
