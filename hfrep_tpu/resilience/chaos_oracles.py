"""Chaos oracles: the invariants every fault schedule is judged by.

The chaos engine's value is entirely here — random fault schedules are
cheap, *knowing a run went wrong* is the hard part.  Each oracle is a
pure function of on-disk evidence (the subject's ``<out>`` directory,
the recorded subprocess attempts, the reference digests) returning
violations; the set is shared by every subject, so a new subject buys
the whole invariant battery for free:

``exit_contract``
    every attempt exits 0 (complete) or 75 (drained, resumable) — never
    a watchdog kill (wedge), never another code, never a Python
    traceback on an ostensibly clean exit; and a CLEAN attempt (no
    faults armed) must complete — a drive that keeps exiting 75 with no
    fault plan has wedged its own drain flag.
``resume_bit_identical``
    for deterministic subjects, the final ``artifacts/`` digest map of
    the faulted-then-resumed run equals the undisturbed reference's —
    the PR-5/7/9 bit-identity contract, now under *composed* faults.
``artifact_atomicity``
    every ``meta.json``-carrying directory under ``artifacts/``
    checksum-verifies (a crash may cost progress, never a half-published
    artifact).  Skipped when the schedule itself rots final artifacts
    (``torn``/``corrupt`` at ``result``/``bank``): post-publication bit
    rot is the *restore* path's problem, not the writer's.
``zero_silent_drop``
    declared invariant counters conserve: ``terminal == submitted``
    (serving ledger), ``items == expected_items`` (pipeline).
``obs_stream``
    the run's telemetry streams parse (torn final line tolerated —
    that's the documented crash shape); and any drained (exit-75)
    attempt left a crash-forensics bundle (the flight-recorder contract
    behind ``report --crash``).

Violations carry the oracle name + a one-line detail; the shrinker
minimizes against the *same* oracle so a multi-fault schedule cannot
drift onto a different bug while shrinking.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

#: exit codes the contract always allows (sysexits: 0 OK, 75
#: EX_TEMPFAIL/drained).  74 (EX_IOERR, typed persistent-storage
#: failure) is additionally allowed ONLY on attempts whose own armed
#: spec contains ``io_fail`` — the injected burst earned that exit; a
#: clean run has no business dying of I/O
EXIT_OK, EXIT_DRAINED, EXIT_IO = 0, 75, 74
ALLOWED_EXITS = (EXIT_OK, EXIT_DRAINED)

#: post-save sites whose damage lands on FINAL artifacts — rot there is
#: injected after a successful atomic publish, so the digest/atomicity
#: oracles cannot blame the writer and stand down for those schedules
FINAL_ARTIFACT_SITES = ("result", "bank")


@dataclasses.dataclass(frozen=True)
class Violation:
    oracle: str
    detail: str

    def render(self) -> str:
        return f"{self.oracle}: {self.detail}"


@dataclasses.dataclass(frozen=True)
class Attempt:
    """One subject subprocess run as the driver observed it."""

    spec: str              # HFREP_FAULTS armed for this attempt ("" = clean)
    exit_code: Optional[int]   # None = watchdog killed it (wedge)
    secs: float
    stderr_tail: str = ""


# ------------------------------------------------------------- evidence
def digest_map(artifacts_dir) -> Dict[str, str]:
    """sha256 per payload file under ``artifacts/`` (sorted relative
    posix paths, ``meta.json`` excluded — its checksum is the atomicity
    oracle's business, and its key order is not part of the contract)."""
    root = Path(artifacts_dir)
    out: Dict[str, str] = {}
    if not root.exists():
        return out
    for f in sorted(root.rglob("*")):
        if f.is_file() and f.name != "meta.json":
            out[f.relative_to(root).as_posix()] = hashlib.sha256(
                f.read_bytes()).hexdigest()
    return out


def fired_faults(obs_dir) -> List[Tuple[str, str]]:
    """``(kind, site)`` of every injected fault that ACTUALLY fired,
    from the ``fault_injected`` events the plan announces itself with —
    a directive whose occurrence was never reached must not stand any
    oracle down (the schedule says what was *armed*; the stream says
    what *happened*).  Unparseable lines are skipped here — stream
    health has its own oracle."""
    from hfrep_tpu.obs.report import is_stream_file

    out: List[Tuple[str, str]] = []
    root = Path(obs_dir)
    for stream in sorted(root.rglob("events*.jsonl")):
        if not is_stream_file(stream):
            continue
        for line in stream.read_text(errors="replace").splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "event" \
                    and rec.get("name") == "fault_injected":
                out.append((rec.get("kind", ""), rec.get("site", "")))
    return out


def _rots_final_artifacts(fired: Sequence[Tuple[str, str]]) -> bool:
    return any(kind in ("torn", "corrupt")
               and site in FINAL_ARTIFACT_SITES for kind, site in fired)


# -------------------------------------------------------------- oracles
def check_exit_contract(attempts: Sequence[Attempt]) -> List[Violation]:
    out: List[Violation] = []
    for i, a in enumerate(attempts):
        what = f"attempt {i} ({a.spec or 'clean'})"
        allowed = ALLOWED_EXITS + ((EXIT_IO,) if "io_fail@" in a.spec
                                   else ())
        if a.exit_code is None:
            out.append(Violation(
                "exit_contract",
                f"{what} wedged: watchdog killed it after {a.secs:.0f}s"))
        elif a.exit_code not in allowed:
            tail = a.stderr_tail.strip().splitlines()
            hint = f" [{tail[-1]}]" if tail else ""
            out.append(Violation(
                "exit_contract",
                f"{what} exited {a.exit_code}, want one of "
                f"{sorted(allowed)}{hint}"))
        elif "Traceback (most recent call last)" in a.stderr_tail:
            out.append(Violation(
                "exit_contract",
                f"{what} exited {a.exit_code} but printed a traceback — "
                "an error escaped the typed paths"))
    if attempts and attempts[-1].exit_code == EXIT_DRAINED \
            and not attempts[-1].spec:
        out.append(Violation(
            "exit_contract",
            "clean (fault-free) resume still exited 75: the drain flag "
            "or persisted state wedged the drive"))
    return out


def check_resume_bit_identical(ref_digests: Dict[str, str],
                               got_digests: Dict[str, str]) -> List[Violation]:
    if ref_digests == got_digests:
        return []
    missing = sorted(set(ref_digests) - set(got_digests))
    extra = sorted(set(got_digests) - set(ref_digests))
    changed = sorted(k for k in set(ref_digests) & set(got_digests)
                     if ref_digests[k] != got_digests[k])
    parts = []
    if missing:
        parts.append(f"missing {missing[:3]}")
    if extra:
        parts.append(f"unexpected {extra[:3]}")
    if changed:
        parts.append(f"differing {changed[:3]}")
    return [Violation("resume_bit_identical",
                      "artifacts differ from the undisturbed reference: "
                      + "; ".join(parts))]


def check_artifact_atomicity(artifacts_dir) -> List[Violation]:
    from hfrep_tpu.utils import checkpoint as ckpt

    out: List[Violation] = []
    root = Path(artifacts_dir)
    if not root.exists():
        return out
    for meta in sorted(root.rglob(ckpt.META_NAME)):
        try:
            ckpt.verify(meta.parent)
        except ckpt.CheckpointCorrupt as e:
            out.append(Violation(
                "artifact_atomicity",
                f"{meta.parent.name}: published artifact fails its own "
                f"checksum ({e})"))
    return out


def check_zero_silent_drop(result_doc: Optional[dict]) -> List[Violation]:
    if not result_doc:
        return []
    inv = result_doc.get("invariants") or {}
    out: List[Violation] = []
    if "submitted" in inv and "terminal" in inv \
            and inv["terminal"] != inv["submitted"]:
        out.append(Violation(
            "zero_silent_drop",
            f"ledger leaked: terminal {inv['terminal']} != submitted "
            f"{inv['submitted']}"))
    if "items" in inv and "expected_items" in inv \
            and inv["items"] != inv["expected_items"]:
        out.append(Violation(
            "zero_silent_drop",
            f"items leaked: {inv['items']} != expected "
            f"{inv['expected_items']}"))
    return out


def check_obs_stream(obs_dir, any_drained: bool) -> List[Violation]:
    from hfrep_tpu.obs.report import is_stream_file

    out: List[Violation] = []
    root = Path(obs_dir)
    streams = [f for f in sorted(root.rglob("events*.jsonl"))
               if is_stream_file(f)]
    if not streams:
        out.append(Violation("obs_stream",
                             f"no telemetry stream under {root.name}/"))
        return out
    for stream in streams:
        lines = stream.read_text(errors="replace").splitlines()
        # every line but a possibly-torn LAST one must parse — a torn
        # tail is the documented crash shape, torn middles are not
        for i, line in enumerate(lines[:-1] if lines else []):
            if not line.strip():
                continue
            try:
                json.loads(line)
            except json.JSONDecodeError:
                out.append(Violation(
                    "obs_stream",
                    f"{stream.name}:{i + 1} is unparseable mid-stream"))
                break
    if any_drained:
        bundles = [d for d in root.rglob("crash_*")
                   if (d / "crash.json").exists()]
        if not bundles:
            out.append(Violation(
                "obs_stream",
                "a drained (exit 75) attempt left no crash-forensics "
                "bundle"))
    return out


# ------------------------------------------------------------- assembly
def check_run(*, deterministic: bool, attempts: Sequence[Attempt],
              out_dir, ref_digests: Optional[Dict[str, str]],
              result_doc: Optional[dict]) -> List[Violation]:
    """The full battery over one driven schedule.  Artifact-level
    oracles only run when the final attempt completed (exit 0): an
    honest wedge/exit violation already explains a missing artifact."""
    out = Path(out_dir)
    violations = check_exit_contract(attempts)
    completed = bool(attempts) and attempts[-1].exit_code == 0
    if completed:
        if result_doc is None:
            violations.append(Violation(
                "exit_contract",
                "exit 0 without publishing chaos_result.json"))
        violations += check_zero_silent_drop(result_doc)
        if not _rots_final_artifacts(fired_faults(out / "obs")):
            violations += check_artifact_atomicity(out / "artifacts")
            if deterministic and ref_digests is not None:
                violations += check_resume_bit_identical(
                    ref_digests, digest_map(out / "artifacts"))
    any_drained = any(a.exit_code == EXIT_DRAINED for a in attempts)
    violations += check_obs_stream(out / "obs", any_drained)
    return violations
