"""The Drive runtime: ONE declarative fault-tolerant envelope for every
long-running workload (ROADMAP item 5).

Every long-running entry point used to re-implement the same survival
envelope by hand — obs session + graceful drain + exit-75/74 contract +
crash bundling + watchdog — and PRs 11/14/15 each grew analyzer rules
(HF007) or chaos fixes precisely because that envelope was copy-pasted;
the PR-15 soak even caught a drive dying raw because one ``with
session`` line sat outside a try (corpus entry 003), and corpus entry
007 pinned the session-boundary EIO class the body-level handlers
cannot see.  This module provides the envelope exactly once:

* :class:`DriveSpec` — the declaration: name, family, boundary sites,
  snapshot kind, watchdog budget, chaos fixture binding, fault-site
  hints, drain hint;
* :func:`run_drive` — the runtime: ``graceful_drain`` OUTERMOST (the
  obs session opens *inside* it, so a SIGTERM during the session's
  first stream append drains instead of killing the process raw — the
  corpus-003 bug class dead by construction), the per-drive
  :func:`~hfrep_tpu.resilience.watchdog` (closing the GanTrainer /
  scenario-bank watchdog gap), ``drive_start``/``drive_exit`` events +
  ``drive/*`` gauges, Preempted → ``bundle_if_enabled`` → exit 75
  (EX_TEMPFAIL), persistent-storage OSError → exit 74 (EX_IOERR) —
  including at the session boundary itself (corpus-007);
* :func:`drive_boundary` — the boundary crossing for new workloads:
  wall-clock ledger window flush + ``drive_boundary`` event + the
  resilience boundary (fault injection + drain);
* :data:`DRIVE_REGISTRY` — every registered spec.  The chaos subject
  list (:mod:`hfrep_tpu.resilience.chaos_subjects`) derives from this
  registry, so a new workload registered here is born chaos-covered —
  "new drive without chaos coverage" is a test failure
  (tests/test_drive.py, the PR-16 ``PROGRAM_BOUNDARIES`` pattern), not
  a review catch.

Registering a new workload is ~a page: write a fixture function
``run(out: Path, fixture_seed: int, resume: bool) -> dict`` (fixture
shapes, deterministic artifacts under ``out/'artifacts'``), declare a
:class:`DriveSpec` naming it, and route the production entry point
through ``run_drive(spec, work, ...)``.  Everything else — drain,
watchdog, typed exits, forensics, chaos soak membership — is derived.

Import-light on purpose: no jax, no obs at module top — the registry
must be listable (``python -m hfrep_tpu.resilience drives``) and
auditable from CI without paying a backend init.
"""

from __future__ import annotations

import dataclasses
import importlib
import os
import sys
from typing import Callable, Dict, Optional, Tuple

#: EX_TEMPFAIL — drained at a safe boundary with state persisted;
#: re-running (with resume where the drive supports it) continues.
EXIT_DRAINED = 75

#: EX_IOERR — persistent storage failure: an EIO burst outlasting the
#: bounded retry policy at a write the drive cannot proceed without.
EXIT_IO = 74

#: every drive runs under a watchdog; a spec without its own budget gets
#: this generous ceiling (a wedged boundary fails LOUDLY inside a day,
#: instead of silently eating a fleet slot forever).
DEFAULT_WATCHDOG_SECS = 24 * 3600.0

#: env override for the watchdog budget (seconds; ``0`` disarms — the
#: escape hatch for legitimately unbounded runs).
ENV_WATCHDOG = "HFREP_DRIVE_WATCHDOG"

#: the six production drive families (plus ``telemetry`` for the fleet
#: rollup loop and ``canary`` for the chaos engine's planted subject) —
#: tests/test_drive.py asserts the registry covers all six.
FAMILIES = ("trainer", "engine", "walkforward", "orchestrate", "serve",
            "scenario")


@dataclasses.dataclass(frozen=True)
class DriveSpec:
    """One declared long-running workload.

    ``fixture`` is a lazy ``"module:function"`` binding to the drive's
    chaos fixture (``run(out, fixture_seed, resume) -> dict`` of
    invariant counters) — a dotted string so the registry imports
    nothing heavy until a subject actually runs.
    """

    name: str
    family: str                          # FAMILIES + telemetry/canary
    fixture: str                         # "pkg.mod:func" chaos binding
    timeout: float                       # chaos watchdog budget, seconds
    # sites the drive crosses; [0] is the CANONICAL drain boundary —
    # the one a pod-level SIGTERM reaches (tests/test_drive.py's drain
    # leg injects there; for a supervised fabric that is the
    # supervisor's own loop, not a member's item boundary)
    boundary_sites: Tuple[str, ...] = ()
    snapshot: str = "none"               # chunk|checkpoint|progress|blocks|queue|none
    deterministic: bool = True           # artifacts bit-identical on resume
    resumable: bool = True               # a 75 can be continued
    double_buffer: bool = False          # ISSUE-19 Mode A/B capable
    tier: str = "fast"                   # fast|slow|test (soak membership)
    hint_sites: Tuple[str, ...] = ()     # schedule-generator bias
    watchdog_secs: Optional[float] = None  # production budget (None=default)
    drain_hint: str = ""                 # appended to the exit-75 message
    description: str = ""

    def load_fixture(self) -> Callable:
        mod, _, fn = self.fixture.partition(":")
        return getattr(importlib.import_module(mod), fn)


DRIVE_REGISTRY: Dict[str, DriveSpec] = {}


def register_drive(spec: DriveSpec) -> DriveSpec:
    if spec.name in DRIVE_REGISTRY:
        raise ValueError(f"drive {spec.name!r} already registered")
    DRIVE_REGISTRY[spec.name] = spec
    return spec


def resolve_watchdog(spec: DriveSpec,
                     override: Optional[float] = None) -> float:
    """The per-drive budget: explicit caller override, else the
    ``HFREP_DRIVE_WATCHDOG`` env knob, else the spec's own budget, else
    :data:`DEFAULT_WATCHDOG_SECS`.  ``0`` disarms (setitimer(0))."""
    if override is not None:
        return float(override)
    env = os.environ.get(ENV_WATCHDOG)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if spec.watchdog_secs is not None:
        return float(spec.watchdog_secs)
    return DEFAULT_WATCHDOG_SECS


def run_drive(spec: DriveSpec, work: Callable[[], Optional[int]], *,
              obs_dir=None, session_meta: Optional[dict] = None,
              drain_hint: Optional[str] = None,
              watchdog_secs: Optional[float] = None,
              watchdog_name: Optional[str] = None,
              on_preempt: Optional[Callable] = None) -> int:
    """Run ``work`` under the full envelope; return the process exit
    code (``work``'s own int return passes through; 0 when it returns
    None).

    Structure — load-bearing, pinned by the chaos corpus:

    * ``graceful_drain`` wraps the WHOLE run, the obs session open
      included: a SIGTERM landing during the session's first stream
      append (before any drive installed a handler) must set the drain
      flag, not kill the process raw (corpus entry 003);
    * the watchdog is armed around ``work`` for EVERY drive — a wedged
      boundary raises :class:`~hfrep_tpu.resilience.WatchdogTimeout`
      loudly (and the escaping exception lands a crash bundle via the
      session) instead of eating the caller's budget;
    * Preempted → ``crash.bundle_if_enabled`` (drain forensics) →
      ``drain_hint`` on stderr → 75;
    * OSError in the body → bundle → 74; OSError at the SESSION boundary
      (enable's manifest write, the close-path flush) → 74 as well — the
      body-level handler cannot see it because the ``with session`` line
      sits outside its try (corpus entry 007);
    * ``drive_start``/``drive_exit`` events and the ``drive/secs`` gauge
      bracket the run inside the session.

    ``on_preempt(exc)`` runs inside the session after the bundle — the
    hook for drive-specific drain tails (the orchestrate members emit
    ``actor_drained`` and cross the ``drain_barrier`` stall site).
    """
    import hfrep_tpu.obs as obs_pkg
    from hfrep_tpu import resilience
    from hfrep_tpu.obs import get_obs, timeline

    meta = dict(session_meta or {})
    meta.setdefault("command", spec.name)
    budget = resolve_watchdog(spec, watchdog_secs)
    hint = drain_hint if drain_hint is not None else (spec.drain_hint or "")
    wname = watchdog_name or f"drive {spec.name}"
    with resilience.graceful_drain():
        code = 0
        try:
            with obs_pkg.session(obs_dir, **meta):
                obs = get_obs()
                t0 = timeline.clock()
                if obs.enabled:
                    obs.event("drive_start", drive=spec.name,
                              family=spec.family,
                              watchdog_secs=round(budget, 3))
                try:
                    with resilience.watchdog(budget, wname):
                        code = int(work() or 0)
                except resilience.Preempted as e:
                    from hfrep_tpu.obs.crash import bundle_if_enabled
                    bundle_if_enabled(e)   # drain forensics (HF007)
                    if on_preempt is not None:
                        on_preempt(e)
                    tail = f"; {hint}" if hint else ""
                    print(f"preempted: {e}{tail}", file=sys.stderr)
                    code = EXIT_DRAINED
                except OSError as e:
                    # persistent storage failure: an I/O error that
                    # outlasted the bounded retry policy at a REQUIRED
                    # write.  Typed 74 (EX_IOERR), never a traceback;
                    # the chaos oracle accepts it only on attempts whose
                    # own schedule armed io_fail.
                    from hfrep_tpu.obs.crash import bundle_if_enabled
                    bundle_if_enabled(e)
                    print(f"{spec.name}: storage failed persistently: {e}",
                          file=sys.stderr)
                    code = EXIT_IO
                if obs.enabled:
                    obs.event("drive_exit", drive=spec.name, code=code)
                    obs.gauge("drive/secs").set(
                        round(timeline.clock() - t0, 4), drive=spec.name)
        except OSError as e:
            # the SESSION boundary itself died of storage (corpus 007):
            # enable()'s initial manifest write raised through the
            # bounded retry, or the close-path flush did.
            print(f"{spec.name}: telemetry storage failed persistently "
                  f"at the session boundary: {e}", file=sys.stderr)
            code = EXIT_IO
        return code


# per-drive window start for drive_boundary's ledger flush
_WINDOW_T0: Dict[str, float] = {}


def drive_boundary(spec: DriveSpec, site: str,
                   steps: Optional[int] = None) -> None:
    """The envelope's boundary crossing for NEW workloads: flush the
    wall-clock ledger window accumulated since the previous crossing
    (ISSUE 18 — Σ(categories) == wall on every window), emit one
    ``drive_boundary`` event, then cross the resilience boundary (fault
    injection fires; a requested drain raises Preempted).  Migrated
    drives keep their own historical boundary/ledger calls — their
    trajectories are pinned bit-identical."""
    from hfrep_tpu import resilience
    from hfrep_tpu.obs import get_obs, timeline

    now = timeline.clock()
    t0 = _WINDOW_T0.get(spec.name)
    _WINDOW_T0[spec.name] = now
    obs = get_obs()
    if obs.enabled:
        if t0 is not None:
            timeline.flush_window(now - t0, drive=spec.name, steps=steps)
        obs.event("drive_boundary", drive=spec.name, site=site, steps=steps)
        obs.counter("drive/boundaries").inc(drive=spec.name, site=site)
    resilience.boundary(site)


def spec_capabilities(spec: DriveSpec) -> dict:
    """The machine-readable row behind ``resilience drives``."""
    return {
        "name": spec.name, "family": spec.family,
        "fixture": spec.fixture, "timeout": spec.timeout,
        "boundary_sites": list(spec.boundary_sites),
        "snapshot": spec.snapshot,
        "deterministic": spec.deterministic,
        "resumable": spec.resumable,
        "double_buffer": spec.double_buffer,
        "tier": spec.tier,
        "hint_sites": list(spec.hint_sites),
        "watchdog_secs": (spec.watchdog_secs
                          if spec.watchdog_secs is not None
                          else DEFAULT_WATCHDOG_SECS),
        "description": spec.description,
    }


def check_registry() -> Tuple[bool, list]:
    """The CI completeness gate (``resilience drives --check``): every
    spec's fixture resolves, its sites are registered fault sites, the
    six production families are covered, and the chaos subject registry
    mirrors this one in BOTH directions (the PR-16 pattern).  Returns
    ``(ok, problems)``; jax-free."""
    from hfrep_tpu.resilience import faults
    from hfrep_tpu.resilience.chaos_subjects import SUBJECTS

    problems = []
    known = (set(faults.BOUNDARY_SITES) | set(faults.IO_SITES)
             | set(faults.POST_SAVE_SITES) | set(faults.ACTOR_SITES))
    for name, spec in DRIVE_REGISTRY.items():
        try:
            fn = spec.load_fixture()
            if not callable(fn):
                problems.append(f"{name}: fixture {spec.fixture!r} is "
                                "not callable")
        except Exception as e:
            problems.append(f"{name}: fixture {spec.fixture!r} does not "
                            f"resolve: {type(e).__name__}: {e}")
        for site in tuple(spec.boundary_sites) + tuple(spec.hint_sites):
            if site not in known:
                problems.append(f"{name}: unknown fault site {site!r}")
        if spec.family not in FAMILIES + ("telemetry", "canary"):
            problems.append(f"{name}: unknown family {spec.family!r}")
    covered = {s.family for s in DRIVE_REGISTRY.values()}
    for fam in FAMILIES:
        if fam not in covered:
            problems.append(f"drive family {fam!r} has no registered spec")
    reg, subj = set(DRIVE_REGISTRY), set(SUBJECTS)
    if reg - subj:
        problems.append(f"specs without chaos subjects: {sorted(reg - subj)}")
    if subj - reg:
        problems.append(f"chaos subjects without specs: {sorted(subj - reg)}")
    return (not problems), problems


# ------------------------------------------------------------- registry
# Spec names are stable API: the committed chaos corpus
# (resilience/_chaos_corpus/) and the kill/resume/drain oracle harness
# (tests/test_drive.py) key on them.
_FX = "hfrep_tpu.resilience.drive_fixtures"

register_drive(DriveSpec(
    name="ae_sweep", family="engine", fixture=f"{_FX}:run_ae_sweep",
    timeout=75.0, boundary_sites=("chunk",), snapshot="chunk",
    double_buffer=True,
    hint_sites=("chunk", "snapshot_save", "snapshot", "obs_append",
                "result_save", "manifest"),
    drain_hint="re-run the same command to resume from the last chunk",
    description="chunked AE latent sweep (engine _drive_chunks; "
                "CLI `sweep`)"))

register_drive(DriveSpec(
    name="ae_multi", family="engine", fixture=f"{_FX}:run_ae_multi",
    timeout=75.0, boundary_sites=("chunk",), snapshot="chunk",
    double_buffer=True,
    hint_sites=("chunk", "snapshot_save", "snapshot", "result_save",
                "obs_append"),
    description="padded multi-dataset AE fabric (ragged rows via the "
                "mask operand)"))

register_drive(DriveSpec(
    name="ae_mesh", family="engine", fixture=f"{_FX}:run_ae_mesh",
    timeout=75.0, boundary_sites=("chunk",), snapshot="chunk",
    double_buffer=True,
    hint_sites=("chunk", "snapshot_save", "snapshot", "result_save",
                "obs_append"),
    description="multi-dataset fabric through the unified partition-rule "
                "mesh launch (1x1 dp mesh, identical program)"))

register_drive(DriveSpec(
    name="gan_ckpt", family="trainer", fixture=f"{_FX}:run_gan_ckpt",
    timeout=120.0, boundary_sites=("block",), snapshot="checkpoint",
    hint_sites=("block", "ckpt_save", "ckpt", "obs_append", "manifest",
                "result_save"),
    drain_hint="re-run with --resume to continue",
    description="GAN block loop with periodic checkpoints + "
                "torn/corrupt-walk restore (CLI `train-gan`)"))

register_drive(DriveSpec(
    name="serve_load", family="serve", fixture=f"{_FX}:run_serve_load",
    timeout=90.0, boundary_sites=("serve_drive",), snapshot="none",
    deterministic=False, resumable=False,
    hint_sites=("serve_worker", "serve_result", "batcher", "serve_drive",
                "obs_append"),
    description="serving lifecycle shell: admission/shed/drain with the "
                "zero-silent-drop ledger (CLI `serve`)"))

register_drive(DriveSpec(
    name="walkforward", family="walkforward",
    fixture=f"{_FX}:run_walkforward", timeout=120.0,
    boundary_sites=("chunk", "window"), snapshot="progress",
    hint_sites=("chunk", "window", "snapshot_save", "snapshot",
                "result_save", "obs_append"),
    drain_hint="re-run with --resume to continue (published "
               "blocks/windows are kept and verified)",
    description="walk-forward regime sweep: chunk-snapshot training, "
                "window-granular scoring (CLI `scenario`)"))

register_drive(DriveSpec(
    name="scenario_bank", family="scenario",
    fixture=f"{_FX}:run_scenario_bank", timeout=120.0,
    boundary_sites=("gan_block", "bank_block"), snapshot="blocks",
    hint_sites=("gan_block", "bank_block", "bank_save", "bank",
                "obs_append", "manifest"),
    drain_hint="re-run with --resume to continue (published "
               "blocks/windows are kept and verified)",
    description="conditional-GAN train + deterministic scenario bank "
                "(block-granular resume; CLI `scenario --mode bank`)"))

register_drive(DriveSpec(
    name="rollup", family="telemetry", fixture=f"{_FX}:run_rollup",
    timeout=60.0, boundary_sites=("item",), snapshot="progress",
    hint_sites=("item", "rollup_publish", "obs_append"),
    description="fleet telemetry retention loop: append/rotate/compact "
                "against the durable cursor (jax-free)"))

register_drive(DriveSpec(
    name="pipeline", family="orchestrate", fixture=f"{_FX}:run_pipeline",
    timeout=240.0, tier="slow",
    boundary_sites=("supervise", "item", "idle", "drain_barrier"),
    snapshot="queue",
    hint_sites=("item", "idle", "actor", "queue_put", "queue_get",
                "queue_item", "result", "result_save", "snapshot_save",
                "drain_barrier"),
    drain_hint="re-run with --resume to continue from the drained state",
    description="async actor fabric end to end: supervisor + spawned "
                "members over the spool queue (CLI `pipeline`)"))

register_drive(DriveSpec(
    name="_planted", family="canary", fixture=f"{_FX}:run_planted",
    timeout=15.0, tier="test", boundary_sites=("item",),
    hint_sites=("item", "result_save"),
    description="the chaos engine's canary: a deliberate swallowed-EIO "
                "silent drop the search must find (never soaked)"))
