"""Deterministic fault injection: the ``HFREP_FAULTS`` spec.

On preemptible TPU fleets the failure modes that matter — SIGTERM at an
arbitrary point, torn checkpoint writes, flaky host-side storage — are
exactly the ones a normal test run never exercises.  This module makes
them *injectable on purpose*, deterministically, from one env variable,
so kill→resume and corrupt→fallback paths can be driven end to end by
``python -m hfrep_tpu.resilience selftest`` and by tier-1 tests.

Spec grammar (semicolon-separated directives)::

    HFREP_FAULTS = directive [';' directive]*
    directive    = kind '@' site '=' N ['x' COUNT]

``N`` is the 1-based occurrence of ``site`` that triggers the fault;
``x COUNT`` fires it on that and the next ``COUNT - 1`` occurrences
(default 1).  Kinds and the sites they apply to:

======== ===================== ==========================================
kind     sites                 effect at the Nth occurrence
======== ===================== ==========================================
sigterm  boundary              a REAL ``os.kill(getpid(), SIGTERM)`` —
         (:data:`BOUNDARY_    caught by the graceful-drain handler.
         SITES`) or io         Also valid at io sites: the signal then
                               lands DURING that host I/O call (e.g.
                               ``sigterm@snapshot_save=1`` = SIGTERM
                               mid-way through the final drain snapshot)
preempt  boundary, io or       set the drain flag directly (no signal)
         actor
stall    boundary              sleep :data:`STALL_SECS` at the boundary —
                               a member that hangs instead of draining
                               (drives the supervisor's drain-barrier
                               timeout/escalation path); at ``batcher``
                               it wedges the serving layer's batch
                               formation, turning queued requests into
                               a deadline storm the batcher must cancel
                               typed (never dispatch-and-forget)
io_fail  io (:data:`IO_SITES`: raise ``OSError(EIO)`` from that I/O call
         ``ckpt_save``,        (at ``serve_result``: the server's
         ``snapshot_save``,    result-publish boundary — the request
         ``result_save``,      must fail TYPED, never silently)
         ``bank_save``,
         ``obs_append``,
         ``manifest``,
         ``queue_put``,
         ``queue_get``,
         ``serve_result``)
torn     post-save             truncate the just-written payload — a
         (:data:`POST_SAVE_    torn write that survived the process
         SITES`: ``ckpt``,
         ``snapshot``,
         ``queue_item``,
         ``result``, ``bank``)
corrupt  post-save             flip bytes mid-payload (bit rot)
kill     actor (:data:`ACTOR_  tell the caller that owns the victim to
         SITES`: ``actor``,    kill it: the orchestration supervisor
         ``serve_worker``)     SIGKILLs the actor behind the Nth
                               observed queue item
                               (:func:`FaultPlan.actor` returns True;
                               only the supervisor knows the pids), the
                               replication server kills the worker
                               thread holding the Nth dispatched batch
                               mid-flight (its requests must still
                               reach typed terminal outcomes)
======== ===================== ==========================================

The full per-group site vocabulary lives in the module-level registries
:data:`BOUNDARY_SITES` / :data:`IO_SITES` / :data:`POST_SAVE_SITES` /
:data:`ACTOR_SITES` — the single source of truth the static analyzer
(HF002) round-trips every hook call and spec literal against.

Examples::

    HFREP_FAULTS='sigterm@chunk=2'            # kill at the 2nd chunk boundary
    HFREP_FAULTS='io_fail@ckpt_save=1x2'      # first two save calls fail
    HFREP_FAULTS='torn@ckpt=3;preempt@block=5'
    HFREP_FAULTS='kill@actor=2'               # SIGKILL the producer of the
                                              # 2nd queue item the supervisor
                                              # observes

Occurrence counters live on the :class:`FaultPlan` instance, keyed by
(hook group, site), so a plan's behavior is a pure function of the spec
and the sequence of hook calls — no randomness, no wall clock.
"""

from __future__ import annotations

import dataclasses
import difflib
import errno
import os
import re
import signal
import time
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

BOUNDARY_KINDS = ("sigterm", "preempt", "stall")
IO_KINDS = ("io_fail",)
POST_SAVE_KINDS = ("torn", "corrupt")
ACTOR_KINDS = ("kill",)
KINDS = BOUNDARY_KINDS + IO_KINDS + POST_SAVE_KINDS + ACTOR_KINDS

#: THE site registry — every site each hook group fires at, one tuple per
#: group.  This is the round-trip contract the cross-layer analyzer
#: (rule HF002) enforces in both directions: a site string at an
#: injection/hook call (``resilience.boundary("chunk")``,
#: ``write_atomic(..., io_site="ckpt_save")``) or inside an
#: ``HFREP_FAULTS`` spec must appear here, and an entry here that no
#: hook call references is a dead registry row.  A typo'd site would
#: otherwise just never fire — the silently-disarmed-injection failure
#: mode — so :meth:`FaultPlan.parse` also rejects unknown sites at
#: runtime.
BOUNDARY_SITES = (
    "chunk",          # chunked AE engine / scenario training chunk boundary
    "block",          # GAN trainer / multi-seed epoch-block boundary
    "window",         # walk-forward scoring-window boundary
    "item",           # actor produce/consume item boundary
    "idle",           # actor idle-poll boundary
    "supervise",      # orchestration supervisor poll loop
    "drain_barrier",  # coordinated pod-drain barrier crossing
    "batcher",        # serving micro-batch formation loop
    "serve_drive",    # serving selftest drive loop
    "gan_block",      # conditional-GAN bank training block
    "bank_block",     # stress-bank block publication boundary
)
IO_SITES = (
    "ckpt_save",      # checkpoint directory writes (utils/checkpoint.py)
    "snapshot_save",  # chunk/sub-block resume snapshots
    "result_save",    # actor result artifact publication
    "bank_save",      # scenario stress-bank block publication
    "obs_append",     # telemetry event-stream appends
    "manifest",       # run.json manifest writes
    "queue_put",      # spool-queue item publication
    "queue_get",      # spool-queue item claim/read
    "serve_result",   # serving result-publish boundary
    "rollup_publish",  # rollup state/seed/pinned atomic publication
)
POST_SAVE_SITES = (
    "ckpt",           # a published checkpoint directory
    "snapshot",       # a published resume snapshot
    "queue_item",     # a published spool-queue item
    "result",         # a published actor result artifact
    "bank",           # a published stress-bank block
)
ACTOR_SITES = (
    "actor",          # orchestration fabric members (supervisor SIGKILLs)
    "serve_worker",   # serving dispatch worker threads
)
#: every site any hook may be called with; boundary kinds (sigterm /
#: preempt / stall) may target io and actor sites too (the signal lands
#: during that I/O call / at that observed item)
KNOWN_SITES = BOUNDARY_SITES + IO_SITES + POST_SAVE_SITES + ACTOR_SITES

#: how long an injected ``stall`` holds its boundary — long enough that
#: any realistic drain-barrier timeout fires first (the stalled member is
#: then escalated/SIGKILLed; it never wakes up to matter), short enough
#: that a misconfigured test cannot hang CI forever.  Read at fire time,
#: so in-process drivers that stall a *thread* they cannot escalate (the
#: serving chaos scenario stalls the batcher to manufacture a deadline
#: storm) shorten it for the scenario's scope and restore it after.
STALL_SECS = 120.0

_DIRECTIVE_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<site>[a-z_]+)=(?P<n>[0-9]+)(?:x(?P<count>[0-9]+))?$")


class FaultSpecError(ValueError):
    """An ``HFREP_FAULTS`` spec that does not parse."""


#: which sites each kind can actually FIRE at — the hook dispatch above,
#: as data.  Boundary kinds fire at boundary, io and actor sites (the
#: signal lands between chunks, mid-I/O, or at an observed item); the
#: other kinds are hook-specific.  :meth:`FaultPlan.parse` rejects a
#: directive outside its kind's reach: such a spec would parse, never
#: fire, and read as "the system survived" — the silently-disarmed
#: injection again, one level up from an unknown site.
def kind_sites(kind: str) -> Tuple[str, ...]:
    if kind in BOUNDARY_KINDS:
        return BOUNDARY_SITES + IO_SITES + ACTOR_SITES
    if kind in IO_KINDS:
        return IO_SITES
    if kind in POST_SAVE_KINDS:
        return POST_SAVE_SITES
    if kind in ACTOR_KINDS:
        return ACTOR_SITES
    return ()


def site_group(site: str) -> str:
    """The occurrence-counter group a directive at ``site`` ticks
    against (boundary kinds at an io site count io occurrences)."""
    if site in BOUNDARY_SITES:
        return "boundary"
    if site in IO_SITES:
        return "io"
    if site in POST_SAVE_SITES:
        return "post_save"
    return "actor"


#: one-line effect summaries, keyed by kind — the ``explain-faults``
#: CLI's rendering vocabulary (the long-form table lives in the module
#: docstring)
KIND_EFFECTS = {
    "sigterm": "REAL os.kill(SIGTERM) -> graceful-drain handler",
    "preempt": "set the drain flag directly (no signal)",
    "stall": f"sleep STALL_SECS ({STALL_SECS:.0f}s) at the site",
    "io_fail": "raise OSError(EIO) from that host I/O call",
    "torn": "truncate the just-published payload to half",
    "corrupt": "XOR-flip bytes mid-payload (bit rot)",
    "kill": "caller SIGKILLs the actor/worker behind the occurrence",
}


@dataclasses.dataclass(frozen=True)
class Directive:
    kind: str
    site: str
    n: int            # 1-based occurrence that triggers
    count: int = 1    # consecutive occurrences that fire

    def hits(self, occurrence: int) -> bool:
        return self.n <= occurrence < self.n + self.count

    def spec(self) -> str:
        """The directive back in ``HFREP_FAULTS`` grammar — the shrink
        loop re-emits reduced plans through this, so a minimal repro is
        always a paste-able spec."""
        return f"{self.kind}@{self.site}={self.n}" + (
            f"x{self.count}" if self.count != 1 else "")


class FaultPlan:
    """A parsed spec plus its per-(hook group, site) occurrence counters."""

    def __init__(self, directives: Iterable[Directive]):
        self.directives: Tuple[Directive, ...] = tuple(directives)
        self._counts: Dict[Tuple[str, str], int] = {}

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        directives = []
        for part in filter(None, (s.strip() for s in spec.split(";"))):
            m = _DIRECTIVE_RE.match(part)
            if m is None:
                raise FaultSpecError(
                    f"bad fault directive {part!r} (want kind@site=N[xCOUNT])")
            kind = m.group("kind")
            if kind not in KINDS:
                raise FaultSpecError(
                    f"unknown fault kind {kind!r} (one of {', '.join(KINDS)})")
            site = m.group("site")
            if site not in KNOWN_SITES:
                # an unknown site would parse fine and then never fire —
                # the silently-disarmed injection the registry exists to
                # prevent; fail the spec as loudly as an unknown kind,
                # and name the registry's nearest candidates (a repro
                # line with one typo should correct itself in one paste)
                near = difflib.get_close_matches(site, KNOWN_SITES, n=3,
                                                 cutoff=0.4)
                hint = (f"did you mean {', '.join(near)}? " if near else "")
                raise FaultSpecError(
                    f"unknown fault site {site!r} — {hint}(registry: "
                    f"{', '.join(KNOWN_SITES)})")
            if site not in kind_sites(kind):
                # parses, but the dispatching hook would never match it:
                # e.g. io_fail@chunk or torn@actor can't fire by
                # construction — reject as loudly as an unknown site
                raise FaultSpecError(
                    f"{part!r}: kind {kind!r} never fires at site "
                    f"{site!r} (valid sites: "
                    f"{', '.join(kind_sites(kind))})")
            n = int(m.group("n"))
            if n < 1:
                raise FaultSpecError(f"{part!r}: N is 1-based, got {n}")
            directives.append(Directive(kind=kind, site=site, n=n,
                                        count=int(m.group("count") or 1)))
        return cls(directives)

    def spec(self) -> str:
        """The plan back in ``HFREP_FAULTS`` grammar (round-trips through
        :meth:`parse`)."""
        return ";".join(d.spec() for d in self.directives)

    def _tick(self, group: str, site: str) -> int:
        key = (group, site)
        self._counts[key] = occ = self._counts.get(key, 0) + 1
        return occ

    def _matching(self, kinds: Tuple[str, ...], site: str, occ: int):
        for d in self.directives:
            if d.site == site and d.kind in kinds and d.hits(occ):
                yield d

    def _fire_signalish(self, d: Directive, site: str, occ: int) -> None:
        """The sigterm/preempt/stall effects, shared by the boundary and
        io hooks (a SIGTERM can land mid-I/O just as well as between
        chunks — the drain-during-final-checkpoint scenario)."""
        if d.kind == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif d.kind == "stall":
            time.sleep(STALL_SECS)
        else:
            from hfrep_tpu import resilience
            resilience.request_drain(f"injected preempt@{site}={occ}")

    # ------------------------------------------------------------- hooks
    def boundary(self, site: str) -> None:
        """Called by the drives at each ``site`` boundary crossing."""
        occ = self._tick("boundary", site)
        for d in self._matching(BOUNDARY_KINDS, site, occ):
            _note(d, occ)
            self._fire_signalish(d, site, occ)

    def io(self, site: str) -> None:
        """Called just before a host-side I/O operation at ``site``.

        ``io_fail`` raises the injected EIO; boundary kinds (``sigterm``
        / ``preempt`` / ``stall``) fire here too — their occurrence is
        counted against the SAME ("io", site) counter, so e.g.
        ``sigterm@snapshot_save=1`` lands during the first snapshot
        write of the process.
        """
        occ = self._tick("io", site)
        for d in self._matching(BOUNDARY_KINDS, site, occ):
            _note(d, occ)
            self._fire_signalish(d, site, occ)
        for d in self._matching(IO_KINDS, site, occ):
            _note(d, occ)
            raise OSError(errno.EIO, f"injected io_fail@{site} (call {occ})")

    def actor(self, site: str = "actor") -> bool:
        """Called by the orchestration supervisor once per newly observed
        queue item; True = a ``kill`` directive fired and the supervisor
        should SIGKILL the actor that produced it (the effect lives in
        the supervisor — only it knows the member pids).  Boundary kinds
        fire here too: ``preempt@actor=N`` requests a pod drain at the
        Nth observed item — a drain deterministically coupled to stream
        progress rather than to supervision-loop timing."""
        occ = self._tick("actor", site)
        for d in self._matching(BOUNDARY_KINDS, site, occ):
            _note(d, occ)
            self._fire_signalish(d, site, occ)
        fired = False
        for d in self._matching(ACTOR_KINDS, site, occ):
            _note(d, occ)
            fired = True
        return fired

    def post_save(self, site: str, path) -> None:
        """Called after a successful save of ``path`` — may damage it."""
        occ = self._tick("post_save", site)
        for d in self._matching(POST_SAVE_KINDS, site, occ):
            _note(d, occ)
            target = _payload_file(Path(path))
            if target is None:
                continue
            if d.kind == "torn":
                tear_file(target)
            else:
                corrupt_file(target)


def _note(d: Directive, occ: int) -> None:
    """Injected faults announce themselves in the telemetry stream (and
    never anywhere that could mask the fault's own effect)."""
    try:
        from hfrep_tpu.obs import get_obs
        get_obs().event("fault_injected", kind=d.kind, site=d.site,
                        occurrence=occ)
    except Exception:
        pass


def _payload_file(path: Path):
    """The file whose bytes a torn/corrupt directive damages: the largest
    non-metadata file under a checkpoint dir (or the path itself)."""
    if path.is_file():
        return path
    best, best_size = None, -1
    try:
        for f in path.rglob("*"):
            if f.is_file() and f.name != "meta.json":
                size = f.stat().st_size
                if size > best_size:
                    best, best_size = f, size
    except OSError:
        return None
    return best


def tear_file(path: Path) -> None:
    """Simulate a torn write: keep only the first half of the file."""
    size = path.stat().st_size
    with open(path, "r+b") as f:
        f.truncate(size // 2)


# ----------------------------------------------------------- explanation
def plan_rows(plan: FaultPlan) -> List[dict]:
    """One dict per directive — the machine form behind
    ``python -m hfrep_tpu.resilience explain-faults``: kind, site, the
    occurrence-counter group the directive ticks against, the 1-based
    trigger occurrence, the consecutive-fire count, and the effect."""
    return [{"kind": d.kind, "site": d.site,
             "counter": f"({site_group(d.site)}, {d.site})",
             "occurrence": d.n, "count": d.count,
             "spec": d.spec(), "effect": KIND_EFFECTS.get(d.kind, "?")}
            for d in plan.directives]


def render_plan(plan: FaultPlan) -> str:
    """The human table for ``explain-faults`` — a shrunk repro spec one
    paste away from readable."""
    rows = plan_rows(plan)
    if not rows:
        return "(empty plan: no directives)"
    headers = ("kind", "site", "counter", "fires at", "count", "effect")
    cells = [(r["kind"], r["site"], r["counter"],
              f"occurrence {r['occurrence']}"
              + (f"..{r['occurrence'] + r['count'] - 1}"
                 if r["count"] > 1 else ""),
              str(r["count"]), r["effect"]) for r in rows]
    widths = [max(len(h), *(len(c[i]) for c in cells))
              for i, h in enumerate(headers)]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(c.ljust(w) for c, w in zip(row, widths))
              for row in cells]
    return "\n".join(lines)


def corrupt_file(path: Path) -> None:
    """Simulate bit rot: XOR a 16-byte run in the middle of the file."""
    size = path.stat().st_size
    if size == 0:
        return
    start = size // 2
    length = min(16, size - start) or size
    with open(path, "r+b") as f:
        f.seek(start)
        chunk = f.read(length)
        f.seek(start)
        f.write(bytes(b ^ 0xFF for b in chunk))
