"""hfrep_tpu.resilience — fault injection + preemption-safe recovery.

The reference saves only the generator, only once, after the full
5000-epoch run (``GAN/MTSS_WGAN_GP.py:285-287``) — a crash loses
everything.  On preemptible accelerator fleets (the Podracer pattern,
arxiv 2104.06272) a training system is defined by how it survives
SIGTERM, torn writes and flaky storage.  This package provides the
machinery and the means to *test* it:

* **fault injection** — a deterministic, env-driven plan
  (``HFREP_FAULTS``, :mod:`hfrep_tpu.resilience.faults`) that fires
  SIGTERM/preemption at a chosen chunk/block boundary, fails host-side
  I/O (checkpoint save, obs append, manifest writes) on the Nth call,
  and tears/corrupts checkpoint bytes after a save;
* **graceful drain** — :func:`graceful_drain` installs a SIGTERM handler
  for the duration of a training drive; the drives poll
  :func:`drain_requested` at their natural sync points (chunk/block
  boundaries), persist state, and raise :class:`Preempted` instead of
  dying mid-write;
* **bounded I/O retry** — :func:`retry_io` wraps host-side writes
  (checkpoints, run manifests) in a small exponential-backoff policy,
  surfaced as ``resilience/io_retries`` counters and ``io_retry``
  events in the obs stream;
* **chunk-boundary resume** — :class:`~hfrep_tpu.resilience.snapshot.
  ChunkSnapshot` persists the chunked AE drives' carry pytree + chunk
  counter at each boundary so a killed sweep resumes bit-identically
  (``replication/engine.py``);
* **selftest** — ``python -m hfrep_tpu.resilience selftest`` drives a
  real training run through kill→resume and asserts bit-identical
  results, plus corrupt-checkpoint → fallback-to-previous-good (wired
  into ``tools/check.sh``).

Everything here is host-side only; nothing runs inside ``jit``, and with
no plan installed every hook is one ``None`` check.
"""

from __future__ import annotations

import contextlib
import os
import random
import signal
import time
from typing import Callable, Optional

from hfrep_tpu.resilience.faults import (  # noqa: F401  (public re-exports)
    Directive,
    FaultPlan,
    FaultSpecError,
)

ENV_FAULTS = "HFREP_FAULTS"
ENV_RETRIES = "HFREP_IO_RETRIES"


class WatchdogTimeout(RuntimeError):
    """A watched drive overran its watchdog budget (see :func:`watchdog`)."""


@contextlib.contextmanager
def watchdog(secs: float, name: str):
    """SIGALRM watchdog around a drive: raise :class:`WatchdogTimeout`
    naming ``name`` if the body runs longer than ``secs``.

    The generalization of the selftest's per-scenario timeout, shared by
    the chaos subjects (:mod:`hfrep_tpu.resilience.chaos_subjects`) and
    the selftest alike: any wedged drive fails loudly with its own name
    instead of silently eating the caller's (or CI's) whole budget.
    Nests: the previous SIGALRM handler and any pending itimer are
    restored on exit, so an outer watchdog keeps (approximately) its
    remaining budget.  A no-op off the main thread or on platforms
    without SIGALRM — a degraded watchdog must not block the drive.
    """
    import threading

    if (not hasattr(signal, "SIGALRM")
            or threading.current_thread() is not threading.main_thread()):
        yield
        return

    def _alarm(signum, frame):
        raise WatchdogTimeout(
            f"{name!r} exceeded its {secs:.0f}s watchdog budget")

    prev_handler = signal.signal(signal.SIGALRM, _alarm)
    prev_delay, _ = signal.setitimer(signal.ITIMER_REAL, secs)
    t0 = time.monotonic()
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev_handler if prev_handler is not None
                      else signal.SIG_DFL)
        if prev_delay:
            # hand the remainder of the outer watchdog's budget back
            remaining = max(prev_delay - (time.monotonic() - t0), 0.001)
            signal.setitimer(signal.ITIMER_REAL, remaining)


class Preempted(RuntimeError):
    """Graceful preemption: a drive stopped at a safe boundary after
    persisting its state.  Callers translate this into a resumable exit
    (the CLIs exit 75 / EX_TEMPFAIL) rather than a crash; their exit-75
    handlers also land the crash-forensics bundle explicitly
    (:func:`hfrep_tpu.obs.crash.bundle_if_enabled`) — a drive that
    catches a Preempted and successfully RESUMES must not bundle."""

    def __init__(self, site: str, reason: Optional[str] = None,
                 epoch: Optional[int] = None, snapshot: Optional[str] = None):
        self.site, self.reason, self.epoch, self.snapshot = (
            site, reason, epoch, snapshot)
        msg = f"preempted at {site} boundary"
        if epoch is not None:
            msg += f" (epoch {epoch})"
        if snapshot:
            msg += f"; state persisted at {snapshot}"
        if reason:
            msg += f" [{reason}]"
        super().__init__(msg)


# ------------------------------------------------------------- fault plan
_plan: Optional[FaultPlan] = None
_env_consumed = False


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Activate a fault plan programmatically (tests, selftest)."""
    global _plan, _env_consumed
    _plan, _env_consumed = plan, True
    return plan


def clear_plan() -> None:
    global _plan
    _plan = None


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one parsed from ``HFREP_FAULTS`` (read
    once per process — a plan's counters must persist across hooks).

    A spec that does not parse raises :class:`FaultSpecError` — and
    keeps raising on every later call (the env read is only marked
    consumed on success): a malformed plan must fail the drive loudly,
    never silently disable the injection it was asked for.
    """
    global _plan, _env_consumed
    if _plan is None and not _env_consumed:
        spec = os.environ.get(ENV_FAULTS)
        if spec:
            _plan = FaultPlan.parse(spec)      # FaultSpecError propagates
        _env_consumed = True
    return _plan


# ---------------------------------------------------------- graceful drain
class _DrainState:
    requested = False
    reason: Optional[str] = None
    depth = 0
    installed = False
    prev = None


_DRAIN = _DrainState()


def drain_requested() -> bool:
    return _DRAIN.requested


def request_drain(reason: str = "request") -> None:
    """Ask every active drive to stop at its next safe boundary."""
    first = not _DRAIN.requested
    _DRAIN.requested = True
    _DRAIN.reason = reason
    if first:
        try:
            from hfrep_tpu.obs import get_obs
            get_obs().event("preempt_requested", reason=reason)
        except Exception:
            pass


def _sigterm_handler(signum, frame):
    request_drain(f"signal {signum} (SIGTERM)")


@contextlib.contextmanager
def graceful_drain():
    """Install the SIGTERM→drain handler while a training drive runs.

    Re-entrant (the trainers and the chunked engine may nest); the
    outermost exit restores the previous handler and clears the drain
    flag, so a drained-and-resumed process is not instantly preempted
    again.  In a non-main thread ``signal.signal`` is unavailable —
    the drain flag still works via :func:`request_drain` and injected
    ``preempt`` faults, only the OS signal route is off.

    Entry also resolves the ``HFREP_FAULTS`` plan eagerly: every long
    drive (GAN trainer, chunked AE engine, multi-seed trainer, the
    orchestration supervisor) enters through here, so a malformed spec
    raises :class:`FaultSpecError` at the drive entry point — before any
    work is paid for — instead of at whichever hook happens to fire
    first deep inside the loop.
    """
    active_plan()
    outermost = _DRAIN.depth == 0
    _DRAIN.depth += 1
    if outermost:
        try:
            _DRAIN.prev = signal.signal(signal.SIGTERM, _sigterm_handler)
            _DRAIN.installed = True
        except ValueError:              # not the main thread
            _DRAIN.installed = False
    try:
        yield
    finally:
        _DRAIN.depth -= 1
        if outermost:
            if _DRAIN.installed:
                try:
                    signal.signal(signal.SIGTERM,
                                  _DRAIN.prev or signal.SIG_DFL)
                except ValueError:
                    pass
                _DRAIN.installed = False
            _DRAIN.prev = None
            _DRAIN.requested = False
            _DRAIN.reason = None


# ----------------------------------------------------------------- hooks
def tick(site: str) -> None:
    """Cross a boundary ``site`` for fault-injection purposes only — the
    caller handles its own drain (checkpoint first, then raise)."""
    plan = active_plan()
    if plan is not None:
        plan.boundary(site)


def boundary(site: str) -> None:
    """Cross a boundary: fire any injected faults for ``site``, then
    raise :class:`Preempted` if a drain was requested.  For drives whose
    state is already persisted when they cross (the chunked AE engine
    snapshots *before* the boundary call)."""
    tick(site)
    if _DRAIN.requested:
        raise Preempted(site=site, reason=_DRAIN.reason)


def io_point(site: str) -> None:
    """Fault-injection hook just before a host-side I/O operation."""
    plan = active_plan()
    if plan is not None:
        plan.io(site)


def io_hook(site: str) -> Optional[Callable[[], None]]:
    """:func:`io_point` pre-bound for hot paths: ``None`` when no plan is
    active at resolve time, so the caller's per-call cost is one ``if``."""
    plan = active_plan()
    if plan is None:
        return None
    return lambda: plan.io(site)


def post_save(site: str, path) -> None:
    """Fault-injection hook after a successful save of ``path``."""
    plan = active_plan()
    if plan is not None:
        plan.post_save(site, path)


def actor_kill_point(site: str = "actor") -> bool:
    """Fault-injection hook for the orchestration supervisor: True when
    a ``kill@actor=N`` directive fires at this occurrence (one call per
    newly observed queue item) — the supervisor then SIGKILLs the member
    that produced the item.  The effect lives in the caller because only
    the supervisor knows the actor pids."""
    plan = active_plan()
    return plan.actor(site) if plan is not None else False


# ------------------------------------------------------------------ retry
def io_attempts(default: int = 3) -> int:
    try:
        return max(1, int(os.environ.get(ENV_RETRIES, default)))
    except ValueError:
        return default


def backoff_delay(attempt: int, base: float = 0.05, factor: float = 2.0,
                  cap: float = 30.0,
                  rng: Callable[[], float] = random.random) -> float:
    """Full-jitter exponential backoff: uniform in
    ``[0, min(cap, base * factor**attempt)]`` (``attempt`` 0-based).

    The jitter is the point, not a refinement: a preemption or an EIO
    burst hits every pod member at the same moment, and a deterministic
    schedule would march all of them back onto the shared storage (or
    the supervisor's restart path) in lockstep, re-creating the
    contention that failed them.  ``rng`` is injectable so tests can pin
    the bounds exactly (``rng=lambda: 1.0`` = the deterministic ceiling,
    the pre-jitter behavior).
    """
    return min(cap, base * (factor ** attempt)) * rng()


def retry_io(fn: Callable, *, what: str, attempts: Optional[int] = None,
             base_delay: float = 0.05, factor: float = 2.0,
             sleep: Callable[[float], None] = time.sleep,
             rng: Callable[[], float] = random.random):
    """Run ``fn`` with a small bounded retry/backoff on ``OSError``.

    The policy for host-side I/O that must survive flaky storage
    (checkpoint saves, obs manifest writes): ``attempts`` tries total
    (default 3, env override ``HFREP_IO_RETRIES``), full-jitter
    exponential backoff from ``base_delay`` (:func:`backoff_delay` — the
    k-th retry sleeps uniform in ``[0, base_delay * factor**(k-1)]``).
    Each retry lands in the obs stream as an ``io_retry`` event +
    ``resilience/io_retries`` counter; the final failure propagates —
    bounded means bounded.
    """
    attempts = attempts if attempts is not None else io_attempts()
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except OSError as e:
            if attempt == attempts:
                raise
            delay = backoff_delay(attempt - 1, base=base_delay,
                                  factor=factor, rng=rng)
            try:
                from hfrep_tpu.obs import get_obs
                obs = get_obs()
                obs.counter("resilience/io_retries").inc(site=what)
                obs.event("io_retry", site=what, attempt=attempt,
                          error=str(e), backoff_s=round(delay, 4))
            except Exception:
                pass
            sleep(delay)
