"""Chaos fixture bindings for every registered drive.

Each ``run_*`` function is one end-to-end drive at fixture shapes — a
**pure function of** ``(fixture_seed, schedule)``: fixed fixture data
derived from the seed, fixed configs, every artifact written
deterministically.  The :class:`~hfrep_tpu.resilience.drive.DriveSpec`
registry binds them lazily (``"module:function"``), the chaos engine
spawns them as ``python -m hfrep_tpu.resilience chaos-subject``
subprocesses under the one :func:`~hfrep_tpu.resilience.drive.run_drive`
envelope, and the shared oracles judge the wreckage
(:mod:`hfrep_tpu.resilience.chaos_oracles`).

Contract per fixture (what the envelope + oracles enforce):

* final outputs land under ``<out>/artifacts`` through the atomic
  writers; scratch state (checkpoints, resume snapshots, queues) under
  ``<out>/scratch``;
* ``deterministic`` specs must produce bit-identical ``artifacts/``
  for any faulted-then-resumed run vs. an undisturbed reference run of
  the same ``fixture_seed``;
* heavy imports (jax, the training stacks) stay INSIDE the functions —
  the registry must be listable without a backend init.

The ``_planted`` fixture is the engine's own canary: a deliberately
buggy drive (non-atomic artifact write that SWALLOWS an injected EIO —
the silent-drop class every real drive types or retries) that the
search must find and the shrinker must reduce to its one-directive
minimal spec.  Excluded from soaks (tier ``test``); pinned by
``tests/test_chaos.py``.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path


# ------------------------------------------------------------- helpers
def _panel(rows: int, feats: int, fixture_seed: int, salt: int):
    from hfrep_tpu.utils.fixture_data import scaled_panel
    return scaled_panel(rows, feats, seed=1000 + 31 * fixture_seed + salt)


def _write_npz_artifact(out: Path, name: str, arrays: dict) -> None:
    """Publish ``arrays`` as ``<out>/artifacts/<name>/data.npz`` through
    the one crash-consistent writer (``result_save``/``result`` fault
    sites — the artifact-publication boundary of every subject)."""
    import numpy as np

    from hfrep_tpu.utils import checkpoint as ckpt

    def writer(tmp: Path) -> None:
        np.savez(tmp / "data.npz", **arrays)

    ckpt.write_atomic(out / "artifacts" / name, writer,
                      metadata={"subject": name},
                      io_site="result_save", fault_site="result")


def _result_arrays(res) -> dict:
    """An AEResult (params pytree + traces) as a flat npz-ready dict."""
    import jax
    import numpy as np

    arrays = {f"p{i}": np.asarray(leaf) for i, leaf in
              enumerate(jax.tree_util.tree_leaves(res.params))}
    arrays["train_loss"] = np.asarray(res.train_loss)
    arrays["val_loss"] = np.asarray(res.val_loss)
    arrays["stop_epoch"] = np.asarray(res.stop_epoch)
    return arrays


# ------------------------------------------------------------- fixtures
def run_ae_sweep(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The paper's latent sweep at fixture shape, chunked with resume —
    kill→resume must stay bit-identical (PR-5's core contract)."""
    import jax

    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.replication.engine import sweep_autoencoders_chunked

    xs = _panel(32, 4, fixture_seed, salt=1)
    cfg = AEConfig(n_factors=4, latent_dim=3, epochs=4, batch_size=16,
                   patience=2, seed=fixture_seed, chunk_epochs=2)
    res, stats = sweep_autoencoders_chunked(
        jax.random.PRNGKey(fixture_seed), xs, cfg, [1, 2, 3],
        resume_dir=str(out / "scratch" / "resume"))
    _write_npz_artifact(out, "sweep", _result_arrays(res))
    return {"chunks": int(stats.chunks_dispatched)}


def run_ae_multi(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The padded multi-dataset fabric (ragged rows via the mask
    operand) under the same kill→resume contract."""
    import jax

    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.replication.engine import (
        stack_padded,
        sweep_autoencoders_multi,
    )

    a = _panel(36, 4, fixture_seed, salt=2)
    stack, rows = stack_padded([a, a[:28]])
    cfg = AEConfig(n_factors=4, latent_dim=2, epochs=4, batch_size=16,
                   patience=2, seed=fixture_seed, chunk_epochs=2)
    res, stats = sweep_autoencoders_multi(
        jax.random.PRNGKey(fixture_seed + 1), stack, rows, cfg, [1, 2],
        resume_dir=str(out / "scratch" / "resume"))
    _write_npz_artifact(out, "multi", _result_arrays(res))
    return {"chunks": int(stats.chunks_dispatched)}


def run_ae_mesh(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The padded multi-dataset fabric dispatched through the unified
    partition-rule mesh launch (ISSUE 15) on a 1×1 ``('dp',)`` mesh —
    the pjit dispatch path under the same kill→resume / exit-contract /
    atomic-artifact oracles as the plain drive.  A 1×1 mesh runs the
    identical program (pinned), so the oracle reference stays the
    meshless undisturbed run."""
    import jax

    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.parallel.rules import MeshSpec, build_mesh
    from hfrep_tpu.replication.engine import (
        stack_padded,
        sweep_autoencoders_multi,
    )

    a = _panel(36, 4, fixture_seed, salt=2)
    stack, rows = stack_padded([a, a[:28]])
    cfg = AEConfig(n_factors=4, latent_dim=2, epochs=4, batch_size=16,
                   patience=2, seed=fixture_seed, chunk_epochs=2)
    res, stats = sweep_autoencoders_multi(
        jax.random.PRNGKey(fixture_seed + 1), stack, rows, cfg, [1, 2],
        resume_dir=str(out / "scratch" / "resume"),
        mesh=build_mesh(MeshSpec(dp=1), devices=jax.devices()[:1]))
    _write_npz_artifact(out, "multi", _result_arrays(res))
    return {"chunks": int(stats.chunks_dispatched)}


def run_gan_ckpt(out: Path, fixture_seed: int, resume: bool) -> dict:
    """GAN train→checkpoint→resume: periodic checkpoints, drain at a
    block boundary, restore walking past torn/corrupt checkpoints —
    including the all-candidates-corrupt degrade-to-fresh path (which a
    fresh deterministic retrain makes bit-identical again)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hfrep_tpu.config import ExperimentConfig, ModelConfig, TrainConfig
    from hfrep_tpu.train.trainer import GanTrainer

    epochs = 4
    cfg = ExperimentConfig(
        model=ModelConfig(features=4, window=8, hidden=8, family="gan"),
        train=TrainConfig(epochs=epochs, batch_size=4, n_critic=1,
                          steps_per_call=2, seed=fixture_seed,
                          checkpoint_dir=str(out / "scratch" / "ckpts"),
                          checkpoint_every=2))
    rng = np.random.default_rng(2000 + fixture_seed)
    ds = jnp.asarray(rng.standard_normal((12, 8, 4)), jnp.float32)
    tr = GanTrainer(cfg, ds)
    if resume:
        try:
            path = tr.restore_checkpoint()
        except FileNotFoundError:
            path = ""           # nothing persisted yet: clean fresh start
        if not path:
            print("gan_ckpt: no restorable checkpoint, fresh start",
                  file=sys.stderr)
    remaining = epochs - tr.epoch
    if remaining > 0:
        tr.train(epochs=remaining)
    _write_npz_artifact(out, "gan", {
        f"g{i}": np.asarray(leaf) for i, leaf in
        enumerate(jax.tree_util.tree_leaves(tr.state.g_params))})
    return {"epochs": int(tr.epoch)}


def run_serve_load(out: Path, fixture_seed: int, resume: bool) -> dict:
    """Serving chaos load: a real server over a really-trained tiny AE
    head under whatever the schedule throws at it.  Not bit-identical
    (thread timing decides sheds/deadlines) — the oracles here are the
    ledger (terminal == submitted, zero silent drops) and the exit-code
    contract.  A resumed leg is simply a fresh load run."""
    import jax

    from hfrep_tpu import resilience
    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.replication.engine import train_autoencoder_chunked
    from hfrep_tpu.serve import AEServeModel, ReplicationServer, ServeConfig
    from hfrep_tpu.serve.loadgen import make_panels

    cfg = AEConfig(n_factors=4, latent_dim=2, epochs=6, batch_size=16,
                   patience=2, seed=fixture_seed, chunk_epochs=3)
    res, _ = train_autoencoder_chunked(
        jax.random.PRNGKey(fixture_seed), _panel(36, 4, fixture_seed, 3),
        cfg)
    model = AEServeModel.create(cfg, res.params)
    scfg = ServeConfig(max_batch=4, batch_window_ms=5.0,
                       request_timeout_ms=2000.0, max_queue=16, workers=1,
                       row_buckets=(16, 32), breaker_failures=2,
                       breaker_cooldown_s=0.2, compile_storm=64)
    server = ReplicationServer(scfg, ae_model=model).start()
    panels = make_panels(fixture_seed + 1, 4, (12, 20), variants=3)
    from concurrent.futures import wait
    try:
        with resilience.graceful_drain():
            futs = []
            try:
                for burst in range(2):
                    futs += [server.replicate(panels[i % len(panels)],
                                              timeout_ms=2000.0)
                             for i in range(8)]
                    wait(futs, timeout=30)
                    # the drive boundary: injected sigterm/preempt land
                    # here and drain the server like the CLI would
                    resilience.boundary("serve_drive")
            except resilience.Preempted:
                server.drain(reason="chaos drain", timeout=30.0)
                wait(futs, timeout=30)
                raise
        wait(futs, timeout=30)
    finally:
        ledger = server.outcomes.as_dict()
        server.stop()
    return {"submitted": int(ledger["submitted"]),
            "terminal": int(ledger["terminal"])}


def run_walkforward(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The scenario factory's walk-forward regime sweep at fixture
    shape: chunk-snapshot training, window-granular scoring, resume
    byte-identical."""
    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.scenario.walkforward import WalkForwardSpec, run_walkforward
    from hfrep_tpu.utils.fixture_data import universe_arrays

    x, y, rf = universe_arrays(3000 + fixture_seed, funds=6, months=48,
                               n_factors=4)
    spec = WalkForwardSpec(start=24, n_windows=2, horizon=10, step=2)
    cfg = AEConfig(n_factors=4, latent_dim=2, epochs=4, batch_size=16,
                   patience=2, seed=fixture_seed, chunk_epochs=2,
                   ols_window=8)
    doc = run_walkforward(x, y, rf, spec, cfg, [1, 2],
                          out / "scratch" / "wf", resume=resume)
    _write_npz_artifact(out, "walkforward", {
        "surface_post": doc["surface_post"],
        "surface_ante": doc["surface_ante"]})
    return {"windows": int(spec.n_windows)}


def run_scenario_bank(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The scenario factory's bank drive (CLI ``scenario --mode bank``):
    a tiny conditional GAN trained on the fixture panel, then a
    deterministic regime-conditioned sample bank published block by
    block through the atomic writer.

    Drain points: ``gan_block`` between training dispatches,
    ``bank_block`` after each published block — a SIGTERM'd bank run
    exits 75 and a resumed run completes only the gap (verified blocks
    with THIS bank's fingerprint are skipped), bit-identical to the
    undisturbed reference because the deterministic retrain rebuilds
    the identical generator."""
    import numpy as np

    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.scenario import regimes as reg
    from hfrep_tpu.scenario.conditional import (
        generate_bank,
        sliding_windows,
        train_conditional,
    )

    feats, window, n_regimes = 4, 8, 2
    panel = np.asarray(_panel(40, feats, fixture_seed, salt=4))
    labels = reg.label_regimes(panel, window=window, n_regimes=n_regimes)
    windows = sliding_windows(panel, window)
    conds = reg.window_conditions(labels, window, n_regimes)
    mcfg = ModelConfig(family="gan", features=feats, window=window,
                       hidden=8)
    # steps_per_call=1 => a gan_block boundary between every training
    # dispatch: the site a pre-drive SIGTERM drains at
    tcfg = TrainConfig(batch_size=8, n_critic=1, seed=fixture_seed,
                       steps_per_call=1)
    bundle = train_conditional(mcfg, tcfg, windows, conds, epochs=2,
                               seed=fixture_seed)
    manifest = generate_bank(bundle, out / "artifacts" / "bank",
                             blocks=2, block_size=4,
                             stream_seed=100 + fixture_seed)
    return {"blocks": len(manifest["block_digests"]),
            "generated": int(manifest["generated"])}


def run_rollup(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The fleet telemetry plane's retention loop (ISSUE 17) under
    fire: a compressed-time soak that appends deterministic event
    batches to a synthetic run dir, rotates the live stream at a byte
    threshold and compacts every cycle — SIGKILL/EIO landing
    mid-segment (``rollup_publish`` during the state publish) or
    mid-compaction (during a pinned/ledger publish) must resume from
    the durable cursor with zero lost or double-counted events.

    Determinism notes (the oracle digests ``artifacts/`` bit-exactly):

    * events are written as raw JSONL with seed-derived timestamps —
      never through :class:`Obs`, whose ``perf_counter`` clock is
      wall-nondeterministic;
    * rotation is BYTE-driven and happens in the same guarded step as
      the append (no fault site between them), so chunk numbering and
      content are a pure function of the bytes appended — identical
      between a faulted-then-resumed run and the undisturbed reference;
    * per-batch progress is published atomically AFTER append+rotate
      and BEFORE compaction, so a kill anywhere in compaction resumes
      into the idempotent per-chunk ledger protocol, never into a
      double append.

    Invariants: ``items`` = records the final rollup state folded,
    ``expected_items`` = records written — any drop or double-count
    breaks the pair (zero-silent-drop oracle).
    """
    import hashlib
    import json as _json

    from hfrep_tpu import resilience
    from hfrep_tpu.obs import rollup
    from hfrep_tpu.utils.checkpoint import atomic_text

    batches, rotate_bytes, bucket_secs = 24, 2048, 60.0
    run = out / "scratch" / "soak_run"
    run.mkdir(parents=True, exist_ok=True)
    live = run / "events.jsonl"
    progress_path = out / "scratch" / "progress.json"

    def batch_lines(k: int) -> list:
        base_t = k * 37.0
        rnd = hashlib.sha256(f"{fixture_seed}:{k}".encode()).digest()
        recs = []
        for i in range(10):
            recs.append({"v": 1, "t": base_t + i * 0.31, "type": "metric",
                         "kind": "gauge", "name": "soak/depth",
                         "value": rnd[i] % 17})
        for i in range(8):
            recs.append({"v": 1, "t": base_t + 3.1 + i * 0.17,
                         "type": "metric", "kind": "histogram",
                         "name": "serve/latency_ms",
                         "value": 1.0 + (rnd[10 + i] % 50)})
        for i in range(4):
            recs.append({"v": 1, "t": base_t + 5.0 + i * 0.13,
                         "type": "metric", "kind": "counter",
                         "name": "soak/requests",
                         "value": k * 4 + i + 1, "delta": 1})
        for i in range(5):
            recs.append({"v": 1, "t": base_t + 6.0 + i * 0.11,
                         "type": "span", "name": "work",
                         "dur": 0.01 * (1 + rnd[18 + i] % 9), "depth": 0})
        recs.append({"v": 1, "t": base_t + 9.0, "type": "event",
                     "name": "batch_end", "batch": k})
        return [_json.dumps(r, sort_keys=True) for r in recs]

    per_batch = len(batch_lines(0))
    done = 0
    if resume:
        try:
            done = int(_json.loads(progress_path.read_text())["batches"])
        except (OSError, ValueError, KeyError):
            done = 0
        print(f"rollup: resuming after batch {done}", file=sys.stderr)

    for k in range(done, batches):
        # kills/preempts land here — between cycles, never mid-append
        resilience.boundary("item")
        data = "".join(ln + "\n" for ln in batch_lines(k))
        with open(live, "a") as fh:
            fh.write(data)
        # byte-driven rotation INSIDE the guarded step: deterministic
        rollup.rotate_live(run, rotate_bytes)
        atomic_text(progress_path, _json.dumps({"batches": k + 1}))
        # the consumer under test: one EIO is absorbed by a single
        # bounded retry against the idempotent ledger; a persistent
        # burst propagates as the typed storage exit (74)
        try:
            rollup.compact(run, bucket_secs=bucket_secs)
        except OSError:
            rollup.compact(run, bucket_secs=bucket_secs)

    # drain the tail: rotate whatever is left, compact it, then
    # normalize the cursor table to the (now empty) live stream
    rollup.compact(run, bucket_secs=bucket_secs, force_rotate=True)
    state, _ = rollup.ingest(run, bucket_secs=bucket_secs, persist=True)

    art = out / "artifacts"
    art.mkdir(parents=True, exist_ok=True)
    atomic_text(art / "rollup_state.json",
                _json.dumps(state, indent=2, sort_keys=True))
    comp = rollup.load_compact(run) or {}
    atomic_text(art / "rollup_compact.json",
                _json.dumps(comp, indent=2, sort_keys=True))
    pinned_digests = {
        p.name: hashlib.sha256(p.read_bytes()).hexdigest()
        for p in rollup.pinned_files(run)}
    atomic_text(art / "pinned_digests.json",
                _json.dumps(pinned_digests, indent=2, sort_keys=True))
    return {"items": rollup.n_records(state),
            "expected_items": batches * per_batch,
            "chunk_cycles": len((comp.get("chunks") or {})),
            "disk_bytes": rollup.disk_footprint(run)}


def run_pipeline(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The async actor fabric end to end (spawned members over the spool
    queue).  Expensive — slow tier, soaked only with a real budget; the
    artifact digest manifest is the fabric's determinism contract."""
    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.orchestrate import PipelinePlan, SourceSpec, run_pipeline
    from hfrep_tpu.utils.checkpoint import atomic_text

    cfg = AEConfig(n_factors=4, latent_dim=2, epochs=6, batch_size=16,
                   patience=2, seed=0, chunk_epochs=3)
    plan = PipelinePlan(
        out_dir=str(out / "scratch" / "pipe"),
        sources=[SourceSpec(name="s0", mode="fixture",
                            params={"rows": 32, "feats": 4})],
        blocks=2, consumers=1, capacity=1, ae_cfg=cfg, latent_dims=[1, 2],
        consume_mode="direct", stream_seed=10 + fixture_seed,
        drain_timeout=60.0, timeout=180.0)
    doc = run_pipeline(plan, resume=resume)
    digests = {name: src["items"]
               for name, src in doc["summary"]["sources"].items()}
    art = out / "artifacts"
    art.mkdir(parents=True, exist_ok=True)
    atomic_text(art / "pipeline_digests.json",
                json.dumps(digests, indent=2, sort_keys=True))
    n_items = sum(len(v) for v in digests.values())
    return {"items": n_items, "expected_items": plan.blocks,
            "restarts": int(doc["stats"]["restarts"])}


def run_planted(out: Path, fixture_seed: int, resume: bool) -> dict:
    """The engine's canary: a drive with a DELIBERATE silent-drop bug.

    It writes its one artifact with a plain non-atomic write and — the
    planted violation — swallows an injected EIO at the publication
    site, so ``io_fail@result_save=1`` makes the artifact silently
    vanish while the run still exits 0.  The search must catch the
    digest mismatch against the reference and the shrinker must reduce
    any schedule containing that directive to the one-fault minimal
    spec.  Kept out of real soaks; driven by ``tests/test_chaos.py``.
    """
    import hashlib

    from hfrep_tpu import resilience

    payload = hashlib.sha256(f"planted:{fixture_seed}".encode()).hexdigest()
    with resilience.graceful_drain():
        for _ in range(3):
            resilience.boundary("item")
        art = out / "artifacts" / "planted"
        art.mkdir(parents=True, exist_ok=True)
        try:
            resilience.io_point("result_save")
            (art / "result.json").write_text(
                json.dumps({"payload": payload}))
        except OSError:
            pass    # the planted bug: a swallowed publish EIO = silent drop
    return {"items": 3}
