"""Chaos search: property-based fault-schedule fuzzing with shrinking.

PRs 5/7/8 built a deterministic fault lattice (``HFREP_FAULTS`` kinds ×
sites × occurrences) — but every schedule ever executed through it was
authored by a human, so the *composition* space (an EIO during the
drain snapshot of a resumed run, a kill racing a breaker probe, a torn
checkpoint under backpressure) stayed unexplored.  This module explores
it the FoundationDB/Jepsen way:

* **generate** — seeded random schedules over the machine-readable
  fault alphabet (the ``BOUNDARY_SITES``/``IO_SITES``/
  ``POST_SAVE_SITES``/``ACTOR_SITES`` registries in
  :mod:`hfrep_tpu.resilience.faults` — the single source of truth the
  analyzer already round-trips, so a new fault site is automatically in
  scope), composing 1–4 directives per schedule across kinds,
  occurrences and *legs* (the initial run or the first resume — the
  "fault during recovery" compositions scenario suites structurally
  miss);
* **drive** — each schedule through a registered subject
  (:mod:`hfrep_tpu.resilience.chaos_subjects`) as a spawned subprocess
  chain: faulted attempt, then resume attempts until completion, all
  under watchdogs;
* **check** — the shared oracle battery
  (:mod:`hfrep_tpu.resilience.chaos_oracles`): exit-code contract,
  resume bit-identity vs. an undisturbed reference, atomic-artifact
  validity, ledger conservation, obs-stream health;
* **shrink** — a failing schedule is minimized (drop directives, then
  lower counts and occurrences, re-running at each step — the lattice's
  determinism makes shrinking sound) to a minimal ``HFREP_FAULTS`` spec
  plus a one-line repro command;
* **persist** — minimal schedules land in the committed regression
  corpus (``hfrep_tpu/resilience/_chaos_corpus/``) that the CI gate
  replays forever (``--replay-corpus``), and the budgeted soak is wired
  env-stripped into ``tools/check.sh``.

Everything is seeded and wall-clock-free at the schedule level: the
soak's *content* is a pure function of ``--seed``; the time budget only
bounds how much of that deterministic sequence runs (never below
``--min-schedules``, so the CI gate's coverage floor is deterministic).

Telemetry: one ``chaos_schedule`` event per driven schedule, a
``chaos_violation`` event per shrunk finding, and ``chaos/*`` gauges
(explicit ``DEFAULT_THRESHOLDS`` rows — the HF001 contract).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from hfrep_tpu.obs import timeline
from hfrep_tpu.resilience import faults
from hfrep_tpu.resilience.chaos_oracles import (
    Attempt,
    Violation,
    check_run,
    digest_map,
)
from hfrep_tpu.resilience.chaos_subjects import (
    RESULT_NAME,
    SUBJECTS,
    Subject,
    fast_subjects,
)
from hfrep_tpu.resilience.faults import Directive, FaultPlan, kind_sites

#: committed regression corpus — minimal schedules that once violated an
#: invariant, fixed since, replayed forever by the CI gate
CORPUS_DIR = Path(__file__).resolve().parent / "_chaos_corpus"

#: subprocess attempts per schedule: the faulted run plus at most this
#: many resume legs; a drive still exiting 75 on a CLEAN leg is a wedge
#: (the exit-contract oracle flags it), not grounds for more retries
MAX_ATTEMPTS = 5

#: parent-side backstop over the subject's own in-process watchdog
SPAWN_GRACE_SECS = 45.0


class ChaosError(RuntimeError):
    """Engine misuse / unusable configuration (not a found violation)."""


# ------------------------------------------------------------- schedules
@dataclasses.dataclass(frozen=True)
class Schedule:
    """One generated fault composition: a spec armed on the initial
    attempt and (optionally) one armed on the first resume leg — the
    encoding of "the fault lands during recovery"."""

    subject: str
    fixture_seed: int
    spec: str
    resume_spec: str = ""

    def encode(self) -> str:
        parts = [self.subject, str(self.fixture_seed), self.spec]
        if self.resume_spec:
            parts.append(self.resume_spec)
        return "|".join(parts)

    @classmethod
    def decode(cls, text: str) -> "Schedule":
        parts = text.split("|")
        if len(parts) not in (3, 4) or not parts[0]:
            raise ChaosError(
                f"bad schedule {text!r} "
                "(want subject|fixture_seed|spec[|resume_spec])")
        try:
            seed = int(parts[1])
        except ValueError:
            raise ChaosError(f"bad fixture seed in schedule {text!r}")
        # parse both legs eagerly so a typo'd corpus entry / --replay
        # argument fails loudly with the registry's suggestions
        FaultPlan.parse(parts[2])
        if len(parts) == 4:
            FaultPlan.parse(parts[3])
        return cls(subject=parts[0], fixture_seed=seed, spec=parts[2],
                   resume_spec=parts[3] if len(parts) == 4 else "")

    def directives(self) -> List[Tuple[int, Directive]]:
        """(leg, directive) pairs; leg 0 = initial attempt, 1 = first
        resume."""
        out: List[Tuple[int, Directive]] = []
        for leg, spec in ((0, self.spec), (1, self.resume_spec)):
            if spec:
                out += [(leg, d) for d in FaultPlan.parse(spec).directives]
        return out

    @classmethod
    def from_directives(cls, subject: str, fixture_seed: int,
                        pairs: Sequence[Tuple[int, Directive]]) -> "Schedule":
        spec = ";".join(d.spec() for leg, d in pairs if leg == 0)
        resume = ";".join(d.spec() for leg, d in pairs if leg == 1)
        return cls(subject=subject, fixture_seed=fixture_seed, spec=spec,
                   resume_spec=resume)

    def n_faults(self) -> int:
        return len(self.directives())


_KIND_WEIGHTS = {
    "sigterm": 3, "preempt": 3, "io_fail": 3, "torn": 2, "corrupt": 2,
    "stall": 1, "kill": 2,
}


def _draw_directive(rng: random.Random, subject: Subject) -> Directive:
    kinds = list(faults.KINDS)
    kind = rng.choices(kinds, weights=[_KIND_WEIGHTS[k] for k in kinds])[0]
    legal = kind_sites(kind)
    hinted = [s for s in subject.hint_sites if s in legal]
    # bias toward sites the subject actually crosses, but keep the whole
    # registry in scope — a fresh fault site gets explored with no code
    # change here
    if hinted and rng.random() < 0.75:
        site = rng.choice(hinted)
    else:
        site = rng.choice(list(legal))
    n = rng.choices((1, 2, 3), weights=(5, 3, 1))[0]
    if kind == "io_fail":
        # a single EIO is absorbed by the bounded retry policy (by
        # design); bursts that outlast HFREP_IO_RETRIES are the
        # interesting class, so weight counts upward
        count = rng.choices((1, 2, 3, 4), weights=(2, 2, 3, 2))[0]
    else:
        count = rng.choices((1, 2), weights=(8, 2))[0]
    return Directive(kind=kind, site=site, n=n, count=count)


def generate_schedule(rng: random.Random, subject: Subject,
                      fixture_seeds: int = 1) -> Schedule:
    """One seeded random schedule for ``subject``: 1–4 distinct
    directives spread over the initial leg and (sometimes) the first
    resume leg.  Pure function of the rng state — the soak's schedule
    sequence is reproducible from its seed."""
    n_faults = rng.choices((1, 2, 3, 4), weights=(35, 30, 20, 15))[0]
    pairs: List[Tuple[int, Directive]] = []
    seen = set()
    for _ in range(n_faults * 4):
        if len(pairs) >= n_faults:
            break
        d = _draw_directive(rng, subject)
        leg = 1 if rng.random() < 0.2 else 0
        key = (leg, d.kind, d.site)
        if key in seen:
            continue
        seen.add(key)
        pairs.append((leg, d))
    if pairs and all(leg == 1 for leg, _ in pairs):
        # a schedule whose every fault waits for the resume leg never
        # fires at all (nothing preempts the first attempt) — ground
        # one directive on the initial leg so the draw is never wasted
        pairs[0] = (0, pairs[0][1])
    pairs.sort(key=lambda p: (p[0], p[1].kind, p[1].site, p[1].n))
    seed = rng.randrange(fixture_seeds) if fixture_seeds > 1 else 0
    return Schedule.from_directives(subject.name, seed, pairs)


# ---------------------------------------------------------------- driver
@dataclasses.dataclass
class Report:
    """One driven schedule's verdict."""

    schedule: Schedule
    attempts: List[Attempt]
    violations: List[Violation]
    secs: float

    @property
    def ok(self) -> bool:
        return not self.violations


class Driver:
    """Runs schedules through spawned subject subprocesses and the
    oracle battery, caching one undisturbed reference per
    ``(subject, fixture_seed)``."""

    def __init__(self, workdir, env: Optional[dict] = None):
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self._refs: Dict[Tuple[str, int], Dict[str, str]] = {}
        self._runs = 0
        self._run_secs = 0.0
        base = dict(os.environ if env is None else env)
        # the soak's children must see OUR plan (or none), never the
        # ambient shell's; telemetry/history env likewise must not leak
        # a CI soak's fixture runs into a committed store
        for k in ("HFREP_FAULTS", "HFREP_OBS_DIR", "HFREP_HISTORY",
                  "HFREP_HEALTH"):
            base.pop(k, None)
        base["JAX_PLATFORMS"] = "cpu"       # fixture shapes; determinism
        # msgpack checkpoints: bitwise-equivalent restore, ~4x cheaper
        # save on the chip-free fixture drives this soak spawns by the
        # dozen (utils/checkpoint.py HFREP_CKPT_FORMAT knob)
        base.setdefault("HFREP_CKPT_FORMAT", "msgpack")
        self._env = base

    # ------------------------------------------------------------ spawn
    def _spawn(self, subject: Subject, fixture_seed: int, out: Path,
               spec: str, resume: bool) -> Attempt:
        env = dict(self._env)
        if spec:
            env["HFREP_FAULTS"] = spec
        cmd = [sys.executable, "-m", "hfrep_tpu.resilience",
               "chaos-subject", subject.name, "--out", str(out),
               "--fixture-seed", str(fixture_seed)]
        if resume:
            cmd.append("--resume")
        t0 = timeline.clock()
        try:
            proc = subprocess.run(
                cmd, env=env, capture_output=True, text=True,
                timeout=subject.timeout + SPAWN_GRACE_SECS)
            code: Optional[int] = proc.returncode
            stderr = proc.stderr
        except subprocess.TimeoutExpired as e:
            code = None
            stderr = (e.stderr or b"").decode(errors="replace") \
                if isinstance(e.stderr, bytes) else (e.stderr or "")
        secs = timeline.clock() - t0
        self._runs += 1
        self._run_secs += secs
        return Attempt(spec=spec, exit_code=code, secs=secs,
                       stderr_tail=stderr[-4000:])

    def _drive(self, sched: Schedule, out: Path) -> List[Attempt]:
        subject = self._subject(sched)
        attempts = [self._spawn(subject, sched.fixture_seed, out,
                                sched.spec, resume=False)]
        while attempts[-1].exit_code == 75 and len(attempts) < MAX_ATTEMPTS:
            spec = sched.resume_spec if len(attempts) == 1 else ""
            attempts.append(self._spawn(subject, sched.fixture_seed, out,
                                        spec, resume=True))
        return attempts

    def _subject(self, sched: Schedule) -> Subject:
        subject = SUBJECTS.get(sched.subject)
        if subject is None:
            raise ChaosError(
                f"unknown chaos subject {sched.subject!r} "
                f"(registry: {', '.join(sorted(SUBJECTS))})")
        return subject

    # -------------------------------------------------------- reference
    def reference(self, subject_name: str, fixture_seed: int) -> Dict[str, str]:
        """The undisturbed run's artifact digests (cached).  A reference
        that itself breaks the contract is a finding about the CLEAN
        drive — surfaced loudly, not compared against."""
        key = (subject_name, fixture_seed)
        if key in self._refs:
            return self._refs[key]
        subject = SUBJECTS[subject_name]
        out = self.workdir / f"ref_{subject_name}_{fixture_seed}"
        attempt = self._spawn(subject, fixture_seed, out, spec="",
                              resume=False)
        violations = check_run(
            deterministic=subject.deterministic,
            attempts=[attempt], out_dir=out, ref_digests=None,
            result_doc=_read_result(out))
        if violations:
            raise ChaosError(
                f"reference (fault-free) run of {subject_name}/"
                f"{fixture_seed} violates the contract on its own: "
                + "; ".join(v.render() for v in violations))
        self._refs[key] = digest_map(out / "artifacts")
        return self._refs[key]

    # ------------------------------------------------------------- runs
    def run_schedule(self, sched: Schedule, tag: str = "run") -> Report:
        subject = self._subject(sched)
        ref = self.reference(sched.subject, sched.fixture_seed) \
            if subject.deterministic else None
        # pid-prefixed: a second soak into the same --out must not
        # inherit a previous invocation's fingerprint-matched scratch
        # (a walk-forward rerun would silently SKIP the work the
        # schedule meant to fault; reference dirs may be reused — their
        # fingerprint-gated reuse is bit-identical by construction)
        out = self.workdir / f"r{os.getpid():x}_{tag}_{self._runs:04d}"
        t0 = timeline.clock()
        attempts = self._drive(sched, out)
        violations = check_run(
            deterministic=subject.deterministic,
            attempts=attempts, out_dir=out, ref_digests=ref,
            result_doc=_read_result(out))
        return Report(schedule=sched, attempts=attempts,
                      violations=violations,
                      secs=timeline.clock() - t0)

    @property
    def stats(self) -> dict:
        return {"runs": self._runs,
                "run_secs_mean": round(self._run_secs / self._runs, 3)
                if self._runs else 0.0}


def _read_result(out: Path) -> Optional[dict]:
    try:
        return json.loads((out / RESULT_NAME).read_text())
    except (OSError, json.JSONDecodeError):
        return None


# -------------------------------------------------------------- shrinking
def shrink(driver: Driver, report: Report,
           max_runs: int = 32) -> Tuple[Schedule, int]:
    """Minimize a failing schedule: drop directives, then lower counts,
    then occurrences — re-running the full drive+oracle protocol at
    each step and keeping a reduction only while the SAME oracle still
    fires (determinism makes each re-run a faithful replay, so greedy
    delta-debugging is sound).  Returns (minimal schedule, runs spent).
    """
    target = report.violations[0].oracle
    runs = 0

    def still_fails(s: Schedule) -> bool:
        nonlocal runs
        runs += 1
        r = driver.run_schedule(s, tag="shrink")
        return any(v.oracle == target for v in r.violations)

    cur = report.schedule
    # pass 1: drop whole directives to a local fixpoint
    changed = True
    while changed and runs < max_runs:
        changed = False
        pairs = cur.directives()
        if len(pairs) <= 1:
            break
        for i in range(len(pairs)):
            cand = Schedule.from_directives(
                cur.subject, cur.fixture_seed,
                pairs[:i] + pairs[i + 1:])
            if runs >= max_runs:
                break
            if still_fails(cand):
                cur = cand
                changed = True
                break
    # pass 2: lower occurrence counts, then trigger occurrences, to 1
    for field, floor in (("count", 1), ("n", 1)):
        pairs = cur.directives()
        for i, (leg, d) in enumerate(pairs):
            if getattr(d, field) <= floor or runs >= max_runs:
                continue
            cand_pairs = list(pairs)
            cand_pairs[i] = (leg, dataclasses.replace(d, **{field: floor}))
            cand = Schedule.from_directives(cur.subject, cur.fixture_seed,
                                            cand_pairs)
            if still_fails(cand):
                cur = cand
                pairs = cur.directives()
    return cur, runs


def repro_line(sched: Schedule) -> str:
    return ("python -m hfrep_tpu.resilience chaos --replay "
            f"'{sched.encode()}'")


# ---------------------------------------------------------------- corpus
def corpus_entries(corpus_dir=None) -> List[dict]:
    """The committed regression corpus, schema-checked: every entry
    carries the discovering seed, the (shrunk) schedule, its subject
    and the invariant it violated when found."""
    root = Path(corpus_dir) if corpus_dir is not None else CORPUS_DIR
    entries = []
    for f in sorted(root.glob("*.json")):
        try:
            doc = json.loads(f.read_text())
        except (OSError, json.JSONDecodeError) as e:
            raise ChaosError(f"unreadable corpus entry {f.name}: {e}")
        for field in ("schedule", "invariant", "found_by_seed"):
            if field not in doc:
                raise ChaosError(f"corpus entry {f.name} lacks {field!r}")
        doc["_file"] = f.name
        doc["_schedule"] = Schedule.decode(doc["schedule"])
        entries.append(doc)
    return entries


def corpus_entry_doc(sched: Schedule, invariant: str, seed: int,
                     detail: str) -> dict:
    return {"v": 1, "schedule": sched.encode(), "subject": sched.subject,
            "fixture_seed": sched.fixture_seed, "spec": sched.spec,
            "resume_spec": sched.resume_spec, "invariant": invariant,
            "found_by_seed": seed, "detail": detail,
            "repro": repro_line(sched)}


# ------------------------------------------------------------------ soak
def run_soak(*, seed: int, budget_secs: float, min_schedules: int,
             subjects: Sequence[str], fixture_seeds: int, workdir,
             replay_corpus: bool, shrink_findings: bool = True,
             max_schedules: int = 500) -> dict:
    """The budgeted search + (optionally) the corpus replay, sharing one
    reference cache.  Returns the machine summary the CLI prints; the
    ``ok`` field decides the gate."""
    from hfrep_tpu.obs import get_obs

    t_start = time.monotonic()
    obs = get_obs()
    driver = Driver(workdir)
    subjects = list(subjects)
    for name in subjects:
        if name not in SUBJECTS:
            raise ChaosError(
                f"unknown subject {name!r} "
                f"(registry: {', '.join(sorted(SUBJECTS))})")
    doc: dict = {"seed": seed, "subjects": subjects}
    findings: List[dict] = []

    # --- corpus replay first: a regression on a pinned schedule should
    # fail the gate before any budget is spent searching
    replayed = 0
    if replay_corpus:
        for entry in corpus_entries():
            sched = entry["_schedule"]
            report = driver.run_schedule(sched, tag="corpus")
            replayed += 1
            if not report.ok:
                findings.append({
                    "schedule": sched.encode(),
                    "invariant": report.violations[0].oracle,
                    "detail": report.violations[0].render(),
                    "shrunk": True, "corpus": entry["_file"],
                    "repro": repro_line(sched)})
                obs.event("chaos_violation", subject=sched.subject,
                          schedule=sched.encode(),
                          invariant=report.violations[0].oracle,
                          corpus=entry["_file"])
    doc["corpus_replayed"] = replayed

    # --- the seeded soak: deterministic schedule sequence; the budget
    # bounds wall time but never the coverage floor
    rng = random.Random(seed)
    driven: List[Report] = []
    seen = set()
    i = 0
    while i < max_schedules:
        elapsed = time.monotonic() - t_start
        if i >= min_schedules and elapsed >= budget_secs:
            break
        subject = SUBJECTS[subjects[i % len(subjects)]]
        sched = generate_schedule(rng, subject, fixture_seeds)
        for _ in range(20):
            if sched.encode() not in seen:
                break
            sched = generate_schedule(rng, subject, fixture_seeds)
        seen.add(sched.encode())
        report = driver.run_schedule(sched)
        driven.append(report)
        obs.event("chaos_schedule", subject=sched.subject,
                  schedule=sched.encode(),
                  attempts=len(report.attempts),
                  exits=[a.exit_code for a in report.attempts],
                  verdict="ok" if report.ok else
                  report.violations[0].oracle)
        if not report.ok:
            entry = {"schedule": sched.encode(),
                     "invariant": report.violations[0].oracle,
                     "detail": report.violations[0].render(),
                     "shrunk": False, "repro": repro_line(sched)}
            if shrink_findings:
                minimal, shrink_runs = shrink(driver, report)
                entry.update({
                    "schedule": minimal.encode(), "shrunk": True,
                    "shrink_runs": shrink_runs,
                    "minimal_spec": minimal.spec,
                    "minimal_resume_spec": minimal.resume_spec,
                    "repro": repro_line(minimal)})
                sched = minimal
            obs.event("chaos_violation", subject=sched.subject,
                      schedule=sched.encode(),
                      invariant=entry["invariant"],
                      shrunk=entry["shrunk"])
            findings.append(entry)
            _write_finding(driver.workdir, seed, entry, sched)
        i += 1

    doc.update({
        "schedules": len(driven),
        "distinct_subjects": len({r.schedule.subject for r in driven}),
        "preempted_runs": sum(
            1 for r in driven for a in r.attempts if a.exit_code == 75),
        "violations": len(findings),
        "findings": findings,
        "secs": round(time.monotonic() - t_start, 2),
        **driver.stats,
        "ok": not findings,
    })
    obs.gauge("chaos/schedules").set(len(driven))
    obs.gauge("chaos/subjects").set(doc["distinct_subjects"])
    obs.gauge("chaos/violations").set(len(findings))
    obs.gauge("chaos/run_secs").set(doc["run_secs_mean"])
    return doc


def _write_finding(workdir: Path, seed: int, entry: dict,
                   sched: Schedule) -> None:
    """Found minimal schedules land under ``<workdir>/found/`` as
    ready-to-commit corpus entries (the soak reports them; committing
    the fix + the pin is the human's move)."""
    from hfrep_tpu.utils.checkpoint import atomic_text

    found = workdir / "found"
    found.mkdir(parents=True, exist_ok=True)
    doc = corpus_entry_doc(sched, entry["invariant"], seed,
                           entry["detail"])
    atomic_text(found / f"{sched.subject}_{len(list(found.glob('*.json'))):03d}.json",
                json.dumps(doc, indent=2, sort_keys=True))


# -------------------------------------------------------------------- CLI
def add_chaos_args(ap) -> None:
    ap.add_argument("--seed", type=int, default=0,
                    help="soak seed: the schedule sequence is a pure "
                         "function of it")
    ap.add_argument("--budget-secs", type=float, default=120.0,
                    help="stop starting new schedules once elapsed "
                         "(never below --min-schedules)")
    ap.add_argument("--min-schedules", type=int, default=0,
                    help="coverage floor driven regardless of budget — "
                         "the CI gate's deterministic minimum")
    ap.add_argument("--subjects", default=None,
                    help="comma-separated subject names (default: the "
                         "fast tier: %s)" % ",".join(fast_subjects()))
    ap.add_argument("--fixture-seeds", type=int, default=1,
                    help="fixture seeds to draw from (more = more "
                         "reference runs, more data diversity)")
    ap.add_argument("--replay-corpus", action="store_true",
                    help="replay the committed regression corpus first")
    ap.add_argument("--replay", default=None, metavar="SCHEDULE",
                    help="drive ONE encoded schedule "
                         "(subject|seed|spec[|resume_spec]) and report")
    ap.add_argument("--no-shrink", action="store_true",
                    help="report findings unshrunk (faster triage)")
    ap.add_argument("--out", default=None,
                    help="work directory (default: a temp dir)")


def run_chaos(args) -> int:
    """``python -m hfrep_tpu.resilience chaos`` — exit 0 = no invariant
    violated, 1 = findings (repro lines on stderr), 2 = engine misuse."""
    import contextlib

    import hfrep_tpu.obs as obs_pkg

    subjects = (args.subjects.split(",") if args.subjects
                else list(fast_subjects()))
    with contextlib.ExitStack() as stack:
        if args.out:
            workdir = Path(args.out)
        else:
            workdir = Path(stack.enter_context(
                tempfile.TemporaryDirectory(prefix="hfrep_chaos_")))
        stack.enter_context(obs_pkg.session_or_off(
            os.environ.get("HFREP_OBS_DIR"), "chaos"))
        try:
            if args.replay:
                sched = Schedule.decode(args.replay)
                driver = Driver(workdir)
                report = driver.run_schedule(sched, tag="replay")
                doc = {
                    "schedule": sched.encode(),
                    "attempts": [[a.spec, a.exit_code, round(a.secs, 2)]
                                 for a in report.attempts],
                    "violations": [v.render() for v in report.violations],
                    "findings": [
                        {"detail": v.render(), "repro": repro_line(sched)}
                        for v in report.violations],
                    "ok": report.ok,
                }
            else:
                doc = run_soak(
                    seed=args.seed, budget_secs=args.budget_secs,
                    min_schedules=args.min_schedules, subjects=subjects,
                    fixture_seeds=max(1, args.fixture_seeds),
                    workdir=workdir, replay_corpus=args.replay_corpus,
                    shrink_findings=not args.no_shrink)
        except ChaosError as e:
            print(f"chaos: {e}", file=sys.stderr)
            return 2
        print(json.dumps(doc, sort_keys=True))
        if not doc["ok"]:
            for f in doc.get("findings", []):
                print(f"chaos VIOLATION: {f.get('detail')}\n"
                      f"  repro: {f.get('repro')}", file=sys.stderr)
            if args.out is None:
                print("(re-run with --out DIR to keep the evidence and "
                      "the ready-to-commit corpus entries)",
                      file=sys.stderr)
            return 1
    return 0
