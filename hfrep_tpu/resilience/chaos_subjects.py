"""Chaos subjects: the registry-derived view the fault-schedule search
exercises.

A *subject* is one end-to-end drive — chunked AE sweep, GAN
train→checkpoint→resume, serving load, walk-forward sweep, scenario
bank, orchestrate pipeline — wrapped so that a run is a **pure function
of ``(fixture_seed, schedule)``**.  Since ISSUE 20 the subject list is
100% DERIVED from :data:`hfrep_tpu.resilience.drive.DRIVE_REGISTRY`:
every registered :class:`~hfrep_tpu.resilience.drive.DriveSpec` becomes
one :class:`Subject` (its ``fixture`` binding is the run function, its
``timeout``/``tier``/``hint_sites`` carry over), so a new workload
registered with the Drive runtime is born chaos-covered — there is no
hand-maintained list to forget to extend, and
``drive.check_registry()`` + tests/test_drive.py fail if the two views
ever diverge.  The fixture bodies live in
:mod:`hfrep_tpu.resilience.drive_fixtures`.

The chaos engine (:mod:`hfrep_tpu.resilience.chaos`) spawns each run as
a fresh subprocess (``python -m hfrep_tpu.resilience chaos-subject ...``)
with the schedule's ``HFREP_FAULTS`` spec in the environment, under a
watchdog, and judges the wreckage with the shared oracles
(:mod:`hfrep_tpu.resilience.chaos_oracles`).

Subject contract (what :func:`subject_main` — now a thin shell over
:func:`hfrep_tpu.resilience.drive.run_drive` — enforces):

* runs under the drive's own :func:`hfrep_tpu.resilience.watchdog`
  and a real obs session at ``<out>/obs`` (stream parseability and
  crash-bundle presence are oracle surfaces);
* a drain (:class:`~hfrep_tpu.resilience.Preempted`) maps to exit 75
  through :func:`hfrep_tpu.obs.crash.bundle_if_enabled` — the repo's
  exit-code contract (analyzer rule HF007);
* final outputs land under ``<out>/artifacts`` through the atomic
  writers; scratch state (checkpoints, resume snapshots, queues) under
  ``<out>/scratch``; a completed run publishes ``chaos_result.json``
  with its invariant counters;
* ``deterministic=True`` subjects must produce bit-identical
  ``artifacts/`` for any faulted-then-resumed run vs. an undisturbed
  reference run of the same ``fixture_seed``.

``hint_sites`` bias the schedule generator toward fault sites the
subject actually crosses; the full registry stays in scope regardless
(:func:`hfrep_tpu.resilience.chaos.generate_schedule` mixes in
registry-wide draws, so a new fault site is automatically explored).
"""

from __future__ import annotations

import dataclasses
import json
import sys
from pathlib import Path
from typing import Callable, Dict, Tuple

from hfrep_tpu.resilience.drive import DRIVE_REGISTRY, DriveSpec
from hfrep_tpu.resilience.drive import EXIT_IO  # re-export: oracle contract

#: serving and stalls: an injected ``stall`` holds its site for
#: ``faults.STALL_SECS`` (120s) so that supervisor escalation paths win;
#: inside a single-process chaos subject there is no escalator, so the
#: subject harness scope-shortens it (documented knob on STALL_SECS) —
#: a stall becomes a bounded delay the deadline machinery must absorb,
#: not a watchdog-eating wedge.
SUBJECT_STALL_SECS = 0.5


@dataclasses.dataclass(frozen=True)
class Subject:
    """One chaos subject — the engine's view of a registered drive."""

    name: str
    run: Callable[[Path, int, bool], dict]
    timeout: float                 # in-process watchdog budget, seconds
    deterministic: bool = True     # artifacts bit-identical to reference
    tier: str = "fast"             # "fast" = soak default; "slow" = opt-in
    hint_sites: Tuple[str, ...] = ()


def _subject_of(spec: DriveSpec) -> Subject:
    def run(out: Path, fixture_seed: int, resume: bool,
            _spec: DriveSpec = spec) -> dict:
        # lazy: the registry stays listable without importing jax or
        # the training stacks — the fixture module loads on first run
        return _spec.load_fixture()(out, fixture_seed, resume)

    return Subject(name=spec.name, run=run, timeout=spec.timeout,
                   deterministic=spec.deterministic, tier=spec.tier,
                   hint_sites=tuple(spec.hint_sites))


#: DERIVED, never hand-edited: one subject per registered DriveSpec, in
#: registration order.  Register a drive, get a chaos subject.
SUBJECTS: Dict[str, Subject] = {
    name: _subject_of(spec) for name, spec in DRIVE_REGISTRY.items()}


def fast_subjects() -> Tuple[str, ...]:
    """The default soak set (registration order, hidden/slow excluded)."""
    return tuple(n for n, s in SUBJECTS.items()
                 if s.tier == "fast" and not n.startswith("_"))


# ------------------------------------------------------------ subprocess
RESULT_NAME = "chaos_result.json"


def subject_main(name: str, out_dir: str, fixture_seed: int,
                 resume: bool) -> int:
    """The ``chaos-subject`` subprocess entry: one drive fixture run
    under the full :func:`~hfrep_tpu.resilience.drive.run_drive`
    envelope (0 = complete, 75 = drained with state persisted, 74 =
    persistent storage failure, anything else = a bug the oracles will
    flag).  The envelope structure the corpus pins — graceful_drain
    OUTERMOST so a SIGTERM during the session's first stream append
    drains instead of killing the process raw (entry 003), and the
    session-boundary EIO typed 74 (entry 007) — now lives in
    ``drive.run_drive``, shared with every production entry point."""
    from hfrep_tpu.resilience import faults
    from hfrep_tpu.resilience.drive import run_drive

    spec = DRIVE_REGISTRY.get(name)
    if spec is None:
        print(f"unknown chaos subject {name!r} "
              f"(registry: {', '.join(sorted(DRIVE_REGISTRY))})",
              file=sys.stderr)
        return 2
    out = Path(out_dir)
    for sub in ("artifacts", "scratch"):
        (out / sub).mkdir(parents=True, exist_ok=True)
    faults.STALL_SECS = SUBJECT_STALL_SECS
    # NO persistent XLA compile cache here, deliberately: with the
    # persist threshold lowered so these ms-scale programs would cache,
    # deserialized executables on this runtime returned numerically
    # WRONG results on cache hit (a resumed gan_ckpt leg exploded to
    # NaN from a bit-verified healthy checkpoint — this engine's own
    # first catch; see utils/xla_cache.py).  Subjects pay their tiny
    # compiles fresh; correctness of the oracle surface over ~1s/run.
    invariants: dict = {}

    def work() -> int:
        invariants.update(
            spec.load_fixture()(out, fixture_seed, resume) or {})
        return 0

    code = run_drive(
        spec, work, obs_dir=out / "obs",
        session_meta={"command": f"chaos:{name}",
                      "chaos": {"subject": name,
                                "fixture_seed": fixture_seed,
                                "resume": resume}},
        watchdog_secs=spec.timeout,
        watchdog_name=f"chaos subject {name}")
    if code:
        return code
    from hfrep_tpu.utils.checkpoint import atomic_text
    atomic_text(out / RESULT_NAME, json.dumps(
        {"v": 1, "subject": name, "fixture_seed": fixture_seed,
         "resumed": bool(resume), "invariants": invariants},
        indent=2, sort_keys=True))
    return 0
