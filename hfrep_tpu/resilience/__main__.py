"""``python -m hfrep_tpu.resilience`` — the resilience subsystem CLI.

    selftest        the scripted kill→resume / chaos-scenario gate
                    (selftest.py; wired into tools/check.sh)
    chaos           property-based fault-schedule search: seeded random
                    schedules over the fault registries, driven through
                    real subjects in subprocesses, judged by the shared
                    invariant oracles, failures auto-shrunk to minimal
                    HFREP_FAULTS specs; --replay-corpus replays the
                    committed regression corpus (chaos.py)
    chaos-subject   internal: one subject run in THIS process (the
                    chaos driver's spawn target; exit 0 complete /
                    75 drained)
    explain-faults  pretty-print a parsed HFREP_FAULTS spec — kind /
                    site / counter-group / occurrence / count / effect —
                    so a shrunk repro line is one paste from readable
    drives          list every registered DriveSpec and its envelope
                    capabilities (drive.py); --check runs the registry
                    completeness gate (fixtures resolve, fault sites
                    known, all six families covered, registry↔chaos
                    subjects mirror in both directions) — wired into
                    tools/check.sh
"""

from __future__ import annotations

import argparse
import json
import sys
from hfrep_tpu.obs import timeline
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hfrep_tpu.resilience",
        description="fault injection + recovery subsystem CLI")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("selftest",
                   help="drive kill→resume + corrupt→fallback end to end "
                        "and assert bit-identical recovery (fast fixture "
                        "shapes; wired into tools/check.sh)")

    chaos_p = sub.add_parser(
        "chaos",
        help="seeded property-based fault-schedule search with shrinking "
             "and corpus replay (exit 1 on any invariant violation)")
    from hfrep_tpu.resilience.chaos import add_chaos_args
    add_chaos_args(chaos_p)

    subj_p = sub.add_parser(
        "chaos-subject",
        help="internal: run ONE chaos subject in this process (the "
             "driver spawns these; HFREP_FAULTS arms the schedule)")
    subj_p.add_argument("name")
    subj_p.add_argument("--out", required=True)
    subj_p.add_argument("--fixture-seed", type=int, default=0)
    subj_p.add_argument("--resume", action="store_true")

    drv_p = sub.add_parser(
        "drives",
        help="list registered DriveSpecs + envelope capabilities; "
             "--check gates registry completeness (exit 1 on a hole)")
    drv_p.add_argument("--format", choices=("human", "json"),
                       default="human")
    drv_p.add_argument("--check", action="store_true",
                       help="run the completeness gate instead of just "
                            "listing")

    exp_p = sub.add_parser(
        "explain-faults",
        help="pretty-print a parsed HFREP_FAULTS spec (unknown sites "
             "error with the registry's nearest candidates)")
    exp_p.add_argument("spec")
    exp_p.add_argument("--format", choices=("human", "json"),
                       default="human")

    args = ap.parse_args(argv)

    if args.cmd == "selftest":
        from hfrep_tpu.resilience.selftest import run_selftest
        t0 = timeline.clock()
        try:
            doc = run_selftest()
        except Exception as e:
            print(json.dumps({"selftest": "FAIL",
                              "error": f"{type(e).__name__}: {e}"}))
            return 1
        doc["selftest"] = "ok"
        doc["secs"] = round(timeline.clock() - t0, 2)
        print(json.dumps(doc))
        return 0

    if args.cmd == "chaos":
        from hfrep_tpu.resilience.chaos import run_chaos
        return run_chaos(args)

    if args.cmd == "chaos-subject":
        from hfrep_tpu.resilience.chaos_subjects import subject_main
        return subject_main(args.name, args.out, args.fixture_seed,
                            args.resume)

    if args.cmd == "drives":
        from hfrep_tpu.resilience.drive import (
            DRIVE_REGISTRY,
            check_registry,
            spec_capabilities,
        )
        rows = [spec_capabilities(s) for s in DRIVE_REGISTRY.values()]
        ok, problems = (check_registry() if args.check else (True, []))
        if args.format == "json":
            print(json.dumps({"drives": rows, "ok": ok,
                              "problems": problems}, indent=2,
                             sort_keys=True))
        else:
            for r in rows:
                caps = [r["snapshot"] if r["snapshot"] != "none" else "",
                        "deterministic" if r["deterministic"] else "",
                        "resumable" if r["resumable"] else "",
                        "double-buffer" if r["double_buffer"] else ""]
                print(f"{r['name']:<14} {r['family']:<12} "
                      f"tier={r['tier']:<5} "
                      f"sites={','.join(r['boundary_sites']) or '-':<28} "
                      f"{' '.join(c for c in caps if c)}")
            for p in problems:
                print(f"PROBLEM: {p}", file=sys.stderr)
            if args.check:
                print(f"drives: {len(rows)} specs, "
                      f"{'ok' if ok else f'{len(problems)} problem(s)'}",
                      file=sys.stderr)
        return 0 if ok else 1

    # explain-faults
    from hfrep_tpu.resilience.faults import (
        FaultPlan,
        FaultSpecError,
        plan_rows,
        render_plan,
    )
    try:
        plan = FaultPlan.parse(args.spec)
    except FaultSpecError as e:
        print(f"explain-faults: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps({"spec": plan.spec(), "directives":
                          plan_rows(plan)}, sort_keys=True))
    else:
        print(render_plan(plan))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
