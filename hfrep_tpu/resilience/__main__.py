"""``python -m hfrep_tpu.resilience`` — see selftest.py."""

from __future__ import annotations

import sys

from hfrep_tpu.resilience.selftest import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
