"""``python -m hfrep_tpu.resilience selftest`` — the kill→resume gate.

Drives REAL training through the failure modes the package exists for,
at fast fixture shapes (seconds on CPU), and asserts the recovery
contracts hold bitwise:

1. **Checkpoint cycle** — atomic save (embedded checksum'd ``meta.json``),
   verified restore, injected *corrupt* and *torn* checkpoints detected
   (:class:`~hfrep_tpu.utils.checkpoint.CheckpointCorrupt`) and
   ``restore_latest_good`` falling back to the previous good checkpoint;
   the msgpack (``coordination_free``) format round-trips.
2. **Kill→resume, 21-lane sweep** — an injected REAL SIGTERM
   (``sigterm@chunk=2``) lands mid-sweep; the graceful-drain handler
   turns it into a chunk-boundary :class:`~hfrep_tpu.resilience.
   Preempted` with state snapshotted; a re-run resumes from the last
   chunk and must produce results **bit-identical** to an uninterrupted
   run (params, loss traces, stop epochs).
3. **Kill→resume, multi-dataset sweep** — same contract for the fused
   (K+1)×L padded program, via the signal-free ``preempt`` injection.
4. **Ensemble: SIGKILL one actor of a running fabric** — a real
   multi-process pipeline (2 generator actors streaming into an AE
   sweep consumer over the bounded spool queue,
   :mod:`hfrep_tpu.orchestrate`); an injected ``kill@actor`` makes the
   supervisor SIGKILL a generator mid-stream — REAL ``SIGKILL``, no
   handler, no cleanup — and the run must still complete with every
   artifact **bit-identical** to the undisturbed reference (computed
   in-process from the same pure functions: the fabric's determinism
   contract is that no interleaving, restart or kill can change a byte).
5. **Ensemble: coordinated pod drain → resume** — an injected pod-wide
   drain (``preempt@actor=2``: at the 2nd queue item observed) triggers
   the supervisor's drain barrier; every member checkpoints at its item
   boundary and the pipeline raises
   :class:`~hfrep_tpu.resilience.Preempted`; the resumed pipeline
   completes bit-identical to the reference.
6. **Serving chaos (``hfrep_tpu.serve``)** — a real
   :class:`~hfrep_tpu.serve.ReplicationServer` over a trained AE head
   under ``kill@serve_worker`` (worker dies mid-batch, batch fails over
   and retries) + ``io_fail@serve_result`` (result publish raises EIO)
   + a ``stall@batcher`` deadline storm + an overload burst past the
   admission bound: **every submitted request reaches exactly one
   terminal outcome** (zero silent drops — the ledger's
   ``terminal == submitted`` invariant), sheds and deadline misses are
   typed, the circuit breaker trips on repeated faults and serves
   degraded last-good answers *flagged stale*, closes again after
   cooldown, and a REAL SIGTERM drains the server (admission stops,
   in-flight flushes, :class:`~hfrep_tpu.resilience.Preempted` at the
   next boundary → the CLI's exit 75).

Every scenario runs under its own watchdog timeout (the shared
:func:`hfrep_tpu.resilience.watchdog`, SIGALRM — the chaos subjects use
the same one): one wedged scenario fails loudly with its name and
budget instead of eating the whole ``tools/check.sh`` time budget as a
silent hang.

Exit 0 with one JSON line on stdout; any violated contract raises and
exits 1.  Wired into ``tools/check.sh`` (env-stripped, CPU-pinned) next
to the analyzer/obs/bench gates.
"""

from __future__ import annotations

import contextlib
import os
import signal
import tempfile
import time
from pathlib import Path

import numpy as np

from hfrep_tpu.resilience import WatchdogTimeout, watchdog

#: backwards-compatible alias — the scenario watchdog is now the shared
#: :func:`hfrep_tpu.resilience.watchdog`
ScenarioTimeout = WatchdogTimeout


@contextlib.contextmanager
def _scenario_timeout(name: str, secs: float):
    with watchdog(secs, f"scenario {name}"):
        yield


def _fixture_panel(rows: int = 90, feats: int = 6):
    # shared builder (utils/fixture_data); seed 11 is this selftest's
    # pinned stream — the kill→resume bit-identity references depend on it
    from hfrep_tpu.utils.fixture_data import scaled_panel
    return scaled_panel(rows, feats, seed=11)


def _assert_results_identical(a, b, what: str) -> None:
    import jax
    import jax.numpy as jnp
    la, lb = (jax.tree_util.tree_leaves(a.params),
              jax.tree_util.tree_leaves(b.params))
    assert len(la) == len(lb) and all(
        bool(jnp.array_equal(x, y)) for x, y in zip(la, lb)), \
        f"{what}: resumed params differ from the uninterrupted run"
    for field in ("stop_epoch", "train_loss", "val_loss"):
        assert np.array_equal(np.asarray(getattr(a, field)),
                              np.asarray(getattr(b, field)),
                              equal_nan=True), \
            f"{what}: resumed {field} differs from the uninterrupted run"


def _check_checkpoint_cycle(td: str) -> dict:
    import jax.numpy as jnp
    from hfrep_tpu.resilience import faults
    from hfrep_tpu.utils import checkpoint as ckpt

    d = os.path.join(td, "ckpts")
    t1 = {"w": jnp.arange(8.0), "step": jnp.asarray(3)}
    t2 = {"w": jnp.arange(8.0) * 2.0, "step": jnp.asarray(6)}
    ckpt.save(os.path.join(d, "ckpt_1"), t1, metadata={"epoch": 1})
    p2 = ckpt.save(os.path.join(d, "ckpt_2"), t2, metadata={"epoch": 2})
    meta = ckpt.read_meta(p2)
    assert meta and "checksum" in meta and "epoch" in meta, \
        "meta.json (metadata + checksum) must live inside the checkpoint"

    restored = ckpt.restore(p2, target=t1)
    assert np.allclose(np.asarray(restored["w"]), np.arange(8.0) * 2.0)

    # corrupt the newest payload → detected, and fallback lands on ckpt_1
    faults.corrupt_file(faults._payload_file(Path(p2)))
    try:
        ckpt.restore(p2, target=t1)
        raise AssertionError("corrupted checkpoint restored without error")
    except ckpt.CheckpointCorrupt:
        pass
    good, path = ckpt.restore_latest_good(d, target=t1)
    assert path.endswith("ckpt_1") and np.allclose(
        np.asarray(good["w"]), np.arange(8.0)), \
        "fallback must restore the previous good checkpoint"

    # torn msgpack round-trip: coordination-free format + tear detection
    p3 = ckpt.save(os.path.join(d, "ckpt_3"), t1, coordination_free=True)
    assert (Path(p3) / "checkpoint.msgpack").exists()
    r3 = ckpt.restore(p3, target=t2)
    assert np.allclose(np.asarray(r3["w"]), np.arange(8.0))
    faults.tear_file(Path(p3) / "checkpoint.msgpack")
    try:
        ckpt.restore(p3, target=t2)
        raise AssertionError("torn checkpoint restored without error")
    except ckpt.CheckpointCorrupt:
        pass
    good, path = ckpt.restore_latest_good(d, target=t1)
    assert path.endswith("ckpt_1"), "fallback must skip the torn checkpoint"
    return {"checkpoint_cycle": "ok"}


def _kill_resume(td: str, name: str, spec: str, run) -> dict:
    """``run(resume_dir)`` once uninterrupted (resume_dir=None), once
    under the fault ``spec`` (must raise Preempted), once resuming —
    the resumed results must be bit-identical."""
    import hfrep_tpu.resilience as res

    base, base_stats = run(None)
    rd = os.path.join(td, name)
    res.install_plan(res.FaultPlan.parse(spec))
    try:
        run(rd)
        raise AssertionError(f"{name}: injected fault {spec!r} did not "
                             "preempt the sweep")
    except res.Preempted as e:
        assert e.snapshot, f"{name}: drain must report the persisted snapshot"
    finally:
        res.clear_plan()
    resumed, stats = run(rd)
    _assert_results_identical(base, resumed, name)
    assert not os.path.exists(os.path.join(rd, "chunk_snapshot")), \
        f"{name}: snapshot must be cleared after a completed drive"
    return {name: "ok", f"{name}_chunks": int(stats.chunks_dispatched),
            f"{name}_lanes": int(stats.lanes)}


def _ensemble_plan(out_dir: str):
    """The tiny fixture pipeline shared by the ensemble scenarios: 2
    generator actors × 2 blocks, 1 consumer, capacity-1 backpressure (so
    a producer is reliably alive/blocked when the injected kill lands)."""
    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.orchestrate import PipelinePlan, SourceSpec

    rows, feats = 32, 4
    cfg = AEConfig(n_factors=feats, latent_dim=2, epochs=6, batch_size=16,
                   patience=2, seed=0, chunk_epochs=3)
    sources = [SourceSpec(name=f"s{i}", mode="fixture",
                          params={"rows": rows, "feats": feats})
               for i in range(2)]
    return PipelinePlan(out_dir=out_dir, sources=sources, blocks=2,
                        consumers=1, capacity=1, ae_cfg=cfg,
                        latent_dims=[1, 2], consume_mode="direct",
                        stream_seed=11, drain_timeout=60.0, timeout=240.0)


def _expected_digests(plan) -> dict:
    """The undisturbed reference, computed IN-PROCESS with no actors:
    every item is a pure function of (stream_seed, source, seq) and
    every result a pure function of its item, so the expected artifact
    digests follow from the same code the consumers run — the fabric
    under injected kills must reproduce these bytes exactly."""
    import hashlib
    import io

    import jax
    from hfrep_tpu.orchestrate.actors import _fixture_panel
    from hfrep_tpu.replication.engine import sweep_item_arrays
    from hfrep_tpu.utils import checkpoint as ckpt_mod

    out = {}
    for idx, src in enumerate(plan.sources):
        items = {}
        for seq in range(plan.blocks):
            panel = _fixture_panel(plan.stream_seed, idx, seq,
                                   src.params["rows"], src.params["feats"])
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(plan.ae_cfg.seed),
                                   idx), seq)
            arrays = sweep_item_arrays(key, panel, plan.ae_cfg,
                                       plan.latent_dims)
            buf = io.BytesIO()
            np.savez(buf, **arrays)
            # the aggregate digest checkpoint.compute_checksum embeds in
            # the artifact's meta.json (one payload file: sweep.npz)
            items[f"{seq:05d}"] = ckpt_mod.aggregate_digest(
                {"sweep.npz": hashlib.sha256(buf.getvalue()).hexdigest()})
        out[src.name] = items
    return out


def _summary_digests(summary: dict) -> dict:
    return {name: doc["items"] for name, doc in summary["sources"].items()}


def _check_ensemble(td: str) -> dict:
    import hfrep_tpu.obs as obs_pkg
    import hfrep_tpu.resilience as res
    from hfrep_tpu.orchestrate import run_pipeline

    expected = _expected_digests(_ensemble_plan(os.path.join(td, "unused")))

    # --- scenario 4: REAL SIGKILL of a generator actor mid-stream; the
    # supervisor restarts it from its sub-block snapshot and the run
    # completes bit-identical to the undisturbed reference.  Runs under
    # a real obs session so the flight recorder's cross-process,
    # cross-RESTART trace reconstruction is asserted on the same kill
    # (ISSUE 12: trace IDs are pure functions of the item coordinate,
    # so the restarted member re-emits the same IDs)
    kill_plan = _ensemble_plan(os.path.join(td, "ens_kill"))
    obs_dir = os.path.join(td, "obs_ens_kill")
    res.install_plan(res.FaultPlan.parse("kill@actor=1"))
    try:
        with obs_pkg.session(obs_dir, command="selftest-ensemble"):
            out = run_pipeline(kill_plan)
    finally:
        res.clear_plan()
    assert out["stats"]["restarts"] >= 1, \
        "ensemble kill: the SIGKILL did not land on a live member"
    assert _summary_digests(out["summary"]) == expected, \
        "ensemble kill: artifacts differ from the undisturbed reference"
    _check_trace_continuity(kill_plan, obs_dir)

    # --- scenario 5: pod-wide drain at the 2nd observed item → barrier
    # (every member checkpoints at its item boundary) → resume completes
    # bit-identical
    drain_out = os.path.join(td, "ens_drain")
    res.install_plan(res.FaultPlan.parse("preempt@actor=2"))
    try:
        run_pipeline(_ensemble_plan(drain_out))
        raise AssertionError("ensemble drain: injected pod drain did not "
                             "preempt the pipeline")
    except res.Preempted:
        pass
    finally:
        res.clear_plan()
    resumed = run_pipeline(_ensemble_plan(drain_out), resume=True)
    assert _summary_digests(resumed["summary"]) == expected, \
        "ensemble drain: resumed artifacts differ from the reference"
    return {"ensemble_kill": "ok",
            "ensemble_kill_restarts": int(out["stats"]["restarts"]),
            "ensemble_kill_traces": "ok",
            "ensemble_drain": "ok"}


def _check_trace_continuity(plan, obs_dir: str) -> None:
    """The flight-recorder acceptance on the SIGKILL scenario: every
    pipeline item's trace reconstructs end to end (queue_put →
    queue_get → sweep → result_publish) across the producer's and
    consumer's separate processes, and the reconstruction SPANS the
    restart — the killed incarnation's rotated stream contributes the
    pre-kill hop under the same (deterministic) trace ID."""
    from hfrep_tpu.obs.report import trace_index
    from hfrep_tpu.orchestrate.queue import item_trace_id

    tids = [item_trace_id(plan.stream_seed, src.name, seq)
            for src in plan.sources for seq in range(plan.blocks)]
    index = trace_index([obs_dir], tids)   # one parse for all items
    all_records = []
    for src in plan.sources:
        for seq in range(plan.blocks):
            tid = item_trace_id(plan.stream_seed, src.name, seq)
            recs = index.get(tid, [])
            names = {r.get("name") for r in recs}
            assert "queue_put" in names, \
                f"trace {tid}: producer hop (queue_put) missing"
            assert "result_publish" in names, \
                f"trace {tid}: terminal hop (result_publish) missing"
            assert len({r["_dir"] for r in recs}) >= 2, \
                f"trace {tid}: events do not span producer + consumer " \
                f"processes ({names})"
            all_records.extend(recs)
    assert any(r["_rotated"] and r.get("name") == "queue_put"
               for r in all_records), \
        "no pre-kill (rotated-stream) queue_put found: the " \
        "reconstruction does not span the restart"


def _serving_fixture_server(workers: int = 1):
    """A real server over a really-trained (tiny) AE replication head."""
    import jax
    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.replication.engine import train_autoencoder_chunked
    from hfrep_tpu.serve import AEServeModel, ReplicationServer, ServeConfig

    cfg = AEConfig(n_factors=6, latent_dim=3, epochs=8, batch_size=16,
                   patience=2, seed=0, chunk_epochs=4)
    res, _ = train_autoencoder_chunked(jax.random.PRNGKey(3),
                                       _fixture_panel(40, 6), cfg)
    model = AEServeModel.create(cfg, res.params)
    scfg = ServeConfig(max_batch=4, batch_window_ms=5.0,
                       request_timeout_ms=3000.0, max_queue=32,
                       workers=workers, row_buckets=(32, 64),
                       breaker_failures=2, breaker_cooldown_s=0.3,
                       compile_storm=64)
    return ReplicationServer(scfg, ae_model=model).start()


def _await_all(futures) -> None:
    from concurrent.futures import wait
    wait(futures, timeout=60)
    undone = [f for f in futures if not f.done()]
    assert not undone, (f"serving: {len(undone)} requests never reached a "
                        "terminal outcome (silent drop / hang)")


def _check_serving(td: str) -> dict:
    """Scenario 6 runs under a REAL obs session so the flight recorder's
    request tracing is asserted against the same chaos the envelope
    takes: the settle probe threads an explicit trace ID and its
    admit → dispatch → complete path must reconstruct with per-hop
    durations via ``report --trace`` machinery."""
    import hfrep_tpu.obs as obs_pkg

    obs_dir = os.path.join(td, "obs_serve")
    with obs_pkg.session(obs_dir, command="selftest-serving"):
        return _serving_scenario(td, obs_dir)


def _serving_scenario(td: str, obs_dir: str) -> dict:
    import hfrep_tpu.resilience as res
    from hfrep_tpu.resilience import faults
    from hfrep_tpu.serve import Overloaded
    from hfrep_tpu.serve.loadgen import classify, make_panels

    server = _serving_fixture_server(workers=1)
    panels = make_panels(5, 6, (16, 28), variants=4)
    try:
        # warm the (batch-bucket, row-bucket) programs OUTSIDE the fault
        # plan so the chaos phase measures the envelope, not XLA compiles
        for n in (1, 2, 4):
            _await_all([server.replicate(panels[i % len(panels)],
                                         timeout_ms=30000)
                        for i in range(n)])

        # --- chaos: worker killed mid-batch + result-publish EIO + a
        # wedged batcher manufacturing a deadline storm, plus an
        # overload burst past the admission bound — every submitted
        # request must land in exactly one typed terminal outcome
        res.install_plan(res.FaultPlan.parse(
            "kill@serve_worker=2;io_fail@serve_result=5;stall@batcher=3"))
        prev_stall, faults.STALL_SECS = faults.STALL_SECS, 0.3
        try:
            futs = []
            for i in range(40):
                tight = i % 5 == 4
                futs.append(server.replicate(
                    panels[i % len(panels)],
                    timeout_ms=25.0 if tight else 5000.0))
                if i % 8 == 7:
                    time.sleep(0.01)
            # burst: 2x the admission bound at once — the excess must
            # shed typed, immediately
            futs += [server.replicate(panels[0], timeout_ms=5000.0)
                     for _ in range(2 * server.cfg.max_queue)]
            _await_all(futs)
        finally:
            faults.STALL_SECS = prev_stall
            res.clear_plan()
        chaos = classify(futs)
        ledger = server.outcomes.as_dict()
        assert ledger["terminal"] == ledger["submitted"], \
            f"serving chaos: silent drops — ledger {ledger}"
        assert ledger["worker_kills"] >= 1, \
            "serving chaos: the injected worker kill never landed"
        assert ledger["requeues"] >= 1, \
            "serving chaos: the killed batch was not failed over"
        assert ledger["worker_faults"] >= 1, \
            "serving chaos: the injected result EIO produced no typed fault"
        assert ledger["deadline_missed"] >= 1, \
            "serving chaos: the stall produced no deadline miss"
        assert chaos["shed"] >= 1, \
            "serving chaos: the overload burst was not shed"
        assert chaos["results"] >= 1, \
            "serving chaos: nothing was actually served"
        assert chaos["errors"] == 0, \
            f"serving chaos: untyped outcomes: {chaos}"

        # settle: the chaos faults may have left the breaker open — wait
        # out the cooldown and let one clean probe close it, so the
        # breaker phase below observes its own trip, not the chaos one's
        time.sleep(server.cfg.breaker_cooldown_s + 0.1)
        settle = server.replicate(panels[0], timeout_ms=5000.0,
                                  trace_id="st-settle")
        _await_all([settle])
        assert server.breaker.state == "closed", \
            f"breaker did not settle closed: {server.breaker.state}"

        # flight recorder: the settle probe's critical path must
        # reconstruct — admit → (batch-wait) dispatch → complete — with
        # per-hop durations attributed
        from hfrep_tpu.obs import get_obs
        from hfrep_tpu.obs.report import trace_events
        get_obs().flush()
        recs = trace_events([obs_dir], "st-settle")
        names = [r.get("name") for r in recs]
        for hop in ("serve_admit", "serve_dispatch", "serve_complete"):
            assert hop in names, \
                f"serve trace missing hop {hop}: {names}"
        (done,) = [r for r in recs if r.get("name") == "serve_complete"]
        assert done.get("queue_ms") is not None \
            and done.get("exec_ms") is not None, \
            f"serve trace lacks per-hop durations: {done}"

        # --- breaker: every publish fails → consecutive faults trip it
        # OPEN; submits then get last-good DEGRADED answers flagged
        # stale; cooldown + one good probe close it again
        res.install_plan(res.FaultPlan.parse("io_fail@serve_result=1x50"))
        try:
            faulted = 0
            for _ in range(4):
                f = server.replicate(panels[0], timeout_ms=5000.0)
                _await_all([f])
                if f.exception() is not None:
                    faulted += 1
                if server.breaker.state == "open":
                    break
            assert server.breaker.state == "open" and faulted >= 2, \
                (f"serving breaker: {faulted} faults did not trip it "
                 f"(state {server.breaker.state})")
            degraded = server.replicate(panels[1], timeout_ms=5000.0)
            _await_all([degraded])
            out = degraded.result()
            assert out.stale, "breaker-open answer must be flagged stale"
        finally:
            res.clear_plan()
        time.sleep(server.cfg.breaker_cooldown_s + 0.1)
        probe = server.replicate(panels[0], timeout_ms=5000.0)
        _await_all([probe])
        assert probe.exception() is None and not probe.result().stale, \
            "post-cooldown probe must serve fresh"
        assert server.breaker.state == "closed", \
            f"breaker did not close after a good probe: {server.breaker.state}"

        # --- drain: a REAL SIGTERM through graceful_drain stops
        # admission, flushes in-flight work, and preempts at the next
        # boundary (the CLI maps this to exit 75)
        with res.graceful_drain():
            inflight = [server.replicate(panels[i % len(panels)],
                                         timeout_ms=10000.0)
                        for i in range(6)]
            os.kill(os.getpid(), signal.SIGTERM)
            assert res.drain_requested(), \
                "SIGTERM did not set the drain flag"
            doc = server.drain(reason="selftest SIGTERM", timeout=30.0)
            assert doc["flushed"], f"drain did not flush in-flight: {doc}"
            _await_all(inflight)
            for f in inflight:
                err = f.exception()
                assert err is None or isinstance(err, Overloaded) or \
                    getattr(err, "code", "") in ("draining", "deadline"), \
                    f"drain left an untyped outcome: {err!r}"
            late = server.replicate(panels[0])
            _await_all([late])
            assert getattr(late.exception(), "code", None) in (
                "draining", "closed"), \
                "post-drain admission must be a typed rejection"
            try:
                res.boundary("serve_drive")
                raise AssertionError(
                    "drain flag set but boundary did not preempt")
            except res.Preempted:
                pass
        ledger = server.outcomes.as_dict()
        assert ledger["terminal"] == ledger["submitted"], \
            f"serving drain: silent drops — ledger {ledger}"
        return {"serving_chaos": "ok",
                "serving_submitted": ledger["submitted"],
                "serving_sheds": ledger["shed"],
                "serving_deadline_misses": ledger["deadline_missed"],
                "serving_worker_kills": ledger["worker_kills"],
                "serving_breaker_trips": server.breaker.trips,
                "serving_drain": "ok"}
    finally:
        server.stop()


#: per-scenario watchdog budgets (seconds) — generous multiples of the
#: measured CPU fixture times, tight enough that a wedge cannot eat the
#: whole tools/check.sh budget silently
SCENARIO_BUDGETS = {
    "checkpoint_cycle": 60.0,
    "lanes21": 120.0,
    "multi": 120.0,
    "ensemble": 300.0,
    "serving": 120.0,
}


def run_selftest() -> dict:
    import dataclasses

    import jax
    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.replication.engine import (
        stack_padded,
        sweep_autoencoders_chunked,
        sweep_autoencoders_multi,
    )

    xs = _fixture_panel()
    doc: dict = {}
    with tempfile.TemporaryDirectory(prefix="hfrep_resilience_") as td:
        with _scenario_timeout("checkpoint_cycle",
                               SCENARIO_BUDGETS["checkpoint_cycle"]):
            doc.update(_check_checkpoint_cycle(td))

        # the paper's 21-lane latent sweep, shrunk to fixture epochs —
        # a real vmapped training drive killed by a REAL SIGTERM
        cfg = AEConfig(n_factors=6, latent_dim=21, epochs=24, batch_size=16,
                       patience=3, seed=0, chunk_epochs=6)
        dims = list(range(1, 22))
        key = jax.random.PRNGKey(0)
        with _scenario_timeout("lanes21", SCENARIO_BUDGETS["lanes21"]):
            doc.update(_kill_resume(
                td, "lanes21", "sigterm@chunk=2",
                lambda rd: sweep_autoencoders_chunked(key, xs, cfg, dims,
                                                      resume_dir=rd)))

        # the fused multi-dataset fabric (2 padded datasets × 3 lanes)
        mcfg = dataclasses.replace(cfg, latent_dim=4)
        stack, rows = stack_padded([xs, xs[:70]])
        with _scenario_timeout("multi", SCENARIO_BUDGETS["multi"]):
            doc.update(_kill_resume(
                td, "multi", "preempt@chunk=1",
                lambda rd: sweep_autoencoders_multi(key, stack, rows, mcfg,
                                                    [1, 2, 3],
                                                    resume_dir=rd)))

        # the async actor fabric: REAL SIGKILL of a running ensemble
        # member + coordinated pod drain → resume, both bit-identical
        with _scenario_timeout("ensemble", SCENARIO_BUDGETS["ensemble"]):
            doc.update(_check_ensemble(td))

        # the serving layer: chaos (kill/EIO/deadline storm/overload),
        # breaker + degraded answers, SIGTERM drain — zero silent drops
        with _scenario_timeout("serving", SCENARIO_BUDGETS["serving"]):
            doc.update(_check_serving(td))
    return doc
