
from __future__ import annotations
from hfrep_tpu.utils.logging import MetricLogger  # noqa: F401
from hfrep_tpu.utils.profiling import StepTimer  # noqa: F401
