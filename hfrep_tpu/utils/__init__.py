from __future__ import annotations
