"""Version-gated JAX API gates for the parallel launch paths.

The shard_map launch paths target ``jax.shard_map`` with
``check_vma=True`` — the varying-manual-axes replication checker of the
newer JAX typing stack.  The pinned runtime in this image (jax 0.4.37)
has neither ``jax.shard_map`` nor ``check_vma``; the experimental
``jax.experimental.shard_map`` that *does* exist carries the older
``check_rep`` semantics (no vma types, no ``lax.pcast``) and is NOT a
drop-in — silently substituting it would change what the type checker
proves.  Until the partition-rule mesh refactor (ROADMAP item 1)
replaces these paths, the contract is:

* every version-gated reference lives behind THE one guarded import in
  this module (rule HF005 flags any direct ``jax.shard_map`` /
  ``jax.lax.axis_size`` reference elsewhere);
* importing a launch-path module always succeeds — on a runtime without
  the API, building a shard_map step raises a typed
  :class:`ShardMapUnavailable` at the call site instead of an
  ``ImportError`` killing the whole module (and every test file that
  imports it) at collection time;
* tests gate on :data:`HAS_SHARD_MAP` and skip, not error, where the
  runtime cannot run them.

The committed HF005 kill list
(``hfrep_tpu/analysis/HF005_KILL_LIST.md``) enumerates exactly which
entry points die at this gate on the pinned runtime.
"""

from __future__ import annotations

try:
    from jax import shard_map as shard_map  # noqa: F401
    HAS_SHARD_MAP = True
except ImportError:                # pinned jax 0.4.37: API absent
    HAS_SHARD_MAP = False


class ShardMapUnavailable(RuntimeError):
    """A shard_map launch path was exercised on a runtime without
    ``jax.shard_map`` (+ ``check_vma``).  The vmap/single-device paths
    and all checkpoint/resume machinery keep working; only sharded
    execution needs the newer runtime."""


if not HAS_SHARD_MAP:
    def shard_map(*args, **kwargs):        # noqa: F811  (the gate stub)
        import jax
        raise ShardMapUnavailable(
            "jax.shard_map (with check_vma) is absent on this runtime "
            f"(jax {jax.__version__}); this shard_map launch path is dead "
            "here — see hfrep_tpu/analysis/HF005_KILL_LIST.md and ROADMAP "
            "item 1 (partition-rule mesh refactor)")


try:
    from jax.lax import axis_size as axis_size  # noqa: F401
except ImportError:
    def axis_size(axis_name):              # noqa: F811
        """``lax.axis_size`` where present; the ``psum(1, axis)`` idiom
        (identical value, one collective) on older runtimes."""
        from jax import lax
        return lax.psum(1, axis_name)
