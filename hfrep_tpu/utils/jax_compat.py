"""Version-gated JAX API gates for the parallel launch paths.

The shard_map launch paths target ``jax.shard_map`` with
``check_vma=True`` — the varying-manual-axes replication checker of the
newer JAX typing stack.  The pinned runtime in this image (jax 0.4.37)
has neither ``jax.shard_map`` nor ``check_vma``; the experimental
``jax.experimental.shard_map`` that *does* exist carries the older
``check_rep`` semantics (no vma types, no ``lax.pcast``) and is NOT a
drop-in — silently substituting it would change what the type checker
proves.  Since the partition-rule mesh refactor (ISSUE 15) the only
launch that still needs the gate is the manual layer pipeline
(``parallel/layer_pipeline.py``); the contract stays:

* every version-gated reference lives behind THE one guarded import in
  this module (rule HF005 flags any direct ``jax.shard_map`` /
  ``jax.lax.axis_size`` reference elsewhere);
* importing a launch-path module always succeeds — on a runtime without
  the API, building a shard_map step raises a typed
  :class:`ShardMapUnavailable` at the call site instead of an
  ``ImportError`` killing the whole module (and every test file that
  imports it) at collection time;
* tests gate on :data:`HAS_SHARD_MAP` and skip, not error, where the
  runtime cannot run them.

The committed HF005 kill list
(``hfrep_tpu/analysis/HF005_KILL_LIST.md``) enumerates exactly which
entry points die at this gate on the pinned runtime.
"""

from __future__ import annotations

try:
    from jax import shard_map as shard_map  # noqa: F401
    HAS_SHARD_MAP = True
except ImportError:                # pinned jax 0.4.37: API absent
    HAS_SHARD_MAP = False


class ShardMapUnavailable(RuntimeError):
    """A shard_map launch path was exercised on a runtime without
    ``jax.shard_map`` (+ ``check_vma``).  The vmap/single-device paths
    and all checkpoint/resume machinery keep working; only sharded
    execution needs the newer runtime."""


if not HAS_SHARD_MAP:
    def shard_map(*args, **kwargs):        # noqa: F811  (the gate stub)
        import jax
        raise ShardMapUnavailable(
            "jax.shard_map (with check_vma) is absent on this runtime "
            f"(jax {jax.__version__}); this shard_map launch path is dead "
            "here — see hfrep_tpu/analysis/HF005_KILL_LIST.md and ROADMAP "
            "item 1 (partition-rule mesh refactor)")


def _version_tuple(v: str):
    parts = []
    for p in v.split("."):
        digits = "".join(c for c in p if c.isdigit())
        if not digits:
            break
        parts.append(int(digits))
    return tuple(parts)


def _has_cpu_multiprocess() -> bool:
    import jax
    return _version_tuple(jax.__version__) >= (0, 5)


#: jax 0.4.x's CPU client cannot EXECUTE a cross-process SPMD program —
#: a multi-host pjit dispatch dies with "Multiprocess computations
#: aren't implemented on the CPU backend" (the Gloo-backed cross-host
#: CPU collectives landed in later jax).  The two-process CPU tests
#: (tests/test_distributed.py) gate on this and skip on the pinned
#: runtime; real pods (TPU/GPU backends) are unaffected.
HAS_CPU_MULTIPROCESS_SPMD = _has_cpu_multiprocess()


try:
    from jax.lax import axis_size as axis_size  # noqa: F401
except ImportError:
    def axis_size(axis_name):              # noqa: F811
        """``lax.axis_size`` where present; the ``psum(1, axis)`` idiom
        (identical value, one collective) on older runtimes."""
        from jax import lax
        return lax.psum(1, axis_name)


# ------------------------------------------------- AOT-stage introspection
# The perf microscope (hfrep_tpu/obs/attrib.py) reads compiled-program
# facts — lowered HLO text, cost_analysis, memory_analysis — off the
# jax.stages objects at every compile boundary.  Those APIs exist on the
# pinned 0.4.37 but have drifted across jax versions (cost_analysis
# moved Lowered→Compiled and back; memory_analysis is Compiled-only;
# AOT ``.lower`` is absent on plain callables), so every access is
# gated HERE, returns None instead of raising, and the telemetry layer
# degrades to fingerprint-less profiles — a missing introspection API
# must never cost a run or a measurement.

def lower_jitted(fn, *args, **kwargs):
    """``jit(f).lower(*args)`` (trace + lower, NO XLA compile) where this
    runtime supports it, unwrapping one obs instrumentation layer
    (``__wrapped__``); None when ``fn`` has no usable ``.lower`` or the
    trace itself fails (non-jax operands, donated-shape mismatch...)."""
    # a jitted callable carries BOTH .lower and __wrapped__ (the plain
    # python function) — prefer .lower; unwrap only when absent (the
    # obs instrument_step wrapper hides the jitted fn one level down)
    lower = getattr(fn, "lower", None)
    if lower is None:
        lower = getattr(getattr(fn, "__wrapped__", None), "lower", None)
    if lower is None:
        return None
    try:
        return lower(*args, **kwargs)
    except Exception:
        return None


def stage_hlo_text(stage):
    """The stage's program text (``as_text()``; the pre-optimization HLO
    for a Lowered, the optimized module for a Compiled), or None."""
    as_text = getattr(stage, "as_text", None)
    if as_text is None:
        return None
    try:
        text = as_text()
    except Exception:
        return None
    return text if isinstance(text, str) else None


def stage_cost_analysis(stage):
    """Flat ``{metric: float}`` cost analysis of a Lowered/Compiled stage
    (0.4.37 returns a dict from Lowered and a one-per-computation list
    from Compiled — normalized here by summing), or None."""
    cost = getattr(stage, "cost_analysis", None)
    if cost is None:
        return None
    try:
        raw = cost()
    except Exception:
        return None
    if isinstance(raw, dict):
        raw = [raw]
    if not isinstance(raw, (list, tuple)) or not raw:
        return None
    out = {}
    for entry in raw:
        if not isinstance(entry, dict):
            continue
        for k, v in entry.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[str(k)] = out.get(str(k), 0.0) + float(v)
    return out or None


def stage_memory_analysis(stage):
    """``{field: bytes}`` from a Compiled stage's ``memory_analysis()``
    (a ``CompiledMemoryStats``-shaped object), or None — Lowered stages
    and older runtimes simply lack it."""
    mem = getattr(stage, "memory_analysis", None)
    if mem is None:
        return None
    try:
        stats = mem()
    except Exception:
        return None
    if stats is None:
        return None
    out = {}
    for field in dir(stats):
        if field.startswith("_"):
            continue
        v = getattr(stats, field, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[field] = float(v)
    return out or None
