"""Varying-manual-axis (vma) helpers for `shard_map(check_vma=True)`.

Under `jax.shard_map`'s static replication checker every value carries the
set of mesh axes it *varies* over.  Loop carries initialized with plain
`jnp.zeros` are replicated (vma = ∅), but a scan/fori body fed per-device
data returns varying carries — a static type mismatch the checker rejects.
The fix is to pre-cast each initial carry to the variance of the data that
will flow into it; :func:`match_vma` does that generically by reading the
reference value's vma with `jax.typeof`, so call sites never need to know
the mesh axis names (and outside `shard_map` it is a no-op).
"""

from __future__ import annotations

import jax

try:                   # vma types and pcast exist together (newer jax)
    from jax.lax import pcast as _pcast
except ImportError:    # pinned jax 0.4.37: vma_of() is always ∅ there,
    _pcast = None      # so match_vma's early return means this never runs


def vma_of(x) -> frozenset:
    """The union of manual mesh axes the leaves of ``x`` vary over
    (∅ outside shard_map)."""
    axes: frozenset = frozenset()
    for leaf in jax.tree_util.tree_leaves(x):
        try:
            axes |= frozenset(jax.typeof(leaf).vma)
        except (AttributeError, TypeError):  # non-jax value
            pass
    return axes


def shape_struct(shape, dtype, like) -> "jax.ShapeDtypeStruct":
    """`jax.ShapeDtypeStruct` carrying the vma of ``like`` — required for
    `pallas_call` out_shapes under `shard_map(check_vma=True)`, where
    every output aval must state how it varies over the mesh (a kernel
    output varies exactly as much as its inputs do).  The vma is always a
    (possibly empty) frozenset, never None: inside a check_vma shard_map
    an all-invariant kernel (e.g. dp=1 controlled sampling) still needs
    an explicit empty vma, and outside shard_map the empty set is
    equivalent to the default."""
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma_of(like))


def match_vma(x, like):
    """Cast ``x`` (pytree) to vary over the same manual axes as ``like``.

    ``like`` may be any array already carrying the intended variance (for
    a scan carry: the scanned-over input).  Equal-or-superset variance is
    required by pcast, so only the *missing* axes are added; values
    already varying are returned untouched.  A no-op when not inside
    `shard_map` or when ``like`` is replicated.
    """
    target = vma_of(like)
    if not target:
        return x

    def cast(leaf):
        missing = target - vma_of(leaf)
        return _pcast(leaf, tuple(missing), to="varying") if missing else leaf

    return jax.tree_util.tree_map(cast, x)
