"""Import reference Keras ``.h5`` generator artifacts into Flax params.

The paper's headline GAN-augmentation experiment starts from a trained
generator saved by ``GAN/MTSS_WGAN_GP.py:285-287``, loaded with Keras
``load_model`` at ``autoencoder_v4.ipynb`` cell 42 and sampled at cell
43.  This module makes those artifacts (production
``trained_generator/MTTS_GAN_GP20220621_02-49-32.h5`` plus the six
``old/`` family checkpoints) first-class inputs to the TPU pipeline: it
parses the h5's ``model_config`` JSON into a layer spec, builds the
matching Flax module from the same Keras-semantics primitives the
native models use (:class:`~hfrep_tpu.ops.lstm.KerasLSTM`,
:class:`~hfrep_tpu.ops.layers.KerasDense`, …), and binds the stored
weights.

The model is built from the artifact's *own* config rather than assumed
from the family name, because the production artifact's architecture
differs from the committed script: in the h5, ``LeakyReLU`` follows
*both* LSTMs, while ``GAN/MTSS_WGAN_GP.py:221-235`` applies it only
after the second.  Committed-script shapes (48, 35) and the production
shape (168, 36) (SURVEY §2 tail) both load through the same path.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from hfrep_tpu.ops.layers import KerasDense, KerasLayerNorm, leaky_relu
from hfrep_tpu.ops.lstm import KerasLSTM

# A spec is a hashable tuple so it can live in a Flax module field:
#   ("lstm", units, activation, recurrent_activation)
#   ("dense", units, activation, use_bias)
#   ("layer_norm", epsilon)
#   ("leaky_relu", alpha)
#   ("flatten" | "activation" | "dropout", activation_or_None)
Spec = Tuple[Any, ...]

_WEIGHTED = {"lstm", "dense", "layer_norm"}


def _as_str(x) -> str:
    return x.decode() if isinstance(x, bytes) else str(x)


def _checked_activation(name, cls: str):
    """Validate an activation name at parse time, so an unsupported
    artifact fails with the artifact path (via :func:`parse_model_config`)
    instead of a bare ``KeyError`` at apply time."""
    from hfrep_tpu.ops.layers import ACTIVATIONS

    if name not in ACTIVATIONS:
        raise ValueError(f"unsupported activation {name!r} on {cls} layer")
    return name


def _flatten_layers(layers: Sequence[dict], specs: List[Spec],
                    input_shape: List[Tuple[int, ...]]) -> None:
    for layer in layers:
        cls, cfg = layer["class_name"], layer["config"]
        if cls in ("Sequential", "Functional", "Model"):
            _flatten_layers(cfg["layers"], specs, input_shape)
        elif cls == "InputLayer":
            shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
            if shape is not None:
                input_shape.append(tuple(shape[1:]))
        elif cls == "LSTM":
            # Fields our KerasLSTM does not model must fail loudly, not
            # load as a silently different function.
            for field, default in (("return_sequences", True),
                                   ("go_backwards", False),
                                   ("stateful", False), ("use_bias", True)):
                if cfg.get(field, default) != default:
                    raise ValueError(
                        f"unsupported LSTM config {field}={cfg[field]!r}")
            specs.append(("lstm", int(cfg["units"]),
                          _checked_activation(cfg.get("activation", "tanh"), cls),
                          _checked_activation(
                              cfg.get("recurrent_activation", "sigmoid"), cls)))
        elif cls == "Dense":
            specs.append(("dense", int(cfg["units"]),
                          _checked_activation(cfg.get("activation"), cls),
                          bool(cfg.get("use_bias", True))))
        elif cls == "LayerNormalization":
            specs.append(("layer_norm", float(cfg.get("epsilon", 1e-3))))
        elif cls == "LeakyReLU":
            specs.append(("leaky_relu",
                          float(cfg.get("alpha", cfg.get("negative_slope", 0.3)))))
        elif cls in ("Flatten", "Activation", "Dropout"):
            # Flatten appears only in critics (not saved); tolerate anyway.
            act = cfg.get("activation")
            if cls == "Activation":
                _checked_activation(act, cls)
            specs.append((cls.lower(), act))
        else:
            raise ValueError(f"unsupported Keras layer in artifact: {cls}")


def parse_model_config(path: str) -> Tuple[Tuple[Spec, ...], Tuple[int, ...]]:
    """h5 ``model_config`` attr → (layer specs, per-sample input shape)."""
    import h5py

    with h5py.File(path, "r") as f:
        cfg = json.loads(_as_str(f.attrs["model_config"]))
    specs: List[Spec] = []
    input_shapes: List[Tuple[int, ...]] = []
    try:
        _flatten_layers([cfg] if "class_name" in cfg else cfg["config"]["layers"],
                        specs, input_shapes)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from None
    if not input_shapes:
        raise ValueError(f"no InputLayer shape found in {path}")
    return tuple(specs), input_shapes[0]


class ImportedSequential(nn.Module):
    """A reference Sequential generator rebuilt on the native primitives.

    Parameter tree keys are ``layer_{i}`` with ``i`` the position in
    ``specs`` — weightless layers (LeakyReLU) simply have no entry.
    """

    specs: Tuple[Spec, ...]

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, spec in enumerate(self.specs):
            kind = spec[0]
            name = f"layer_{i}"
            if kind == "lstm":
                x = KerasLSTM(spec[1], activation=spec[2],
                              recurrent_activation=spec[3], name=name)(x)
            elif kind == "dense":
                use_bias = spec[3] if len(spec) > 3 else True
                x = KerasDense(spec[1], activation=spec[2],
                               use_bias=use_bias, name=name)(x)
            elif kind == "layer_norm":
                x = KerasLayerNorm(epsilon=spec[1], name=name)(x)
            elif kind == "leaky_relu":
                x = leaky_relu(x, spec[1])
            elif kind == "flatten":
                x = x.reshape(x.shape[0], -1)
            elif kind == "activation":
                from hfrep_tpu.ops.layers import ACTIVATIONS
                x = ACTIVATIONS[spec[1]](x)
            elif kind == "dropout":
                pass                                   # inference: identity
            else:  # pragma: no cover - parse_model_config rejects these
                raise ValueError(f"unsupported spec {spec}")
        return x


def _ordered_weight_groups(path: str) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """Flatten ``model_weights`` into per-layer {basename: array} dicts,
    preserving the save order recorded in the ``weight_names`` attrs.

    Keras writes one entry per variable as e.g.
    ``sequential_2/lstm_4/lstm_cell_4/kernel:0``; consecutive entries
    sharing a dirname belong to one layer.
    """
    import h5py

    groups: List[Tuple[str, Dict[str, np.ndarray]]] = []
    with h5py.File(path, "r") as f:
        mw = f["model_weights"]
        for layer_name in mw.attrs["layer_names"]:
            g = mw[_as_str(layer_name)]
            for wn in g.attrs.get("weight_names", []):
                wn = _as_str(wn)
                dirname, base = wn.rsplit("/", 1)
                base = base.split(":")[0]
                if not groups or groups[-1][0] != dirname:
                    groups.append((dirname, {}))
                groups[-1][1][base] = np.array(g[wn])
    return groups


def load_keras_weights(path: str, specs: Sequence[Spec]) -> Dict[str, Any]:
    """h5 weights → params dict matching :class:`ImportedSequential`."""
    groups = _ordered_weight_groups(path)
    weighted = [(i, s) for i, s in enumerate(specs) if s[0] in _WEIGHTED]
    if len(groups) != len(weighted):
        raise ValueError(
            f"{path}: {len(groups)} weighted layer groups in h5 vs "
            f"{len(weighted)} weighted specs from model_config")
    params: Dict[str, Any] = {}
    for (i, spec), (dirname, w) in zip(weighted, groups):
        kind = spec[0]
        try:
            if kind == "lstm":
                params[f"layer_{i}"] = {
                    "kernel": jnp.asarray(w["kernel"]),
                    "recurrent_kernel": jnp.asarray(w["recurrent_kernel"]),
                    "bias": jnp.asarray(w["bias"]),
                }
            elif kind == "dense":
                p = {"kernel": jnp.asarray(w["kernel"])}
                if "bias" in w:
                    p["bias"] = jnp.asarray(w["bias"])
                params[f"layer_{i}"] = {"Dense_0": p}
            elif kind == "layer_norm":
                params[f"layer_{i}"] = {"LayerNorm_0": {
                    "scale": jnp.asarray(w["gamma"]),
                    "bias": jnp.asarray(w["beta"]),
                }}
        except KeyError as e:  # pragma: no cover - malformed artifact
            raise ValueError(
                f"{path}: layer group '{dirname}' missing weight {e} "
                f"for spec {spec}") from e
    return params


def load_keras_generator(path: str):
    """Load a reference generator artifact.

    Returns ``(module, params, input_shape)`` where ``input_shape`` is
    the per-sample noise shape, e.g. ``(168, 36)`` for the production
    artifact (``autoencoder_v4.ipynb`` cell 43 samples
    ``normal(0, 1, (10, 168, 36))``).
    """
    specs, input_shape = parse_model_config(path)
    params = load_keras_weights(path, specs)
    module = ImportedSequential(specs=specs)

    # Structural validation: imported tree must match a fresh init.
    ref = jax.eval_shape(
        lambda k: module.init(k, jnp.zeros((1,) + tuple(input_shape), jnp.float32)),
        jax.random.PRNGKey(0))["params"]
    ref_shapes = jax.tree_util.tree_map(lambda a: tuple(a.shape), ref)
    got_shapes = jax.tree_util.tree_map(lambda a: tuple(a.shape), params)
    if ref_shapes != got_shapes:
        raise ValueError(
            f"{path}: imported weight shapes do not match model_config "
            f"architecture:\n  config: {ref_shapes}\n  h5: {got_shapes}")
    return module, params, tuple(input_shape)
