"""Export trained Flax generators as reference-compatible Keras ``.h5``.

The reference ecosystem consumes generator artifacts via Keras
``load_model`` (``autoencoder_v4.ipynb`` cell 42, saved at
``GAN/MTSS_WGAN_GP.py:285-287``).  This is the outbound half of the
artifact interop (:mod:`hfrep_tpu.utils.keras_import` is the inbound
half): a generator trained here can be dropped into the reference's own
notebook flow.  Requires tensorflow (present in this image; gated
import so the core package never depends on it).

Layer order mirrors :mod:`hfrep_tpu.models.generators` exactly:

* LSTM family: ``LSTM(H, act=sigmoid) → LayerNorm → LSTM(H, act=sigmoid)
  → LeakyReLU(0.2) → LayerNorm → Dense(F)``
* Dense family: ``Dense(H, sigmoid) → LeakyReLU → LayerNorm ×2 → Dense(F)``
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from hfrep_tpu.config import ModelConfig
from hfrep_tpu.models.generators import LSTMGenerator
from hfrep_tpu.models.registry import FAMILIES


def _tf():
    try:
        import tensorflow as tf
        return tf
    except ImportError as e:  # pragma: no cover - image ships TF
        raise ImportError(
            "keras_export requires tensorflow for writing .h5 artifacts; "
            "use checkpoints (hfrep_tpu.utils.checkpoint) when TF is "
            "unavailable") from e


def _np(tree: Any) -> Any:
    return np.asarray(tree)


def export_keras_generator(mcfg: ModelConfig, params: Dict[str, Any],
                           path: str) -> str:
    """Write generator ``params`` (a Flax tree from
    :func:`hfrep_tpu.models.registry.build_gan`) as a Keras ``.h5``.

    Returns ``path``.  Round-trips through
    :func:`hfrep_tpu.utils.keras_import.load_keras_generator` (tested).
    """
    tf = _tf()
    h, w, f = mcfg.hidden, mcfg.window, mcfg.features
    slope = mcfg.leaky_slope
    if mcfg.family not in FAMILIES:
        raise ValueError(f"unknown generator family {mcfg.family!r}; "
                         f"available: {sorted(FAMILIES)}")
    body = FAMILIES[mcfg.family][0]

    if body is LSTMGenerator:
        model = tf.keras.Sequential([
            tf.keras.layers.Input((w, f)),
            tf.keras.layers.LSTM(h, activation="sigmoid",
                                 recurrent_activation="sigmoid",
                                 return_sequences=True),
            tf.keras.layers.LayerNormalization(epsilon=1e-3),
            tf.keras.layers.LSTM(h, activation="sigmoid",
                                 recurrent_activation="sigmoid",
                                 return_sequences=True),
            tf.keras.layers.LeakyReLU(negative_slope=slope),
            tf.keras.layers.LayerNormalization(epsilon=1e-3),
            tf.keras.layers.Dense(f),
        ])
        weighted = [
            (model.layers[0], [params["KerasLSTM_0"][k] for k in
                               ("kernel", "recurrent_kernel", "bias")]),
            (model.layers[1], [params["KerasLayerNorm_0"]["LayerNorm_0"]["scale"],
                               params["KerasLayerNorm_0"]["LayerNorm_0"]["bias"]]),
            (model.layers[2], [params["KerasLSTM_1"][k] for k in
                               ("kernel", "recurrent_kernel", "bias")]),
            (model.layers[4], [params["KerasLayerNorm_1"]["LayerNorm_0"]["scale"],
                               params["KerasLayerNorm_1"]["LayerNorm_0"]["bias"]]),
            (model.layers[5], [params["KerasDense_0"]["Dense_0"]["kernel"],
                               params["KerasDense_0"]["Dense_0"]["bias"]]),
        ]
    else:
        model = tf.keras.Sequential([
            tf.keras.layers.Input((w, f)),
            tf.keras.layers.Dense(h, activation="sigmoid"),
            tf.keras.layers.LeakyReLU(negative_slope=slope),
            tf.keras.layers.LayerNormalization(epsilon=1e-3),
            tf.keras.layers.Dense(h, activation="sigmoid"),
            tf.keras.layers.LeakyReLU(negative_slope=slope),
            tf.keras.layers.LayerNormalization(epsilon=1e-3),
            tf.keras.layers.Dense(f),
        ])
        weighted = [
            (model.layers[0], [params["KerasDense_0"]["Dense_0"]["kernel"],
                               params["KerasDense_0"]["Dense_0"]["bias"]]),
            (model.layers[2], [params["KerasLayerNorm_0"]["LayerNorm_0"]["scale"],
                               params["KerasLayerNorm_0"]["LayerNorm_0"]["bias"]]),
            (model.layers[3], [params["KerasDense_1"]["Dense_0"]["kernel"],
                               params["KerasDense_1"]["Dense_0"]["bias"]]),
            (model.layers[5], [params["KerasLayerNorm_1"]["LayerNorm_0"]["scale"],
                               params["KerasLayerNorm_1"]["LayerNorm_0"]["bias"]]),
            (model.layers[6], [params["KerasDense_2"]["Dense_0"]["kernel"],
                               params["KerasDense_2"]["Dense_0"]["bias"]]),
        ]
    for layer, ws in weighted:
        layer.set_weights([_np(v) for v in ws])
    model.save(path)
    return path
