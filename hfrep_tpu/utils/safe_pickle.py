"""Restricted unpickling for artifacts read from ``/root/reference``.

The reference tree is treated as untrusted public content; ``pickle.load``
executes arbitrary callables named in the stream.  The reference's pickles
are plain data — str→str name dicts (``cleaned_data/*_fullname.pkl``,
written by ``helper.py:155-162``) and a numpy cube
(``GAN/generated_data2022-07-09.pkl``) — so an allowlist of numpy
reconstruction globals covers everything legitimately present while any
smuggled callable raises ``UnpicklingError`` instead of executing.
"""

from __future__ import annotations

import io
import pickle

_ALLOWED_GLOBALS = {
    # numpy ndarray/dtype reconstruction (module path moved in numpy 2.x)
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if (module, name) in _ALLOWED_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"blocked pickle global {module}.{name!r}: only plain-data "
            "pickles (builtins + numpy arrays) may be loaded from the "
            "untrusted reference tree")


def safe_pickle_load(fh) -> object:
    """``pickle.load`` with the restricted allowlist."""
    return _RestrictedUnpickler(fh).load()


def safe_pickle_loads(data: bytes) -> object:
    return _RestrictedUnpickler(io.BytesIO(data)).load()
