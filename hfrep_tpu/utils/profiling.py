"""Step timing and profiler hooks (SURVEY §5.1 — absent in the reference;
compatibility shim over ``hfrep_tpu.obs`` since the telemetry layer).

`StepTimer` measures device-synchronized wall time around jitted calls
and reports steps/sec — BASELINE.json's primary runtime metric.  Every
``stop()`` now also lands in the active obs event stream as a ``block``
span (with ``steps``/``warmup`` attributes) and a ``step_time``
histogram sample, so the trainer's existing timing discipline feeds the
unified telemetry without a second set of call sites.
`trace` wraps `jax.profiler.trace` for on-demand XLA profiles.
"""

from __future__ import annotations

import contextlib
import time
from typing import List, Optional

import jax

from hfrep_tpu.obs import get_obs


class StepTimer:
    """Accumulates (steps, seconds) pairs; call ``sync()`` on a device
    array before stopping the clock so XLA's async dispatch doesn't lie."""

    def __init__(self) -> None:
        self.samples: List[tuple[int, float, bool]] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int, sync_on=None, warmup: bool = False) -> float:
        """``warmup=True`` marks a sample that carries XLA compile time
        (~30-40s for the GAN steps); such samples are excluded from
        :attr:`steps_per_sec` whenever steady-state samples exist.

        This stop is the one device-synced boundary every timed drive
        already pays, so it doubles as the perf microscope's attribution
        boundary: the dispatch seconds the instrumented steps
        accumulated inside this window (hfrep_tpu/obs/attrib.py) are
        flushed against the synced wall clock into
        ``attrib/{dispatch_ms,compute_ms,dispatch_frac}`` gauges —
        warmup windows are discarded (their dispatch time is XLA
        compile), and with telemetry off the flush is a no-op."""
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        dt = time.perf_counter() - self._t0
        self.samples.append((n_steps, dt, warmup))
        obs = get_obs()
        if obs.enabled:
            obs.record_span("block", dt, steps=int(n_steps),
                            warmup=bool(warmup), synced=sync_on is not None)
            if n_steps > 0:
                obs.histogram("step_time").observe(dt / n_steps,
                                                   warmup=bool(warmup))
            from hfrep_tpu.obs import attrib
            if warmup or sync_on is None:
                # compile-polluted or un-synced wall: either would lie
                attrib.reset_window()
            else:
                attrib.flush_window(dt, steps=int(n_steps))
        return dt

    @property
    def steps_per_sec(self) -> float:
        """Steady-state rate (warmup samples excluded when possible).

        Guarded against zero-duration windows: on a fast-enough host a
        warmup-only sample set can carry ``dt == 0`` at perf_counter
        resolution — the rate is then undefined, not infinite, so this
        returns ``nan`` rather than dividing by zero.
        """
        steady = [(n, t) for n, t, w in self.samples if not w]
        samples = steady or [(n, t) for n, t, _ in self.samples]
        steps = sum(n for n, _ in samples)
        secs = sum(t for _, t in samples)
        return steps / secs if secs > 0.0 else float("nan")

    def reset(self) -> None:
        self.samples.clear()


@contextlib.contextmanager
def trace(log_dir: str):
    """On-demand XLA profile — now via :func:`hfrep_tpu.obs.trace_capture`,
    so when telemetry is enabled the capture is recorded in the event
    stream and linked from ``run.json`` (path + xplane count) instead of
    living entirely outside the run's record."""
    from hfrep_tpu.obs import trace_capture

    with trace_capture(log_dir):
        yield
