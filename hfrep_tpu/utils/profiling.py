"""Step timing and profiler hooks (SURVEY §5.1 — absent in the reference).

`StepTimer` measures device-synchronized wall time around jitted calls
and reports steps/sec — BASELINE.json's primary runtime metric.
`trace` wraps `jax.profiler.trace` for on-demand XLA profiles.
"""

from __future__ import annotations

import contextlib
import time
from typing import List, Optional

import jax


class StepTimer:
    """Accumulates (steps, seconds) pairs; call ``sync()`` on a device
    array before stopping the clock so XLA's async dispatch doesn't lie."""

    def __init__(self) -> None:
        self.samples: List[tuple[int, float, bool]] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int, sync_on=None, warmup: bool = False) -> float:
        """``warmup=True`` marks a sample that carries XLA compile time
        (~30-40s for the GAN steps); such samples are excluded from
        :attr:`steps_per_sec` whenever steady-state samples exist."""
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        dt = time.perf_counter() - self._t0
        self.samples.append((n_steps, dt, warmup))
        return dt

    @property
    def steps_per_sec(self) -> float:
        """Steady-state rate (warmup samples excluded when possible)."""
        steady = [(n, t) for n, t, w in self.samples if not w]
        samples = steady or [(n, t) for n, t, _ in self.samples]
        steps = sum(n for n, _ in samples)
        secs = sum(t for _, t in samples)
        return steps / secs if secs else float("nan")

    def reset(self) -> None:
        self.samples.clear()


@contextlib.contextmanager
def trace(log_dir: str):
    with jax.profiler.trace(log_dir):
        yield
