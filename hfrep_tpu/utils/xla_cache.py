"""The one persistent-XLA-compilation-cache policy.

Shared by the CLI entry points and the orchestration fabric's actor
processes (a spawned member is a fresh interpreter — without the cache
every consumer restart re-pays its AE chunk-program compile).  One
implementation so the cache path/threshold cannot drift between the
parent CLI process and fabric members.
"""

from __future__ import annotations

import os


def enable_compilation_cache() -> None:
    """Persist XLA compilations across processes (best-effort).

    The sweep/train programs cost ~2 min of compiles per fresh process;
    with the on-disk cache a repeat run on a directly-attached backend
    skips them.  (On a tunneled backend compilation happens on the far
    side, so the local cache cannot shortcut it — measured no-op there,
    effective on standard CPU/TPU backends.)  Disable with
    ``HFREP_COMPILATION_CACHE=''``.  Failures degrade to no cache — a
    cache is an optimization, never a blocker.

    The 1.0s persist threshold is load-bearing, not a tuning nit: with
    it lowered to 0 so the chaos subjects' ms-scale fixture programs
    would cache, deserialized executables on this runtime (jax 0.4.37,
    CPU) returned NUMERICALLY WRONG results on cache hit — a resumed
    GAN fixture drive exploded to NaN from a bit-verified healthy
    checkpoint, and a cache-hit ``jnp.max`` over an f32[8] leaf
    returned a different leaf's value (found by the chaos engine's
    resume-bit-identity oracle, ISSUE 14).  The chaos subjects
    therefore run cache-free; do not lower this threshold.
    """
    cache = os.environ.get("HFREP_COMPILATION_CACHE",
                           os.path.expanduser("~/.cache/hfrep_tpu_xla"))
    if not cache:
        return
    try:
        import jax
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (OSError, AttributeError):
        pass
