"""Shared fabricated-data fixture builders.

One home for the deterministic synthetic panels every selftest, CLI
fixture and test suite previously hand-rolled (three byte-divergent
copies: ``tests/test_orchestrate.py``, ``resilience/selftest.py``,
``serve/fixture.py``).  The RNG streams here are pinned: each builder
consumes its generator in exactly the order the original copies did, so
the artifacts (and every bit-identity pin built on them) are
byte-compatible with the pre-dedupe fixtures.

Stdlib + numpy only at import time; jax/pandas are imported inside the
builders that need them so the module stays cheap for worker bootstrap.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def low_rank_returns(g: np.random.Generator, rows: int, feats: int,
                     rank: int = 3) -> np.ndarray:
    """The shared return-panel core: ``rank`` latent factors mixed into
    ``feats`` observed columns plus idiosyncratic noise, scaled to
    monthly-return magnitude.  Consumes ``g`` in the pinned order
    (z, mix, noise) — every caller's byte-compatibility depends on it."""
    z = g.normal(size=(rows, rank))
    return (z @ g.normal(size=(rank, feats))
            + 0.05 * g.normal(size=(rows, feats))).astype(np.float32) * 0.02


def scaled_panel(rows: int, feats: int, *, seed: int, rank: int = 3):
    """MinMax-scaled low-rank panel as a jnp array — the resilience
    selftest's ``_fixture_panel`` (seed 11) and the serve fixture's
    training panel (seed+17) share this builder."""
    import jax.numpy as jnp

    from hfrep_tpu.core import scaler as mm

    x = low_rank_returns(np.random.default_rng(seed), rows, feats, rank)
    _, scaled = mm.fit_transform(jnp.asarray(x))
    return scaled


def keyed_scaled_panel(stream_seed: int, source_idx: int, seq: int,
                       rows: int, feats: int, rank: int = 3) -> np.ndarray:
    """Numpy-scaled low-rank panel seeded by a full (stream, source, seq)
    coordinate — the orchestration fabric's fixture item: unique per
    coordinate yet reproducible on any member (the kill→resume
    bit-identity contract)."""
    g = np.random.default_rng((stream_seed, source_idx, seq))
    x = low_rank_returns(g, rows, feats, rank)
    lo, hi = x.min(axis=0), x.max(axis=0)
    scale = np.where(hi - lo == 0.0, 1.0, hi - lo)
    return ((x - lo) / scale).astype(np.float32)


def write_cleaned_fixture(d, months: int = 96, seed: int = 5) -> None:
    """A fabricated ``cleaned_data/`` directory shaped like the real one
    (22 factors, 13 HF indices, 1 rf, Date index) — loadable by
    ``core.data.load_panel``.  Seed 5 reproduces the byte-exact fixture
    the orchestration CLI tests pinned their artifacts against."""
    import pandas as pd

    from hfrep_tpu.core.data import dic_save
    from pathlib import Path

    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    g = np.random.default_rng(seed)
    dates = pd.date_range("2000-01-31", periods=months, freq="ME")
    fac = [f"F{j}" for j in range(22)]
    hf = [f"H{j}" for j in range(13)]
    mix = g.normal(size=(22, 13)) * 0.3
    x = g.normal(0, 0.03, (months, 22))
    y = x @ mix + g.normal(0, 0.01, (months, 13))
    for name, cols, vals in (
            ("factor_etf_data.csv", fac, x),
            ("hfd.csv", hf, y),
            ("rf.csv", ["RF"], np.abs(g.normal(0.002, 5e-4, (months, 1))))):
        df = pd.DataFrame(vals.astype(np.float32), columns=cols)
        df.insert(0, "Date", dates)
        df.to_csv(d / name, index=False)
    dic_save({c: c for c in hf}, d / "hfd_fullname.pkl")
    dic_save({c: c for c in fac}, d / "factor_etf_name.pkl")


def fund_cross_section(factors: np.ndarray, seed: int,
                       funds: int) -> Tuple[np.ndarray, np.ndarray]:
    """``(hfd, rf)`` for an arbitrary factor panel: the fund mix/noise
    stream is seeded independently of the factor VALUES, so swapping the
    factor source (fixture model vs GAN samples) leaves the fund
    cross-section construction unchanged — the one implementation both
    universe paths share (its draw order is part of the determinism
    contract)."""
    months, n_factors = factors.shape
    g_mix = np.random.default_rng((seed, months, funds, 1))
    mix = (g_mix.normal(size=(n_factors, funds)) * 0.3).astype(np.float32)
    hfd = (factors @ mix
           + 0.01 * g_mix.normal(size=(months, funds))).astype(np.float32)
    rf = np.abs(g_mix.normal(0.002, 5e-4, months)).astype(np.float32)
    return hfd, rf


def universe_arrays(seed: int, funds: int, months: int,
                    n_factors: int = 22,
                    rank: int = 4) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray]:
    """Synthetic ``(factors, hfd, rf)`` universe of arbitrary size — the
    scenario factory's fixture generator (``scenario/universe.py``)."""
    g_fac = np.random.default_rng((seed, months, n_factors, 0))
    factors = low_rank_returns(g_fac, months, n_factors, rank)
    hfd, rf = fund_cross_section(factors, seed, funds)
    return factors, hfd, rf
