"""Checkpoint / resume (SURVEY §5.3-5.4 — near-absent in the reference).

The reference saves only the generator, only once, after the full
5000-epoch run (``GAN/MTSS_WGAN_GP.py:285-287``) — a crash loses
everything, and resume is impossible because optimizer/critic state is
discarded.  Here a checkpoint is the complete training pytree: G and D
params, both optimizer states, the step counter, the PRNG key, and the
MinMax scaler params needed to inverse-transform generated samples.

Backed by orbax's PyTree checkpointer (async-capable, TPU-sharding
aware); falls back to msgpack via flax.serialization if orbax is
unavailable at runtime.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


def save(path: str, pytree: Any, metadata: Optional[dict] = None,
         coordination_free: bool = False) -> None:
    """``coordination_free=True`` writes the msgpack format directly —
    required for leader-only multi-host checkpointing of replicated
    state, where orbax's internal cross-process barrier would deadlock a
    single-process save (the other processes never reach it)."""
    p = Path(path).absolute()
    p.parent.mkdir(parents=True, exist_ok=True)
    pytree = jax.device_get(pytree)

    def _msgpack():
        import flax.serialization as ser
        p.mkdir(parents=True, exist_ok=True)
        (p / "checkpoint.msgpack").write_bytes(ser.to_bytes(pytree))

    if coordination_free:
        _msgpack()
    else:
        try:
            ckptr = _ocp().PyTreeCheckpointer()
            ckptr.save(p, pytree, force=True)
        except Exception:
            _msgpack()
    if metadata is not None:
        (p.parent / (p.name + ".meta.json")).write_text(json.dumps(metadata))


def restore(path: str, target: Any = None) -> Any:
    p = Path(path).absolute()
    msgpack = p / "checkpoint.msgpack"
    if msgpack.exists():
        import flax.serialization as ser
        if target is None:
            raise ValueError("msgpack restore requires a target pytree")
        return ser.from_bytes(target, msgpack.read_bytes())
    ckptr = _ocp().PyTreeCheckpointer()
    restored = ckptr.restore(p, item=jax.device_get(target) if target is not None else None)
    return restored


def latest(dirpath: str, prefix: str = "ckpt_") -> Optional[str]:
    d = Path(dirpath)
    if not d.exists():
        return None
    cands = [
        p for p in d.iterdir()
        if p.is_dir() and p.name.startswith(prefix) and p.name[len(prefix):].isdigit()
    ]
    cands.sort(key=lambda p: int(p.name[len(prefix):]))
    return str(cands[-1]) if cands else None
