"""Crash-consistent checkpoint / resume (SURVEY §5.3-5.4).

The reference saves only the generator, only once, after the full
5000-epoch run (``GAN/MTSS_WGAN_GP.py:285-287``) — a crash loses
everything, and resume is impossible because optimizer/critic state is
discarded.  Here a checkpoint is the complete training pytree: G and D
params, both optimizer states, the step counter, the PRNG key, and the
MinMax scaler params needed to inverse-transform generated samples.

Durability model (ISSUE 5):

* **Atomic publication** — every save materializes into a hidden tmp
  directory next to the destination, fsyncs the payload, and becomes
  visible in ONE ``rename``.  ``meta.json`` (caller metadata + a
  sha256 content checksum over every payload file) lives INSIDE the
  directory, so payload and metadata commit together — a crash can
  leave a stale tmp dir, never a half-published checkpoint.
* **Verified restore** — :func:`restore` recomputes the checksum before
  decoding and raises :class:`CheckpointCorrupt` on a torn/rotted
  checkpoint; :func:`restore_latest_good` walks a checkpoint directory
  newest-first and falls back to the previous good one instead of
  crashing (the fallback is announced in the obs stream).
* **Bounded I/O retry** — the write path runs under
  :func:`hfrep_tpu.resilience.retry_io` (flaky-storage policy; retries
  surface as ``resilience/io_retries`` counters).
* **Retention** — ``save(..., keep=N)`` prunes all but the newest N
  numbered siblings (``ckpt_<n>``), so periodic checkpointing on a
  long run cannot fill the disk.

Backed by orbax's PyTree checkpointer (async-capable, TPU-sharding
aware); falls back to msgpack via flax.serialization if orbax is
unavailable at runtime.  ``coordination_free=True`` forces the msgpack
format — required for leader-only multi-host checkpointing of
replicated state, where orbax's internal cross-process barrier would
deadlock a single-process save.  Pre-ISSUE-5 checkpoints (no embedded
``meta.json``) restore unchanged, just without verification.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from pathlib import Path
from typing import Any, Callable, List, Optional, Tuple

import jax

from hfrep_tpu import resilience
from hfrep_tpu.obs import timeline

META_NAME = "meta.json"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed checksum verification or cannot be decoded
    (torn write, bit rot, truncation)."""


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


# ---------------------------------------------------------------- checksum
def aggregate_digest(file_digests: dict) -> str:
    """The ``checksum["digest"]`` aggregate for a ``{relpath: sha256}``
    map — THE format, exposed so callers that need to predict a written
    artifact's digest without writing it (the resilience selftest's
    in-process reference, ``tools/bench_async.py``) cannot drift from
    the writer."""
    return hashlib.sha256("\n".join(
        f"{k}:{v}" for k, v in sorted(file_digests.items())).encode()
    ).hexdigest()


def compute_checksum(path) -> dict:
    """sha256 per payload file (sorted relative paths, ``meta.json``
    excluded) plus one aggregate digest over the file list."""
    p = Path(path)
    files = {}
    for f in sorted(p.rglob("*")):
        if f.is_file() and f.name != META_NAME:
            files[f.relative_to(p).as_posix()] = hashlib.sha256(
                f.read_bytes()).hexdigest()
    return {"algo": "sha256", "digest": aggregate_digest(files),
            "files": files}


def read_meta(path) -> Optional[dict]:
    """The embedded ``meta.json``; None for legacy checkpoints without
    one; :class:`CheckpointCorrupt` when present but unparseable."""
    f = Path(path) / META_NAME
    if not f.exists():
        return None
    try:
        return json.loads(f.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable {META_NAME}: {e}") from e


def verify(path) -> Optional[dict]:
    """Checksum-verify a checkpoint directory.

    Returns its metadata (None for legacy no-meta checkpoints, which
    cannot be verified); raises :class:`CheckpointCorrupt` on mismatch.
    """
    meta = read_meta(path)
    if meta is None or "checksum" not in meta:
        return meta
    want = meta["checksum"]
    have = compute_checksum(path)
    if have["digest"] != want.get("digest"):
        missing = sorted(set(want.get("files", {})) - set(have["files"]))
        detail = f" (missing files: {missing})" if missing else ""
        raise CheckpointCorrupt(f"{path}: checksum mismatch{detail}")
    return meta


# ------------------------------------------------------------ atomic write
def _fsync_path(p: Path) -> None:
    fd = os.open(p, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def prev_path(dst) -> Path:
    """Where :func:`_atomic_publish` parks the previous payload while
    overwriting ``dst`` (and leaves it, under ``keep_prev=True``)."""
    dst = Path(dst)
    return dst.parent / f".{dst.name}.prev"


def _atomic_publish(tmp: Path, dst: Path, keep_prev: bool = False) -> None:
    """fsync the tree, then swap ``tmp`` into ``dst``.

    A fresh publish is ONE rename.  Overwriting an existing ``dst``
    cannot be a single rename on POSIX (directories don't replace), so
    the previous payload is first parked at a *deterministic* sibling
    (:func:`prev_path`) — a crash between the two renames leaves the
    last complete payload there, where recovery-aware readers
    (``ChunkSnapshot.load``) find it instead of nothing.  With
    ``keep_prev=True`` the parked copy is retained even on success (one
    bounded extra copy), closing the window entirely for payloads that
    are overwritten at every boundary.
    """
    for f in tmp.rglob("*"):
        if f.is_file():
            _fsync_path(f)
    for d in (tmp, *(x for x in tmp.rglob("*") if x.is_dir())):
        try:
            _fsync_path(d)              # not all filesystems fsync dirs
        except OSError:
            pass
    if dst.exists():
        prev = prev_path(dst)
        if prev.exists():
            shutil.rmtree(prev)
        dst.rename(prev)
        tmp.rename(dst)
        if not keep_prev:
            shutil.rmtree(prev, ignore_errors=True)
    else:
        tmp.rename(dst)
    try:
        _fsync_path(dst.parent)
    except OSError:
        pass


def write_atomic(path, writer: Callable[[Path], Optional[dict]],
                 metadata: Optional[dict] = None, *,
                 io_site: str = "ckpt_save", fault_site: str = "ckpt",
                 retry: bool = True, keep_prev: bool = False) -> Path:
    """The one crash-consistent directory writer (checkpoints AND the
    engine's chunk snapshots).

    ``writer(tmp_dir)`` materializes the payload (its optional dict
    return merges into the metadata); the checksum'd ``meta.json`` is
    written beside it and the whole directory published atomically.
    The write runs under the bounded I/O retry policy and passes
    through the fault-injection hooks (``io_site`` before the write,
    ``fault_site`` after success — where injected torn/corrupt
    directives bite).
    """
    dst = Path(path).absolute()
    dst.parent.mkdir(parents=True, exist_ok=True)
    tmp = dst.parent / f".{dst.name}.tmp-{os.getpid()}"

    def _write():
        resilience.io_point(io_site)
        if tmp.exists():                # a failed earlier attempt
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
        meta = dict(metadata or {})
        extra = writer(tmp)
        if extra:
            meta.update(extra)
        meta["checksum"] = compute_checksum(tmp)
        (tmp / META_NAME).write_text(json.dumps(meta, indent=2, default=str))
        _atomic_publish(tmp, dst, keep_prev=keep_prev)

    # the wall-clock ledger's categorization rides the fault_site the
    # callers already declare: checkpoint/snapshot publication is
    # "checkpoint" time, everything else (queue items, spooled
    # artifacts) is generic "host_io"
    with timeline.timed("checkpoint" if fault_site in ("ckpt", "snapshot")
                        else "host_io"):
        try:
            if retry:
                resilience.retry_io(_write, what=io_site)
            else:
                _write()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        resilience.post_save(fault_site, dst)
    return dst


def atomic_text(path, text: str) -> Path:
    """Crash-consistent single-FILE publication: write to a hidden tmp
    sibling, fsync, and ``os.replace`` into place — the file-shaped
    sibling of :func:`write_atomic` for result/summary JSON that a
    crashed process must never leave torn (rule HF003 enforces that
    artifact writes go through one of the sanctioned writers).  No
    retry/checksum machinery: callers that need the full durability
    model (metadata, verification, fault hooks) want
    :func:`write_atomic`."""
    dst = Path(path).absolute()
    dst.parent.mkdir(parents=True, exist_ok=True)
    tmp = dst.parent / f".{dst.name}.tmp-{os.getpid()}"
    try:
        tmp.write_text(text, encoding="utf-8")
        _fsync_path(tmp)
        os.replace(tmp, dst)
    finally:
        if tmp.exists():
            tmp.unlink(missing_ok=True)
    try:
        _fsync_path(dst.parent)
    except OSError:
        pass
    return dst


# ------------------------------------------------------------- save/restore
def _write_msgpack(tmp: Path, pytree: Any) -> None:
    """Stage the msgpack payload into ``tmp`` — always a
    :func:`write_atomic` staging dir; the publish is the caller's."""
    import flax.serialization as ser
    (tmp / "checkpoint.msgpack").write_bytes(ser.to_bytes(pytree))


def save(path: str, pytree: Any, metadata: Optional[dict] = None,
         coordination_free: bool = False, keep: int = 0) -> str:
    """Atomically write ``pytree`` (and ``metadata``) as a checkpoint.

    ``coordination_free=True`` writes the msgpack format directly —
    required for leader-only multi-host checkpointing of replicated
    state, where orbax's internal cross-process barrier would deadlock a
    single-process save (the other processes never reach it).  The env
    knob ``HFREP_CKPT_FORMAT=msgpack`` forces the same format globally:
    restore is format-transparent either way, and the chaos soak sets it
    for its dozens of spawned fixture drives (an orbax save pays a ~1s
    internal barrier per call that msgpack doesn't).

    ``keep > 0`` prunes all but the newest ``keep`` sibling checkpoints
    sharing this one's numbered naming scheme (``ckpt_<epoch>``).
    """
    p = Path(path).absolute()
    with timeline.timed("checkpoint"):
        # the device→host fetch is part of the checkpoint's bill, not a
        # training sync — booked with the write it feeds
        pytree = jax.device_get(pytree)
    if os.environ.get("HFREP_CKPT_FORMAT", "").lower() == "msgpack":
        coordination_free = True

    def writer(tmp: Path) -> dict:
        if coordination_free:
            _write_msgpack(tmp, pytree)
            return {"format": "msgpack"}
        try:
            ckptr = _ocp().PyTreeCheckpointer()
            ckptr.save(tmp / "tree", pytree, force=True)
            return {"format": "orbax"}
        except Exception:
            shutil.rmtree(tmp / "tree", ignore_errors=True)
            _write_msgpack(tmp, pytree)
            return {"format": "msgpack"}

    write_atomic(p, writer, metadata)
    if keep > 0:
        prefix, digits = _split_numbered(p.name)
        if digits is not None:
            retain(p.parent, keep, prefix=prefix)
    return str(p)


def restore(path: str, target: Any = None, verify_checksum: bool = True) -> Any:
    """Restore one checkpoint, checksum-verified when it carries a
    checksum; decode failures surface as :class:`CheckpointCorrupt` so
    callers (:func:`restore_latest_good`) can fall back."""
    p = Path(path).absolute()
    if not p.exists():
        raise FileNotFoundError(str(p))
    if verify_checksum:
        verify(p)
    msgpack = p / "checkpoint.msgpack"
    if msgpack.exists():
        import flax.serialization as ser
        if target is None:
            raise ValueError("msgpack restore requires a target pytree")
        try:
            return ser.from_bytes(target, msgpack.read_bytes())
        except Exception as e:
            raise CheckpointCorrupt(f"{p}: msgpack decode failed: {e}") from e
    tree = p / "tree" if (p / "tree").exists() else p
    try:
        ckptr = _ocp().PyTreeCheckpointer()
        return ckptr.restore(
            tree, item=jax.device_get(target) if target is not None else None)
    except ImportError:
        raise
    except Exception as e:
        raise CheckpointCorrupt(f"{p}: orbax restore failed: {e}") from e


def restore_latest_good(dirpath: str, target: Any = None,
                        prefix: str = "ckpt_",
                        on_exhausted: str = "raise") -> Tuple[Any, str]:
    """Restore the newest checkpoint that verifies and decodes, falling
    back past torn/corrupted ones instead of crashing.

    Returns ``(pytree, path)``.  Each candidate's parked ``.prev``
    sibling (the overwrite window's last complete payload,
    :func:`prev_path`) is tried right after the candidate itself, so a
    crash mid-overwrite costs one save, never the fallback chain.  Each
    skipped checkpoint lands in the obs stream as a ``ckpt_fallback``
    event (+ counter); raises :class:`FileNotFoundError` when the
    directory holds no candidates.

    When *every* candidate (``.prev`` siblings included) fails:
    ``on_exhausted="raise"`` raises :class:`CheckpointCorrupt`;
    ``"fresh"`` emits a ``ckpt_fallback_exhausted`` event and returns
    ``(None, "")`` — the trainers' resume paths use it to degrade to a
    clean fresh start instead of wedging a drive on unrecoverable state
    (the chaos engine's ``corrupt@ckpt`` composition found the raise).
    """
    # epoch -> the paths to try at that epoch, newest first.  A crash
    # exactly between _atomic_publish's two renames leaves ONLY the
    # parked `.ckpt_<n>.prev` (dst renamed away, tmp never promoted) —
    # an ORPHANED prev is still that epoch's last complete payload and
    # must join the walk at its epoch position, or the mid-overwrite
    # crash window the .prev mechanism exists for would silently lose
    # the newest save to an older sibling.
    entries = {int(p.name[len(prefix):]): [p, prev_path(p)]
               for p in _numbered(dirpath, prefix)}
    d = Path(dirpath)
    if d.exists():
        for q in d.iterdir():
            name = q.name
            if not (q.is_dir() and name.startswith(f".{prefix}")
                    and name.endswith(".prev")):
                continue
            digits = name[len(prefix) + 1:-len(".prev")]
            if digits.isdigit() and int(digits) not in entries:
                entries[int(digits)] = [q]
    if not entries:
        raise FileNotFoundError(f"no {prefix}* checkpoints under {dirpath}")
    errors: List[str] = []
    for epoch in sorted(entries, reverse=True):
        for attempt in entries[epoch]:
            if not attempt.exists():
                continue
            try:
                out = restore(str(attempt), target)
            except (CheckpointCorrupt, FileNotFoundError) as e:
                errors.append(f"{attempt.name}: {e}")
                try:
                    from hfrep_tpu.obs import get_obs
                    obs = get_obs()
                    obs.counter("resilience/ckpt_fallbacks").inc()
                    obs.event("ckpt_fallback", skipped=attempt.name,
                              error=str(e))
                except Exception:
                    pass
                continue
            return out, str(attempt)
    detail = (f"no restorable checkpoint under {dirpath}: "
              + "; ".join(errors))
    if on_exhausted == "fresh":
        try:
            from hfrep_tpu.obs import get_obs
            get_obs().event("ckpt_fallback_exhausted", dir=str(dirpath),
                            candidates=len(entries),
                            error="; ".join(errors))
        except Exception:
            pass
        return None, ""
    raise CheckpointCorrupt(detail)


# --------------------------------------------------------------- retention
def _split_numbered(name: str) -> Tuple[str, Optional[str]]:
    """``'ckpt_120' -> ('ckpt_', '120')``; non-numbered names get
    ``(name, None)`` and are exempt from retention."""
    i = len(name)
    while i > 0 and name[i - 1].isdigit():
        i -= 1
    digits = name[i:]
    return (name[:i], digits) if digits else (name, None)


def _numbered(dirpath, prefix: str) -> List[Path]:
    """Numbered checkpoint dirs under ``dirpath``, oldest first."""
    d = Path(dirpath)
    if not d.exists():
        return []
    cands = [
        p for p in d.iterdir()
        if p.is_dir() and p.name.startswith(prefix)
        and p.name[len(prefix):].isdigit()
    ]
    cands.sort(key=lambda p: int(p.name[len(prefix):]))
    return cands


def retain(dirpath: str, keep: int, prefix: str = "ckpt_") -> List[str]:
    """Delete all but the newest ``keep`` numbered checkpoints; returns
    the removed paths (best-effort — retention must never fail a save)."""
    if keep <= 0:
        return []
    removed = []
    for doomed in _numbered(dirpath, prefix)[:-keep]:
        shutil.rmtree(doomed, ignore_errors=True)
        removed.append(str(doomed))
    return removed


def latest(dirpath: str, prefix: str = "ckpt_") -> Optional[str]:
    cands = _numbered(dirpath, prefix)
    return str(cands[-1]) if cands else None
