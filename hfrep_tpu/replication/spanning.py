"""Huberman-Kandel and GRS spanning tests, pure jnp.

The reference defines both in ~120 lines of R bridged through
rpy2/anndata2ri (``autoencoder_v4.ipynb`` cells 16-20) — a Python→R
process boundary in the middle of the stats loop (SURVEY §3.3).  Here
they are closed-form jnp: R's ``mldivide`` → least squares via pinv,
``pseudoinverse`` → `jnp.linalg.pinv`, and the 2×2 eigenvalue product in
HK collapses to ``1 + tr(M) + det(M)`` so no eigensolver is needed.
F-distribution p-values via the regularized incomplete beta function
(`jax.scipy.special.betainc`) — no scipy on the path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.scipy.special import betainc

Array = jnp.ndarray


def f_sf(x: Array, d1: Array, d2: Array) -> Array:
    """Survival function of the F(d1, d2) distribution:
    P(F > x) = I_{d2/(d2 + d1 x)}(d2/2, d1/2)."""
    x = jnp.maximum(x, 0.0)
    return betainc(d2 / 2.0, d1 / 2.0, d2 / (d2 + d1 * x))


def _centered_ols(y: Array, x: Array) -> Tuple[Array, Array, Array]:
    """OLS-with-intercept of every column of ``y`` (T, N) on ``x`` (T, K),
    computed as an SVD least-squares solve on demeaned data.  Returns ``(slopes (K, N),
    intercepts (N, 1), residuals (T, N))`` — identical to the
    intercept-augmented regression, but without squaring the design's
    condition number (f32-safe)."""
    ym = jnp.mean(y, axis=0, keepdims=True)
    xm = jnp.mean(x, axis=0, keepdims=True)
    yc, xc = y - ym, x - xm
    # SVD least squares, not QR+triangular-solve: keeps the minimum-norm
    # behavior of the old pinv path for rank-deficient panels (a constant
    # column demeans to zeros; a duplicated factor is exactly collinear)
    # while avoiding the normal equations' squared condition number.
    slopes = jnp.linalg.lstsq(xc, yc)[0]
    alpha = (ym - xm @ slopes).T
    resid = yc - xc @ slopes
    return slopes, alpha, resid


@jax.jit
def hktest(rt: Array, rb: Array) -> Tuple[Array, Array]:
    """Huberman-Kandel spanning test (R ``hktest``, notebook cell 17).

    ``rt`` (T, N) test assets, ``rb`` (T, K) benchmark/spanning assets.
    Returns (F-stat, p-value).
    """
    rt = jnp.atleast_2d(rt)
    rb = jnp.atleast_2d(rb)
    t, n = rt.shape
    k = rb.shape[1]

    # Centered least-squares regression instead of R's mldivide on the raw design:
    # normal equations square the condition number, and in f32 that cost
    # the intercept (the quantity the test is ABOUT) ~2 digits — enough
    # to move the published benchmark F-stats by >10%.  Slopes from an SVD
    # solve on demeaned data + intercept by mean-matching are the same
    # estimator, computed stably (verified against the published cell-30
    # table in tests/test_experiments.py).
    slopes, alpha, e = _centered_ols(rt, rb)                       # (K,N),(N,1),(T,N)
    # Theta = A @ B - C with B = [intercept row; slope rows]:
    # row 1 = intercept, row 2 = 1 - colsums(slopes)
    theta = jnp.concatenate([alpha.T, 1.0 - jnp.sum(slopes, axis=0,
                                                    keepdims=True)])  # (2, N)
    sigma = (e.T @ e) / (t - 1)            # R cov(e): T-1 denominator
    h = theta @ jnp.linalg.pinv(sigma) @ theta.T                   # (2, 2)

    mu1 = jnp.mean(rb, axis=0, keepdims=True)                      # (1, K)
    rbc = rb - mu1
    v11i = jnp.linalg.pinv((rbc.T @ rbc) / (t - 1))
    a1 = (mu1 @ v11i @ mu1.T)[0, 0]
    b1 = jnp.sum(v11i @ mu1.T)
    c1 = jnp.sum(v11i)
    g = jnp.array([[1.0 + a1, b1], [b1, c1]])

    m = h @ jnp.linalg.inv(g)
    # prod(1 + eig(M)) for 2×2 M is det(I + M) = 1 + tr(M) + det(M)
    ui = 1.0 + jnp.trace(m) + jnp.linalg.det(m)

    if n == 1:
        f_stat = (t - k - 1) * (ui - 1.0) / 2.0
        p = f_sf(f_stat, 2.0, jnp.asarray(t - k - 1, jnp.float32))
    else:
        f_stat = (t - k - n) * (jnp.sqrt(ui) - 1.0) / n
        p = f_sf(f_stat, 2.0 * n, 2.0 * (t - n - k))
    return f_stat, p


@jax.jit
def grstest(ret: Array, factors: Array) -> Tuple[Array, Array]:
    """Gibbons-Ross-Shanken test (R ``grstest``, notebook cell 19).

    ``ret`` (T, N), ``factors`` (T, K) → (F-stat, p-value).  All N
    time-series regressions run as one batched solve.
    """
    ret = jnp.atleast_2d(ret)
    factors = jnp.atleast_2d(factors)
    t, n = ret.shape
    k = factors.shape[1]

    # Same centered least-squares estimator as hktest (see the stability note there).
    slopes, alpha, e = _centered_ols(ret, factors)
    sigma = (e.T @ e) / (t - k - 1)
    f_mean = jnp.mean(factors, axis=0, keepdims=True)              # (1, K)
    fc = factors - f_mean
    omega = (fc.T @ fc) / (t - 1)
    tem1 = (alpha.T @ jnp.linalg.pinv(sigma) @ alpha)[0, 0]
    tem2 = 1.0 + (f_mean @ jnp.linalg.pinv(omega) @ f_mean.T)[0, 0]
    f_stat = (t / n) * ((t - n - k) / (t - k - 1)) * (tem1 / tem2)
    p = f_sf(f_stat, jnp.asarray(n, jnp.float32), jnp.asarray(t - n - k, jnp.float32))
    return f_stat, p
