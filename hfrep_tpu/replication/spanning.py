"""Huberman-Kandel and GRS spanning tests, pure jnp.

The reference defines both in ~120 lines of R bridged through
rpy2/anndata2ri (``autoencoder_v4.ipynb`` cells 16-20) — a Python→R
process boundary in the middle of the stats loop (SURVEY §3.3).  Here
they are closed-form jnp: R's ``mldivide`` → least squares via pinv,
``pseudoinverse`` → `jnp.linalg.pinv`, and the 2×2 eigenvalue product in
HK collapses to ``1 + tr(M) + det(M)`` so no eigensolver is needed.
F-distribution p-values via the regularized incomplete beta function
(`jax.scipy.special.betainc`) — no scipy on the path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.scipy.special import betainc

Array = jnp.ndarray


def f_sf(x: Array, d1: Array, d2: Array) -> Array:
    """Survival function of the F(d1, d2) distribution:
    P(F > x) = I_{d2/(d2 + d1 x)}(d2/2, d1/2)."""
    x = jnp.maximum(x, 0.0)
    return betainc(d2 / 2.0, d1 / 2.0, d2 / (d2 + d1 * x))


@jax.jit
def hktest(rt: Array, rb: Array) -> Tuple[Array, Array]:
    """Huberman-Kandel spanning test (R ``hktest``, notebook cell 17).

    ``rt`` (T, N) test assets, ``rb`` (T, K) benchmark/spanning assets.
    Returns (F-stat, p-value).
    """
    rt = jnp.atleast_2d(rt)
    rb = jnp.atleast_2d(rb)
    t, n = rt.shape
    k = rb.shape[1]

    a = jnp.block([[jnp.ones((1, 1)), jnp.zeros((1, k))],
                   [jnp.zeros((1, 1)), -jnp.ones((1, k))]])        # (2, K+1)
    c = jnp.concatenate([jnp.zeros((1, n)), -jnp.ones((1, n))])    # (2, N)
    x = jnp.concatenate([jnp.ones((t, 1)), rb], axis=1)            # (T, K+1)
    b = jnp.linalg.pinv(x.T @ x) @ (x.T @ rt)                      # mldivide
    theta = a @ b - c                                              # (2, N)
    e = rt - x @ b
    sigma = jnp.cov(e, rowvar=False).reshape(n, n)
    h = theta @ jnp.linalg.pinv(sigma) @ theta.T                   # (2, 2)

    mu1 = jnp.mean(rb, axis=0, keepdims=True)                      # (1, K)
    v11i = jnp.linalg.pinv(jnp.cov(rb, rowvar=False).reshape(k, k))
    a1 = (mu1 @ v11i @ mu1.T)[0, 0]
    b1 = jnp.sum(v11i @ mu1.T)
    c1 = jnp.sum(v11i)
    g = jnp.array([[1.0 + a1, b1], [b1, c1]])

    m = h @ jnp.linalg.inv(g)
    # prod(1 + eig(M)) for 2×2 M is det(I + M) = 1 + tr(M) + det(M)
    ui = 1.0 + jnp.trace(m) + jnp.linalg.det(m)

    if n == 1:
        f_stat = (t - k - 1) * (ui - 1.0) / 2.0
        p = f_sf(f_stat, 2.0, jnp.asarray(t - k - 1, jnp.float32))
    else:
        f_stat = (t - k - n) * (jnp.sqrt(ui) - 1.0) / n
        p = f_sf(f_stat, 2.0 * n, 2.0 * (t - n - k))
    return f_stat, p


@jax.jit
def grstest(ret: Array, factors: Array) -> Tuple[Array, Array]:
    """Gibbons-Ross-Shanken test (R ``grstest``, notebook cell 19).

    ``ret`` (T, N), ``factors`` (T, K) → (F-stat, p-value).  All N
    time-series regressions run as one batched solve.
    """
    ret = jnp.atleast_2d(ret)
    factors = jnp.atleast_2d(factors)
    t, n = ret.shape
    k = factors.shape[1]

    x = jnp.concatenate([jnp.ones((t, 1)), factors], axis=1)       # (T, K+1)
    b = jnp.linalg.pinv(x.T @ x) @ (x.T @ ret)                     # (K+1, N)
    e = ret - x @ b                                                # (T, N)
    sigma = (e.T @ e) / (t - k - 1)
    alpha = b[0][:, None]                                          # (N, 1)
    f_mean = jnp.mean(factors, axis=0, keepdims=True)              # (1, K)
    omega = ((factors - f_mean).T @ (factors - f_mean)) / (t - 1)
    tem1 = (alpha.T @ jnp.linalg.pinv(sigma) @ alpha)[0, 0]
    tem2 = 1.0 + (f_mean @ jnp.linalg.pinv(omega) @ f_mean.T)[0, 0]
    f_stat = (t / n) * ((t - n - k) / (t - k - 1)) * (tem1 / tem2)
    p = f_sf(f_stat, jnp.asarray(n, jnp.float32), jnp.asarray(t - n - k, jnp.float32))
    return f_stat, p
