
from __future__ import annotations
from hfrep_tpu.replication.engine import AEResult, ReplicationEngine, train_autoencoder  # noqa: F401
from hfrep_tpu.replication import perf_stats, spanning  # noqa: F401
