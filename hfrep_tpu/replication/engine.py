"""Autoencoder replication engine: training, evaluation, strategy build.

TPU-native re-design of ``Autoencoder_encapsulate.py:38-224`` (class
``AE``).  Where the reference trains 21 separate Keras models in a Python
loop with per-call ``predict`` inside O(T) host loops (SURVEY §3.3), here:

* one AE training run is a single `lax.scan` over epochs with
  Keras-faithful early stopping folded into the carry;
* the latent-dim sweep is `vmap` over a latent *mask* (same param shapes,
  see :mod:`hfrep_tpu.models.autoencoder`) — all 21 trainings execute as
  one batched XLA program;
* the expanding-window OOS metrics use prefix min/max scans instead of
  167 scaler refits;
* the 24-month rolling OLS is one batched least-squares.

Training recipe ported from ``Autoencoder_encapsulate.py:72-105``:
MinMax-scale x_train only (``:62-67``; note ``_x_test`` stays *unscaled* —
the encoder is later applied to raw test returns, ``:67,140``), Nadam on
MSE, ≤1000 epochs, batch 48, ``validation_split=.25`` (Keras semantics:
the *last* 25% of rows are validation, the first 75% train), per-epoch
reshuffling of the train block, EarlyStopping(patience=5) on val_loss
without best-weight restore.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hfrep_tpu import resilience
from hfrep_tpu.config import AEConfig
from hfrep_tpu.core import costs
from hfrep_tpu.obs import health as health_mod
from hfrep_tpu.core import scaler as mm
from hfrep_tpu.models.autoencoder import Autoencoder, latent_mask
from hfrep_tpu.ops.optimizers import keras_nadam
from hfrep_tpu.ops.rolling import expanding_minmax_scale, rolling_ols_beta

import optax


class AEResult(NamedTuple):
    params: dict                 # encoder/decoder kernels (possibly batched)
    stop_epoch: jnp.ndarray      # epoch index where early stopping fired
    train_loss: jnp.ndarray     # (epochs,) per-epoch training loss (NaN after stop)
    val_loss: jnp.ndarray       # (epochs,)


class ChunkStats(NamedTuple):
    """Dispatch accounting of a chunked early-exit training drive."""

    chunks_dispatched: int       # jitted scan calls the host actually issued
    epochs_dispatched: int       # epochs those chunks executed on device
    epochs_total: int            # cfg.epochs (what the monolithic scan pays)
    chunk_epochs: int            # epochs per chunk
    lanes: int                   # vmapped training lanes in the program
    lanes_stopped: int           # lanes whose early stopping fired
    overshoot_chunks: int = 0    # chunks the double-buffered drive ran past
    #                              all(stopped) before the deferred flag sync
    #                              observed it (0 or 1; always 0 serial) —
    #                              pure accounting, results are bit-identical

    @property
    def epochs_saved(self) -> int:
        return self.epochs_total - self.epochs_dispatched


def _epoch_batches(n_train: int, batch_size: int) -> Tuple[int, int]:
    n_batches = -(-n_train // batch_size)
    return n_batches, n_batches * batch_size


def _ae_model(cfg: AEConfig) -> Autoencoder:
    # dtype="float32" maps to module dtype None — the no-cast graph the
    # pre-policy engine traced, so the fp32 pins hold by construction;
    # "bfloat16" runs the two matmuls at MXU rate with fp32 master
    # weights, and the MSE below still accumulates in float32 (the
    # subtraction against the float32 panel promotes before reduction)
    dt = None if cfg.dtype in (None, "float32") else jnp.dtype(cfg.dtype)
    return Autoencoder(n_features=cfg.n_factors, latent_dim=cfg.latent_dim,
                       slope=cfg.leaky_slope, dtype=dt)


def _ae_init(cfg: AEConfig, x_train_scaled: jnp.ndarray, key: jax.Array):
    """Initial training carry + the per-epoch PRNG keys.

    Shared by the monolithic scan and the chunked driver so the two paths
    consume bit-identical initial state and key streams."""
    model = _ae_model(cfg)
    key, init_key = jax.random.split(key)
    params = model.init(init_key, x_train_scaled[:1])["params"]
    tx = keras_nadam(cfg.lr, b1=0.9, b2=0.999, eps=1e-7)   # tf.keras-exact Nadam
    opt_state = tx.init(params)
    # best-val-loss slot as a STRONGLY-typed f32 scalar: a bare
    # ``jnp.inf`` is a weak-typed Python float, which rides the carry
    # into every chunk program's abstract signature — one resume path
    # feeding a concrete array where another fed the weak scalar would
    # compile two executables for the same program (JPX004)
    carry = (params, opt_state, jnp.asarray(jnp.inf, jnp.float32),
             jnp.zeros((), jnp.int32), jnp.zeros((), bool))
    return carry, jax.random.split(key, cfg.epochs)


def _ae_epoch_step(cfg: AEConfig, x_train_scaled: jnp.ndarray,
                   mask: Optional[jnp.ndarray],
                   rows_info=None):
    """One training epoch as a ``lax.scan`` body, shared by every path.

    ``rows_info`` — a traced ``(n_rows, n_train_eff)`` scalar pair —
    switches on the padded multi-dataset semantics: ``x_train_scaled``
    then holds ``n_rows`` real rows followed by zero padding up to a
    common static shape, ``n_train_eff`` is the dataset's own Keras
    ``validation_split`` boundary (computed host-side in exact Python
    arithmetic by :func:`_rows_info` — a traced float32
    ``floor(n * 0.9)`` rounds the wrong way for some splits), and the
    per-batch sample weights additionally zero every slot whose permuted
    row index falls outside the dataset's own fit block — so one
    compiled program trains datasets of different true lengths.  Each
    lane still takes the full static batch count of optimizer steps per
    epoch (all-masked batches contribute exactly-zero gradients — note
    the Nadam momentum still decays through them, which is why the
    padded path is pinned against the padded serial sweep, not bitwise
    against the dense one); with ``n_rows == x.shape[0]`` the batch
    stream degenerates to the dense path's exactly, only the validation
    loss reduces through the weighted (rather than sliced) mean.
    """
    model = _ae_model(cfg)
    tx = keras_nadam(cfg.lr, b1=0.9, b2=0.999, eps=1e-7)
    # Flight-recorder health, decided at build time: None (default)
    # traces the literal pre-health program; a config extends the epoch
    # outputs with (grad_norm, nonfinite) traces accumulated inside the
    # existing batch/epoch scans — the training carry is untouched, so
    # results stay bit-identical (pinned by tests/test_obs_health.py)
    hcfg = health_mod.active()
    n = x_train_scaled.shape[0]
    # Keras validation_split semantics: split_at = floor(n * (1 - split))
    # training rows, the rest validation (167 → 125 train / 42 val).
    n_train = int(n * (1.0 - cfg.val_split))
    x_fit, x_val = x_train_scaled[:n_train], x_train_scaled[n_train:]
    n_batches, padded = _epoch_batches(n_train, cfg.batch_size)

    if rows_info is None:
        n_train_eff = None
        val_x, val_w = x_val, None
    else:
        n_rows, n_train_eff = rows_info
        rows = jnp.arange(n)
        val_x = x_train_scaled
        val_w = jnp.logical_and(rows >= n_train_eff,
                                rows < n_rows).astype(jnp.float32)

    def mse(p, x, w=None):
        pred = model.apply({"params": p}, x, mask)
        err = jnp.mean((pred - x) ** 2, axis=1)
        if w is None:
            return jnp.mean(err)
        return jnp.sum(err * w) / jnp.maximum(jnp.sum(w), 1.0)

    def epoch_step(carry, epoch_key):
        params, opt_state, best_val, wait, stopped = carry
        perm = jax.random.permutation(epoch_key, n_train)
        order = jnp.concatenate([perm, jnp.zeros(padded - n_train, jnp.int32)])
        weights = (jnp.arange(padded) < n_train).astype(jnp.float32)
        if n_train_eff is not None:
            weights = weights * (order < n_train_eff)

        def batch_step(c, i):
            p, o = c
            sl = lax.dynamic_slice_in_dim(order, i * cfg.batch_size, cfg.batch_size)
            w = lax.dynamic_slice_in_dim(weights, i * cfg.batch_size, cfg.batch_size)
            xb = jnp.take(x_fit, sl, axis=0)
            loss, grads = jax.value_and_grad(mse)(p, xb, w)
            updates, o = tx.update(grads, o, p)
            out = ((loss, health_mod.tree_sq_norm(grads)) if hcfg else loss)
            return (optax.apply_updates(p, updates), o), out

        (new_params, new_opt), batch_out = lax.scan(
            batch_step, (params, opt_state), jnp.arange(n_batches))
        batch_losses, batch_gsq = (batch_out if hcfg else (batch_out, None))

        # freeze updates once stopped (Keras keeps stop-epoch weights)
        params = jax.tree_util.tree_map(
            lambda old, new: jnp.where(stopped, old, new), params, new_params)
        opt_state = jax.tree_util.tree_map(
            lambda old, new: jnp.where(stopped, old, new), opt_state, new_opt)

        val = mse(params, val_x, val_w)
        improved = val < best_val
        wait = jnp.where(stopped, wait, jnp.where(improved, 0, wait + 1))
        best_val = jnp.where(stopped, best_val, jnp.minimum(best_val, val))
        newly_stopped = jnp.logical_and(jnp.logical_not(stopped), wait >= cfg.patience)
        train_loss = jnp.where(stopped, jnp.nan, jnp.mean(batch_losses))
        val_out = jnp.where(stopped, jnp.nan, val)
        outs = (train_loss, val_out)
        if hcfg:
            # per-epoch health traces: global grad norm across the batch
            # scan (NaN after the lane stopped, like the losses) + a
            # nonfinite count over the kept params and the epoch's val
            # loss — read back only at the chunk boundary the host
            # already syncs at
            gn = jnp.where(stopped, jnp.nan,
                           jnp.sqrt(jnp.sum(batch_gsq)))
            nf = (health_mod.tree_nonfinite(params)
                  + (~jnp.isfinite(val)).astype(jnp.float32))
            outs = outs + (gn, nf)
        stopped = jnp.logical_or(stopped, newly_stopped)
        return ((params, opt_state, best_val, wait, stopped),
                outs[:2] + (stopped,) + outs[2:])

    return epoch_step


def _ae_result(params: dict, tl: jnp.ndarray, vl: jnp.ndarray,
               stop_trace: jnp.ndarray, epochs: int) -> AEResult:
    stop_epoch = jnp.argmax(stop_trace, axis=-1) + jnp.where(
        jnp.any(stop_trace, axis=-1), 0, epochs)
    return AEResult(params=params, stop_epoch=stop_epoch, train_loss=tl,
                    val_loss=vl)


def train_autoencoder(key: jax.Array, x_train_scaled: jnp.ndarray, cfg: AEConfig,
                      mask: Optional[jnp.ndarray] = None) -> AEResult:
    """Train one (optionally masked) AE; pure function of (key, data, cfg).

    ``mask`` is a (max_latent,) 0/1 vector selecting active latent dims;
    None trains the full ``cfg.latent_dim``.  This is the monolithic
    single-scan form (traceable, so it vmaps/jits freely); the host-driven
    early-exit form with identical results is
    :func:`train_autoencoder_chunked`.
    """
    carry, keys = _ae_init(cfg, x_train_scaled, key)
    step = _ae_epoch_step(cfg, x_train_scaled, mask)
    (params, _, _, _, _), traces = lax.scan(step, carry, keys)
    # traces[3:] are the optional health traces (flight recorder); the
    # result contract is the first three either way
    return _ae_result(params, traces[0], traces[1], traces[2], cfg.epochs)


def _donate_argnums() -> Tuple[int, ...]:
    # donated carries let XLA reuse the parameter/optimizer buffers across
    # chunk dispatches; the CPU backend does not implement donation and
    # warns per call, so only donate where it can land
    return () if jax.default_backend() == "cpu" else (0,)


# The compiled chunk/init programs, cached by (cfg, program kind).  The
# chunked drive's economics depend on this: the fixed-size chunk program
# compiles ONCE and every later dispatch — across chunks, re-trains,
# sweep variants, bench repeats — reuses it; a per-call
# ``jax.jit(lambda ...)`` would recompile per drive and hand the
# early-exit savings straight back to XLA.  Data (panel, masks, row
# counts) enters as traced operands, never as baked constants, for the
# same reason; new shapes retrace inside the cached jit as usual.
_PROGRAM_CACHE: dict = {}

#: (program-cache key, run dir) pairs already fingerprinted into an obs
#: run — the perf microscope profiles each cached program ONCE per run,
#: not once per drive (a per-drive re-lower is a fixed trace cost inside
#: every timed chunked window)
_PROFILED_PROGRAMS: set = set()


def _cached_program(cfg: AEConfig, kind: str, build, mesh=None):
    # the health flag changes the traced program's OUTPUT arity (extra
    # grad-norm/nonfinite traces), so it must key the cache: a test that
    # toggles health between drives must not replay the other mode's
    # compiled program.  The mesh keys it too (jax.sharding.Mesh is
    # hashable): a dp-sharded chunk program and the single-device one
    # are different executables even though they trace the same jaxpr.
    key = (dataclasses.astuple(cfg), kind,
           bool(health_mod.active()), mesh)
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        fn = _PROGRAM_CACHE[key] = build()
    return fn


def _lane_specs(kind: str):
    """PartitionSpec layout of one chunk/init dispatch, per drive kind:
    ``(lane_prefix, keys, xs, masks, rows_info)``.  The lane grid's
    leading axis — L latent lanes (``lanes``) or D datasets (``multi``)
    — shards over ``dp``; the grid is embarrassingly parallel (each lane
    is an independent training), so GSPMD splits the vmap with ZERO
    collectives and the sharded run is BIT-identical to the single
    device's (pinned).  ``single`` has no lane axis: replicated.  The
    lane layout itself is the one declaration
    :data:`~hfrep_tpu.parallel.rules.AE_LANE_SPEC` (whose rule form is
    pinned against the real engine carry in tests/test_mesh_rules.py)."""
    from jax.sharding import PartitionSpec as P

    from hfrep_tpu.parallel.rules import AE_LANE_SPEC as lane
    if kind == "lanes":
        return lane, lane, P(), lane, P()
    if kind == "multi":
        return lane, lane, lane, P(), lane
    return P(), P(), P(), P(), P()


def _chunk_fn(cfg: AEConfig, kind: str, mesh=None):
    """The jitted ``chunk_epochs``-long scan program for one drive kind:
    ``single`` (one lane), ``lanes`` (L vmapped latent lanes over one —
    dense or padded — dataset), ``multi`` (D×L lanes over stacked padded
    datasets).  Signature is uniform — ``fn(carry, keys, xs, masks,
    rows_info)``, with ``masks``/``rows_info`` None on the paths that
    lack them — so :func:`_drive_chunks` stays one host loop for all
    three.  With ``mesh`` the program dispatches through
    :func:`~hfrep_tpu.parallel.rules.mesh_launch` with the lane grid
    sharded over ``dp`` (ROADMAP item 1's multi-chip sweep fabric);
    without, the plain jit — identical jaxpr either way."""
    def build():
        if kind == "single":
            def run(carry, keys, xs, masks, rows_info):
                return lax.scan(
                    _ae_epoch_step(cfg, xs, masks, rows_info=rows_info),
                    carry, keys)
        elif kind == "lanes":
            def run(carry, keys, xs, masks, rows_info):
                def lane(c, ks, m):
                    return lax.scan(
                        _ae_epoch_step(cfg, xs, m, rows_info=rows_info),
                        c, ks)
                return jax.vmap(lane)(carry, keys, masks)
        elif kind == "multi":
            def run(carry, keys, xs, masks, rows_info):
                def dataset(c, ks, x, ri):
                    def lane(cl, kl, m):
                        return lax.scan(
                            _ae_epoch_step(cfg, x, m, rows_info=ri), cl, kl)
                    return jax.vmap(lane)(c, ks, masks)
                return jax.vmap(dataset)(carry, keys, xs, rows_info)
        else:
            raise ValueError(f"unknown chunk program kind {kind!r}")
        if mesh is not None:
            from hfrep_tpu.parallel.rules import mesh_launch
            lane, keys_s, xs_s, masks_s, rows_s = _lane_specs(kind)
            return mesh_launch(run, mesh,
                               in_specs=(lane, keys_s, xs_s, masks_s, rows_s),
                               out_specs=lane,
                               donate_argnums=_donate_argnums())
        return jax.jit(run, donate_argnums=_donate_argnums())
    return _cached_program(cfg, f"chunk:{kind}", build, mesh=mesh)


def _init_program(cfg: AEConfig, kind: str, n_lanes: int = 0, mesh=None):
    """The jitted initial-carry program matching :func:`_chunk_fn`'s
    kind: ``fn(keys, xs)`` with ``keys`` one PRNG key per lane (single:
    one key; multi: one per dataset, split into ``n_lanes`` latent lanes
    inside).  With ``mesh`` the returned carry comes back already
    lane-sharded, so the first chunk dispatch moves nothing."""
    def build():
        if kind == "single":
            def run(keys, xs):
                return _ae_init(cfg, xs, keys)
        elif kind == "lanes":
            def run(keys, xs):
                return jax.vmap(lambda k: _ae_init(cfg, xs, k))(keys)
        elif kind == "multi":
            def run(keys, xs):
                def dataset(dk, x):
                    lane_keys = jax.random.split(dk, n_lanes)
                    return jax.vmap(lambda k: _ae_init(cfg, x, k))(lane_keys)
                return jax.vmap(dataset)(keys, xs)
        else:
            raise ValueError(f"unknown init program kind {kind!r}")
        if mesh is not None:
            from hfrep_tpu.parallel.rules import mesh_launch
            lane, keys_s, xs_s, _, _ = _lane_specs(kind)
            return mesh_launch(run, mesh, in_specs=(keys_s, xs_s),
                               out_specs=lane)
        return jax.jit(run)
    return _cached_program(cfg, f"init:{kind}:{n_lanes}", build, mesh=mesh)


def _rows_info(cfg: AEConfig, n_rows) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The padded paths' ``(n_rows, n_train_eff)`` operand pair, with the
    Keras ``validation_split`` boundary computed host-side in exact
    Python arithmetic — ``int(r * (1 - val_split))`` in float64, exactly
    the dense path's formula.  A traced float32 ``floor`` disagrees for
    some (split, rows) pairs: ``float32(0.9) * 10`` floors to 8 where
    Python's ``int(10 * 0.9)`` is 9."""
    arr = np.asarray(jax.device_get(n_rows), dtype=np.int64)
    fit = (arr * (1.0 - cfg.val_split)).astype(np.int64)  # float64, truncating
    return jnp.asarray(arr, jnp.int32), jnp.asarray(fit, jnp.int32)


def _run_chunked(cfg: AEConfig, kind: str, keys, xs, masks, rows_info,
                 lanes: int, n_lanes_init: int = 0,
                 resume_dir: Optional[str] = None, mesh=None,
                 ) -> Tuple[AEResult, ChunkStats]:
    """The shared drive tail of every chunked public entry point: init
    carry, dispatch chunks until ``all(stopped)``, assemble the
    bit-identical :class:`AEResult` and the :class:`ChunkStats`
    accounting.

    ``resume_dir`` makes the drive preemption-safe: the carry pytree,
    accumulated traces and chunk counter are persisted there at every
    chunk boundary (crash-consistent — see
    :class:`~hfrep_tpu.resilience.snapshot.ChunkSnapshot`), a SIGTERM
    drains at the boundary instead of dying mid-dispatch
    (:class:`~hfrep_tpu.resilience.Preempted`), and a re-run against the
    same (cfg, key, data) resumes from the last completed chunk with
    bit-identical final results (the snapshot fingerprint refuses
    foreign state).  The per-chunk snapshot costs one carry
    ``device_get`` + atomic write per boundary, so it is opt-in.

    ``mesh`` (a ``('dp',)`` mesh, :func:`~hfrep_tpu.parallel.rules.
    build_mesh`/``lane_mesh``) dispatches every chunk through the
    unified pjit launch with the lane grid's leading axis sharded over
    ``dp``: operands are placed once via the shard fns, results are
    BIT-identical to the meshless drive (independent lanes — no
    cross-lane reduction exists to reorder; pinned), and snapshots/
    resume work unchanged (carries restored host-side reshard on the
    next dispatch).  The snapshot fingerprint deliberately excludes the
    mesh — a drive may resume on a different device count.
    """
    if mesh is not None:
        if "dp" not in mesh.axis_names:
            raise ValueError(f"chunked drive wants a mesh with a 'dp' "
                             f"axis, got {mesh.axis_names}")
        n_dp = int(mesh.shape["dp"])
        lane_rows = {"lanes": lanes, "multi": int(xs.shape[0]),
                     "single": 1}[kind]
        if kind != "single" and lane_rows % n_dp:
            raise ValueError(
                f"lane axis of size {lane_rows} not divisible by the "
                f"dp={n_dp} mesh (pick a divisor — "
                f"hfrep_tpu.parallel.rules.lane_mesh does)")
        from hfrep_tpu.parallel.rules import make_shard_and_gather_fns
        _, keys_s, xs_s, masks_s, rows_s = _lane_specs(kind)
        shard_keys, _ = make_shard_and_gather_fns(mesh, keys_s)
        shard_xs, _ = make_shard_and_gather_fns(mesh, xs_s)
        shard_masks, _ = make_shard_and_gather_fns(mesh, masks_s)
        shard_rows, _ = make_shard_and_gather_fns(mesh, rows_s)
        keys, xs = shard_keys(keys), shard_xs(xs)
        masks = shard_masks(masks) if masks is not None else None
        rows_info = shard_rows(rows_info) if rows_info is not None else None
    snap = None
    if resume_dir is not None:
        from hfrep_tpu.resilience.snapshot import ChunkSnapshot, digest_arrays
        snap = ChunkSnapshot(resume_dir, fingerprint={
            "cfg": list(dataclasses.astuple(cfg)), "kind": kind,
            "lanes": lanes,
            # health changes the persisted trace arity — a health-on
            # resume must not adopt a health-off snapshot (or vice versa)
            "health": bool(health_mod.active()),
            "operands": digest_arrays(keys, xs, masks, rows_info)})
    carry, epoch_keys = _init_program(cfg, kind, n_lanes_init,
                                      mesh=mesh)(keys, xs)
    fn = _chunk_fn(cfg, kind, mesh=mesh)
    from hfrep_tpu.obs import attrib as attrib_mod
    from hfrep_tpu.obs import get_obs
    obs = get_obs()
    profile_key = (((dataclasses.astuple(cfg), kind,
                     bool(health_mod.active()), mesh), str(obs.run_dir))
                   if obs.enabled else None)
    if obs.enabled and profile_key not in _PROFILED_PROGRAMS:
        # fingerprint the chunk program against the first dispatch's
        # exact operands (trace+lower only, before any donation): the
        # program-cache economics — ONE compile reused across chunks/
        # re-trains — become a machine-checkable fact, and a silent
        # retrace between runs a diffable digest change.  Once per
        # (program-cache key, run dir), like the compile it describes:
        # re-lowering on EVERY drive put a fixed trace cost inside
        # bench_ae's timed chunked window and sank its speedup floor
        # at fixture scale (caught by the gate; measured, not guessed)
        _PROFILED_PROGRAMS.add(profile_key)
        n_chunk = min(cfg.chunk_epochs or cfg.epochs, cfg.epochs)
        attrib_mod.profile_jitted(
            fn, f"ae_chunk:{kind}", carry,
            epoch_keys[..., :n_chunk, :], xs, masks, rows_info)
    with resilience.graceful_drain():
        carry, traces, dispatched, chunks, overshoot = _drive_chunks(
            lambda c, ks: fn(c, ks, xs, masks, rows_info), carry, epoch_keys,
            cfg.epochs, cfg.chunk_epochs, snapshot=snap,
            double_buffer=cfg.double_buffer)
    res = _ae_result(carry[0], traces[0], traces[1], traces[2], cfg.epochs)
    # final boundary: lanes_stopped (and, with health on, the last
    # dispatched epoch's health scalars) in ONE device_get — the drive's
    # pre-existing end-of-run sync, no new sync points
    stopped_dev = jnp.sum(res.stop_epoch < cfg.epochs)
    if health_mod.active() is not None and len(traces) >= 5:
        last = max(0, dispatched - 1)
        n_stopped, gnv, nfv, pnv = jax.device_get(
            (stopped_dev, jnp.nanmax(traces[3][..., last]),
             jnp.nansum(traces[4][..., last]),
             health_mod.tree_norm(carry[0])))
        _emit_ae_health(float(gnv), float(nfv), float(pnv), dispatched,
                        carry, snap)
    else:
        n_stopped = jax.device_get(stopped_dev)
    stats = ChunkStats(chunks_dispatched=chunks, epochs_dispatched=dispatched,
                       epochs_total=cfg.epochs,
                       chunk_epochs=cfg.chunk_epochs or cfg.epochs,
                       lanes=lanes, lanes_stopped=int(n_stopped),
                       overshoot_chunks=overshoot)
    if snap is not None:
        snap.clear()
    return res, stats


def _concat_traces(traces: list) -> Tuple[jnp.ndarray, ...]:
    """Concatenate per-chunk trace tuples along the epoch axis.  The
    first three components are always (train_loss, val_loss, stopped);
    health-enabled drives carry two more (grad_norm, nonfinite)."""
    return tuple(jnp.concatenate([t[i] for t in traces], axis=-1)
                 for i in range(len(traces[0])))


def _drive_chunks(chunk_fn, carry, keys, epochs: int, chunk_epochs: int,
                  snapshot=None, double_buffer: bool = True):
    """The host side of chunked early-exit training.

    Dispatches ``chunk_epochs``-long jitted scans, reading back ONE scalar
    (``all(stopped)``) between dispatches, and stops paying for epochs the
    early stopping already cancelled.  Undispatched epochs are padded with
    the exact values the monolithic scan's post-stop masking would have
    produced (NaN losses, True stop flags), so the assembled traces — and
    therefore :func:`_ae_result` — are bit-identical to the single-scan
    path.  Returns ``(carry, (tl, vl, stop_trace), epochs_dispatched,
    chunks_dispatched, overshoot_chunks)``.

    ``double_buffer`` is the async boundary engine (ROADMAP item 2a).
    On an un-snapshotted health-off drive the continue/stop read-back
    becomes a ONE-SLOT PENDING FUTURE: chunk k+1 is dispatched before
    chunk k's flag is synced, so the host blocks one chunk behind the
    device and the boundary's host work (trace bookkeeping, the next
    dispatch itself) overlaps the in-flight chunk.  The price is at
    most one chunk of overshoot after ``all(stopped)`` lands — and the
    overshoot chunk's outputs are exactly the padding values (params
    frozen by the post-stop masking, NaN losses, True flags), so the
    assembled result stays bit-identical to serial dispatch (pinned).
    Snapshotted drives keep the eager flag sync (the staged carry must
    leave the device before the next donating dispatch) but defer the
    snapshot's FILE WRITE until after the next dispatch, so the atomic
    publish overlaps device compute; the pending write is committed
    before any :class:`~hfrep_tpu.resilience.Preempted` surfaces and on
    every loop exit.  Health-armed drives stay fully serial: the
    boundary's forensic dump must describe the chunk it just synced.

    ``snapshot`` (a :class:`~hfrep_tpu.resilience.snapshot.ChunkSnapshot`)
    adds the preemption story: resume state is loaded before the loop
    and persisted after every chunk, and each boundary crossing passes
    through :func:`hfrep_tpu.resilience.boundary` — where injected
    faults fire and a requested drain raises
    :class:`~hfrep_tpu.resilience.Preempted` (state already on disk).
    The boundary is honored even without a snapshot: a SIGTERM'd
    un-snapshotted drive still exits cleanly between dispatches rather
    than mid-write.
    """
    chunk = int(chunk_epochs) if chunk_epochs and chunk_epochs > 0 else epochs
    traces: list = []
    pos = 0
    chunks = 0
    overshoot = 0
    stopped_all = False
    if snapshot is not None:
        loaded = snapshot.load(carry)
        if loaded is not None:
            carry, tr, pos, chunks, stopped_all = loaded
            traces.append(tr)
            from hfrep_tpu.obs import get_obs
            obs = get_obs()
            if obs.enabled:
                obs.counter("resilience/resumes").inc()
                obs.event("chunk_resume", pos=pos, chunks=chunks,
                          epochs=epochs, path=str(snapshot.path))
    # perf-microscope attribution (hfrep_tpu/obs/attrib.py), decided
    # once per drive: each chunk's un-blocked dispatch is timed on the
    # host and flushed against the wall clock ending at the boundary's
    # continue/stop device_get — the sync the drive already pays, so
    # attribution adds zero sync points and cannot perturb the chunk
    # economics.  The first chunk is a warmup window (its dispatch
    # carries the XLA compile) and is discarded, like the trainer's.
    from hfrep_tpu.obs import attrib, get_obs, timeline
    attrib_on = get_obs().enabled
    calls_here = 0          # dispatches THIS drive issued (≠ ``chunks``,
    #                         which a snapshot resume restores: the first
    #                         post-resume dispatch pays the fresh
    #                         process's XLA compile and must be discarded
    #                         as warmup even at chunks > 1)
    # mode selection, once per drive: the deferred-flag path needs an
    # un-snapshotted (no staged carry to fetch eagerly) health-off (the
    # boundary sync may raise with forensics of the chunk it describes)
    # drive; everything else keeps the eager sync, and double_buffer
    # still buys the deferred snapshot write below
    deferred_flag = (double_buffer and snapshot is None
                     and health_mod.active() is None)
    pending_flag = None     # Mode A one-slot future: last chunk's flag
    pending_save = None     # Mode B: staged, not-yet-written snapshot
    flushes = 0             # ledger windows emitted (warmup = the first)
    steps_window = 0        # epochs covered since the last flush

    def _commit_pending_save():
        # a chunk snapshot is a RESUME OPTIMIZATION: a persistent write
        # failure (an EIO burst outlasting retry_io's bounded attempts)
        # costs resume granularity — the drive falls back to the last
        # snapshot that did land (or a fresh start), both bit-identical
        # by determinism — never the drive itself.  Found by the chaos
        # engine: the preempt→resume leg with io_fail@snapshot_save
        # killed the resumed sweep with a raw OSError (corpus entry 001).
        nonlocal pending_save
        if pending_save is None:
            return
        staged, pending_save = pending_save, None
        try:
            snapshot.commit(staged)
        except OSError as e:
            _snapshot_save_failed(snapshot, staged[2], e)

    # the wall-clock ledger's window runs boundary→boundary (opening at
    # drive start), unlike attrib's dispatch-anchored wall: snapshot
    # saves and chunk bookkeeping between boundaries then land inside
    # the NEXT window instead of leaking into uncovered run span
    t_window0 = timeline.clock()
    try:
        while pos < epochs and not stopped_all:
            length = min(chunk, epochs - pos)
            t_chunk0 = timeline.clock() if attrib_on else 0.0
            with attrib.dispatch_timer("ae_chunk") if attrib_on \
                    else contextlib.nullcontext():
                carry, tr = chunk_fn(carry, keys[..., pos:pos + length, :])
            traces.append(tr)
            pos += length
            chunks += 1
            calls_here += 1
            steps_window += length
            # the PREVIOUS boundary's staged snapshot commits here, after
            # the dispatch above — the atomic write's file I/O overlaps
            # the chunk now in flight instead of serializing against it
            _commit_pending_save()
            if deferred_flag:
                # enqueue this chunk's flag reduction while its buffers
                # are live (the next dispatch donates them), then sync
                # the PREVIOUS chunk's — the host runs one chunk behind
                flag_dev = jnp.all(carry[4])
                if pending_flag is not None:
                    t_sync0 = timeline.clock()
                    # THE one-slot pending-future sync HF010 sanctions:
                    # deliberately one chunk behind, timed, ledgered
                    stopped_all = bool(jax.device_get(pending_flag))  # noqa: HF010
                    if stopped_all:
                        # the chunk just dispatched ran past the stop the
                        # deferred sync had not yet observed; its outputs
                        # ARE the padding values, so results don't change
                        overshoot = 1
                    if attrib_on:
                        now = timeline.clock()
                        warm = flushes == 0
                        # the wait parked on an already-RESOLVING value
                        # with the successor chunk queued behind it: the
                        # device cannot idle on this block, so it books
                        # as device_compute (conservation) but counts as
                        # OVERLAPPED host time — ``sync_wait_s=0``.  A
                        # deferred drive therefore saturates
                        # timeline/overlap_frac by construction (the
                        # structural dual of the synchronous backend's
                        # dispatch-is-compute ≈1), and the gauge becomes
                        # the boundary's tripwire: an eager sync snuck
                        # into this loop (the HF010 class) re-serializes
                        # the drive and drags it back below 1.  The raw
                        # parked time stays visible per window as
                        # ``pending_wait_ms``.
                        wait_s = now - t_sync0
                        timeline.note_sync(wait_s)
                        with attrib._WINDOW.lock:
                            disp_s = sum(
                                attrib._WINDOW.dispatch_s.values())
                        attrib.flush_window(now - t_window0,
                                            steps=steps_window,
                                            warmup=warm, epoch=pos)
                        timeline.flush_window(
                            now - t_window0, drive="ae_chunk",
                            steps=steps_window, warmup=warm,
                            dispatch_s=disp_s,
                            sync_wait_s=0.0, epoch=pos,
                            pending_wait_ms=round(wait_s * 1e3, 3))
                        t_window0 = now
                        flushes += 1
                        steps_window = 0
                pending_flag = flag_dev
            # one device→host sync per chunk decides continue/stop; with
            # health on, the boundary's health scalars ride the SAME sync
            # (and may raise NumericFault under abort_on_nonfinite)
            elif pos < epochs:
                t_sync0 = timeline.clock()
                stopped_all = _boundary_sync(carry, tr, pos, snapshot)
                if attrib_on:
                    now = timeline.clock()
                    warm = calls_here == 1
                    # read the dispatch seconds before attrib's flush
                    # takes the window (a warmup flush discards them,
                    # but the ledger still owes that time to a category)
                    with attrib._WINDOW.lock:
                        disp_s = sum(attrib._WINDOW.dispatch_s.values())
                    attrib.flush_window(now - t_chunk0, steps=length,
                                        warmup=warm, epoch=pos)
                    timeline.flush_window(now - t_window0, drive="ae_chunk",
                                          steps=length, warmup=warm,
                                          dispatch_s=disp_s,
                                          sync_wait_s=now - t_sync0,
                                          epoch=pos)
                    t_window0 = now
                    flushes += 1
                    steps_window = 0
            if snapshot is not None and not resilience.drain_requested():
                # a requested drain (e.g. a SIGTERM taken during the
                # deferred commit above) suppresses this boundary's
                # stage: the serial engine exits with ONE write per
                # drained boundary, and the resume replays this chunk
                # bit-identically from the committed predecessor
                pending_save = snapshot.stage(carry, _concat_traces(traces),
                                              pos, chunks, stopped_all)
                if not double_buffer or stopped_all or pos >= epochs:
                    # serial mode writes eagerly; and on the LAST chunk
                    # there is no later dispatch for the deferred write
                    # to overlap — land it before the boundary call
                    # below so a SIGTERM taken mid-write drains exactly
                    # like the serial engine (snapshot on disk, exit 75)
                    _commit_pending_save()
            try:
                resilience.boundary("chunk")
            except resilience.Preempted as e:
                # the staged boundary must reach disk BEFORE the drain
                # surfaces — the operator is told "state persisted at
                # ..." and a resume expects this chunk, not the previous
                _commit_pending_save()
                # re-raise with the drive's context: Preempted renders
                # its message at construction, so mutating attrs on the
                # caught one would lose "state persisted at ..." from
                # the operator
                raise resilience.Preempted(
                    site=e.site, reason=e.reason, epoch=pos,
                    snapshot=(str(snapshot.path)
                              if snapshot is not None else None)) from None
    finally:
        # any exit — normal, drain, NumericFault, device error — lands
        # the staged boundary; a kill that beats this commit costs one
        # chunk of resume granularity (the .prev fallback), never the
        # drive (chaos-searched)
        _commit_pending_save()
        if attrib_on:
            # the FINAL chunk has no boundary sync inside the loop (and
            # a drain/NumericFault exits mid-window): its un-flushed
            # dispatch must not bleed into the next drive's window
            attrib.reset_window()
    out = _concat_traces(traces)
    if pos < epochs:
        lead = out[0].shape[:-1]
        pad = (epochs - pos,)
        padded = []
        for i, t in enumerate(out):
            # index 2 is the stop trace (padded True — the exact values
            # the monolithic scan's post-stop masking produces); every
            # other trace pads NaN
            fill = (jnp.ones(lead + pad, t.dtype) if i == 2
                    else jnp.full(lead + pad, jnp.nan, t.dtype))
            padded.append(jnp.concatenate([t, fill], axis=-1))
        out = tuple(padded)
    return carry, out, pos, chunks, overshoot


def _snapshot_save_failed(snapshot, pos: int, e: OSError) -> None:
    """Degraded-snapshot accounting: the failure is loud in telemetry
    (event + counter) and on stderr, but the drive keeps training."""
    import sys

    from hfrep_tpu.obs import get_obs
    try:
        obs = get_obs()
        obs.counter("resilience/snapshot_save_failures").inc()
        obs.event("snapshot_save_failed", path=str(snapshot.path),
                  epoch=pos, error=str(e))
    except Exception:
        pass
    print(f"warning: chunk snapshot {snapshot.path} not saved ({e}); "
          "resume granularity degraded, training continues",
          file=sys.stderr)


def _boundary_sync(carry, tr, pos: int, snapshot) -> bool:
    """The chunk boundary's continue/stop read-back.  Health-off: the
    exact pre-health single-scalar sync.  Health-on: the boundary's
    grad-norm / nonfinite / param-norm scalars join the SAME
    ``device_get`` (zero additional sync points), surface as
    ``health/ae_*`` gauges, and arm the nonfinite tripwire."""
    stopped_dev = jnp.all(carry[4])
    if health_mod.active() is None or len(tr) < 5:
        return bool(jax.device_get(stopped_dev))
    gn = jnp.nanmax(tr[3][..., -1])
    nf = jnp.nansum(tr[4][..., -1])
    pn = health_mod.tree_norm(carry[0])
    stopped_all, gnv, nfv, pnv = jax.device_get((stopped_dev, gn, nf, pn))
    _emit_ae_health(float(gnv), float(nfv), float(pnv), pos, carry, snapshot)
    return bool(stopped_all)


def _emit_ae_health(gn: float, nf: float, pn: float, epoch: int,
                    carry, snapshot) -> None:
    """Publish one AE boundary's health scalars; under
    ``abort_on_nonfinite`` a nonfinite count converts into a typed
    :class:`~hfrep_tpu.obs.health.NumericFault` after an atomic forensic
    dump of the offending carry (the chunk snapshot of the failing chunk
    is deliberately NOT yet written, so a resume replays it)."""
    from hfrep_tpu.obs import get_obs
    obs = get_obs()
    if obs.enabled:
        obs.gauge("health/ae_grad_norm").set(gn, epoch=epoch)
        obs.gauge("health/ae_nonfinite").set(nf, epoch=epoch)
        obs.gauge("health/ae_param_norm").set(pn, epoch=epoch)
    if not nf > 0:
        return
    hcfg = health_mod.active()
    abort = bool(hcfg and hcfg.abort_on_nonfinite)
    obs.event("numeric_fault", site="chunk", epoch=epoch, nonfinite=nf,
              abort=abort)
    if not abort:
        return
    dump = health_mod.dump_forensics(
        health_mod.resolve_dump_dir(
            hcfg, str(snapshot.dir) if snapshot is not None else None),
        carry, detail={"site": "chunk", "epoch": epoch, "nonfinite": nf,
                       "grad_norm": gn, "param_norm": pn},
        name=f"numeric_fault_{epoch}")
    obs.flush()
    raise health_mod.NumericFault("chunk", epoch=epoch, nonfinite=nf,
                                  dump=dump)


def train_autoencoder_chunked(key: jax.Array, x_train_scaled: jnp.ndarray,
                              cfg: AEConfig,
                              mask: Optional[jnp.ndarray] = None,
                              resume_dir: Optional[str] = None,
                              ) -> Tuple[AEResult, ChunkStats]:
    """:func:`train_autoencoder` as a chunked early-exit drive.

    Scans ``cfg.chunk_epochs`` epochs per jitted call (donated carries)
    and stops dispatching once early stopping fired — a run that stops at
    epoch ~60 executes ~2 chunks instead of the full 1000-epoch scan.
    The returned :class:`AEResult` is bit-identical to the monolithic
    scan's (pinned by test); :class:`ChunkStats` reports what the exit
    saved.  ``resume_dir`` enables chunk-boundary snapshots + resume
    (see :func:`_run_chunked`).
    """
    return _run_chunked(cfg, "single", key, x_train_scaled, mask, None,
                        lanes=1, resume_dir=resume_dir)


def sweep_autoencoders(key: jax.Array, x_train_scaled: jnp.ndarray, cfg: AEConfig,
                       latent_dims: Sequence[int]) -> AEResult:
    """All latent dims in one vmapped program (vs 21 serial Keras fits,
    ``autoencoder_v4.ipynb`` cell 6).  Params come back with a leading
    sweep axis; index with `jax.tree_util.tree_map(lambda a: a[i], ...)`."""
    max_latent = max(latent_dims)
    cfg = dataclasses.replace(cfg, latent_dim=max_latent)
    masks = jnp.stack([latent_mask(d, max_latent) for d in latent_dims])
    keys = jax.random.split(key, len(latent_dims))
    return jax.vmap(lambda k, m: train_autoencoder(k, x_train_scaled, cfg, m))(keys, masks)


def sweep_autoencoders_chunked(key: jax.Array, x_train_scaled: jnp.ndarray,
                               cfg: AEConfig, latent_dims: Sequence[int],
                               resume_dir: Optional[str] = None,
                               mesh=None,
                               ) -> Tuple[AEResult, ChunkStats]:
    """:func:`sweep_autoencoders` as a chunked early-exit drive.

    One vmapped chunk program covers every latent lane; the host keeps
    dispatching until ``all(stopped)`` across the sweep — the slowest lane
    bounds the dispatch count, but nothing pays for the full 1000-epoch
    scan once the last lane has stopped.  Bit-identical results to the
    monolithic vmapped sweep (pinned by test).  ``resume_dir`` makes the
    21-lane sweep preemption-safe: killed mid-sweep, a re-run resumes
    from the last chunk with bit-identical results (pinned by test).
    """
    max_latent = max(latent_dims)
    cfg = dataclasses.replace(cfg, latent_dim=max_latent)
    masks = jnp.stack([latent_mask(d, max_latent) for d in latent_dims])
    lane_keys = jax.random.split(key, len(latent_dims))
    return _run_chunked(cfg, "lanes", lane_keys, x_train_scaled, masks, None,
                        lanes=len(latent_dims), resume_dir=resume_dir,
                        mesh=mesh)


# ------------------------------------------- padded multi-dataset sweep
def stack_padded(x_list: Sequence[jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stack differing-length (T_d, F) panels into a ``(D, T_max, F)``
    cube (zero rows after each dataset's true tail) plus the ``(D,)``
    true-row-count vector the padded training semantics key off."""
    n_max = max(int(x.shape[0]) for x in x_list)
    padded, rows = [], []
    for x in x_list:
        x = jnp.asarray(x, jnp.float32)
        rows.append(x.shape[0])
        if x.shape[0] < n_max:
            x = jnp.concatenate(
                [x, jnp.zeros((n_max - x.shape[0], x.shape[1]), x.dtype)])
        padded.append(x)
    return jnp.stack(padded), jnp.asarray(rows, jnp.int32)


def sweep_autoencoders_padded(key: jax.Array, x_pad: jnp.ndarray,
                              n_rows, cfg: AEConfig,
                              latent_dims: Sequence[int],
                              resume_dir: Optional[str] = None,
                              mesh=None,
                              ) -> Tuple[AEResult, ChunkStats]:
    """One padded dataset's latent sweep — the serial unit
    :func:`sweep_autoencoders_multi` batches across datasets.  ``x_pad``
    is a (T_max, F) panel holding ``n_rows`` real rows then zero padding;
    the program shape depends on T_max only, so serially sweeping K
    datasets padded to a common T_max is numerically equivalent to the
    one batched multi-dataset program (pinned by test)."""
    max_latent = max(latent_dims)
    cfg = dataclasses.replace(cfg, latent_dim=max_latent)
    masks = jnp.stack([latent_mask(d, max_latent) for d in latent_dims])
    lane_keys = jax.random.split(key, len(latent_dims))
    return _run_chunked(cfg, "lanes", lane_keys, x_pad, masks,
                        _rows_info(cfg, n_rows), lanes=len(latent_dims),
                        resume_dir=resume_dir, mesh=mesh)


def sweep_autoencoders_multi(key: jax.Array, x_stack: jnp.ndarray,
                             n_rows: jnp.ndarray, cfg: AEConfig,
                             latent_dims: Sequence[int],
                             resume_dir: Optional[str] = None,
                             mesh=None,
                             ) -> Tuple[AEResult, ChunkStats]:
    """The cross-dataset sweep fabric: every (dataset, latent) pair as one
    vmapped chunked program.

    ``x_stack`` is the :func:`stack_padded` cube of K+1 training sets
    (real + GAN-augmented variants, padded to a common row count) and
    ``n_rows`` their true row counts; the result's arrays lead with a
    ``(D, L)`` lane grid.  Replaces K+1 serial sweeps with ONE program —
    and the chunked early exit only keeps dispatching while *some* lane
    anywhere in the grid is still training.  ``mesh`` (a ``('dp',)``
    mesh; :func:`hfrep_tpu.parallel.rules.lane_mesh` picks a divisor
    size) shards the leading dataset axis over ``dp`` through the
    unified pjit launch — the multi-chip dp mode of the sweep fabric,
    bit-identical to the meshless drive (pinned).
    """
    max_latent = max(latent_dims)
    cfg = dataclasses.replace(cfg, latent_dim=max_latent)
    masks = jnp.stack([latent_mask(d, max_latent) for d in latent_dims])
    n_lanes = len(latent_dims)
    dkeys = jax.random.split(key, x_stack.shape[0])
    return _run_chunked(cfg, "multi", dkeys, x_stack, masks,
                        _rows_info(cfg, n_rows),
                        lanes=int(x_stack.shape[0]) * n_lanes,
                        n_lanes_init=n_lanes, resume_dir=resume_dir,
                        mesh=mesh)


def sweep_item_arrays(key: jax.Array, panel, cfg: AEConfig,
                      latent_dims: Sequence[int]) -> dict:
    """Actor-driven entry point: one queue item's latent sweep as a flat
    ``{name: np.ndarray}`` dict ready for an ``npz`` artifact.

    The orchestration fabric's consumer actors
    (:mod:`hfrep_tpu.orchestrate.actors`) call this once per claimed
    item; the output is a pure function of ``(key, panel, cfg,
    latent_dims)`` — the property the fabric's kill→resume bit-identity
    rests on — and flat so the artifact needs no pytree bookkeeping
    (``param_<name>`` carries each parameter with its leading lane
    axis).  Runs the chunked early-exit drive, so a consumer stops
    paying for an item's epochs the moment its lanes stop.
    """
    xs = jnp.asarray(panel, jnp.float32)
    res, stats = sweep_autoencoders_chunked(key, xs, cfg, list(latent_dims))
    out = {f"param_{k}": np.asarray(jax.device_get(v))
           for k, v in sorted(res.params.items())}
    out["stop_epoch"] = np.asarray(jax.device_get(res.stop_epoch))
    out["train_loss"] = np.asarray(jax.device_get(res.train_loss))
    out["val_loss"] = np.asarray(jax.device_get(res.val_loss))
    out["chunks_dispatched"] = np.asarray(stats.chunks_dispatched)
    return out


def emit_chunk_stats(stats: Optional[ChunkStats]) -> None:
    """Publish a chunked drive's savings as obs gauges (no-op when
    telemetry is off or the drive ran monolithically)."""
    if stats is None:
        return
    from hfrep_tpu.obs import get_obs
    obs = get_obs()
    if not obs.enabled:
        return
    obs.gauge("ae/epochs_saved").set(int(stats.epochs_saved),
                                     epochs_total=int(stats.epochs_total),
                                     chunk_epochs=int(stats.chunk_epochs),
                                     overshoot_chunks=int(
                                         stats.overshoot_chunks))
    obs.gauge("ae/lanes_stopped").set(int(stats.lanes_stopped),
                                      lanes=int(stats.lanes))
    obs.counter("ae_chunks_dispatched").inc(int(stats.chunks_dispatched))


# ----------------------------------------------------- pure evaluation
def oos_prefix_metrics(model: Autoencoder, x_test: jnp.ndarray,
                       params: dict, mask: jnp.ndarray):
    """Per-prefix OOS R² and RMSE, all expanding windows as one batch.

    Vectorization of ``Autoencoder_encapsulate.py:115-131``: for prefix
    length i ∈ [2, T], MinMax-scale ``x_test[:i]`` with its own min/max
    (prefix scans, no 167 scaler refits), reconstruct, and score — the
    R²/RMSE reductions happen *inside* each prefix lane so nothing
    (T, F)-sized leaves the program.  Pure in (params, mask): vmappable
    across a latent sweep."""
    t = x_test.shape[0]
    mins, maxs = expanding_minmax_scale(x_test)
    scale = jnp.where(maxs - mins == 0.0, 1.0, maxs - mins)

    def one_prefix(i):
        scaled = (x_test - mins[i - 1]) / scale[i - 1]
        mask_rows = (jnp.arange(t) < i)[:, None]
        pred = model.apply({"params": params}, scaled, mask)
        r2 = _r2_columns_mean_masked(scaled, pred, mask_rows)
        sq = jnp.sum((scaled - pred) ** 2 * mask_rows)
        rmse = jnp.sqrt(sq / (jnp.sum(mask_rows) * x_test.shape[1]))
        return r2, rmse

    return jax.vmap(one_prefix)(jnp.arange(2, t))


def ante_weights(model: Autoencoder, cfg: AEConfig, params: dict,
                 mask: Optional[jnp.ndarray], x_test: jnp.ndarray,
                 y_test: jnp.ndarray, rf: jnp.ndarray, window: int):
    """Ex-ante replication returns + strategy weights, pure in
    (params, mask) — the body of ``Autoencoder_encapsulate.py:133-201``
    shared by :meth:`ReplicationEngine.ante` and the vmapped sweep
    evaluation.  Returns ``(ante (P, S), weights (P, F, S))``."""
    rf = jnp.asarray(rf, jnp.float32).reshape(-1, 1)
    factors = model.apply({"params": params}, x_test, mask,
                          method=Autoencoder.encode)            # raw-input encode, :140
    # Policy output boundary: a bf16-policy model emits bf16 factors, but
    # everything downstream is evaluation — the rolling OLS in particular
    # is a lapack least-squares with no bf16 kernel (hard NotImplemented,
    # found driving the bf16 sweep end-to-end).  Identity on fp32.
    factors = factors.astype(jnp.float32)
    betas = rolling_ols_beta(y_test, factors, window)           # (T-w+1, L, S)
    n_windows = x_test.shape[0] - window                        # :148 range
    betas = betas[:n_windows]

    def norm_factor(i):
        xw = lax.dynamic_slice_in_dim(factors, i, window)
        yw = lax.dynamic_slice_in_dim(y_test, i, window)
        return costs.normalization(yw, xw, betas[i], window)

    norms = jax.vmap(norm_factor)(jnp.arange(n_windows))        # (n_windows, S)

    w_dec = params["decoder_kernel"]                            # (L, F) factor→ETF map, :159
    if mask is not None:
        w_dec = w_dec * mask[:, None]

    def month_weights(i, beta, norm):
        # LeakyReLU mask from the *current* month's decoded sign, :163-166
        decoded = factors[window + i] @ w_dec                   # (F,)
        leaky = jnp.where(decoded < 0, cfg.leaky_slope, 1.0)
        return (jnp.swapaxes(beta, 0, 1) @ w_dec * leaky[None, :]).T * norm[None, :]

    if cfg.beta_mode == "first":
        beta_used = jnp.broadcast_to(betas[0], betas.shape)
        norm_used = jnp.broadcast_to(norms[0], norms.shape)
    else:
        beta_used, norm_used = betas, norms
    weights = jax.vmap(month_weights)(jnp.arange(n_windows), beta_used, norm_used)

    # last window has no realized month — drop it (:179-180)
    weights = weights[:-1]                                      # (P, F, S)
    p = weights.shape[0]
    delta = 1.0 - jnp.sum(weights, axis=1)                      # (P, S)
    ante = delta * rf[-p:] + jnp.einsum("pf,pfs->ps", x_test[-p:], weights)
    return ante, weights


def evaluate_params(model: Autoencoder, cfg: AEConfig, x_train_scaled, x_test,
                    y_test, rf, factor_full, params: dict,
                    mask: jnp.ndarray) -> dict:
    """Every per-latent number the notebook's result cells need, as one
    pure jnp program: IS/OOS fit metrics, ex-ante/ex-post replication
    returns, turnover, and Sharpe ratios.  Pure in (params, mask) so a
    latent sweep evaluates as a single vmapped XLA program
    (:func:`sweep_evaluate`) instead of 21 host-serial eval passes."""
    from hfrep_tpu.replication import perf_stats

    pred_train = model.apply({"params": params}, x_train_scaled, mask)
    is_r2 = _r2_columns_mean(x_train_scaled, pred_train)
    is_rmse = jnp.sqrt(jnp.mean((x_train_scaled - pred_train) ** 2))
    oos_r2, oos_rmse = oos_prefix_metrics(model, x_test, params, mask)

    window = cfg.ols_window
    ante, weights = ante_weights(model, cfg, params, mask, x_test, y_test,
                                 rf, window)
    p = ante.shape[0]
    panel = jnp.asarray(factor_full, jnp.float32)[-(p + window):]
    post = costs.ex_post_return(ante, window,
                                jnp.transpose(weights, (2, 0, 1)), panel)
    rf_tail = jnp.asarray(rf, jnp.float32).reshape(-1)[-p:]
    return {
        "is_r2": is_r2, "is_rmse": is_rmse,
        "oos_r2": oos_r2, "oos_rmse": oos_rmse,
        "ante": ante, "post": post,
        "turnover": costs.turnover(weights),
        "sharpe_ante": perf_stats.annualized_sharpe(ante, rf_tail),
        "sharpe_post": perf_stats.annualized_sharpe(post, rf_tail),
    }


def sweep_evaluate(model: Autoencoder, cfg: AEConfig, x_train_scaled, x_test,
                   y_test, rf, factor_full, stacked_params: dict,
                   masks: jnp.ndarray) -> dict:
    """Evaluate every latent dim of a sweep in ONE compiled program.

    ``stacked_params``/``masks`` carry a leading sweep axis (the output of
    :func:`sweep_autoencoders`); the result dict's arrays all lead with
    that axis.  Replaces the reference's 21-serial eval loop
    (``autoencoder_v4.ipynb`` cells 6/24) *and* round 1's host-serial
    ``use_params → IS/OOS/ante/post/turnover`` loop."""
    fn = lambda p, m: evaluate_params(model, cfg, x_train_scaled, x_test,
                                      y_test, rf, factor_full, p, m)
    return jax.jit(jax.vmap(fn))(stacked_params, masks)


# ---------------------------------------------------------------- engine
class ReplicationEngine:
    """The reference ``AE`` wrapper's full API on one trained model.

    Construction mirrors ``AE.__init__`` (``Autoencoder_encapsulate.py:39-70``):
    unscaled train/test panels in, train-set MinMax params fit internally.
    """

    def __init__(self, x_train, y_train, x_test, y_test, cfg: AEConfig | None = None):
        self.cfg = cfg or AEConfig()
        if len(x_train) != len(y_train) or len(x_test) != len(y_test):
            raise ValueError("x/y length mismatch")
        self.x_train_raw = jnp.asarray(x_train, jnp.float32)
        self.x_test = jnp.asarray(x_test, jnp.float32)      # unscaled, :67
        self.y_train = jnp.asarray(y_train, jnp.float32)
        self.y_test = jnp.asarray(y_test, jnp.float32)
        self.train_scale, self.x_train = mm.fit_transform(self.x_train_raw)
        self.model = _ae_model(self.cfg)   # honors cfg.dtype (precision policy)
        self.result: Optional[AEResult] = None
        self.mask: Optional[jnp.ndarray] = None
        self._ante = None
        self._strat_weights = None      # (P, A, S)
        self._post = None
        self._train_fn = None
        self._oos_eval_fn = None
        self._oos_cache = None

    # ------------------------------------------------------------ training
    def train(self, key: Optional[jax.Array] = None) -> AEResult:
        """Train the full-latent model.  With ``cfg.chunk_epochs > 0``
        (the default) the scan is dispatched in early-exit chunks — the
        host stops paying once early stopping fired — with results
        bit-identical to the monolithic scan (``cfg.chunk_epochs = 0``)."""
        from hfrep_tpu.obs import get_obs
        obs = get_obs()
        key = key if key is not None else jax.random.PRNGKey(self.cfg.seed)
        stats = None
        with obs.span("ae_train", latent_dim=self.cfg.latent_dim,
                      epochs=self.cfg.epochs):
            if self.cfg.chunk_epochs and self.cfg.chunk_epochs > 0:
                # compile reuse across re-train()s comes from the
                # module-level chunk-program cache, not per-instance state
                self.result, stats = train_autoencoder_chunked(
                    key, self.x_train, self.cfg)
            else:
                if self._train_fn is None:
                    self._train_fn = jax.jit(
                        lambda k: train_autoencoder(k, self.x_train, self.cfg))
                self.result = self._train_fn(key)
            if obs.enabled:        # time the scan, not its async dispatch
                jax.block_until_ready(self.result.params)
        if obs.enabled:
            obs.counter("ae_trainings").inc()
            obs.gauge("ae_stop_epoch").set(int(self.result.stop_epoch))
            emit_chunk_stats(stats)
        self.mask = None            # full-latent model: drop any use_params() mask
        self._invalidate()
        return self.result

    def use_params(self, params: dict, mask: Optional[jnp.ndarray] = None) -> None:
        """Adopt externally trained (e.g. sweep-sliced) parameters."""
        self.result = AEResult(params=params, stop_epoch=jnp.zeros((), jnp.int32),
                               train_loss=jnp.zeros(()), val_loss=jnp.zeros(()))
        self.mask = mask
        self._invalidate()

    def _invalidate(self) -> None:
        """Drop every derived artifact of the previous parameter set."""
        self._oos_cache = None
        self._ante = None
        self._strat_weights = None
        self._post = None

    @property
    def params(self) -> dict:
        if self.result is None:
            raise RuntimeError("train() first")
        return self.result.params

    def _apply(self, x):
        return self.model.apply({"params": self.params}, x, self.mask)

    def _encode(self, x):
        return self.model.apply({"params": self.params}, x, self.mask,
                                method=Autoencoder.encode)

    # ------------------------------------------------------------- metrics
    def model_IS_r2(self) -> float:
        """r2_score(x_train_scaled, reconstruction) — uniform average over
        columns (``Autoencoder_encapsulate.py:107-109``)."""
        pred = self._apply(self.x_train)
        return float(_r2_columns_mean(self.x_train, pred))

    def model_IS_RMSE(self) -> float:
        pred = self._apply(self.x_train)
        return float(jnp.sqrt(jnp.mean((self.x_train - pred) ** 2)))

    def _oos_eval(self):
        """Cached expanding-window metrics — R² and RMSE share one
        compiled program (:func:`oos_prefix_metrics`); ``params``/``mask``
        are traced arguments (not baked constants) so the program survives
        retraining / param swaps."""
        if self._oos_cache is None:
            from hfrep_tpu.obs import get_obs
            if self._oos_eval_fn is None:
                self._oos_eval_fn = jax.jit(
                    lambda p, m: oos_prefix_metrics(self.model, self.x_test, p, m))
            mask = self.mask if self.mask is not None else jnp.ones(
                (self.params["encoder_kernel"].shape[1],), jnp.float32)
            with get_obs().span("ae_oos_eval"):
                self._oos_cache = self._oos_eval_fn(self.params, mask)
        return self._oos_cache

    def model_OOS_r2(self) -> np.ndarray:
        return np.asarray(self._oos_eval()[0])

    def model_OOS_RMSE(self) -> np.ndarray:
        return np.asarray(self._oos_eval()[1])

    # ------------------------------------------------------------ strategy
    def ante(self, rf, window: Optional[int] = None) -> np.ndarray:
        """Ex-ante replication returns (``Autoencoder_encapsulate.py:133-201``).

        ``beta_mode='first'`` (default) reproduces the reference exactly:
        the OLS beta and normalization factor of the *first* 24-month
        window are reused for every month (``:167`` indexes
        ``ae_ols_beta[0]``), only the LeakyReLU activation mask varies.
        ``beta_mode='rolling'`` uses each window's own beta.  Body shared
        with the vmapped sweep path via :func:`ante_weights`.
        """
        from hfrep_tpu.obs import get_obs
        window = window or self.cfg.ols_window
        with get_obs().span("ae_ante", window=int(window)):
            ante, weights = ante_weights(self.model, self.cfg, self.params,
                                         self.mask, self.x_test, self.y_test,
                                         jnp.asarray(rf, jnp.float32), window)
        p = weights.shape[0]
        self._strat_weights = weights
        self._ante = ante
        self.window = window
        self.oos_hfd = self.y_test[-p:]
        return np.asarray(ante)

    def post(self, factor_etf_full) -> np.ndarray:
        """Ex-post returns net of costs (``Autoencoder_encapsulate.py:203-208``):
        applies the cost penalty using the *full* factor panel's trailing
        ``P + window`` months."""
        if self._ante is None:
            raise RuntimeError("ante() first")
        p = self._ante.shape[0]
        panel = jnp.asarray(factor_etf_full, jnp.float32)[-(p + self.window):]
        weights_s_p_a = jnp.transpose(self._strat_weights, (2, 0, 1))   # (S, P, A)
        self._post = costs.ex_post_return(self._ante, self.window, weights_s_p_a, panel)
        return np.asarray(self._post)

    def turnover(self) -> np.ndarray:
        """Annualized turnover per strategy (``Autoencoder_encapsulate.py:210-224``)."""
        if self._strat_weights is None:
            raise RuntimeError("ante() first")
        return np.asarray(costs.turnover(self._strat_weights))


# ------------------------------------------------------------------ utils
def _r2_columns_mean(actual: jnp.ndarray, pred: jnp.ndarray) -> jnp.ndarray:
    """sklearn r2_score with multioutput='uniform_average'."""
    ss_res = jnp.sum((actual - pred) ** 2, axis=0)
    ss_tot = jnp.sum((actual - jnp.mean(actual, axis=0)) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / ss_tot)


def _r2_columns_mean_masked(actual, pred, mask_rows) -> jnp.ndarray:
    w = mask_rows.astype(actual.dtype)
    n = jnp.maximum(jnp.sum(w), 1.0)
    mean = jnp.sum(actual * w, axis=0) / n
    ss_res = jnp.sum(((actual - pred) * w) ** 2, axis=0)
    ss_tot = jnp.sum(((actual - mean) * w) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / ss_tot)
