"""Autoencoder replication engine: training, evaluation, strategy build.

TPU-native re-design of ``Autoencoder_encapsulate.py:38-224`` (class
``AE``).  Where the reference trains 21 separate Keras models in a Python
loop with per-call ``predict`` inside O(T) host loops (SURVEY §3.3), here:

* one AE training run is a single `lax.scan` over epochs with
  Keras-faithful early stopping folded into the carry;
* the latent-dim sweep is `vmap` over a latent *mask* (same param shapes,
  see :mod:`hfrep_tpu.models.autoencoder`) — all 21 trainings execute as
  one batched XLA program;
* the expanding-window OOS metrics use prefix min/max scans instead of
  167 scaler refits;
* the 24-month rolling OLS is one batched least-squares.

Training recipe ported from ``Autoencoder_encapsulate.py:72-105``:
MinMax-scale x_train only (``:62-67``; note ``_x_test`` stays *unscaled* —
the encoder is later applied to raw test returns, ``:67,140``), Nadam on
MSE, ≤1000 epochs, batch 48, ``validation_split=.25`` (Keras semantics:
the *last* 25% of rows are validation, the first 75% train), per-epoch
reshuffling of the train block, EarlyStopping(patience=5) on val_loss
without best-weight restore.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hfrep_tpu.config import AEConfig
from hfrep_tpu.core import costs
from hfrep_tpu.core import scaler as mm
from hfrep_tpu.models.autoencoder import Autoencoder, latent_mask
from hfrep_tpu.ops.optimizers import keras_nadam
from hfrep_tpu.ops.rolling import expanding_minmax_scale, rolling_ols_beta

import optax


class AEResult(NamedTuple):
    params: dict                 # encoder/decoder kernels (possibly batched)
    stop_epoch: jnp.ndarray      # epoch index where early stopping fired
    train_loss: jnp.ndarray     # (epochs,) per-epoch training loss (NaN after stop)
    val_loss: jnp.ndarray       # (epochs,)


def _epoch_batches(n_train: int, batch_size: int) -> Tuple[int, int]:
    n_batches = -(-n_train // batch_size)
    return n_batches, n_batches * batch_size


def train_autoencoder(key: jax.Array, x_train_scaled: jnp.ndarray, cfg: AEConfig,
                      mask: Optional[jnp.ndarray] = None) -> AEResult:
    """Train one (optionally masked) AE; pure function of (key, data, cfg).

    ``mask`` is a (max_latent,) 0/1 vector selecting active latent dims;
    None trains the full ``cfg.latent_dim``.
    """
    model = Autoencoder(n_features=cfg.n_factors, latent_dim=cfg.latent_dim,
                        slope=cfg.leaky_slope)
    n = x_train_scaled.shape[0]
    # Keras validation_split semantics: split_at = floor(n * (1 - split))
    # training rows, the rest validation (167 → 125 train / 42 val).
    n_train = int(n * (1.0 - cfg.val_split))
    n_val = n - n_train
    x_fit, x_val = x_train_scaled[:n_train], x_train_scaled[n_train:]

    key, init_key = jax.random.split(key)
    params = model.init(init_key, x_fit[:1])["params"]
    tx = keras_nadam(cfg.lr, b1=0.9, b2=0.999, eps=1e-7)   # tf.keras-exact Nadam
    opt_state = tx.init(params)

    n_batches, padded = _epoch_batches(n_train, cfg.batch_size)

    def mse(p, x, w=None):
        pred = model.apply({"params": p}, x, mask)
        err = jnp.mean((pred - x) ** 2, axis=1)
        if w is None:
            return jnp.mean(err)
        return jnp.sum(err * w) / jnp.maximum(jnp.sum(w), 1.0)

    def epoch_step(carry, epoch_key):
        params, opt_state, best_val, wait, stopped = carry
        perm = jax.random.permutation(epoch_key, n_train)
        order = jnp.concatenate([perm, jnp.zeros(padded - n_train, jnp.int32)])
        weights = (jnp.arange(padded) < n_train).astype(jnp.float32)

        def batch_step(c, i):
            p, o = c
            sl = lax.dynamic_slice_in_dim(order, i * cfg.batch_size, cfg.batch_size)
            w = lax.dynamic_slice_in_dim(weights, i * cfg.batch_size, cfg.batch_size)
            xb = jnp.take(x_fit, sl, axis=0)
            loss, grads = jax.value_and_grad(mse)(p, xb, w)
            updates, o = tx.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), loss

        (new_params, new_opt), batch_losses = lax.scan(
            batch_step, (params, opt_state), jnp.arange(n_batches))

        # freeze updates once stopped (Keras keeps stop-epoch weights)
        params = jax.tree_util.tree_map(
            lambda old, new: jnp.where(stopped, old, new), params, new_params)
        opt_state = jax.tree_util.tree_map(
            lambda old, new: jnp.where(stopped, old, new), opt_state, new_opt)

        val = mse(params, x_val)
        improved = val < best_val
        wait = jnp.where(stopped, wait, jnp.where(improved, 0, wait + 1))
        best_val = jnp.where(stopped, best_val, jnp.minimum(best_val, val))
        newly_stopped = jnp.logical_and(jnp.logical_not(stopped), wait >= cfg.patience)
        train_loss = jnp.where(stopped, jnp.nan, jnp.mean(batch_losses))
        val_out = jnp.where(stopped, jnp.nan, val)
        stopped = jnp.logical_or(stopped, newly_stopped)
        return (params, opt_state, best_val, wait, stopped), (train_loss, val_out, stopped)

    keys = jax.random.split(key, cfg.epochs)
    init = (params, opt_state, jnp.inf, jnp.zeros((), jnp.int32), jnp.zeros((), bool))
    (params, _, _, _, _), (tl, vl, stop_trace) = lax.scan(epoch_step, init, keys)
    stop_epoch = jnp.argmax(stop_trace) + jnp.where(jnp.any(stop_trace), 0, cfg.epochs)
    return AEResult(params=params, stop_epoch=stop_epoch, train_loss=tl, val_loss=vl)


def sweep_autoencoders(key: jax.Array, x_train_scaled: jnp.ndarray, cfg: AEConfig,
                       latent_dims: Sequence[int]) -> AEResult:
    """All latent dims in one vmapped program (vs 21 serial Keras fits,
    ``autoencoder_v4.ipynb`` cell 6).  Params come back with a leading
    sweep axis; index with `jax.tree_util.tree_map(lambda a: a[i], ...)`."""
    max_latent = max(latent_dims)
    cfg = dataclasses.replace(cfg, latent_dim=max_latent)
    masks = jnp.stack([latent_mask(d, max_latent) for d in latent_dims])
    keys = jax.random.split(key, len(latent_dims))
    return jax.vmap(lambda k, m: train_autoencoder(k, x_train_scaled, cfg, m))(keys, masks)


# ----------------------------------------------------- pure evaluation
def oos_prefix_metrics(model: Autoencoder, x_test: jnp.ndarray,
                       params: dict, mask: jnp.ndarray):
    """Per-prefix OOS R² and RMSE, all expanding windows as one batch.

    Vectorization of ``Autoencoder_encapsulate.py:115-131``: for prefix
    length i ∈ [2, T], MinMax-scale ``x_test[:i]`` with its own min/max
    (prefix scans, no 167 scaler refits), reconstruct, and score — the
    R²/RMSE reductions happen *inside* each prefix lane so nothing
    (T, F)-sized leaves the program.  Pure in (params, mask): vmappable
    across a latent sweep."""
    t = x_test.shape[0]
    mins, maxs = expanding_minmax_scale(x_test)
    scale = jnp.where(maxs - mins == 0.0, 1.0, maxs - mins)

    def one_prefix(i):
        scaled = (x_test - mins[i - 1]) / scale[i - 1]
        mask_rows = (jnp.arange(t) < i)[:, None]
        pred = model.apply({"params": params}, scaled, mask)
        r2 = _r2_columns_mean_masked(scaled, pred, mask_rows)
        sq = jnp.sum((scaled - pred) ** 2 * mask_rows)
        rmse = jnp.sqrt(sq / (jnp.sum(mask_rows) * x_test.shape[1]))
        return r2, rmse

    return jax.vmap(one_prefix)(jnp.arange(2, t))


def ante_weights(model: Autoencoder, cfg: AEConfig, params: dict,
                 mask: Optional[jnp.ndarray], x_test: jnp.ndarray,
                 y_test: jnp.ndarray, rf: jnp.ndarray, window: int):
    """Ex-ante replication returns + strategy weights, pure in
    (params, mask) — the body of ``Autoencoder_encapsulate.py:133-201``
    shared by :meth:`ReplicationEngine.ante` and the vmapped sweep
    evaluation.  Returns ``(ante (P, S), weights (P, F, S))``."""
    rf = jnp.asarray(rf, jnp.float32).reshape(-1, 1)
    factors = model.apply({"params": params}, x_test, mask,
                          method=Autoencoder.encode)            # raw-input encode, :140
    betas = rolling_ols_beta(y_test, factors, window)           # (T-w+1, L, S)
    n_windows = x_test.shape[0] - window                        # :148 range
    betas = betas[:n_windows]

    def norm_factor(i):
        xw = lax.dynamic_slice_in_dim(factors, i, window)
        yw = lax.dynamic_slice_in_dim(y_test, i, window)
        return costs.normalization(yw, xw, betas[i], window)

    norms = jax.vmap(norm_factor)(jnp.arange(n_windows))        # (n_windows, S)

    w_dec = params["decoder_kernel"]                            # (L, F) factor→ETF map, :159
    if mask is not None:
        w_dec = w_dec * mask[:, None]

    def month_weights(i, beta, norm):
        # LeakyReLU mask from the *current* month's decoded sign, :163-166
        decoded = factors[window + i] @ w_dec                   # (F,)
        leaky = jnp.where(decoded < 0, cfg.leaky_slope, 1.0)
        return (jnp.swapaxes(beta, 0, 1) @ w_dec * leaky[None, :]).T * norm[None, :]

    if cfg.beta_mode == "first":
        beta_used = jnp.broadcast_to(betas[0], betas.shape)
        norm_used = jnp.broadcast_to(norms[0], norms.shape)
    else:
        beta_used, norm_used = betas, norms
    weights = jax.vmap(month_weights)(jnp.arange(n_windows), beta_used, norm_used)

    # last window has no realized month — drop it (:179-180)
    weights = weights[:-1]                                      # (P, F, S)
    p = weights.shape[0]
    delta = 1.0 - jnp.sum(weights, axis=1)                      # (P, S)
    ante = delta * rf[-p:] + jnp.einsum("pf,pfs->ps", x_test[-p:], weights)
    return ante, weights


def evaluate_params(model: Autoencoder, cfg: AEConfig, x_train_scaled, x_test,
                    y_test, rf, factor_full, params: dict,
                    mask: jnp.ndarray) -> dict:
    """Every per-latent number the notebook's result cells need, as one
    pure jnp program: IS/OOS fit metrics, ex-ante/ex-post replication
    returns, turnover, and Sharpe ratios.  Pure in (params, mask) so a
    latent sweep evaluates as a single vmapped XLA program
    (:func:`sweep_evaluate`) instead of 21 host-serial eval passes."""
    from hfrep_tpu.replication import perf_stats

    pred_train = model.apply({"params": params}, x_train_scaled, mask)
    is_r2 = _r2_columns_mean(x_train_scaled, pred_train)
    is_rmse = jnp.sqrt(jnp.mean((x_train_scaled - pred_train) ** 2))
    oos_r2, oos_rmse = oos_prefix_metrics(model, x_test, params, mask)

    window = cfg.ols_window
    ante, weights = ante_weights(model, cfg, params, mask, x_test, y_test,
                                 rf, window)
    p = ante.shape[0]
    panel = jnp.asarray(factor_full, jnp.float32)[-(p + window):]
    post = costs.ex_post_return(ante, window,
                                jnp.transpose(weights, (2, 0, 1)), panel)
    rf_tail = jnp.asarray(rf, jnp.float32).reshape(-1)[-p:]
    return {
        "is_r2": is_r2, "is_rmse": is_rmse,
        "oos_r2": oos_r2, "oos_rmse": oos_rmse,
        "ante": ante, "post": post,
        "turnover": costs.turnover(weights),
        "sharpe_ante": perf_stats.annualized_sharpe(ante, rf_tail),
        "sharpe_post": perf_stats.annualized_sharpe(post, rf_tail),
    }


def sweep_evaluate(model: Autoencoder, cfg: AEConfig, x_train_scaled, x_test,
                   y_test, rf, factor_full, stacked_params: dict,
                   masks: jnp.ndarray) -> dict:
    """Evaluate every latent dim of a sweep in ONE compiled program.

    ``stacked_params``/``masks`` carry a leading sweep axis (the output of
    :func:`sweep_autoencoders`); the result dict's arrays all lead with
    that axis.  Replaces the reference's 21-serial eval loop
    (``autoencoder_v4.ipynb`` cells 6/24) *and* round 1's host-serial
    ``use_params → IS/OOS/ante/post/turnover`` loop."""
    fn = lambda p, m: evaluate_params(model, cfg, x_train_scaled, x_test,
                                      y_test, rf, factor_full, p, m)
    return jax.jit(jax.vmap(fn))(stacked_params, masks)


# ---------------------------------------------------------------- engine
class ReplicationEngine:
    """The reference ``AE`` wrapper's full API on one trained model.

    Construction mirrors ``AE.__init__`` (``Autoencoder_encapsulate.py:39-70``):
    unscaled train/test panels in, train-set MinMax params fit internally.
    """

    def __init__(self, x_train, y_train, x_test, y_test, cfg: AEConfig | None = None):
        self.cfg = cfg or AEConfig()
        if len(x_train) != len(y_train) or len(x_test) != len(y_test):
            raise ValueError("x/y length mismatch")
        self.x_train_raw = jnp.asarray(x_train, jnp.float32)
        self.x_test = jnp.asarray(x_test, jnp.float32)      # unscaled, :67
        self.y_train = jnp.asarray(y_train, jnp.float32)
        self.y_test = jnp.asarray(y_test, jnp.float32)
        self.train_scale, self.x_train = mm.fit_transform(self.x_train_raw)
        self.model = Autoencoder(n_features=self.cfg.n_factors,
                                 latent_dim=self.cfg.latent_dim,
                                 slope=self.cfg.leaky_slope)
        self.result: Optional[AEResult] = None
        self.mask: Optional[jnp.ndarray] = None
        self._ante = None
        self._strat_weights = None      # (P, A, S)
        self._post = None
        self._train_fn = None
        self._oos_eval_fn = None
        self._oos_cache = None

    # ------------------------------------------------------------ training
    def train(self, key: Optional[jax.Array] = None) -> AEResult:
        from hfrep_tpu.obs import get_obs
        obs = get_obs()
        key = key if key is not None else jax.random.PRNGKey(self.cfg.seed)
        if self._train_fn is None:
            self._train_fn = jax.jit(lambda k: train_autoencoder(k, self.x_train, self.cfg))
        with obs.span("ae_train", latent_dim=self.cfg.latent_dim,
                      epochs=self.cfg.epochs):
            self.result = self._train_fn(key)
            if obs.enabled:        # time the scan, not its async dispatch
                jax.block_until_ready(self.result.params)
        if obs.enabled:
            obs.counter("ae_trainings").inc()
            obs.gauge("ae_stop_epoch").set(int(self.result.stop_epoch))
        self.mask = None            # full-latent model: drop any use_params() mask
        self._invalidate()
        return self.result

    def use_params(self, params: dict, mask: Optional[jnp.ndarray] = None) -> None:
        """Adopt externally trained (e.g. sweep-sliced) parameters."""
        self.result = AEResult(params=params, stop_epoch=jnp.zeros((), jnp.int32),
                               train_loss=jnp.zeros(()), val_loss=jnp.zeros(()))
        self.mask = mask
        self._invalidate()

    def _invalidate(self) -> None:
        """Drop every derived artifact of the previous parameter set."""
        self._oos_cache = None
        self._ante = None
        self._strat_weights = None
        self._post = None

    @property
    def params(self) -> dict:
        if self.result is None:
            raise RuntimeError("train() first")
        return self.result.params

    def _apply(self, x):
        return self.model.apply({"params": self.params}, x, self.mask)

    def _encode(self, x):
        return self.model.apply({"params": self.params}, x, self.mask,
                                method=Autoencoder.encode)

    # ------------------------------------------------------------- metrics
    def model_IS_r2(self) -> float:
        """r2_score(x_train_scaled, reconstruction) — uniform average over
        columns (``Autoencoder_encapsulate.py:107-109``)."""
        pred = self._apply(self.x_train)
        return float(_r2_columns_mean(self.x_train, pred))

    def model_IS_RMSE(self) -> float:
        pred = self._apply(self.x_train)
        return float(jnp.sqrt(jnp.mean((self.x_train - pred) ** 2)))

    def _oos_eval(self):
        """Cached expanding-window metrics — R² and RMSE share one
        compiled program (:func:`oos_prefix_metrics`); ``params``/``mask``
        are traced arguments (not baked constants) so the program survives
        retraining / param swaps."""
        if self._oos_cache is None:
            from hfrep_tpu.obs import get_obs
            if self._oos_eval_fn is None:
                self._oos_eval_fn = jax.jit(
                    lambda p, m: oos_prefix_metrics(self.model, self.x_test, p, m))
            mask = self.mask if self.mask is not None else jnp.ones(
                (self.params["encoder_kernel"].shape[1],), jnp.float32)
            with get_obs().span("ae_oos_eval"):
                self._oos_cache = self._oos_eval_fn(self.params, mask)
        return self._oos_cache

    def model_OOS_r2(self) -> np.ndarray:
        return np.asarray(self._oos_eval()[0])

    def model_OOS_RMSE(self) -> np.ndarray:
        return np.asarray(self._oos_eval()[1])

    # ------------------------------------------------------------ strategy
    def ante(self, rf, window: Optional[int] = None) -> np.ndarray:
        """Ex-ante replication returns (``Autoencoder_encapsulate.py:133-201``).

        ``beta_mode='first'`` (default) reproduces the reference exactly:
        the OLS beta and normalization factor of the *first* 24-month
        window are reused for every month (``:167`` indexes
        ``ae_ols_beta[0]``), only the LeakyReLU activation mask varies.
        ``beta_mode='rolling'`` uses each window's own beta.  Body shared
        with the vmapped sweep path via :func:`ante_weights`.
        """
        from hfrep_tpu.obs import get_obs
        window = window or self.cfg.ols_window
        with get_obs().span("ae_ante", window=int(window)):
            ante, weights = ante_weights(self.model, self.cfg, self.params,
                                         self.mask, self.x_test, self.y_test,
                                         jnp.asarray(rf, jnp.float32), window)
        p = weights.shape[0]
        self._strat_weights = weights
        self._ante = ante
        self.window = window
        self.oos_hfd = self.y_test[-p:]
        return np.asarray(ante)

    def post(self, factor_etf_full) -> np.ndarray:
        """Ex-post returns net of costs (``Autoencoder_encapsulate.py:203-208``):
        applies the cost penalty using the *full* factor panel's trailing
        ``P + window`` months."""
        if self._ante is None:
            raise RuntimeError("ante() first")
        p = self._ante.shape[0]
        panel = jnp.asarray(factor_etf_full, jnp.float32)[-(p + self.window):]
        weights_s_p_a = jnp.transpose(self._strat_weights, (2, 0, 1))   # (S, P, A)
        self._post = costs.ex_post_return(self._ante, self.window, weights_s_p_a, panel)
        return np.asarray(self._post)

    def turnover(self) -> np.ndarray:
        """Annualized turnover per strategy (``Autoencoder_encapsulate.py:210-224``)."""
        if self._strat_weights is None:
            raise RuntimeError("ante() first")
        return np.asarray(costs.turnover(self._strat_weights))


# ------------------------------------------------------------------ utils
def _r2_columns_mean(actual: jnp.ndarray, pred: jnp.ndarray) -> jnp.ndarray:
    """sklearn r2_score with multioutput='uniform_average'."""
    ss_res = jnp.sum((actual - pred) ** 2, axis=0)
    ss_tot = jnp.sum((actual - jnp.mean(actual, axis=0)) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / ss_tot)


def _r2_columns_mean_masked(actual, pred, mask_rows) -> jnp.ndarray:
    w = mask_rows.astype(actual.dtype)
    n = jnp.maximum(jnp.sum(w), 1.0)
    mean = jnp.sum(actual * w, axis=0) / n
    ss_res = jnp.sum(((actual - pred) * w) ** 2, axis=0)
    ss_tot = jnp.sum(((actual - mean) * w) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / ss_tot)
