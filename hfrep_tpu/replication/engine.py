"""Autoencoder replication engine: training, evaluation, strategy build.

TPU-native re-design of ``Autoencoder_encapsulate.py:38-224`` (class
``AE``).  Where the reference trains 21 separate Keras models in a Python
loop with per-call ``predict`` inside O(T) host loops (SURVEY §3.3), here:

* one AE training run is a single `lax.scan` over epochs with
  Keras-faithful early stopping folded into the carry;
* the latent-dim sweep is `vmap` over a latent *mask* (same param shapes,
  see :mod:`hfrep_tpu.models.autoencoder`) — all 21 trainings execute as
  one batched XLA program;
* the expanding-window OOS metrics use prefix min/max scans instead of
  167 scaler refits;
* the 24-month rolling OLS is one batched least-squares.

Training recipe ported from ``Autoencoder_encapsulate.py:72-105``:
MinMax-scale x_train only (``:62-67``; note ``_x_test`` stays *unscaled* —
the encoder is later applied to raw test returns, ``:67,140``), Nadam on
MSE, ≤1000 epochs, batch 48, ``validation_split=.25`` (Keras semantics:
the *last* 25% of rows are validation, the first 75% train), per-epoch
reshuffling of the train block, EarlyStopping(patience=5) on val_loss
without best-weight restore.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hfrep_tpu.config import AEConfig
from hfrep_tpu.core import costs
from hfrep_tpu.core import scaler as mm
from hfrep_tpu.models.autoencoder import Autoencoder, latent_mask
from hfrep_tpu.ops.rolling import expanding_minmax_scale, rolling_ols_beta

import optax


class AEResult(NamedTuple):
    params: dict                 # encoder/decoder kernels (possibly batched)
    stop_epoch: jnp.ndarray      # epoch index where early stopping fired
    train_loss: jnp.ndarray     # (epochs,) per-epoch training loss (NaN after stop)
    val_loss: jnp.ndarray       # (epochs,)


def _epoch_batches(n_train: int, batch_size: int) -> Tuple[int, int]:
    n_batches = -(-n_train // batch_size)
    return n_batches, n_batches * batch_size


def train_autoencoder(key: jax.Array, x_train_scaled: jnp.ndarray, cfg: AEConfig,
                      mask: Optional[jnp.ndarray] = None) -> AEResult:
    """Train one (optionally masked) AE; pure function of (key, data, cfg).

    ``mask`` is a (max_latent,) 0/1 vector selecting active latent dims;
    None trains the full ``cfg.latent_dim``.
    """
    model = Autoencoder(n_features=cfg.n_factors, latent_dim=cfg.latent_dim,
                        slope=cfg.leaky_slope)
    n = x_train_scaled.shape[0]
    # Keras validation_split semantics: split_at = floor(n * (1 - split))
    # training rows, the rest validation (167 → 125 train / 42 val).
    n_train = int(n * (1.0 - cfg.val_split))
    n_val = n - n_train
    x_fit, x_val = x_train_scaled[:n_train], x_train_scaled[n_train:]

    key, init_key = jax.random.split(key)
    params = model.init(init_key, x_fit[:1])["params"]
    tx = optax.nadam(cfg.lr, b1=0.9, b2=0.999, eps=1e-7)   # Keras Nadam defaults
    opt_state = tx.init(params)

    n_batches, padded = _epoch_batches(n_train, cfg.batch_size)

    def mse(p, x, w=None):
        pred = model.apply({"params": p}, x, mask)
        err = jnp.mean((pred - x) ** 2, axis=1)
        if w is None:
            return jnp.mean(err)
        return jnp.sum(err * w) / jnp.maximum(jnp.sum(w), 1.0)

    def epoch_step(carry, epoch_key):
        params, opt_state, best_val, wait, stopped = carry
        perm = jax.random.permutation(epoch_key, n_train)
        order = jnp.concatenate([perm, jnp.zeros(padded - n_train, jnp.int32)])
        weights = (jnp.arange(padded) < n_train).astype(jnp.float32)

        def batch_step(c, i):
            p, o = c
            sl = lax.dynamic_slice_in_dim(order, i * cfg.batch_size, cfg.batch_size)
            w = lax.dynamic_slice_in_dim(weights, i * cfg.batch_size, cfg.batch_size)
            xb = jnp.take(x_fit, sl, axis=0)
            loss, grads = jax.value_and_grad(mse)(p, xb, w)
            updates, o = tx.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), loss

        (new_params, new_opt), batch_losses = lax.scan(
            batch_step, (params, opt_state), jnp.arange(n_batches))

        # freeze updates once stopped (Keras keeps stop-epoch weights)
        params = jax.tree_util.tree_map(
            lambda old, new: jnp.where(stopped, old, new), params, new_params)
        opt_state = jax.tree_util.tree_map(
            lambda old, new: jnp.where(stopped, old, new), opt_state, new_opt)

        val = mse(params, x_val)
        improved = val < best_val
        wait = jnp.where(stopped, wait, jnp.where(improved, 0, wait + 1))
        best_val = jnp.where(stopped, best_val, jnp.minimum(best_val, val))
        newly_stopped = jnp.logical_and(jnp.logical_not(stopped), wait >= cfg.patience)
        train_loss = jnp.where(stopped, jnp.nan, jnp.mean(batch_losses))
        val_out = jnp.where(stopped, jnp.nan, val)
        stopped = jnp.logical_or(stopped, newly_stopped)
        return (params, opt_state, best_val, wait, stopped), (train_loss, val_out, stopped)

    keys = jax.random.split(key, cfg.epochs)
    init = (params, opt_state, jnp.inf, jnp.zeros((), jnp.int32), jnp.zeros((), bool))
    (params, _, _, _, _), (tl, vl, stop_trace) = lax.scan(epoch_step, init, keys)
    stop_epoch = jnp.argmax(stop_trace) + jnp.where(jnp.any(stop_trace), 0, cfg.epochs)
    return AEResult(params=params, stop_epoch=stop_epoch, train_loss=tl, val_loss=vl)


def sweep_autoencoders(key: jax.Array, x_train_scaled: jnp.ndarray, cfg: AEConfig,
                       latent_dims: Sequence[int]) -> AEResult:
    """All latent dims in one vmapped program (vs 21 serial Keras fits,
    ``autoencoder_v4.ipynb`` cell 6).  Params come back with a leading
    sweep axis; index with `jax.tree_util.tree_map(lambda a: a[i], ...)`."""
    max_latent = max(latent_dims)
    cfg = dataclasses.replace(cfg, latent_dim=max_latent)
    masks = jnp.stack([latent_mask(d, max_latent) for d in latent_dims])
    keys = jax.random.split(key, len(latent_dims))
    return jax.vmap(lambda k, m: train_autoencoder(k, x_train_scaled, cfg, m))(keys, masks)


# ---------------------------------------------------------------- engine
class ReplicationEngine:
    """The reference ``AE`` wrapper's full API on one trained model.

    Construction mirrors ``AE.__init__`` (``Autoencoder_encapsulate.py:39-70``):
    unscaled train/test panels in, train-set MinMax params fit internally.
    """

    def __init__(self, x_train, y_train, x_test, y_test, cfg: AEConfig | None = None):
        self.cfg = cfg or AEConfig()
        if len(x_train) != len(y_train) or len(x_test) != len(y_test):
            raise ValueError("x/y length mismatch")
        self.x_train_raw = jnp.asarray(x_train, jnp.float32)
        self.x_test = jnp.asarray(x_test, jnp.float32)      # unscaled, :67
        self.y_train = jnp.asarray(y_train, jnp.float32)
        self.y_test = jnp.asarray(y_test, jnp.float32)
        self.train_scale, self.x_train = mm.fit_transform(self.x_train_raw)
        self.model = Autoencoder(n_features=self.cfg.n_factors,
                                 latent_dim=self.cfg.latent_dim,
                                 slope=self.cfg.leaky_slope)
        self.result: Optional[AEResult] = None
        self.mask: Optional[jnp.ndarray] = None
        self._ante = None
        self._strat_weights = None      # (P, A, S)
        self._post = None
        self._train_fn = None
        self._oos_eval_fn = None
        self._oos_cache = None

    # ------------------------------------------------------------ training
    def train(self, key: Optional[jax.Array] = None) -> AEResult:
        key = key if key is not None else jax.random.PRNGKey(self.cfg.seed)
        if self._train_fn is None:
            self._train_fn = jax.jit(lambda k: train_autoencoder(k, self.x_train, self.cfg))
        self.result = self._train_fn(key)
        self.mask = None            # full-latent model: drop any use_params() mask
        self._invalidate()
        return self.result

    def use_params(self, params: dict, mask: Optional[jnp.ndarray] = None) -> None:
        """Adopt externally trained (e.g. sweep-sliced) parameters."""
        self.result = AEResult(params=params, stop_epoch=jnp.zeros((), jnp.int32),
                               train_loss=jnp.zeros(()), val_loss=jnp.zeros(()))
        self.mask = mask
        self._invalidate()

    def _invalidate(self) -> None:
        """Drop every derived artifact of the previous parameter set."""
        self._oos_cache = None
        self._ante = None
        self._strat_weights = None
        self._post = None

    @property
    def params(self) -> dict:
        if self.result is None:
            raise RuntimeError("train() first")
        return self.result.params

    def _apply(self, x):
        return self.model.apply({"params": self.params}, x, self.mask)

    def _encode(self, x):
        return self.model.apply({"params": self.params}, x, self.mask,
                                method=Autoencoder.encode)

    # ------------------------------------------------------------- metrics
    def model_IS_r2(self) -> float:
        """r2_score(x_train_scaled, reconstruction) — uniform average over
        columns (``Autoencoder_encapsulate.py:107-109``)."""
        pred = self._apply(self.x_train)
        return float(_r2_columns_mean(self.x_train, pred))

    def model_IS_RMSE(self) -> float:
        pred = self._apply(self.x_train)
        return float(jnp.sqrt(jnp.mean((self.x_train - pred) ** 2)))

    def _oos_scaled_prefix_eval(self, params, mask):
        """All expanding-window rescale+predict passes as one batch
        (``Autoencoder_encapsulate.py:115-131`` vectorized): for prefix
        length i ∈ [2, T], scale x_test[:i] with its own min/max, predict,
        score — returns masked (T-2, T, F) actual/pred tensors.

        ``params``/``mask`` are traced arguments (not baked constants) so
        the compiled program survives retraining / param swaps."""
        x = self.x_test
        t = x.shape[0]
        mins, maxs = expanding_minmax_scale(x)
        scale = jnp.where(maxs - mins == 0.0, 1.0, maxs - mins)

        def one_prefix(i):
            scaled = (x - mins[i - 1]) / scale[i - 1]
            mask_rows = (jnp.arange(t) < i)[:, None]
            pred = self.model.apply({"params": params}, scaled, mask)
            return scaled, pred, mask_rows

        idx = jnp.arange(2, t)
        return jax.vmap(one_prefix)(idx)

    def _oos_eval(self):
        """Cached one-shot evaluation of the full expanding-window batch —
        r2 and RMSE share the same forward pass and compiled program."""
        if self._oos_cache is None:
            if self._oos_eval_fn is None:
                self._oos_eval_fn = jax.jit(self._oos_scaled_prefix_eval)
            mask = self.mask if self.mask is not None else jnp.ones(
                (self.params["encoder_kernel"].shape[1],), jnp.float32)
            self._oos_cache = self._oos_eval_fn(self.params, mask)
        return self._oos_cache

    def model_OOS_r2(self) -> np.ndarray:
        scaled, pred, mask_rows = self._oos_eval()
        return np.asarray(jax.vmap(_r2_columns_mean_masked)(scaled, pred, mask_rows))

    def model_OOS_RMSE(self) -> np.ndarray:
        scaled, pred, mask_rows = self._oos_eval()
        sq = jnp.sum((scaled - pred) ** 2 * mask_rows, axis=(1, 2))
        n_elems = jnp.sum(mask_rows, axis=(1, 2)) * scaled.shape[2]
        return np.asarray(jnp.sqrt(sq / n_elems))

    # ------------------------------------------------------------ strategy
    def ante(self, rf, window: Optional[int] = None) -> np.ndarray:
        """Ex-ante replication returns (``Autoencoder_encapsulate.py:133-201``).

        ``beta_mode='first'`` (default) reproduces the reference exactly:
        the OLS beta and normalization factor of the *first* 24-month
        window are reused for every month (``:167`` indexes
        ``ae_ols_beta[0]``), only the LeakyReLU activation mask varies.
        ``beta_mode='rolling'`` uses each window's own beta.
        """
        window = window or self.cfg.ols_window
        rf = jnp.asarray(rf, jnp.float32).reshape(-1, 1)

        factors = self._encode(self.x_test)                     # (T, L) raw-input encode, :140
        betas = rolling_ols_beta(self.y_test, factors, window)  # (T-w+1, L, S)
        n_windows = self.x_test.shape[0] - window               # :148 range
        betas = betas[:n_windows]

        def norm_factor(i):
            xw = lax.dynamic_slice_in_dim(factors, i, window)
            yw = lax.dynamic_slice_in_dim(self.y_test, i, window)
            return costs.normalization(yw, xw, betas[i], window)

        norms = jax.vmap(norm_factor)(jnp.arange(n_windows))    # (n_windows, S)

        w_dec = self.params["decoder_kernel"]                   # (L, F) factor→ETF map, :159
        if self.mask is not None:
            w_dec = w_dec * self.mask[:, None]

        def month_weights(i, beta, norm):
            # LeakyReLU mask from the *current* month's decoded sign, :163-166
            decoded = factors[window + i] @ w_dec               # (F,)
            leaky = jnp.where(decoded < 0, self.cfg.leaky_slope, 1.0)
            sw = (jnp.swapaxes(beta, 0, 1) @ w_dec * leaky[None, :]).T * norm[None, :]
            return sw                                           # (F, S)

        if self.cfg.beta_mode == "first":
            beta_used = jnp.broadcast_to(betas[0], betas.shape)
            norm_used = jnp.broadcast_to(norms[0], norms.shape)
        else:
            beta_used, norm_used = betas, norms
        weights = jax.vmap(month_weights)(jnp.arange(n_windows), beta_used, norm_used)

        # last window has no realized month — drop it (:179-180)
        weights = weights[:-1]                                   # (P, F, S)
        p = weights.shape[0]
        delta = 1.0 - jnp.sum(weights, axis=1)                   # (P, S)
        oos_etf = self.x_test[-p:]
        oos_rf = rf[-p:]
        ante = delta * oos_rf + jnp.einsum("pf,pfs->ps", oos_etf, weights)

        self._strat_weights = weights
        self._ante = ante
        self.window = window
        self.oos_hfd = self.y_test[-p:]
        return np.asarray(ante)

    def post(self, factor_etf_full) -> np.ndarray:
        """Ex-post returns net of costs (``Autoencoder_encapsulate.py:203-208``):
        applies the cost penalty using the *full* factor panel's trailing
        ``P + window`` months."""
        if self._ante is None:
            raise RuntimeError("ante() first")
        p = self._ante.shape[0]
        panel = jnp.asarray(factor_etf_full, jnp.float32)[-(p + self.window):]
        weights_s_p_a = jnp.transpose(self._strat_weights, (2, 0, 1))   # (S, P, A)
        self._post = costs.ex_post_return(self._ante, self.window, weights_s_p_a, panel)
        return np.asarray(self._post)

    def turnover(self) -> np.ndarray:
        """Annualized turnover per strategy (``Autoencoder_encapsulate.py:210-224``)."""
        if self._strat_weights is None:
            raise RuntimeError("ante() first")
        return np.asarray(costs.turnover(self._strat_weights))


# ------------------------------------------------------------------ utils
def _r2_columns_mean(actual: jnp.ndarray, pred: jnp.ndarray) -> jnp.ndarray:
    """sklearn r2_score with multioutput='uniform_average'."""
    ss_res = jnp.sum((actual - pred) ** 2, axis=0)
    ss_tot = jnp.sum((actual - jnp.mean(actual, axis=0)) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / ss_tot)


def _r2_columns_mean_masked(actual, pred, mask_rows) -> jnp.ndarray:
    w = mask_rows.astype(actual.dtype)
    n = jnp.maximum(jnp.sum(w), 1.0)
    mean = jnp.sum(actual * w, axis=0) / n
    ss_res = jnp.sum(((actual - pred) * w) ** 2, axis=0)
    ss_tot = jnp.sum(((actual - mean) * w) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / ss_tot)
