"""Performance statistics — the notebook's ``data_analysis`` battery.

jnp ports of ``autoencoder_v4.ipynb`` cell 23 (~190 LoC): Omega ratio and
curve, annualized Sharpe, FF3/FF5 OLS alpha, historical VaR/CVaR, CEQ,
assembled into a per-strategy stats table together with the spanning
tests of :mod:`hfrep_tpu.replication.spanning`.

Reference quirks preserved (each documented at its function):

* ``Omega_ratio`` converts the annual threshold with the exponent
  ``sqrt(1/252)`` — not ``1/252`` (cell 23, ``daily_threashold``) — and
  is applied unchanged to *monthly* series;
* the "five-factor" loader reads only Mkt-RF/SMB/HML from the 5-factor
  CSV (cell 22 ``usecols`` — so FF5F alpha in the published tables is a
  3-factor alpha on dailies from a different sample); the corrected
  loader reads all five, behind ``reference_compat``.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hfrep_tpu.ops.rolling import ols_beta

Array = jnp.ndarray


def omega_ratio(returns, threshold: float = 0.0) -> jnp.ndarray:
    """Ω = Σ max(r−τ,0) / Σ max(τ−r,0) with the reference's τ conversion
    ``(threshold+1)**sqrt(1/252) − 1`` (cell 23)."""
    tau = (threshold + 1.0) ** np.sqrt(1.0 / 252.0) - 1.0
    r = jnp.asarray(returns)
    excess = r - tau
    gains = jnp.sum(jnp.where(excess > 0, excess, 0.0), axis=0)
    losses = -jnp.sum(jnp.where(excess < 0, excess, 0.0), axis=0)
    return gains / losses


def omega_curve(returns, thresholds: Optional[np.ndarray] = None) -> np.ndarray:
    thresholds = thresholds if thresholds is not None else np.linspace(0, 0.2, 50)
    return np.asarray([np.asarray(omega_ratio(returns, t)) for t in thresholds])


def annualized_sharpe(returns, rf=0.0) -> jnp.ndarray:
    """(mean(ret) − mean(rf)) / std(ret) · √12 (cell 23; population std,
    matching np.std)."""
    r = jnp.asarray(returns)
    rf_mean = jnp.mean(jnp.asarray(rf))
    return (jnp.mean(r, axis=0) - rf_mean) / jnp.std(r, axis=0) * jnp.sqrt(12.0)


def ols_alpha(returns, factors) -> jnp.ndarray:
    """Intercept of OLS(ret ~ const + factors) (cell 23 ``OLS_alpha``)."""
    y = jnp.asarray(returns)
    squeeze = y.ndim == 1
    if squeeze:
        y = y[:, None]
    beta = ols_beta(y, jnp.asarray(factors), add_constant=True)
    return beta[0, 0] if squeeze else beta[0]


def historical_var(returns, alpha: float = 5.0) -> np.ndarray:
    """Per-column ``np.percentile(returns, alpha)`` (cell 23)."""
    return np.percentile(np.asarray(returns), alpha, axis=0)


def historical_cvar(returns, alpha: float = 5.0) -> np.ndarray:
    """Mean of returns at or below the VaR quantile (cell 23)."""
    r = np.asarray(returns)
    if r.ndim == 1:
        r = r[:, None]
    var = np.percentile(r, alpha, axis=0)
    out = np.empty(r.shape[1])
    for j in range(r.shape[1]):
        below = r[:, j] <= var[j]
        out[j] = r[below, j].mean() if below.any() else np.nan
    return out


def ceq(returns, rf, gamma: float = 2.0) -> jnp.ndarray:
    """Certainty-equivalent return, CRRA γ ≠ 1 (cell 23 ``ceq``):
    log(mean(((1+r)/(1+rf))^(1−γ))) / ((1−γ)/12)."""
    if gamma == 1:
        raise ValueError("gamma must differ from 1")
    r = jnp.asarray(returns)
    rf = jnp.asarray(rf).reshape(-1, *([1] * (r.ndim - 1)))
    mid = ((1.0 + r) / (1.0 + rf)) ** (1.0 - gamma)
    return jnp.log(jnp.mean(mid, axis=0)) / ((1.0 - gamma) / 12.0)


# ------------------------------------------------------------ FF factors
def load_ff_factors(path, start="1994-04-30", end="2022-04-30",
                    five: bool = False, reference_compat: bool = False):
    """Daily FF factor CSV → monthly log returns (cells 21-22).

    ``reference_compat=True`` reads only Mkt-RF/SMB/HML even from the
    5-factor file, reproducing the notebook's ``usecols`` bug; the
    default False (matching every other ``reference_compat`` switch in
    this package) lets the 5-factor file contribute RMW and CMA too.
    """
    import pandas as pd

    cols = ["Date", "Mkt-RF", "SMB", "HML"]
    if five and not reference_compat:
        cols += ["RMW", "CMA"]
    df = pd.read_csv(path, usecols=cols)
    df["Date"] = pd.to_datetime(df["Date"], format="%Y%m%d")
    df = df.set_index("Date").resample("ME").sum()
    df = np.log(df / 100.0 + 1.0)
    return df.loc[start:end]


# ---------------------------------------------------------- full battery
def data_analysis(df, rf=None, three_factor=None, five_factor=None,
                  span=None, columns: Optional[Sequence[str]] = None,
                  real_data: bool = True) -> Dict[str, np.ndarray]:
    """Assemble the notebook's per-strategy stats table (cell 23
    ``data_analysis``): Omega(0)/Omega(0.1), Sharpe, CVaR, CEQ(2/5/10),
    FF alphas, and HK/GRS spanning stats when a spanning set is given.

    ``df`` is (T, S) returns; ``span`` (T, K) is the spanning regressor
    set (each strategy is tested against it).  Returns a dict of arrays
    keyed by statistic name.
    """
    from hfrep_tpu.replication import spanning

    r = jnp.asarray(df, jnp.float32)
    t = r.shape[0]
    rf_arr = jnp.zeros((t,)) if rf is None else jnp.asarray(rf, jnp.float32).reshape(-1)

    out: Dict[str, np.ndarray] = {
        "Omega(0%)": np.asarray(omega_ratio(r, 0.0)),
        "Omega(10%)": np.asarray(omega_ratio(r, 0.1)),
        "Sharpe": np.asarray(annualized_sharpe(r, rf_arr)),
        "cVaR(95%)": historical_cvar(r),
        "CEQ(2)": np.asarray(ceq(r, rf_arr, 2.0)),
        "CEQ(5)": np.asarray(ceq(r, rf_arr, 5.0)),
        "CEQ(10)": np.asarray(ceq(r, rf_arr, 10.0)),
        "Skewness": _skew(np.asarray(r)),
        "Kurtosis": _kurtosis(np.asarray(r)),
    }
    if real_data and three_factor is not None:
        out["FF3F_alpha"] = np.asarray(ols_alpha(r, jnp.asarray(np.asarray(three_factor), jnp.float32)))
    if real_data and five_factor is not None:
        out["FF5F_alpha"] = np.asarray(ols_alpha(r, jnp.asarray(np.asarray(five_factor), jnp.float32)))
    if span is not None:
        hk_f, hk_p, grs_f, grs_p = [], [], [], []
        span_j = jnp.asarray(np.asarray(span), jnp.float32)
        for j in range(r.shape[1]):
            f_stat, p = spanning.hktest(r[:, j:j + 1], span_j)
            hk_f.append(float(f_stat)); hk_p.append(float(p))
            f_stat, p = spanning.grstest(r[:, j:j + 1], span_j)
            grs_f.append(float(f_stat)); grs_p.append(float(p))
        out["HK_F"] = np.asarray(hk_f); out["HK_p"] = np.asarray(hk_p)
        out["GRS_F"] = np.asarray(grs_f); out["GRS_p"] = np.asarray(grs_p)
    if columns is not None:
        import pandas as pd
        return pd.DataFrame(out, index=list(columns))
    return out


def _skew(r: np.ndarray) -> np.ndarray:
    m = r.mean(axis=0)
    s = r.std(axis=0)
    return (((r - m) / s) ** 3).mean(axis=0)


def _kurtosis(r: np.ndarray) -> np.ndarray:
    m = r.mean(axis=0)
    s = r.std(axis=0)
    return (((r - m) / s) ** 4).mean(axis=0) - 3.0


def res_sort(stats_by_latent: Dict[int, np.ndarray], strategy_names: Sequence[str]):
    """Best latent dim per strategy by Sharpe (notebook cell 27
    ``res_sort``): given {latent_dim: sharpe_array(S,)}, return the
    argmax latent and its Sharpe per strategy."""
    dims = sorted(stats_by_latent)
    mat = np.stack([stats_by_latent[d] for d in dims])       # (L, S)
    best_idx = np.argmax(mat, axis=0)
    return {
        name: {"latent": dims[best_idx[j]], "sharpe": float(mat[best_idx[j], j])}
        for j, name in enumerate(strategy_names)
    }
