"""Host-side training driver: the only Python loop in GAN training.

Replaces the reference's per-epoch host loop of 6 graph launches with
host numpy batch prep (``GAN/MTSS_WGAN_GP.py:260-284``, SURVEY §3.1) by
dispatching one jitted multi-epoch program per ``steps_per_call`` epochs.
Adds everything SURVEY §5 lists as absent: step timing, structured metric
logs, periodic full-state checkpoints with resume, and optional NaN
debugging via ``jax.config.update("jax_debug_nans", True)``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hfrep_tpu import resilience
from hfrep_tpu.config import ExperimentConfig
from hfrep_tpu.core.data import GanDataset
from hfrep_tpu.models.registry import build_gan
from hfrep_tpu.obs import get_obs, mesh_attrs
from hfrep_tpu.train.states import GanState, init_gan_state
from hfrep_tpu.train.steps import make_multi_step
from hfrep_tpu.obs import timeline
from hfrep_tpu.obs.metriclog import MetricLogger
from hfrep_tpu.obs.timeline import BlockTimer
from hfrep_tpu.utils import checkpoint as ckpt


class GanTrainer:
    def __init__(self, cfg: ExperimentConfig, dataset: GanDataset | jnp.ndarray,
                 mesh=None, logger: Optional[MetricLogger] = None,
                 nan_guard: bool = False, max_recoveries: int = 3):
        self.cfg = cfg
        self.windows = dataset.windows if isinstance(dataset, GanDataset) else jnp.asarray(dataset)
        self.scaler = dataset.scaler if isinstance(dataset, GanDataset) else None
        self.pair = build_gan(cfg.model)
        self.mesh = mesh
        key = jax.random.PRNGKey(cfg.train.seed)
        self.key, init_key = jax.random.split(key)
        self.state = init_gan_state(init_key, cfg.model, cfg.train, self.pair)
        self._launch_specs = None        # set on the mesh path below
        if mesh is not None:
            # The mesh's axis names declare the partitioning; every
            # combination launches through the ONE partition-rule-driven
            # builder (hfrep_tpu/parallel/rules.py): batch sharded over
            # dp, window over sp (sampled-tensor constraints), LSTM gate
            # columns over tp (param partition rules) — one pjit'd
            # global program, GSPMD derives the collectives.
            names = tuple(mesh.axis_names)
            if names not in (("dp",), ("sp",), ("tp",), ("dp", "sp"),
                             ("dp", "tp"), ("dp", "sp", "tp")):
                # validate BEFORE any hfrep_tpu.parallel import (the
                # rejection must never depend on import-order residue —
                # the order-dependent test_train failure of round 6)
                raise ValueError(
                    f"mesh axis names {names} not recognized; use ('dp',), "
                    "('sp',), ('tp',), ('dp', 'sp'), ('dp', 'tp'), or "
                    "('dp', 'sp', 'tp')")
            from hfrep_tpu.parallel.mesh import (replicate_to_global,
                                                 shard_to_global,
                                                 spans_processes)
            from hfrep_tpu.parallel.rules import (gan_launch_specs,
                                                  make_gan_multi_step)
            self._multi = make_gan_multi_step(self.pair, cfg.train,
                                              self.windows, mesh)
            #: the launch's state layout — P() on dp/sp meshes, the
            #: rule-resolved per-leaf pytree on tp meshes; multi-host
            #: promotion and checkpointing must agree with it (pjit
            #: refuses committed args whose sharding mismatches)
            self._launch_specs = gan_launch_specs(self.pair, cfg.train,
                                                  self.windows, mesh)
            if spans_processes(mesh):
                # multi-host: promote the (identically-seeded) state and
                # key to global arrays laid out exactly as the pod-wide
                # jit expects (replicated on dp/sp, tp-sharded on tp)
                self.state = shard_to_global(self.state, mesh,
                                             self._launch_specs)
                self.key = replicate_to_global(self.key, mesh)
        else:
            # single-device path joins the same build-time hook the
            # parallel factories use (no-op object passthrough when
            # telemetry is off): compile:<name> span + lowered-program
            # fingerprint on the first call, dispatch counting + the
            # dispatch-vs-compute attribution window on steady calls
            from hfrep_tpu.obs import instrument_step
            self._multi = instrument_step(
                make_multi_step(self.pair, cfg.train, self.windows),
                "multi_step", batch=cfg.train.batch_size,
                steps_per_call=cfg.train.steps_per_call)
        style = {"bce": "gan", "wgan_clip": "wgan", "wgan_gp": "wgan_gp"}[self.pair.loss]
        self.logger = logger or MetricLogger(echo=False, echo_style=style)
        # block-boundary timing + the wall-clock ledger: every stop() is
        # a timeline window flush at the sync the loop already pays
        self.timer = BlockTimer(drive="gan_block")
        self.epoch = 0
        #: per-epoch metric history (host numpy), kept even with a null logger
        self.history: list[dict] = []
        self._single_step = None
        self._generate_fn = None
        self._multi_warm = False    # first block per program carries compile
        self._one_warm = False
        # Failure detection (SURVEY §5.2-5.3: absent in the reference — a
        # diverged 5000-epoch run loses everything).  When enabled, a
        # block producing non-finite metrics is rolled back in memory (the
        # pre-block state is kept as a copy) and retried on a fresh PRNG
        # stream; after max_recoveries consecutive failures it raises.
        self.nan_guard = nan_guard
        self.max_recoveries = max_recoveries
        self.recoveries = 0
        # async boundary engine: a periodic checkpoint is STAGED (state
        # fetched host-side) at the block boundary and its file write
        # COMMITTED after the next block dispatches, so the serialization
        # I/O overlaps device compute instead of stalling the dispatch
        # path.  One slot — at most one boundary's write in flight.
        self._pending_ckpt = None        # (host_tree, path, epoch)

    # ------------------------------------------------------------ training
    def train(self, epochs: Optional[int] = None) -> GanState:
        """Run the schedule; when ``hfrep_tpu.obs`` telemetry is enabled,
        the whole run is wrapped in a ``train`` span with the trainer's
        config/mesh merged into the run manifest.  The jitted programs
        are identical either way — telemetry is host-side only."""
        obs = get_obs()
        if not obs.enabled:
            return self._train_impl(epochs)
        from hfrep_tpu.obs import manifest
        obs.annotate(config=manifest.config_dict(self.cfg),
                     mesh=mesh_attrs(self.mesh))
        n = epochs if epochs is not None else self.cfg.train.epochs
        obs.event("train_start", family=self.cfg.model.family, epochs=n,
                  start_epoch=self.epoch, mesh=mesh_attrs(self.mesh),
                  steps_per_call=self.cfg.train.steps_per_call)
        obs.memory_snapshot(phase="train_start")
        with obs.span("train", epochs=n):
            state = self._train_impl(epochs)
        obs.memory_snapshot(phase="train_end")
        sps = self.timer.steps_per_sec
        obs.gauge("steps_per_sec").set(sps)
        if self.cfg.model.family == "mtss_wgan_gp":
            # the analytic FLOPs model is flagship-specific (obs/flops.py)
            from hfrep_tpu.obs import flops
            obs.gauge("mfu").set(flops.mfu(
                sps, self.cfg.model.window, self.cfg.model.features,
                self.cfg.model.hidden, self.cfg.train.batch_size))
        obs.event("train_end", epoch=self.epoch, recoveries=self.recoveries)
        obs.flush()
        return state

    def _train_impl(self, epochs: Optional[int] = None) -> GanState:
        # SIGTERM drains at a block boundary (final checkpoint + clean
        # metrics) instead of killing the process mid-write
        with resilience.graceful_drain():
            return self._train_loop(epochs)

    def _train_loop(self, epochs: Optional[int] = None) -> GanState:
        tcfg = self.cfg.train
        spc = tcfg.steps_per_call
        epochs = epochs if epochs is not None else tcfg.epochs
        n_full, remainder = divmod(epochs, spc)
        done = 0
        # Steady-state blocks are pipelined: block i's host-side logging
        # (device_get + history/JSONL) runs while block i+1 executes on
        # device, so the chip never idles on the logger.  The NaN guard
        # inspects metrics synchronously, so guard mode keeps the
        # one-block-at-a-time path.  The open steady timing window spans
        # whole pipelined stretches and is closed (synced and recorded)
        # before anything that is not training — checkpoints in
        # particular — so steps_per_sec reflects device throughput only.
        pending = None                      # (metrics, base_epoch)
        steady_steps = 0                    # steps in the open window; 0 = closed

        def flush_pending():
            nonlocal pending
            if pending is not None:
                self._log_block(pending[0], spc, pending[1])
                pending = None

        def close_steady():
            nonlocal steady_steps
            if steady_steps:
                self.timer.stop(steady_steps, sync_on=self.state.g_params)
                steady_steps = 0

        pipeline_ok = False
        try:
            while done < n_full:
                self.key, sub = self._next_key()
                warm_block = not self._multi_warm
                if warm_block or self.nan_guard:
                    close_steady()
                    self.timer.start()
                    metrics = self._guarded(self._multi, sub)
                    if metrics is None:
                        continue                # guard tripped: block retried
                    self.timer.stop(spc, sync_on=self.state.g_params,
                                    warmup=warm_block)
                    self._multi_warm = True
                    flush_pending()
                    self._log_block(metrics, spc, self.epoch)
                else:
                    if steady_steps == 0:
                        self.timer.start()
                    metrics = self._guarded(self._multi, sub)   # async dispatch
                    self._commit_pending_ckpt()  # staged write overlaps the
                    #                              block just dispatched
                    flush_pending()             # overlaps with device compute
                    pending = (metrics, self.epoch)
                    steady_steps += spc
                self.epoch += spc
                done += 1
                if (tcfg.checkpoint_dir and tcfg.checkpoint_every > 0
                        and self.epoch % tcfg.checkpoint_every < spc):
                    close_steady()  # sync first: keep host logging out of the window
                    flush_pending()
                    if self._ckpt_async_ok():
                        # stage now (the state must leave the device before
                        # the next donating dispatch kills its buffers);
                        # the file write commits after that dispatch
                        self._commit_pending_ckpt()   # one-slot: land prior
                        self._stage_checkpoint()
                    else:
                        self.save_checkpoint()
                resilience.tick("block")        # injected faults fire here
                if resilience.drain_requested():
                    close_steady()
                    flush_pending()
                    self._drain_now()
            close_steady()
            flush_pending()
            self._commit_pending_ckpt()
            pipeline_ok = True
        finally:
            if not pipeline_ok:
                # An exception escaped the pipelined loop (device error
                # surfacing on a later dispatch, or a checkpoint failure):
                # drain the pending block's metrics and the open timing
                # window best-effort so history/JSONL don't silently drop
                # up to steps_per_call epochs, without masking the
                # propagating exception with a cleanup failure.
                try:
                    close_steady()
                except Exception:
                    pass
                try:
                    flush_pending()
                except Exception:
                    pass
                try:
                    # the staged checkpoint is plain host numpy — landing
                    # it cannot touch (possibly poisoned) device state
                    self._commit_pending_ckpt()
                except Exception:
                    pass
                try:
                    self.logger.flush()
                except Exception:
                    pass
        done = 0
        while done < remainder:
            # exact epoch counts: leftover epochs run on a cached 1-epoch step
            self.key, sub = self._next_key()
            self.timer.start()
            metrics = self._guarded(self._one, sub)
            if metrics is None:
                continue
            self.timer.stop(1, sync_on=self.state.g_params,
                            warmup=not self._one_warm)
            self._one_warm = True
            self._log_block(
                jax.tree_util.tree_map(lambda v: jnp.asarray(v)[None], metrics),
                1, self.epoch)
            self.epoch += 1
            done += 1
            if (tcfg.checkpoint_dir and tcfg.checkpoint_every > 0
                    and self.epoch % tcfg.checkpoint_every == 0):
                self.save_checkpoint()
            resilience.tick("block")
            if resilience.drain_requested():
                self._drain_now()
        self.logger.flush()
        return self.state

    def _next_key(self):
        """Split + materialize the block's PRNG keys under the ledger.

        The unpack blocks on the split's device computation, and on a
        synchronous backend the runtime may park the host HERE while the
        execution stream drains — host time feeding the dispatch chain
        either way, so it books as ``dispatch`` (exclusive time: µs on
        an async backend, the migrated stream-wait on a blocking one).
        """
        with timeline.timed("dispatch"):
            key, sub = jax.random.split(self.key)
        return key, sub

    def _drain_now(self) -> None:
        """Graceful preemption at a block boundary: persist a final
        checkpoint (when a checkpoint dir is configured), flush the
        metric log, announce the drain in the obs stream, and raise
        :class:`~hfrep_tpu.resilience.Preempted` — the CLI translates it
        into a resumable exit instead of a mid-write death."""
        self._commit_pending_ckpt()   # land any staged boundary first
        path = (self.save_checkpoint()
                if self.cfg.train.checkpoint_dir else None)
        try:
            self.logger.flush()
        except Exception:
            pass
        get_obs().event("preempt_drain", epoch=self.epoch, checkpoint=path)
        raise resilience.Preempted(site="block", epoch=self.epoch,
                                   snapshot=path)

    def _guarded(self, fn, key):
        """Run one block; on non-finite metrics roll back and reseed.

        Returns the metrics, or None when the guard rolled the block back
        (the caller retries with a fresh key).  Raises after
        ``max_recoveries`` consecutive failures.
        """
        # The jitted step donates the input state buffers, so a rollback
        # target must be materialized before the call.
        prev_state = (jax.tree_util.tree_map(jnp.copy, self.state)
                      if self.nan_guard else self.state)
        state, metrics = fn(self.state, key)
        if self.nan_guard:
            host = jax.device_get(metrics)
            finite = all(np.isfinite(v).all() for v in host.values())
            if not finite:
                self.recoveries += 1
                if self.recoveries > self.max_recoveries:
                    raise FloatingPointError(
                        f"training diverged {self.recoveries} times in a row "
                        f"(epoch {self.epoch}); last metrics: "
                        f"{ {k: np.asarray(v).reshape(-1)[-1] for k, v in host.items()} }")
                self.logger.log(self.epoch, {"recovery": self.recoveries})
                self.state = prev_state        # in-memory rollback of the block
                self.key = jax.random.fold_in(self.key, 7919 + self.recoveries)
                return None
            self.recoveries = 0
        self.state = state
        return metrics

    def _one(self, state, key):
        """Cached 1-epoch step for schedule remainders, matching the mesh
        partitioning (a window-sharded run must not fall back to a
        full-window single-device step — on a real pod that shape may not
        even fit one device).  The mesh remainder launches the SAME
        rule-driven single-epoch builder every axis combination shares;
        the meshless remainder keeps the plain donated jit."""
        if self._single_step is None:
            if self.mesh is not None:
                from hfrep_tpu.parallel.rules import make_gan_train_step
                self._single_step = make_gan_train_step(
                    self.pair, self.cfg.train, self.windows, self.mesh)
            else:
                from hfrep_tpu.obs import instrument_step
                from hfrep_tpu.train.steps import make_train_step
                # donate the state like the multi-step does: the remainder
                # epochs rebind `self.state` from the return value, so the
                # input buffers are dead the moment the call is issued;
                # instrumented like the multi-step so the remainder's
                # compile + dispatches land in the same ledger/attrib plane
                self._single_step = instrument_step(
                    jax.jit(
                        make_train_step(self.pair, self.cfg.train,
                                        self.windows),
                        donate_argnums=(0,)),
                    "single_step", batch=self.cfg.train.batch_size)
        return self._single_step(state, key)

    def _log_block(self, metrics: dict, n: int, base_epoch: int) -> None:
        # the metrics fetch is where the pipelined host blocks on the
        # previous block's device work — ledger it as device_compute so
        # the steady windows' wall clock stays attributed (pure
        # accumulator arithmetic when telemetry is off: no new syncs)
        t0 = timeline.clock()
        host = jax.device_get(metrics)
        timeline.note_sync(timeline.clock() - t0)
        for i in range(n):
            e = base_epoch + i
            rec = {k: v[i] for k, v in host.items()}
            self.history.append({"epoch": e, **{k: float(v) for k, v in rec.items()}})
            if e % self.cfg.train.log_every == 0:
                self.logger.log(e, rec)
        if "health_nonfinite" in host:
            self._health_boundary(host, n, base_epoch)

    def _health_boundary(self, host: dict, n: int, base_epoch: int) -> None:
        """Flight-recorder boundary: surface the block's in-graph health
        stats as ``health/*`` gauges and arm the nonfinite tripwire.

        ``host`` is the block's already-fetched metrics — the health
        values rode the metrics sync the trainer performs anyway, so
        this adds zero device→host syncs.  With
        ``HealthConfig.abort_on_nonfinite`` a nonfinite count converts
        into a typed :class:`~hfrep_tpu.obs.health.NumericFault` after
        an atomic forensic dump of the live carry (params + optimizer
        state + key + epoch) — the state the crash bundle's event tail
        points back at.
        """
        from hfrep_tpu.obs import health as health_mod
        obs = get_obs()
        epoch = base_epoch + n - 1
        last = {k: float(np.asarray(v).reshape(-1)[-1])
                for k, v in host.items() if k.startswith("health_")}
        if obs.enabled:
            for k, v in last.items():
                short = k[len("health_"):]
                obs.gauge(f"health/{short}").set(v, epoch=epoch)
        nf = float(np.nansum(np.asarray(host["health_nonfinite"])))
        if nf <= 0:
            return
        hcfg = health_mod.active()
        abort = bool(hcfg and hcfg.abort_on_nonfinite)
        obs.event("numeric_fault", site="block", epoch=epoch,
                  nonfinite=nf, abort=abort)
        if not abort:
            return
        dump = health_mod.dump_forensics(
            health_mod.resolve_dump_dir(hcfg, self.cfg.train.checkpoint_dir),
            self._ckpt_tree(),
            detail={"site": "block", "epoch": epoch, "nonfinite": nf,
                    "family": self.cfg.model.family, "last_metrics": last},
            name=f"numeric_fault_{epoch}")
        try:
            self.logger.flush()
            obs.flush()
        except Exception:
            pass
        raise health_mod.NumericFault("block", epoch=epoch, nonfinite=nf,
                                      dump=dump)

    @property
    def steps_per_sec(self) -> float:
        return self.timer.steps_per_sec

    # ---------------------------------------------------------- checkpoint
    def _ckpt_tree(self):
        tree = {"state": self.state, "key": self.key,
                "epoch": jnp.asarray(self.epoch)}
        if self.scaler is not None:
            tree["scaler"] = {"data_min": self.scaler.data_min,
                              "data_max": self.scaler.data_max}
        return tree

    def _multihost(self) -> bool:
        if self.mesh is None:
            return False
        from hfrep_tpu.parallel.mesh import spans_processes
        return spans_processes(self.mesh)

    def _ckpt_async_ok(self) -> bool:
        """Deferred checkpoint writes need a single-process run (the
        multi-host save's all-gather + leader barrier must stay on the
        synchronous path) and no NaN guard (the guard's rollback
        contract wants the last written checkpoint to be the last
        *verified* block, not a staged one racing the verdict)."""
        return not self._multihost() and not self.nan_guard

    def _stage_checkpoint(self) -> str:
        """Fetch the checkpoint tree host-side WITHOUT writing it.

        The boundary's state must leave the device before the next
        dispatch — the jitted block step donates the state buffers —
        but nothing forces the file write to happen before it; the
        staged numpy tree is committed by :meth:`_commit_pending_ckpt`
        after the next block is in flight, so serialization overlaps
        device compute.  The staged tree is byte-identical to what the
        synchronous :meth:`save_checkpoint` would have written."""
        path = f"{self.cfg.train.checkpoint_dir}/ckpt_{self.epoch}"
        tree = jax.device_get(self._ckpt_tree())
        self._pending_ckpt = (tree, path, self.epoch)
        return path

    def _commit_pending_ckpt(self) -> None:
        """Atomically publish the staged checkpoint, if any.  Called
        after the next block's dispatch (the overlap), at every loop
        exit, and before a drain's final save — a kill that beats the
        commit costs one periodic checkpoint of resume granularity
        (the run re-trains from the previous one, bit-identically),
        never a torn file (the write stays atomic)."""
        if self._pending_ckpt is None:
            return
        tree, path, epoch = self._pending_ckpt
        self._pending_ckpt = None
        obs = get_obs()
        with obs.span("checkpoint", epoch=epoch, path=str(path)):
            ckpt.save(path, tree,
                      metadata={"family": self.cfg.model.family,
                                "epoch": epoch},
                      coordination_free=False,
                      keep=self.cfg.train.checkpoint_keep)
        obs.counter("checkpoints").inc()

    def save_checkpoint(self, path: Optional[str] = None) -> str:
        path = path or f"{self.cfg.train.checkpoint_dir}/ckpt_{self.epoch}"
        # Multi-host: on dp/sp meshes the state is replicated, so the
        # leader's copy is the whole checkpoint — every other process
        # writing the same path concurrently would race on shared
        # storage.  On a tp pod the params live SHARDED across
        # processes, so every process first joins one all-gather (a
        # pjit identity to the replicated layout — a collective, hence
        # BEFORE the leader-only return) and the leader then holds the
        # whole tree.  The leader writes the coordination-free format:
        # orbax's saver runs its own cross-process barrier, which a
        # single-process save never exits.
        multihost = self._multihost()
        tree = self._ckpt_tree()
        from jax.sharding import NamedSharding, PartitionSpec as P
        if multihost and not isinstance(self._launch_specs, P):
            # only the state is sharded; epoch/scaler are host-local
            # leaves a cross-process jit would reject
            tree = dict(tree, state=jax.jit(
                lambda s: s,
                out_shardings=NamedSharding(self.mesh, P()))(tree["state"]))
        if multihost and jax.process_index() != 0:
            return path
        obs = get_obs()
        with obs.span("checkpoint", epoch=self.epoch, path=str(path)):
            ckpt.save(path, tree,
                      metadata={"family": self.cfg.model.family, "epoch": self.epoch},
                      coordination_free=multihost,
                      keep=self.cfg.train.checkpoint_keep)
        obs.counter("checkpoints").inc()
        return path

    def restore_checkpoint(self, path: Optional[str] = None) -> str:
        """Restore ``path``, or the newest checkpoint in the configured
        checkpoint dir that passes checksum verification — a torn or
        corrupted checkpoint (preemption mid-save on a pre-atomic layout,
        bit rot) falls back to the previous good one instead of raising
        (``utils.checkpoint.restore_latest_good``).  Returns the path
        actually restored, which on the fallback path is NOT the one
        asked for — callers reporting "resumed from X" must use it.
        On the dir-walking resume path (``path=None``), when *every*
        candidate (``.prev`` siblings included) is corrupt, the walk
        emits ``ckpt_fallback_exhausted`` and this returns ``""`` with
        the trainer's fresh init state untouched — a resume against
        unrecoverable storage degrades to a clean fresh start instead
        of wedging the drive.  An *explicitly requested* checkpoint
        that cannot be recovered still raises: the caller named state
        it needs (a generator to serve/sample), and fresh-init params
        silently standing in for it would be worse than the crash."""
        ckpt_dir = self.cfg.train.checkpoint_dir
        if path is not None:
            try:
                restored = ckpt.restore(path, target=self._ckpt_tree())
            except ckpt.CheckpointCorrupt:
                if not ckpt_dir:
                    raise
                restored, path = ckpt.restore_latest_good(
                    ckpt_dir, target=self._ckpt_tree())
        else:
            if not ckpt_dir:
                raise FileNotFoundError("no checkpoint found")
            restored, path = ckpt.restore_latest_good(
                ckpt_dir, target=self._ckpt_tree(), on_exhausted="fresh")
        if restored is None:
            return ""
        self.state = jax.tree_util.tree_map(jnp.asarray, restored["state"])
        if not isinstance(self.state, GanState):
            self.state = GanState(**{f: restored["state"][f] for f in
                                     ("g_params", "d_params", "g_opt", "d_opt", "step")})
        self.key = jnp.asarray(restored["key"])
        self.epoch = int(restored["epoch"])
        if self._multihost():
            # re-apply the global-array promotion __init__ performed
            # (same per-leaf launch layout — the cross-process jit
            # rejects both host-local arrays and mismatched shardings)
            from hfrep_tpu.parallel.mesh import (replicate_to_global,
                                                 shard_to_global)
            self.state = shard_to_global(self.state, self.mesh,
                                         self._launch_specs)
            self.key = replicate_to_global(self.key, self.mesh)
        return str(path)

    # ------------------------------------------------------------ sampling
    def generate(self, key: jax.Array, n_samples: int,
                 unscale: bool = True) -> jnp.ndarray:
        """Sample (n, W, F) windows from the trained generator — the
        notebook's ``generator.predict(normal(0,1,(10,168,36)))`` step
        (``autoencoder_v4.ipynb`` cell 43), inverse-scaled by default."""
        w, f = self.windows.shape[1], self.windows.shape[2]
        noise = jax.random.normal(key, (n_samples, w, f))
        if self._multihost():
            # params are pod-global arrays; the jit rejects mixing them
            # with a process-local noise array
            from hfrep_tpu.parallel.mesh import replicate_to_global
            noise = replicate_to_global(noise, self.mesh)
        if self._generate_fn is None:
            from hfrep_tpu.train.steps import resolve_lstm_backend
            be = resolve_lstm_backend(self.cfg.train.lstm_backend)
            self._generate_fn = jax.jit(
                lambda p, z: self.pair.generator.apply({"params": p}, z, backend=be))
        obs = get_obs()
        with obs.span("generate", n_samples=int(n_samples), synced=obs.enabled):
            out = self._generate_fn(self.state.g_params, noise)
            if obs.enabled:      # sync inside the span: time compute, not dispatch
                jax.block_until_ready(out)
        if unscale and self.scaler is not None:
            from hfrep_tpu.core import scaler as mm
            out = mm.inverse_transform(self.scaler, out)
        if self._multihost():
            # hand every process a plain local copy (the output is
            # replicated) so downstream numpy/eval code needs no
            # global-array awareness
            out = jnp.asarray(jax.device_get(out))
        return out

    def generate_block(self, seq: int, n_samples: int,
                       stream_seed: int = 0,
                       unscale: bool = True) -> jnp.ndarray:
        """Actor-driven entry point: the ``seq``-th sample block of a
        deterministic stream.

        A generator actor in the orchestration fabric
        (:mod:`hfrep_tpu.orchestrate`) streams blocks into the spool
        queue by calling this with consecutive ``seq``; the key is
        derived by folding ``seq`` into ``PRNGKey(stream_seed)``, so a
        member restarted after SIGKILL regenerates exactly the block the
        killed one would have delivered — the queue-level dedup and the
        fabric's bit-identity contract both rest on that.  Distinct
        sources use distinct ``stream_seed`` values.
        """
        key = jax.random.fold_in(jax.random.PRNGKey(stream_seed), seq)
        return self.generate(key, n_samples, unscale=unscale)
